"""Priority-class taxonomy and per-tenant defaults (config-driven).

A :class:`QosClass` is a named priority tier with three numbers:

- ``priority`` — strict-preemption rank (lower = more urgent). At flush
  time a flushable higher-priority bucket always dispatches before a
  lower-priority one; within batch assembly the priority order decides
  who gets the leftover seats after the weighted guarantee.
- ``weight`` — weighted-fairness share inside one assembled batch: each
  class present in a queue is guaranteed
  ``floor(batch_capacity * weight / sum(present weights))`` rows before
  strict-priority filling takes over, which bounds starvation of low
  tiers to one guaranteed slice per batch rather than "whenever the
  high tiers go quiet".
- ``rate_share`` — fraction of the domain's ``max_sustainable_qps`` the
  admission controller's token bucket grants this class (shares need
  not sum to 1; >1 total deliberately oversubscribes).

``p99_slo_ms`` is a target carried into records/benchmarks, not an
enforcement knob — the QoS bench gate checks interactive p99 against it.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class QosClass:
    name: str
    #: strict-preemption rank; lower is more urgent (0 = front of line)
    priority: int
    #: weighted-fairness share inside one assembled batch
    weight: float = 1.0
    #: fraction of max_sustainable_qps this class's admission bucket gets
    rate_share: float = 1.0
    #: latency target (ms) carried into records; None = no stated target
    p99_slo_ms: float | None = None


#: the default three-tier taxonomy. ``interactive`` preempts everything
#: and owns most of the admission rate; ``scavenger`` runs on leftovers
#: and is by construction the first tier shed under overload.
DEFAULT_CLASSES: tuple[QosClass, ...] = (
    QosClass("interactive", priority=0, weight=4.0, rate_share=0.6,
             p99_slo_ms=None),
    QosClass("batch", priority=1, weight=2.0, rate_share=0.3),
    QosClass("scavenger", priority=2, weight=1.0, rate_share=0.1),
)


@dataclass
class QosPolicy:
    """The resolved QoS configuration a service instance runs under."""

    classes: dict[str, QosClass] = field(
        default_factory=lambda: {c.name: c for c in DEFAULT_CLASSES}
    )
    #: class assigned when a request names neither a class nor a tenant
    default_class: str = "batch"
    #: tenant name -> class name (per-tenant defaults from serving.yaml)
    tenants: dict[str, str] = field(default_factory=dict)
    #: cost-predictive admission on/off (off = queue-depth 429s only)
    admission: bool = True
    #: admission token-bucket burst horizon in seconds of class rate
    admission_burst_s: float = 2.0
    #: streaming partial results on/off (off = /attack?stream=1 is a 400)
    streaming: bool = True

    def __post_init__(self) -> None:
        if self.default_class not in self.classes:
            raise ValueError(
                f"default_class {self.default_class!r} is not a configured "
                f"class (have: {sorted(self.classes)})"
            )
        for tenant, klass in self.tenants.items():
            if klass not in self.classes:
                raise ValueError(
                    f"tenant {tenant!r} maps to unknown class {klass!r}"
                )

    @classmethod
    def from_config(cls, cfg: dict | None) -> "QosPolicy | None":
        """Build a policy from the ``serving.qos`` config block.

        ``None``/missing block or ``enabled: false`` -> ``None`` (QoS
        fully off: the service runs the exact pre-QoS request path).
        Class entries override/extend the default taxonomy field-wise.
        """
        if not cfg or not cfg.get("enabled", True):
            return None
        classes = {c.name: c for c in DEFAULT_CLASSES}
        for name, spec in (cfg.get("classes") or {}).items():
            spec = dict(spec or {})
            base = classes.get(name)
            classes[name] = QosClass(
                name=name,
                priority=int(
                    spec.get("priority", base.priority if base else 99)
                ),
                weight=float(spec.get("weight", base.weight if base else 1.0)),
                rate_share=float(
                    spec.get("rate_share", base.rate_share if base else 1.0)
                ),
                p99_slo_ms=(
                    float(spec["p99_slo_ms"])
                    if spec.get("p99_slo_ms") is not None
                    else (base.p99_slo_ms if base else None)
                ),
            )
        admission_cfg = cfg.get("admission") or {}
        streaming_cfg = cfg.get("streaming") or {}
        return cls(
            classes=classes,
            default_class=str(cfg.get("default_class", "batch")),
            tenants={
                str(t): str(k) for t, k in (cfg.get("tenants") or {}).items()
            },
            admission=bool(admission_cfg.get("enabled", True)),
            admission_burst_s=float(admission_cfg.get("burst_s", 2.0)),
            streaming=bool(streaming_cfg.get("enabled", True)),
        )

    def resolve(
        self, name: str | None = None, tenant: str | None = None
    ) -> QosClass:
        """Resolve a request's class: explicit name > tenant default >
        policy default. Unknown names fall back to the default class —
        a typo'd priority must degrade service, not reject the request."""
        if name and name in self.classes:
            return self.classes[name]
        if tenant and tenant in self.tenants:
            return self.classes[self.tenants[tenant]]
        return self.classes[self.default_class]

    def priority_of(self, name: str | None) -> int:
        klass = self.classes.get(name) if name else None
        return klass.priority if klass else self.classes[
            self.default_class
        ].priority

    def ordered(self) -> list[QosClass]:
        """Classes in strict-priority order (most urgent first)."""
        return sorted(self.classes.values(), key=lambda c: (c.priority, c.name))
