"""Per-request result streams fed by the MoEvA early-exit gate.

The engine already identifies solved states mid-scan (solved-state
parking) and fetches their populations on a double-buffered gate tail —
but until now those rows sat in host arrays until the whole scan
finished. A :class:`ResultStream` is the bridge: the batcher routes the
engine's partial sink to each rider's stream, solved rows are decoded
and surfaced *as they park*, and the caller consumes them either as
chunked HTTP (``/attack?stream=1``) or by incremental poll
(``GET /attack/<id>?cursor=N``).

Semantics the consumer can rely on:

- Chunks arrive in gate order; within one request each row index
  appears at most once before the final chunk (a row parks once).
- ``time_to_first_solved_s`` is stamped at the first partial chunk —
  the streaming headline number, recorded next to
  ``time_to_complete_s``.
- The final payload always carries the COMPLETE result (every row,
  solved or not), so a consumer that ignores partials loses nothing.
- MoEvA RNG caveat (docs/DESIGN.md § QoS): a partial row's payload is
  the solved population at its park generation; the final result's
  same row comes from the identical parked buffer, so partial and
  final rows agree — but across *different batch shapes* MoEvA results
  are not bit-identical (compaction reshuffles the PRNG), and partial
  streams inherit exactly that caveat, no more.

Thread model: one producer (the batcher's dispatch thread), any number
of consumers. All state sits behind one condition variable; `put` after
`finish`/`fail` is dropped (late gate flush of an already-failed batch).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterator


class ResultStream:
    """One request's ordered sequence of partial chunks + final result."""

    def __init__(
        self,
        request_id: str,
        n_rows: int,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.request_id = request_id
        self.n_rows = int(n_rows)
        self.clock = clock
        self.created_at = clock()
        self._cond = threading.Condition()
        self._chunks: list[dict] = []
        self._done = False
        self._error: BaseException | None = None
        self._final: dict | None = None
        self._closed_by_consumer = False
        self.t_first_solved: float | None = None
        self.t_finished: float | None = None
        self.rows_streamed = 0

    # -- producer ----------------------------------------------------------

    def put(self, rows: list[int], x_rows: Any, gen: int) -> None:
        """Append one partial chunk: request-local ``rows`` solved at
        generation ``gen`` with decoded ML-space payload ``x_rows``."""
        with self._cond:
            if self._done or self._closed_by_consumer:
                return
            if self.t_first_solved is None:
                self.t_first_solved = self.clock()
            self.rows_streamed += len(rows)
            self._chunks.append(
                {
                    "rows": [int(r) for r in rows],
                    "x": x_rows,
                    "gen": int(gen),
                    "t": self.clock(),
                }
            )
            self._cond.notify_all()

    def finish(self, x_adv: Any, meta: dict | None = None) -> None:
        with self._cond:
            if self._done:
                return
            self._done = True
            self.t_finished = self.clock()
            self._final = {"x_adv": x_adv, "meta": meta or {}}
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._done:
                return
            self._done = True
            self.t_finished = self.clock()
            self._error = exc
            self._cond.notify_all()

    # -- consumer ----------------------------------------------------------

    def close(self) -> None:
        """Consumer walked away (chunked connection dropped): further
        partials are discarded, the producer is never blocked or failed."""
        with self._cond:
            self._closed_by_consumer = True
            self._chunks.clear()
            self._cond.notify_all()

    @property
    def done(self) -> bool:
        with self._cond:
            return self._done

    @property
    def error(self) -> BaseException | None:
        with self._cond:
            return self._error

    @property
    def final(self) -> dict | None:
        with self._cond:
            return self._final

    def chunks(self, timeout: float | None = None) -> Iterator[dict]:
        """Blocking iterator over partial chunks, ending when the stream
        finishes or fails (the final payload is NOT yielded — read
        :attr:`final`/:attr:`error` after). Raises ``TimeoutError`` if
        no progress happens within ``timeout`` seconds."""
        cursor = 0
        while True:
            with self._cond:
                while cursor >= len(self._chunks) and not self._done:
                    if not self._cond.wait(timeout):
                        raise TimeoutError(
                            f"stream {self.request_id}: no progress in "
                            f"{timeout}s"
                        )
                batch = self._chunks[cursor:]
                cursor = len(self._chunks)
                done = self._done
            for chunk in batch:
                yield chunk
            if done and cursor >= self._chunk_count():
                return

    def _chunk_count(self) -> int:
        with self._cond:
            return len(self._chunks)

    def poll(self, cursor: int = 0) -> dict:
        """Non-blocking incremental read from ``cursor`` (chunk index)."""
        with self._cond:
            chunks = self._chunks[cursor:]
            return {
                "request_id": self.request_id,
                "cursor": len(self._chunks),
                "chunks": chunks,
                "done": self._done,
                "failed": self._error is not None,
                "rows_streamed": self.rows_streamed,
                "n_rows": self.n_rows,
            }


class StreamRegistry:
    """Bounded request_id -> stream map behind the poll endpoints.

    Finished streams are retained (so a poller can still collect the
    final payload) until capacity pressure evicts the oldest finished
    entries; live streams are never evicted.
    """

    def __init__(self, max_entries: int = 512):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._streams: dict[str, ResultStream] = {}
        self.evicted = 0

    def add(self, stream: ResultStream) -> None:
        with self._lock:
            self._streams[stream.request_id] = stream
            if len(self._streams) > self.max_entries:
                finished = [
                    rid
                    for rid, s in self._streams.items()
                    if s.done and rid != stream.request_id
                ]
                # insertion order == age: evict oldest finished first
                for rid in finished[
                    : len(self._streams) - self.max_entries
                ]:
                    del self._streams[rid]
                    self.evicted += 1

    def get(self, request_id: str) -> ResultStream | None:
        with self._lock:
            return self._streams.get(request_id)

    def snapshot(self) -> dict:
        with self._lock:
            live = sum(1 for s in self._streams.values() if not s.done)
            return {
                "entries": len(self._streams),
                "live": live,
                "evicted": self.evicted,
            }
