"""Stdlib-only JSON/HTTP front for :class:`~.service.AttackService`.

Three routes, no dependencies beyond ``http.server``:

- ``POST /attack`` — body ``{"domain", "rows": [[...]], "attack",
  "loss_evaluation", "eps", "eps_step", "budget", "deadline_s",
  "request_id", "params"}``; replies ``{"request_id", "x_adv", "meta"}``.
  Error mapping: 400 invalid request / unparseable body, 413 request larger
  than the biggest bucket, 429 + ``Retry-After`` on backpressure, 504 on a
  queued deadline or server-side wait timeout, 500 when the request's batch
  failed.
- ``GET /healthz`` — liveness + queue depth + build/config identity (git
  describe, config hash, per-domain mesh description) so load balancers can
  detect a mis-deployed or mis-meshed replica.
- ``GET /metrics`` — the :class:`~..utils.observability.ServiceMetrics`
  snapshot plus engine/artifact cache stats, JSON;
  ``GET /metrics?format=prom`` serves the same numbers as Prometheus text
  exposition (``observability.prom``).

``ThreadingHTTPServer`` gives one handler thread per connection; handlers
block on the request future while the single flusher/dispatch thread keeps
the device fed — the HTTP layer adds concurrency, not parallelism, which is
exactly the microbatcher's input shape.
"""

from __future__ import annotations

import json
import math
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..observability.prom import prometheus_text
from .batcher import BatchExecutionError, DeadlineExceeded, QueueFull, RequestTooLarge
from .service import AttackRequest, AttackService, InvalidRequest


def _jsonable(obj):
    """JSON with NaN/Inf scrubbed to null (strict parsers choke on them)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "tolist"):  # numpy array or scalar
        return _jsonable(obj.tolist())
    return obj


class AttackHTTPHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "AttackHTTPServer"

    # -- plumbing ------------------------------------------------------------
    def _send(self, code: int, obj: dict, headers: dict | None = None):
        body = json.dumps(_jsonable(obj)).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # replica attribution on every response (incl. errors): the fleet
        # router and the chaos sweep account shed/served per replica by it
        rid = getattr(self.server.service, "replica_id", None)
        if rid:
            self.send_header("X-Replica-Id", rid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, body: str, content_type: str):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        if self.server.verbose:
            super().log_message(fmt, *args)

    # -- routes --------------------------------------------------------------
    def do_GET(self):
        service = self.server.service
        parts = urlsplit(self.path)
        if parts.path == "/healthz":
            self._send(200, service.healthz())
        elif parts.path == "/metrics":
            query = parse_qs(parts.query)
            if query.get("format", [""])[0] == "prom":
                self._send_text(
                    200,
                    prometheus_text(service.metrics_snapshot()),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._send(200, service.metrics_snapshot())
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        # always drain the body: HTTP/1.1 keep-alive would otherwise parse
        # the unread bytes as the next request line on a reused connection
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._send(400, {"error": "bad Content-Length header"})
            self.close_connection = True
            return
        body = self.rfile.read(length)
        if self.path != "/attack":
            self._send(404, {"error": f"no route {self.path}"})
            return
        service = self.server.service
        try:
            payload = json.loads(body)
            req = AttackRequest(
                domain=payload["domain"],
                x=payload["rows"],
                attack=payload.get("attack", "pgd"),
                loss_evaluation=payload.get("loss_evaluation", "flip"),
                eps=float(payload.get("eps", 0.1)),
                eps_step=payload.get("eps_step"),
                budget=int(payload.get("budget", 10)),
                deadline_s=payload.get("deadline_s"),
                request_id=payload.get("request_id"),
                params=payload.get("params"),
            )
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"bad request body: {e!r}"})
            return
        try:
            resp = service.attack(req, timeout=self.server.request_timeout_s)
        except InvalidRequest as e:
            self._send(400, {"error": str(e)})
        except RequestTooLarge as e:
            self._send(413, {"error": str(e)})
        except QueueFull as e:
            self._send(
                429,
                {"error": str(e), "retry_after_s": e.retry_after_s},
                headers={"Retry-After": f"{max(e.retry_after_s, 0.001):.3f}"},
            )
        except DeadlineExceeded as e:
            self._send(504, {"error": str(e)})
        except (TimeoutError, FuturesTimeout) as e:  # result(timeout=) expired
            self._send(504, {"error": f"server-side wait timed out: {e!r}"})
        except BatchExecutionError as e:
            self._send(500, {"error": str(e)})
        else:
            self._send(
                200,
                {
                    "request_id": resp.request_id,
                    "x_adv": resp.x_adv,
                    "meta": resp.meta,
                },
            )


class AttackHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(
        self,
        addr: tuple[str, int],
        service: AttackService,
        *,
        request_timeout_s: float = 60.0,
        verbose: bool = False,
    ):
        super().__init__(addr, AttackHTTPHandler)
        self.service = service
        self.request_timeout_s = request_timeout_s
        self.verbose = verbose


def serve(
    service: AttackService,
    host: str = "127.0.0.1",
    port: int = 8787,
    **kw,
) -> AttackHTTPServer:
    """Bind and return the server (caller runs ``serve_forever``; port 0
    picks an ephemeral port — read it back from ``server.server_address``)."""
    return AttackHTTPServer((host, port), service, **kw)
