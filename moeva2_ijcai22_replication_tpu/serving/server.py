"""Stdlib-only JSON/HTTP front for :class:`~.service.AttackService`.

Three routes, no dependencies beyond ``http.server``:

- ``POST /attack`` — body ``{"domain", "rows": [[...]], "attack",
  "loss_evaluation", "eps", "eps_step", "budget", "deadline_s",
  "request_id", "params", "priority", "tenant"}``; replies
  ``{"request_id", "x_adv", "meta"}``. ``priority`` names a QoS class
  (``X-Qos-Class`` header is the fallback when the body omits it); the
  resolved class echoes back as an ``X-Qos-Class`` response header on
  every reply, including errors — the fleet router propagates both ways.
  Error mapping: 400 invalid request / unparseable body, 413 request larger
  than the biggest bucket, 429 + ``Retry-After`` on backpressure (queue
  full OR cost-predictive admission denial), 504 on a queued deadline or
  server-side wait timeout, 500 when the request's batch failed.
- ``POST /attack?stream=1`` — same body; replies chunked JSON-lines
  (``application/x-ndjson``): one record per partial chunk as the MoEvA
  early-exit gate parks solved rows, then a final ``{"done": true,
  "request_id", "x_adv", "meta"}`` record carrying the complete result.
  ``POST /attack?stream=poll`` instead replies 202 with the request id;
  ``GET /attack/<id>?cursor=N`` then reads chunks incrementally.
  Requires ``serving.qos.streaming`` (400 otherwise).
- ``GET /healthz`` — liveness + queue depth + build/config identity (git
  describe, config hash, per-domain mesh description) so load balancers can
  detect a mis-deployed or mis-meshed replica.
- ``GET /metrics`` — the :class:`~..utils.observability.ServiceMetrics`
  snapshot plus engine/artifact cache stats, JSON;
  ``GET /metrics?format=prom`` serves the same numbers as Prometheus text
  exposition (``observability.prom``).

``ThreadingHTTPServer`` gives one handler thread per connection; handlers
block on the request future while the single flusher/dispatch thread keeps
the device fed — the HTTP layer adds concurrency, not parallelism, which is
exactly the microbatcher's input shape.
"""

from __future__ import annotations

import json
import math
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..observability.fleetrace import TRACE_HEADER, parse_trace_context
from ..observability.prom import prometheus_text
from .batcher import BatchExecutionError, DeadlineExceeded, QueueFull, RequestTooLarge
from .service import AttackRequest, AttackService, InvalidRequest


def _jsonable(obj):
    """JSON with NaN/Inf scrubbed to null (strict parsers choke on them)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "tolist"):  # numpy array or scalar
        return _jsonable(obj.tolist())
    return obj


class AttackHTTPHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "AttackHTTPServer"

    # -- plumbing ------------------------------------------------------------
    def _send(self, code: int, obj: dict, headers: dict | None = None):
        body = json.dumps(_jsonable(obj)).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # replica attribution on every response (incl. errors): the fleet
        # router and the chaos sweep account shed/served per replica by it
        rid = getattr(self.server.service, "replica_id", None)
        if rid:
            self.send_header("X-Replica-Id", rid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _write_chunk(self, obj: dict):
        """One HTTP/1.1 chunked-transfer frame holding one JSON line."""
        data = (json.dumps(_jsonable(obj)) + "\n").encode()
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _send_text(self, code: int, body: str, content_type: str):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        if self.server.verbose:
            super().log_message(fmt, *args)

    # -- routes --------------------------------------------------------------
    def do_GET(self):
        service = self.server.service
        parts = urlsplit(self.path)
        if parts.path == "/healthz":
            self._send(200, service.healthz())
        elif parts.path == "/metrics":
            query = parse_qs(parts.query)
            if query.get("format", [""])[0] == "prom":
                self._send_text(
                    200,
                    prometheus_text(service.metrics_snapshot()),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._send(200, service.metrics_snapshot())
        elif parts.path.startswith("/attack/"):
            # incremental poll of a streaming request submitted with
            # ?stream=poll (or any request whose stream is still retained)
            rid = parts.path[len("/attack/") :]
            streams = getattr(service, "streams", None)
            if streams is None:
                self._send(
                    400,
                    {"error": "streaming is not enabled (serving.qos.streaming)"},
                )
                return
            stream = streams.get(rid)
            if stream is None:
                self._send(404, {"error": f"unknown or evicted stream {rid!r}"})
                return
            try:
                cursor = int(parse_qs(parts.query).get("cursor", ["0"])[0])
            except ValueError:
                self._send(400, {"error": "bad cursor (want an integer)"})
                return
            out = stream.poll(cursor)
            if out["done"]:
                err = stream.error
                if err is not None:
                    out["error"] = str(err)
                else:
                    final = stream.final
                    out["x_adv"] = final["x_adv"]
                    out["meta"] = final["meta"]
            self._send(200, out)
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        # always drain the body: HTTP/1.1 keep-alive would otherwise parse
        # the unread bytes as the next request line on a reused connection
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._send(400, {"error": "bad Content-Length header"})
            self.close_connection = True
            return
        body = self.rfile.read(length)
        parts = urlsplit(self.path)
        service = self.server.service
        if parts.path == "/debug/flight":
            # black-box dump on demand: the fleet manager calls this just
            # before SIGKILL and harvests the returned path, so the chaos
            # accounting can attribute lost rows to the exact batch
            try:
                payload = json.loads(body) if body else {}
            except json.JSONDecodeError:
                payload = {}
            reason = str(
                (payload or {}).get("reason")
                or parse_qs(parts.query).get("reason", ["manual"])[0]
            )
            try:
                self._send(200, service.flight_dump(reason))
            except Exception as e:  # noqa: BLE001 — a dump failure must
                self._send(500, {"error": f"flight dump failed: {e!r}"})
            return  # not take the handler thread down
        if parts.path != "/attack":
            self._send(404, {"error": f"no route {self.path}"})
            return
        # distributed trace context (X-Moeva2-Trace): the fleet router's
        # trace id + attempt span + hop count; malformed/absent -> None
        # and the request traces standalone exactly as before
        trace_ctx = parse_trace_context(self.headers.get(TRACE_HEADER))
        try:
            payload = json.loads(body)
            req = AttackRequest(
                domain=payload["domain"],
                x=payload["rows"],
                attack=payload.get("attack", "pgd"),
                loss_evaluation=payload.get("loss_evaluation", "flip"),
                eps=float(payload.get("eps", 0.1)),
                eps_step=payload.get("eps_step"),
                budget=int(payload.get("budget", 10)),
                deadline_s=payload.get("deadline_s"),
                request_id=payload.get("request_id"),
                params=payload.get("params"),
                # body wins; the header is how the fleet router (and any
                # proxy that can't rewrite bodies) forwards the class
                priority=payload.get("priority")
                or self.headers.get("X-Qos-Class"),
                tenant=payload.get("tenant"),
            )
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"bad request body: {e!r}"})
            return
        qos_hdrs: dict = {}
        if getattr(service, "qos", None) is not None:
            qos_hdrs["X-Qos-Class"] = service.qos.resolve(
                req.priority, req.tenant
            ).name
        stream_mode = parse_qs(parts.query).get("stream", [""])[0]
        if stream_mode:
            self._attack_streaming(
                service, req, stream_mode, qos_hdrs, trace_ctx
            )
            return
        try:
            resp = service.attack(
                req,
                timeout=self.server.request_timeout_s,
                trace_context=trace_ctx,
            )
        except InvalidRequest as e:
            self._send(400, {"error": str(e)}, headers=qos_hdrs)
        except RequestTooLarge as e:
            self._send(413, {"error": str(e)}, headers=qos_hdrs)
        except QueueFull as e:
            self._send(
                429,
                {"error": str(e), "retry_after_s": e.retry_after_s},
                headers={
                    "Retry-After": f"{max(e.retry_after_s, 0.001):.3f}",
                    **qos_hdrs,
                },
            )
        except DeadlineExceeded as e:
            self._send(504, {"error": str(e)}, headers=qos_hdrs)
        except (TimeoutError, FuturesTimeout) as e:  # result(timeout=) expired
            self._send(
                504,
                {"error": f"server-side wait timed out: {e!r}"},
                headers=qos_hdrs,
            )
        except BatchExecutionError as e:
            self._send(500, {"error": str(e)}, headers=qos_hdrs)
        else:
            self._send(
                200,
                {
                    "request_id": resp.request_id,
                    "x_adv": resp.x_adv,
                    "meta": resp.meta,
                },
                headers=qos_hdrs,
            )

    def _attack_streaming(
        self, service, req, mode: str, qos_hdrs: dict, trace_ctx=None
    ):
        """``stream=poll`` -> 202 + request id (read via GET
        ``/attack/<id>?cursor=N``); anything else (``stream=1``) -> chunked
        JSON-lines: partial records as rows park, then the final
        ``{"done": true}`` record. Submission errors map exactly like the
        blocking route; errors AFTER the 200 header is on the wire ride the
        final record instead (chunked transfer can't change the status).
        Partial chunks never carry trace data — the request trace rides
        only the final record's meta."""
        try:
            stream, fut = service.submit_stream(req, trace_context=trace_ctx)
        except InvalidRequest as e:
            self._send(400, {"error": str(e)}, headers=qos_hdrs)
            return
        except RequestTooLarge as e:
            self._send(413, {"error": str(e)}, headers=qos_hdrs)
            return
        except QueueFull as e:
            self._send(
                429,
                {"error": str(e), "retry_after_s": e.retry_after_s},
                headers={
                    "Retry-After": f"{max(e.retry_after_s, 0.001):.3f}",
                    **qos_hdrs,
                },
            )
            return
        if mode == "poll":
            self._send(
                202,
                {
                    "request_id": stream.request_id,
                    "poll": f"/attack/{stream.request_id}",
                    "n_rows": stream.n_rows,
                },
                headers=qos_hdrs,
            )
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        replica = getattr(service, "replica_id", None)
        if replica:
            self.send_header("X-Replica-Id", replica)
        for k, v in qos_hdrs.items():
            self.send_header(k, v)
        self.end_headers()
        try:
            try:
                for chunk in stream.chunks(
                    timeout=self.server.request_timeout_s
                ):
                    self._write_chunk(
                        {
                            "request_id": stream.request_id,
                            "rows": chunk["rows"],
                            "x": chunk["x"],
                            "gen": chunk["gen"],
                        }
                    )
            except TimeoutError:
                self._write_chunk(
                    {
                        "done": True,
                        "request_id": stream.request_id,
                        "error": "server-side wait timed out",
                    }
                )
            else:
                err = stream.error
                if err is not None:
                    self._write_chunk(
                        {
                            "done": True,
                            "request_id": stream.request_id,
                            "error": str(err),
                        }
                    )
                else:
                    final = stream.final
                    self._write_chunk(
                        {
                            "done": True,
                            "request_id": stream.request_id,
                            "x_adv": final["x_adv"],
                            "meta": final["meta"],
                        }
                    )
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            # consumer walked away mid-stream: discard partials, never
            # block or fail the producer side
            stream.close()
            self.close_connection = True


class AttackHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(
        self,
        addr: tuple[str, int],
        service: AttackService,
        *,
        request_timeout_s: float = 60.0,
        verbose: bool = False,
    ):
        super().__init__(addr, AttackHTTPHandler)
        self.service = service
        self.request_timeout_s = request_timeout_s
        self.verbose = verbose


def serve(
    service: AttackService,
    host: str = "127.0.0.1",
    port: int = 8787,
    **kw,
) -> AttackHTTPServer:
    """Bind and return the server (caller runs ``serve_forever``; port 0
    picks an ephemeral port — read it back from ``server.server_address``)."""
    return AttackHTTPServer((host, port), service, **kw)
