"""AttackService: request -> cached engine -> microbatched dispatch.

The request path reuses the grid substrate wholesale: artifacts come from
``experiments.common.ARTIFACTS`` (one disk read per file per process),
engines come from ``experiments.common.ENGINES`` through the *same*
cache-key builders the batch runners use (``experiments.pgd._cached_attack``
/ ``experiments.moeva._cached_engine``), so a service and a grid running in
one process share compiled executables. What serving adds is the traffic
shape: concurrent, variably-sized requests are coalesced by the
:class:`~.batcher.Microbatcher` into full fixed-shape batches.

Batch keys: requests only coalesce when one device dispatch can serve all
of them, i.e. when they agree on the engine static config AND on the
runtime scalars that are batch-wide arguments of the compiled program
(ε, ε-step, budget — `attacks/pgd/engine.py` feeds them as traced scalars,
one value per dispatch). The key is the config hash of exactly that tuple;
distinct ε values therefore queue separately but still share the same
compiled program per bucket size.

Bit-identity: plain ConstrainedPGD (no restarts, no history) is per-row
deterministic with no batch-shape-dependent RNG, so a request's rows give
bit-identical results whether dispatched alone or coalesced+padded into any
bucket — the contract tests pin. AutoPGD/restart programs draw
batch-shaped random starts and MoEvA folds chunk-shaped PRNG keys, so those
families serve fine but are NOT bit-identical across batch shapes; the
response metadata carries ``bit_identical`` so callers know which contract
they got.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ..attacks.objective import engine_quality_stats
from ..attacks.pgd import ConstrainedPGD, round_ints_toward_initial
from ..attacks.sharding import describe_mesh
from ..experiments import common
from ..observability import (
    CapacityModel,
    FlightRecorder,
    IncidentDetector,
    SloTracker,
    Trace,
    TraceRecorder,
    all_device_memory_stats,
    build_identity,
    current_ledger_context,
    current_trace,
    device_memory_stats,
    get_coldstart,
    get_gap_tracker,
    get_ledger,
    incidents_block,
    maybe_span,
    mesh_snapshot,
    sample_from_per_state,
    spans_from_recorder,
)
from ..utils.config import get_dict_hash
from ..utils.observability import ServiceMetrics
from .batcher import BucketMenu, Microbatcher, QueueFull, RequestTooLarge
from .qos import AdmissionController, ResultStream, StreamRegistry
from .qos.admission import AdmissionDenied


class InvalidRequest(ValueError):
    """The request can never succeed (unknown domain, bad shape, bad family)."""


def _record_device_span(bt, engine, traces0: int, t0: float, **extra) -> None:
    """The one device-span shape both dispatch closures emit: compile vs run
    split by the engine's ``trace_count`` delta, HBM watermark attached,
    and — when the cost ledger knows the dispatched executables — roofline
    attribution (model FLOPs joined with this span's duration)."""
    if bt is None:
        return
    traced = engine.trace_count - traces0
    dur = time.perf_counter() - t0
    executables = list(getattr(engine, "last_run_executables", ()))
    if engine.mesh is not None and engine.mesh.size > 1:
        # mesh-backed engine: watermark every device, and stamp the device
        # count so the Perfetto exporter fans this span onto per-device
        # tracks (tid = ordinal) instead of stacking the mesh on one row
        stats = all_device_memory_stats(list(engine.mesh.devices.flat))
        attrs = dict(
            traces=int(traced),
            devices=int(engine.mesh.size),
            hbm=(stats or {}).get("max"),
            hbm_devices=(stats or {}).get("per_device"),
            **extra,
        )
    else:
        attrs = dict(
            traces=int(traced),
            hbm=device_memory_stats(
                engine.mesh.devices.flat[0]
                if engine.mesh is not None
                else None
            ),
            **extra,
        )
    if executables:
        attrs["executables"] = executables
        # roofline only on pure run spans: a device_compile span's duration
        # is dominated by compile, and achieved-FLOP/s over it would read
        # orders of magnitude below the replica's real rate
        if not traced:
            counts = getattr(engine, "last_run_dispatch_counts", None)
            roofline = get_ledger().roofline_for(
                counts or executables, dur
            )
            if roofline is not None:
                attrs["roofline"] = roofline
    bt.record_span(
        "device_compile" if traced else "device_run", dur, **attrs
    )


@dataclass
class AttackRequest:
    """One caller's attack: rows to perturb plus the attack coordinates."""

    domain: str
    x: Any  # (n_rows, n_features) unscaled feature rows
    attack: str = "pgd"
    loss_evaluation: str = "flip"
    eps: float = 0.1
    eps_step: float | None = None  # default: the runner's fixed 0.1
    budget: int = 10
    deadline_s: float | None = None  # relative; cancelled-if-exceeded pre-dispatch
    request_id: str | None = None
    params: dict | None = None  # extra engine config (moeva n_pop, nb_random, …)
    #: QoS class name (interactive/batch/scavenger by default); None =
    #: resolve via tenant default, then the policy default. Ignored (and
    #: harmless) when the service runs without a QoS policy.
    priority: str | None = None
    #: tenant label: picks the per-tenant default class from serving.yaml
    tenant: str | None = None


@dataclass
class AttackResponse:
    request_id: str
    x_adv: np.ndarray
    meta: dict


@dataclass
class _Resolved:
    """Per-(run-config) dispatch closure + its response metadata."""

    key: tuple
    dispatch: Callable[[np.ndarray], np.ndarray]
    mesh: Any
    bit_identical: bool
    n_features: int
    execution: dict
    meta: dict = field(default_factory=dict)
    #: shape-only warmup closure: dispatches the engine with synthetic
    #: rows at a given batch shape — same engine knobs as ``dispatch``,
    #: so it compiles (or AOT-loads) the exact executable real traffic
    #: at that bucket uses, but skips constraint validation and every
    #: piece of request-path bookkeeping (SLO, capacity, quality).
    #: NOT safe concurrently with live traffic (engines are
    #: single-dispatch objects) — boot-time only.
    prewarm: Callable[[np.ndarray], None] | None = None


def _domain_origins(domains: dict) -> dict:
    """Per served domain: ``{"origin": handwritten|spec|generated,
    "spec_hash": ...}`` — spec-file domains hash the file content (cheap:
    parse + canonicalize, no kernel compile), registry names resolve
    through the domains registry."""
    out = {}
    for name, cfg in sorted(domains.items()):
        try:
            if cfg.get("spec"):
                from ..domains.ir import load_spec, spec_hash

                spec = load_spec(
                    cfg["spec"], name=cfg.get("project_name", name)
                )
                out[name] = {"origin": "spec", "spec_hash": spec_hash(spec)}
            else:
                from ..domains import domain_origin

                out[name] = domain_origin(cfg["project_name"])
        except Exception as exc:  # mis-deployed replica: visible, not fatal
            out[name] = {"origin": "unknown", "error": str(exc)}
    return out


class AttackService:
    """In-process attack server: bounded queues, microbatched execution.

    ``domains`` maps a request's ``domain`` name to an experiment-style
    config dict (``project_name`` + ``paths{features, constraints, model,
    ml_scaler}`` + optional ``norm`` / ``system.mesh_devices`` / engine
    defaults) — the same shape the batch runners consume, so a committed
    ``config/*.yaml`` can be served as-is.
    """

    def __init__(
        self,
        domains: dict[str, dict],
        *,
        bucket_sizes=common.DEFAULT_BUCKET_SIZES,
        max_delay_s: float = 0.010,
        max_queue_rows: int = 4096,
        seed: int = 42,
        metrics: ServiceMetrics | None = None,
        metrics_window: int = 8192,
        recorder=None,
        stream=None,
        slo_buckets=None,
        slo_capture: bool = True,
        capacity_window: int = 256,
        clock: Callable[[], float] | None = None,
        start: bool = True,
        replica_id: str | None = None,
        qos=None,
        flight_ring: int = 64,
        incident_detection: bool = True,
        flight_dir: str = "out",
        incident_tick_s: float = 2.0,
    ):
        self.domains = dict(domains)
        self.seed = int(seed)
        # fleet label: threaded into trace ids, /healthz, and /metrics so a
        # ReplicaManager pooling N processes can attribute every request and
        # metric line to the replica that served it. Not part of the build
        # fingerprint — replicas with different ids but the same config are
        # interchangeable by design
        self.replica_id = str(replica_id) if replica_id else None
        # the unified tracing recorder: counters always mirror into it; when
        # its spans are enabled (``serving.trace_log`` / an explicit
        # TraceRecorder(spans_enabled=True)), every request gets a
        # correlated trace covering validate -> queue_wait -> batch ->
        # device -> decode, returned in the response meta. Default is a
        # counters-only recorder OWNED by this service (not the process
        # default): record telemetry must report this service's activity,
        # not whatever else instrumented the process
        self.recorder = (
            recorder if recorder is not None else TraceRecorder(spans_enabled=False)
        )
        self.metrics = (
            metrics
            if metrics is not None
            else ServiceMetrics(window=metrics_window, recorder=self.recorder)
        )
        self.stream = stream
        # build identity + per-domain provenance: handwritten class, spec
        # (with the spec's content hash — the revision two replicas must
        # agree on to share AOT executables), or generated family
        self._build = dict(
            build_identity(self.domains),
            domain_origins=_domain_origins(self.domains),
        )
        self.clock = clock or time.monotonic
        self.menu = BucketMenu(bucket_sizes)
        # SLO substrate (observability.slo): per-(domain, stage) latency
        # histograms + shed/deadline attribution. Pure host-side counts —
        # ``slo_capture`` off and on share every compile and dispatch
        # bit-identically (the tier-1 smoke pins it)
        self.slo = SloTracker(bounds=slo_buckets, enabled=slo_capture)
        # ledger-backed capacity model (observability.capacity): fed one
        # sample per pure-run batch dispatch, published on /healthz. Same
        # injectable clock as the batcher and every SLO stage — batch
        # completion timestamps and run_s durations must share one clock
        # domain or the utilization span mixes bases under a fake clock
        self.capacity = CapacityModel(window=capacity_window, clock=self.clock)
        # QoS layer (serving.qos): None = the exact pre-QoS request path
        # (no class lanes, no admission, no streams — bit-identical, zero
        # extra compiles/dispatches by construction). With a QosPolicy,
        # the batcher grows class lanes, admission prices each request
        # from the capacity model before enqueue, and MoEvA requests can
        # stream solved rows as they park.
        self.qos = qos
        self.admission = (
            AdmissionController(qos, self.capacity, clock=self.clock)
            if qos is not None and qos.admission
            else None
        )
        self.streams = (
            StreamRegistry() if qos is not None and qos.streaming else None
        )
        self.batcher = Microbatcher(
            self.menu,
            max_delay_s=max_delay_s,
            max_queue_rows=max_queue_rows,
            metrics=self.metrics,
            slo=self.slo,
            clock=self.clock,
            start=start,
            # honest 429 Retry-After: predicted drain time of the queued
            # rows at the capacity window's sustainable row rate
            retry_after_fn=self.capacity.retry_after_s,
            qos=qos,
        )
        # black-box flight recorder (observability.flightrec): a bounded
        # ring of completed request journeys fed from the done-callback —
        # host-side dict appends only, so flight_ring on/off shares every
        # compile and dispatch bit-identically. 0 disables the ring.
        self.flight = FlightRecorder(capacity=flight_ring)
        self.flight_dir = flight_dir
        # incident detector (observability.incidents): predicate passes
        # over the SLO/capacity snapshots the service already assembles,
        # rate-limited to one pass per ``incident_tick_s`` on the
        # done-callback path — pure host-side comparisons
        self.incidents = IncidentDetector(
            enabled=incident_detection, clock=self.clock
        )
        self.incident_tick_s = float(incident_tick_s)
        self._incident_next_t = self.clock() + self.incident_tick_s
        self._resolved: dict[tuple, _Resolved] = {}
        #: boot-time warmup report (None until :meth:`prewarm` ran)
        self._prewarm_report: dict | None = None
        # per-domain attack-quality aggregation (MoEvA dispatches): last
        # engine-judged sample + a dispatch count, computed host-side from
        # the already-fetched result objectives — zero device work
        self._quality: dict[str, dict] = {}
        self._lock = threading.Lock()
        # misses resolve under one lock: the process-wide ENGINES/ARTIFACTS
        # caches are grid-runner substrate (single-threaded there) and not
        # thread-safe — a racing pair of resolves would build two engine
        # instances for one key and compile every bucket shape twice
        self._resolve_lock = threading.Lock()
        self._t0 = time.time()

    # -- resolution ----------------------------------------------------------
    def _pseudo_config(self, cfg: dict, req: AttackRequest) -> dict:
        """The experiment-config equivalent of this request — the dict the
        batch runners' engine-cache key builders understand."""
        pseudo = {
            "project_name": cfg["project_name"],
            "paths": dict(cfg["paths"]),
            "norm": cfg.get("norm", "inf"),
            "attack_name": req.attack,
            "loss_evaluation": req.loss_evaluation,
            "budget": int(req.budget),
            "system": dict(cfg.get("system", {"mesh_devices": 0})),
            # moeva engine-shape defaults, overridable per domain/request
            "n_pop": cfg.get("n_pop", 16),
            "n_offsprings": cfg.get("n_offsprings", 8),
        }
        for k in (
            # domain-as-data: a domain served from a spec file forwards the
            # path so load_constraints compiles it (and keys caches on it)
            "spec",
            "constraints_optim",
            "nb_random",
            "archive_size",
            "init",
            "init_eps",
            "init_ratio",
            "assoc_block",
            "max_states_per_call",
            # MoEvA early exit: host-side dispatch knobs — they enter the
            # batch key (a request opting in must not share a dispatch with
            # strict-mode batch-mates) but not the engine-cache key
            "early_stop_check_every",
            "early_stop_threshold",
            "early_stop_eps",
        ):
            if k in cfg:
                pseudo[k] = cfg[k]
        if req.params:
            pseudo.update(req.params)
        return pseudo

    def resolve(self, req: AttackRequest) -> _Resolved:
        """Request -> cached dispatch closure (artifacts + engine from the
        process-wide caches; one closure per run config)."""
        cfg = self.domains.get(req.domain)
        if cfg is None:
            raise InvalidRequest(
                f"unknown domain {req.domain!r}; serving {sorted(self.domains)}"
            )
        if req.attack not in ("pgd", "moeva"):
            raise InvalidRequest(f"unknown attack family {req.attack!r}")
        if req.attack == "pgd" and "sat" in req.loss_evaluation:
            raise InvalidRequest(
                "the MILP repair stage is not served (host-side solver, "
                "unbounded latency); run PGD+SAT through the batch runners"
            )
        pseudo = self._pseudo_config(cfg, req)
        eps = float(req.eps)
        if req.eps_step is not None:
            eps_step = float(req.eps_step)
        else:
            # the batch runner's defaults (experiments/pgd.py): AutoPGD uses
            # eps/3, plain PGD a fixed 0.1 — served numbers must match what
            # a runner would commit for the same coordinates
            eps_step = eps / 3 if "autopgd" in req.loss_evaluation else 0.1
        # ε/ε-step are batch-wide runtime scalars for PGD, so they partition
        # batches there; the MoEvA dispatch never reads them, so keying on
        # them would only fragment coalescing
        key_scalars = (eps, eps_step) if req.attack == "pgd" else (None, None)
        key = (req.domain, req.attack, get_dict_hash(pseudo)) + key_scalars
        with self._lock:
            res = self._resolved.get(key)
        if res is not None:
            return res
        with self._resolve_lock:
            return self._resolve_miss(key, pseudo, req, eps, eps_step)

    def _resolve_miss(
        self, key: tuple, pseudo: dict, req: AttackRequest, eps: float, eps_step: float
    ) -> _Resolved:
        with self._lock:
            res = self._resolved.get(key)
        if res is not None:  # lost the race to an identical resolve
            return res

        constraints = common.load_constraints(pseudo)
        scaler = common.load_scaler(pseudo)
        surrogate = common.load_surrogate(pseudo)
        n_features = constraints.schema.n_features

        if req.attack == "pgd":
            from ..experiments.pgd import _cached_attack

            engine = _cached_attack(pseudo, surrogate, constraints, scaler)
            engine.seed = self.seed
            # mirror the batch runner's open-ball ε (experiments/pgd.py):
            # served numbers must match what a runner would commit
            eps_run = eps - 0.000001
            budget = int(req.budget)
            feature_types = constraints.get_feature_type()
            bit_identical = (
                type(engine) is ConstrainedPGD
                and engine.num_random_init == 0
                and not engine.record_loss
            )
            domain_name = req.domain
            strategy = req.loss_evaluation

            def dispatch(x_batch: np.ndarray) -> np.ndarray:
                # the ambient per-batch trace the microbatcher installed
                # around this call (None when tracing is off)
                bt = current_trace()
                # the poisoned-batch isolation boundary: a constraint-invalid
                # row fails the whole bucket here, before any device work
                constraints.check_constraints_error(x_batch)
                traces0 = engine.trace_count
                x_scaled = np.asarray(scaler.transform(x_batch))
                y = np.asarray(surrogate.predict_proba(x_scaled)).argmax(-1)
                # two clock reads: trace spans stay on perf_counter (the
                # PR-4 span timebase), SLO/capacity durations ride the
                # injectable self.clock like every other stage in the
                # histogram family
                t0 = time.perf_counter()
                t0c = self.clock()
                x_adv = engine.generate(
                    x_scaled, y, eps=eps_run, eps_step=eps_step, max_iter=budget
                )
                traced = engine.trace_count - traces0
                dur = self.clock() - t0c
                self.metrics.count("compiles", traced)
                _record_device_span(bt, engine, traces0, t0)
                self._note_device_run(
                    domain_name, strategy, budget, engine, traced, dur,
                    rows=int(x_batch.shape[0]),
                )
                td = self.clock()
                with maybe_span(bt, "decode"):
                    x_adv = np.asarray(scaler.inverse(x_adv))
                    out = round_ints_toward_initial(
                        x_adv, x_batch, feature_types
                    )
                # request-weighted like device_run: every rider of the
                # batch experienced this decode. No ambient context =
                # execute_direct oracle, not serving traffic — skip.
                riders = current_ledger_context().get("batch_requests")
                if riders is not None:
                    self.slo.observe(
                        domain_name, "decode", self.clock() - td,
                        count=int(riders),
                    )
                return out

            def prewarm_dispatch(x_batch: np.ndarray) -> None:
                # shape-only warmup: ε/ε-step/budget are runtime scalars
                # of the compiled program, so zero-rows at the bucket
                # shape compile (or AOT-load) the identical executable
                x_scaled = np.asarray(scaler.transform(x_batch))
                y = np.asarray(surrogate.predict_proba(x_scaled)).argmax(-1)
                engine.generate(
                    x_scaled, y, eps=eps_run, eps_step=eps_step,
                    max_iter=budget,
                )

            chunk = None
        else:  # moeva
            from ..experiments.moeva import _cached_engine

            engine = _cached_engine(pseudo, surrogate, constraints, scaler)
            budget = int(req.budget)
            seed = self.seed
            bit_identical = False  # chunk/batch-shaped PRNG key folds
            # per-request early-exit opt-in (via ``params``): easy rows stop
            # paying for the full budget — lower p99 for solved-fast batches.
            # Compaction repacks down the SAME bucket menu the batcher pads
            # up to, so early-exit dispatches add no new executable shapes.
            early_stop = int(pseudo.get("early_stop_check_every", 0) or 0)
            es_threshold = float(pseudo.get("early_stop_threshold", 0.5))
            es_eps = float(pseudo.get("early_stop_eps", np.inf))
            domain_name = req.domain
            strategy = req.loss_evaluation

            def dispatch(x_batch: np.ndarray) -> np.ndarray:
                bt = current_trace()
                constraints.check_constraints_error(x_batch)
                traces0 = engine.trace_count
                # host-side dispatch knobs, per the engine-cache contract
                engine.n_gen = budget
                engine.seed = seed
                engine.early_stop_check_every = early_stop
                engine.early_stop_threshold = es_threshold
                engine.early_stop_eps = es_eps
                engine.compaction_buckets = self.menu.sizes
                # a batch runner sharing this cached engine may have left
                # its quality capture on; the serving path computes its
                # sample host-side from result.f instead (below)
                engine.record_quality = False
                engine.quality_every = 0
                # the engine's gate progress events (generation index,
                # success fraction, active set, HBM) land in the batch trace
                engine.trace = bt
                # streaming partial results: the microbatcher put a
                # partial router in the ambient context iff some rider of
                # THIS batch streams — the engine then surfaces solved
                # rows at each gate flush. No router (the common case) =
                # sink stays None = the engine's gate tail is unchanged.
                engine.partial_sink = current_ledger_context().get(
                    "partial_router"
                )
                # trace spans on perf_counter, SLO/capacity on the
                # injectable self.clock (see the pgd closure)
                t0 = time.perf_counter()
                t0c = self.clock()
                try:
                    result = engine.generate(x_batch, 1)
                finally:
                    engine.trace = None
                    engine.partial_sink = None
                traced = engine.trace_count - traces0
                dur = self.clock() - t0c
                self.metrics.count("compiles", traced)
                _record_device_span(
                    bt, engine, traces0, t0,
                    gens_executed=int(result.gens_executed),
                )
                self._note_device_run(
                    domain_name, strategy, budget, engine, traced, dur,
                    rows=int(x_batch.shape[0]),
                )
                # batch quality: engine-judged o-rates/violations over the
                # (bucket-padded) batch from the fetched objectives — numpy
                # only; lands in the per-domain gauges, /healthz, /metrics,
                # and (via the batch trace) every rider's meta.trace
                sample = sample_from_per_state(
                    int(result.gens_executed),
                    engine_quality_stats(
                        np.asarray(result.f, np.float64),
                        es_threshold,
                        es_eps / getattr(engine, "_f2_scale", 1.0),
                        xp=np,
                    ),
                )
                self._note_quality(domain_name, sample, bt)
                td = self.clock()
                with maybe_span(bt, "decode"):
                    out = np.asarray(result.x_ml)
                # see the pgd closure: skip the execute_direct oracle
                riders = current_ledger_context().get("batch_requests")
                if riders is not None:
                    self.slo.observe(
                        domain_name, "decode", self.clock() - td,
                        count=int(riders),
                    )
                return out

            def prewarm_dispatch(x_batch: np.ndarray) -> None:
                # mirror the real dispatch's engine knobs exactly (they
                # shape the segment schedule and therefore the compiled
                # lengths); synthetic rows skip constraint validation —
                # the executable depends on shapes, not values
                engine.n_gen = budget
                engine.seed = seed
                engine.early_stop_check_every = early_stop
                engine.early_stop_threshold = es_threshold
                engine.early_stop_eps = es_eps
                engine.compaction_buckets = self.menu.sizes
                engine.record_quality = False
                engine.quality_every = 0
                engine.trace = None
                engine.partial_sink = None
                engine.generate(x_batch, 1)

            chunk = engine.effective_states_chunk()

        mesh = engine.mesh
        if mesh is not None:
            # revalidate the menu against this domain's mesh: every bucket
            # must satisfy the states-axis divisibility contract
            BucketMenu(self.menu.sizes, mesh_size=mesh.size)
        execution = {
            "max_states_per_call": chunk,
            "mesh": describe_mesh(mesh),
            "bucket_menu": list(self.menu.sizes),
        }
        if req.attack == "moeva":
            # the early-exit mode travels with every served number, like the
            # metrics JSONs' execution block
            execution["early_stop_check_every"] = early_stop
        res = _Resolved(
            key=key,
            dispatch=dispatch,
            mesh=mesh,
            bit_identical=bit_identical,
            n_features=n_features,
            execution=execution,
            meta={
                "domain": req.domain,
                "attack": req.attack,
                "loss_evaluation": req.loss_evaluation,
                # ε/ε-step are PGD coordinates; the MoEvA dispatch never
                # reads them, and since they are not in the moeva resolve
                # key the first resolver's values would otherwise leak into
                # every later response's meta
                "eps": eps if req.attack == "pgd" else None,
                "eps_step": eps_step if req.attack == "pgd" else None,
                "budget": int(req.budget),
            },
            prewarm=prewarm_dispatch,
        )
        with self._lock:
            self._resolved[key] = res
        return res

    # -- prewarm -------------------------------------------------------------
    def prewarm(self, specs: list[dict] | None = None, buckets=None) -> dict:
        """Load the bucket menu's executables BEFORE the first request
        lands (``tools/serve.py --prewarm`` / config ``serving.prewarm``):
        for each spec — default: one plain-PGD ``flip`` program per served
        domain — dispatch a shape-only warmup at every menu size, so the
        replica's executables come out of the persistent AOT cache (or
        compile once and land in it) at boot instead of on the first
        caller's clock. The elapsed wall minus the compile/load seconds
        the cold ledger booked is recorded as its ``device_warmup`` phase;
        the report (executables, aot hit/store deltas) lands on /healthz
        ``prewarm``. Boot-time only: engines are single-dispatch objects,
        so this must not run concurrently with live traffic.

        A spec is ``{"domain", "attack", "loss_evaluation", "eps",
        "budget", "params"}`` (all but ``domain`` optional) — config
        ``serving.prewarm`` accepts ``true`` (the default specs) or a
        list of such dicts."""
        from ..observability import get_aot_cache

        cs = get_coldstart()
        if specs is None:
            specs = [
                {"domain": d, "attack": "pgd", "loss_evaluation": "flip"}
                for d in sorted(self.domains)
            ]
        sizes = [int(b) for b in (buckets or self.menu.sizes)]
        ledger0 = get_ledger().summary()
        aot0 = get_aot_cache().state()
        compile0 = cs.compile_phase_seconds()
        t0 = time.perf_counter()
        warmed = []
        for spec in specs:
            req = AttackRequest(
                domain=spec["domain"],
                x=np.zeros((1, 1)),  # resolve() never reads the rows
                attack=spec.get("attack", "pgd"),
                loss_evaluation=spec.get("loss_evaluation", "flip"),
                eps=float(spec.get("eps", 0.1)),
                budget=int(spec.get("budget", 8)),
                params=spec.get("params"),
            )
            res = self.resolve(req)
            for b in sizes:
                res.prewarm(np.zeros((b, res.n_features)))
            warmed.append(
                {
                    "domain": req.domain,
                    "attack": req.attack,
                    "loss_evaluation": req.loss_evaluation,
                    "buckets": sizes,
                }
            )
        elapsed = time.perf_counter() - t0
        # the warmup wall minus the compile/load seconds note_compile
        # already booked IS the device_warmup phase (the phases must
        # decompose the cold wall, not double-count it — same arithmetic
        # as bench.py's serving warmup loop)
        cs.record_phase(
            "device_warmup",
            max(elapsed - (cs.compile_phase_seconds() - compile0), 0.0),
        )
        summary = get_ledger().summary()
        aot1 = get_aot_cache().state()
        report = {
            "seconds": round(elapsed, 3),
            "specs": warmed,
            "executables": summary["executables"] - ledger0["executables"],
            "aot_hits": (aot1.get("hits") or 0) - (aot0.get("hits") or 0),
            "aot_stored": (aot1.get("stores") or 0) - (aot0.get("stores") or 0),
        }
        with self._lock:
            self._prewarm_report = report
        return report

    def _note_device_run(
        self, domain: str, strategy: str, budget: int, engine, traced: int,
        dur: float, *, rows: int,
    ) -> None:
        """Feed one batch dispatch into the SLO histograms and the capacity
        model — pure-run dispatches only: a compile-bearing dispatch's
        wall-clock is compile time, which would poison both the device_run
        tail and the sustainable-QPS estimate (compiles are already counted
        and ledgered separately)."""
        if traced:
            return
        # batch composition the microbatcher pushed for the ledger.
        # batch_rows is the REAL served row count — the closure's x_batch
        # is bucket-padded, and publishing padded rows would overstate
        # capacity by 1/occupancy. No ambient context means the
        # direct-dispatch oracle (execute_direct, bit-identity checks):
        # NOT serving traffic — feeding it would skew the latency tails
        # and the capacity window with padded, un-coalesced dispatches.
        ctx = current_ledger_context()
        if "batch_requests" not in ctx:
            return
        requests = int(ctx["batch_requests"])
        # per-batch stage, request-weighted: every rider of the batch
        # experienced this device run, exactly like the batcher's
        # per-rider dispatch observations — one population per family
        self.slo.observe(domain, "device_run", dur, count=requests)
        counts = getattr(engine, "last_run_dispatch_counts", None)
        executables = counts or list(
            getattr(engine, "last_run_executables", ())
        )
        self.capacity.note_batch(
            domain,
            strategy=strategy,
            bucket=ctx.get("bucket", rows),
            budget=int(budget),
            requests=requests,
            rows=int(ctx.get("batch_rows", rows)),
            run_s=dur,
            flops=get_ledger().flops_for(executables) if executables else None,
            qos_classes=ctx.get("batch_classes"),
        )

    # -- incidents & flight recorder ----------------------------------------
    def _incident_evidence(self) -> dict:
        """The correlated evidence an incident freezes at open time: top
        gap stages, recent recompile causes, the shed matrix, queue depth,
        and the tail of the flight ring (the offending request journeys).
        All snapshots the service already assembles — pure host reads."""
        return {
            "replica_id": self.replica_id,
            "top_gap_stages": get_gap_tracker().gaps_block().get(
                "top_gap_stages"
            ),
            "recompile_causes": get_ledger().recompile_causes[
                -self.RECOMPILE_CAUSES_SHOWN :
            ],
            "shed": self.slo.shed_block(),
            "queue_depth_rows": self.batcher.queue_depth_rows(),
            "flight_tail": self.flight.entries()[-8:],
        }

    def _incident_tick(self) -> None:
        """Rate-limited predicate pass on the done-callback path: at most
        one evaluation per ``incident_tick_s`` of the injectable clock."""
        if not self.incidents.enabled:
            return
        now = self.clock()
        with self._lock:
            if now < self._incident_next_t:
                return
            self._incident_next_t = now + self.incident_tick_s
        self.incidents.tick(
            slo=self.slo.snapshot(),
            capacity=self.capacity.snapshot(),
            evidence_fn=self._incident_evidence,
        )

    def flight_dump(
        self,
        reason: str,
        out_dir: str | None = None,
        extra: dict | None = None,
    ) -> dict:
        """Serialize the black box atomically to
        ``<flight_dir>/flight_<replica>_<reason>.json``: the completed-
        request ring plus what was IN FLIGHT (the batcher's queued and
        dispatching view) and the ledger/capacity/gap/shed/incident
        snapshots at dump time. The fleet manager harvests this over
        ``POST /debug/flight`` just before SIGKILL; ``tools/serve.py``
        dumps on SIGTERM — either way a chaos ``lost_dead_replica`` row
        becomes attributable to the exact batch it died in."""
        label = self.replica_id or "service"
        safe = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in str(reason)
        )
        path = os.path.join(
            out_dir or self.flight_dir, f"flight_{label}_{safe}.json"
        )
        extra_block = {
            "inflight": self.batcher.inflight_view(),
            "ledger": get_ledger().summary(),
            "capacity": self.capacity.snapshot(),
            "gaps": get_gap_tracker().gaps_block(),
            "shed": self.slo.shed_block(),
            "incidents": incidents_block(self.incidents),
        }
        if extra:
            extra_block.update(extra)
        return self.flight.dump(
            path, reason=str(reason), replica_id=self.replica_id,
            extra=extra_block,
        )

    def _validate(self, req: AttackRequest, res: _Resolved) -> np.ndarray:
        x = np.asarray(req.x, dtype=np.float64)
        if x.ndim != 2 or x.shape[0] < 1:
            raise InvalidRequest(
                f"x must be (n_rows >= 1, n_features), got {x.shape}"
            )
        if x.shape[1] != res.n_features:
            raise InvalidRequest(
                f"x has {x.shape[1]} features, domain {req.domain!r} "
                f"expects {res.n_features}"
            )
        return x

    # -- request path --------------------------------------------------------
    def submit(
        self,
        req: AttackRequest,
        on_partial: Callable | None = None,
        trace_context: dict | None = None,
    ):
        """Validate + enqueue; returns a Future of ``(x_adv, meta)``.

        Raises :class:`InvalidRequest` / :class:`~.batcher.QueueFull` /
        :class:`~.batcher.RequestTooLarge` synchronously; queued failures
        (deadline, batch errors) surface through the future.
        ``on_partial`` (streaming) receives ``(local_rows, x_rows, gen)``
        as this request's solved rows surface mid-dispatch — wired by
        :meth:`submit_stream`, which owns the stream bookkeeping.
        ``trace_context`` (a parsed ``X-Moeva2-Trace`` header —
        ``observability.fleetrace.parse_trace_context``) makes this
        request's trace a CONTINUATION of the router's: the trace id is
        adopted verbatim and the replica's root spans parent under the
        router's attempt span, so a merged fleet document shows one
        composed tree per request.
        """
        rid = req.request_id or uuid.uuid4().hex[:12]
        # class resolution is a dict lookup — do it before validate so
        # every shed path (invalid included) carries the class label
        qos_class = (
            self.qos.resolve(req.priority, req.tenant).name
            if self.qos is not None
            else None
        )
        # request-scoped trace (None when spans are off — the whole request
        # path then does no trace work at all, the overhead contract)
        # replica-labelled trace ids: a fleet's merged trace streams stay
        # attributable per process
        tid = f"{self.replica_id}:req-{rid}" if self.replica_id else f"req-{rid}"
        root_parent = None
        if trace_context:
            # distributed propagation: the router already minted the trace
            # id — adopt it verbatim (the merged fleet doc gets ONE process
            # row per request id) and hang this replica's root spans under
            # the router's attempt span id
            tid = trace_context.get("trace_id") or tid
            root_parent = trace_context.get("parent_span")
        trace = (
            Trace(
                self.recorder,
                trace_id=tid,
                name=f"{req.attack}/{req.domain}",
                root_parent=root_parent,
            )
            if self.recorder.spans_enabled
            else None
        )
        if trace is not None and trace_context:
            trace.event(
                "trace_adopted",
                hop=int(trace_context.get("hop") or 0),
                replica=self.replica_id,
            )
        # self.clock, not time.perf_counter: every stage feeding one
        # histogram family must share the injectable clock domain, or a
        # fake-clock test (the batcher's start=False pattern) can steer
        # five stages while the sixth records real wall time
        t_val = self.clock()
        try:
            with maybe_span(
                trace, "validate", domain=req.domain, attack=req.attack
            ):
                res = self.resolve(req)
                x = self._validate(req, res)
        except InvalidRequest:
            # the one shed path reached BEFORE the domain is validated:
            # key it by the served domain only when it is one, else a
            # sentinel — a client posting random domain strings must not
            # mint unbounded (domain, cause, stage) keys / label series
            domain = (
                req.domain if req.domain in self.domains else "(unknown)"
            )
            self.slo.shed(domain, "invalid", "validate", qos_class=qos_class)
            raise
        self.slo.observe(
            req.domain, "validate", self.clock() - t_val, qos_class=qos_class
        )
        if self.admission is not None:
            # cost-predictive admission: one token from the (domain,
            # class) bucket, priced from the capacity model. A denial is
            # a 429 whose Retry-After is the class's predicted token
            # refill time — honest per-class backpressure, shed BEFORE
            # the request holds any queue slot.
            try:
                self.admission.admit(req.domain, qos_class)
            except AdmissionDenied as exc:
                self.metrics.count("admission_rejected")
                self.slo.shed(
                    req.domain, "rejected", "queue_wait", qos_class=qos_class
                )
                raise QueueFull(
                    str(exc), retry_after_s=exc.retry_after_s
                ) from exc
        t_submit = self.clock()
        try:
            fut = self.batcher.submit(
                res.key,
                res.dispatch,
                x,
                deadline_s=req.deadline_s,
                meta=dict(
                    res.meta,
                    request_id=rid,
                    rows=int(x.shape[0]),
                    bit_identical=res.bit_identical,
                    execution=res.execution,
                ),
                trace=trace,
                qos_class=qos_class,
                on_partial=on_partial,
            )
        except QueueFull:
            # shed attribution: backpressure consumed the request at the
            # queue boundary — it never held a slot
            self.slo.shed(
                req.domain, "rejected", "queue_wait", qos_class=qos_class
            )
            raise
        except RequestTooLarge:
            self.slo.shed(
                req.domain, "too_large", "validate", qos_class=qos_class
            )
            raise

        def _done(f):
            latency = self.clock() - t_submit
            ok = f.exception() is None
            self.metrics.observe("latency_s", latency)
            self.metrics.count("completed" if ok else "failed")
            if self.flight.enabled:
                # flight-recorder entry: the journey summary the black box
                # keeps (host-side dicts — never touches device work)
                entry = {
                    "request_id": rid,
                    "trace_id": tid,
                    "domain": req.domain,
                    "attack": req.attack,
                    "rows": int(x.shape[0]),
                    "status": "ok" if ok else type(f.exception()).__name__,
                    "latency_s": round(latency, 6),
                }
                if ok:
                    m = f.result()[1]
                    entry["batch_seq"] = m.get("batch_seq")
                    entry["bucket_size"] = m.get("bucket_size")
                self.flight.note(entry)
            if trace is not None:
                # end-to-end marker in the event stream (the span tree in
                # the response meta was already assembled at dispatch time)
                trace.event(
                    "request_done",
                    status="ok" if ok else type(f.exception()).__name__,
                    latency_s=round(latency, 6),
                )
            if self.stream is not None:
                self.stream.log_event(
                    "request",
                    id=rid,
                    domain=req.domain,
                    attack=req.attack,
                    rows=int(x.shape[0]),
                    status="ok" if ok else type(f.exception()).__name__,
                    latency_s=round(latency, 6),
                )
            self._incident_tick()

        fut.add_done_callback(_done)
        # the streaming path needs the request trace AFTER completion (to
        # stamp the time_to_first_solved event and re-render the tree onto
        # the FINAL chunk only); partial chunks stay trace-free
        fut.request_trace = trace
        return fut

    def attack(
        self,
        req: AttackRequest,
        timeout: float | None = None,
        trace_context: dict | None = None,
    ) -> AttackResponse:
        """Blocking request path: submit, wait, unwrap."""
        fut = self.submit(req, trace_context=trace_context)
        x_adv, meta = fut.result(timeout=timeout)
        return AttackResponse(
            request_id=meta["request_id"], x_adv=x_adv, meta=meta
        )

    def submit_stream(
        self, req: AttackRequest, trace_context: dict | None = None
    ):
        """Streaming request path: returns ``(ResultStream, Future)``.

        The stream surfaces this request's solved rows as the MoEvA
        early-exit gate parks them (chunked HTTP / incremental poll);
        the future resolves to the complete ``(x_adv, meta)`` exactly
        like :meth:`submit`. The final meta carries the streaming
        headline pair: ``time_to_first_solved_s`` (first partial chunk)
        next to ``time_to_complete_s``. A PGD request streams trivially
        (no gate -> no partials, the final result is the first chunk of
        truth); the same holds for a MoEvA request with early exit off.
        """
        if self.streams is None:
            raise InvalidRequest(
                "streaming is not enabled (serving.qos.streaming)"
            )
        rid = req.request_id or uuid.uuid4().hex[:12]
        req.request_id = rid
        n_rows = int(np.asarray(req.x).shape[0])
        stream = ResultStream(rid, n_rows, clock=self.clock)
        self.streams.add(stream)
        t_submit = self.clock()
        try:
            fut = self.submit(
                req, on_partial=stream.put, trace_context=trace_context
            )
        except BaseException as exc:
            stream.fail(exc)
            raise

        def _finish(f):
            exc = f.exception()
            if exc is not None:
                stream.fail(exc)
                return
            x_adv, meta = f.result()
            ttc = self.clock() - t_submit
            meta["time_to_complete_s"] = round(ttc, 6)
            meta["rows_streamed"] = stream.rows_streamed
            if stream.t_first_solved is not None:
                ttfs = stream.t_first_solved - t_submit
                meta["time_to_first_solved_s"] = round(ttfs, 6)
                self.metrics.observe("time_to_first_solved_s", ttfs)
            self.metrics.observe("time_to_complete_s", ttc)
            tr = getattr(f, "request_trace", None)
            if tr is not None and tr.enabled:
                # the streaming headline joins the trace as an event, and
                # the tree is re-rendered so it rides the FINAL chunk's
                # meta — partial chunks never carry trace data
                if stream.t_first_solved is not None:
                    tr.event(
                        "time_to_first_solved",
                        seconds=round(ttfs, 6),
                        rows_streamed=stream.rows_streamed,
                    )
                meta["trace"] = tr.tree()
            stream.finish(x_adv, meta)

        fut.add_done_callback(_finish)
        return stream, fut

    def execute_direct(
        self, req: AttackRequest, bucket: int | None = None
    ) -> np.ndarray:
        """The un-coalesced oracle: run this request's rows through the same
        dispatch pipeline, alone. With ``bucket``, the lone request is padded
        to that menu size — the serving bit-identity contract compares a
        coalesced request against exactly this: same compiled program, same
        shape, no batch-mates. Per-row results must match BIT-IDENTICALLY
        (every op in the served engines is per-row at a fixed shape).
        Without ``bucket``, rows run at their own shape (padded only to a
        mesh multiple, like the batch runners) — across *different* shapes
        XLA may pick differently-tiled kernels, so equality is only
        near-exact (~1e-5 in fp32), an engine property the serving layer
        inherits and documents rather than hides. Not safe concurrently
        with live traffic — engines are single-dispatch objects."""
        res = self.resolve(req)
        x = self._validate(req, res)
        if bucket is not None:
            x_run, n_orig = common.pad_states(x, res.mesh, bucket=bucket)
        else:
            x_run, n_orig = common.pad_states(x, res.mesh)
        return np.asarray(res.dispatch(x_run))[:n_orig]

    def _note_quality(self, domain: str, sample: dict, bt=None) -> None:
        """Fold one batch's engine-judged quality sample into the per-domain
        aggregation: gauges (scrapeable), the structured ``quality``
        snapshot section (labeled Prometheus gauges + /healthz), and — when
        the batch is traced — a ``quality`` event every riding request's
        ``meta.trace`` carries. Payloads round for display; the stored
        sample keeps full precision."""
        stored = {k: v for k, v in sample.items() if k != "per_state"}
        with self._lock:
            prev = self._quality.get(domain)
            self._quality[domain] = {
                "batches": (prev["batches"] if prev else 0) + 1,
                "last": stored,
            }
        self.metrics.gauge(
            f"quality_success_frac_{domain}", sample["success_frac"]
        )
        if bt is not None:
            bt.event(
                "quality",
                o7_rate=round(sample["success_frac"], 4),
                best_cv=round(sample["best_cv"], 6),
                gen=sample["gen"],
            )

    def quality_snapshot(self) -> dict:
        """Structured per-domain quality state: the last engine-judged
        sample per domain plus how many MoEvA batches contributed."""
        with self._lock:
            return {
                "by_domain": {
                    k: {"batches": v["batches"], "last": dict(v["last"])}
                    for k, v in self._quality.items()
                }
            }

    # -- introspection -------------------------------------------------------
    def healthz(self) -> dict:
        # mesh identity per domain: the configured device count always, plus
        # the actual `describe_mesh` once a request resolved the domain — a
        # load balancer comparing replicas can catch a mis-meshed one before
        # (and after) it takes traffic
        meshes = {
            name: {
                "mesh_devices": int(
                    (cfg.get("system") or {}).get("mesh_devices", 0) or 0
                ),
                "mesh": None,
                "resolved": False,
            }
            for name, cfg in self.domains.items()
        }
        with self._lock:
            resolved = list(self._resolved.values())
        for res in resolved:
            entry = meshes.get(res.meta["domain"])
            if entry is not None:
                entry["mesh"] = res.execution["mesh"]
                entry["resolved"] = True
        # one cold-block assembly per poll: build.jax_cache references its
        # persistent_cache section instead of re-scanning the cache dir
        cold = get_coldstart().cold_block()
        cache_keys = (
            "dir", "enabled", "error",
            "entries_start", "entries_now", "entries_added",
            # the serialized-executable tier (counters + counted load
            # failures) rides the same health surface — the aot-cache
            # degradation satellite's contract
            "aot",
        )
        jax_cache = (
            {k: cold["persistent_cache"].get(k) for k in cache_keys}
            if cold.get("enabled")
            else get_coldstart().cache_state()
        )
        return {
            "ok": True,
            "uptime_s": round(time.time() - self._t0, 3),
            # wall-clock at response assembly: the router's clock-offset
            # handshake (fleetrace.clock_offset) reads this against its
            # own send/receive instants at /healthz poll time, so merged
            # fleet traces align per-replica tracks without NTP trust
            "now_wall": round(time.time(), 6),
            # fleet label (None outside a fleet): the ReplicaManager keys
            # its fleet view by this, and refuses a replica whose id moved
            "replica_id": self.replica_id,
            "domains": sorted(self.domains),
            "queue_depth_rows": self.batcher.queue_depth_rows(),
            "bucket_menu": list(self.menu.sizes),
            # jax_cache: the persistent-compilation-cache state (dir,
            # enabled-vs-fallback, setup error) — a replica silently
            # recompiling every program because its cache dir failed to
            # mount shows here, not just in cold latency
            "build": dict(self._build, meshes=meshes, jax_cache=jax_cache),
            # cost-ledger summary next to the build identity: executable
            # count, total compile seconds, executable-cache hit ratio —
            # a replica that recompiles on every request shows up here
            # before it shows up in latency
            "ledger": get_ledger().summary(),
            # attack-quality summary: the last engine-judged o-rates per
            # domain — a replica whose served success rates drifted shows
            # up here before a caller complains
            "quality": self.quality_snapshot(),
            # ledger-backed capacity model: predicted FLOPs/request,
            # achieved FLOP/s, max sustainable QPS, utilization headroom
            # and calibration error per served domain — the number a load
            # balancer weights replicas by, and the basis ROADMAP item
            # 4's admission control prices requests against
            "capacity": self.capacity.snapshot(),
            # mesh view: per-device HBM watermarks, balance ratio, and the
            # collective census over every ledgered executable — a replica
            # whose hot loop grew a collective (or whose devices skewed)
            # shows here before it shows in throughput
            "mesh": mesh_snapshot(),
            # dispatch-gap view: device busy vs idle over the replica's
            # lifetime, overlap ratio per producer/executable, and the
            # host stages the idle attributes to — the replica-level
            # answer to "is the device waiting on the host?"
            "gaps": get_gap_tracker().snapshot(),
            # boot-time prewarm report (None = no prewarm ran): how many
            # executables the replica loaded before taking traffic, and
            # how many came out of the persistent AOT cache vs compiled
            "prewarm": self._prewarm_report,
            # replica warmup report: the startup-phase decomposition
            # (import, artifact builds, lower-vs-compile split,
            # per-executable persistent-cache hits/misses, time to first
            # dispatch) — why THIS replica came up slow
            "coldstart": cold,
            # shed/deadline attribution summary (full histograms stay on
            # /metrics): a replica shedding under backpressure vs losing
            # deadlines to device time reads differently here
            "slo": {
                "enabled": self.slo.enabled,
                "shed": self.slo.shed_block(),
            },
            # QoS layer state (None when no policy is wired): the class
            # taxonomy, per-class admission buckets, live stream count
            "qos": self.qos_snapshot(),
            # incident attribution: open/total incident counts and the
            # bounded history with frozen evidence — "p99 regressed"
            # becomes "p99 regressed because bucket-1024 recompiled"
            "incidents": incidents_block(self.incidents),
            # black-box state: ring occupancy + dump count (the dumps
            # themselves land in flight_dir, harvested by the fleet)
            "flight": self.flight.snapshot(),
            "caches": {
                "engine": dict(
                    common.ENGINES.stats(),
                    recompile_causes=common.ENGINES.recompile_causes[
                        -self.RECOMPILE_CAUSES_SHOWN :
                    ],
                ),
                "artifact": common.ARTIFACTS.stats(),
                "executable_recompile_causes": get_ledger().recompile_causes[
                    -self.RECOMPILE_CAUSES_SHOWN :
                ],
            },
        }

    #: most-recent recompile causes surfaced on /healthz (full, bounded
    #: lists stay on the caches/ledger themselves)
    RECOMPILE_CAUSES_SHOWN = 8

    def qos_snapshot(self) -> dict | None:
        """The QoS layer's introspection block (None = QoS off)."""
        if self.qos is None:
            return None
        return {
            "classes": {
                c.name: {
                    "priority": c.priority,
                    "weight": c.weight,
                    "rate_share": c.rate_share,
                    "p99_slo_ms": c.p99_slo_ms,
                }
                for c in self.qos.ordered()
            },
            "default_class": self.qos.default_class,
            "tenants": dict(self.qos.tenants),
            "admission": (
                self.admission.snapshot()
                if self.admission is not None
                else {"enabled": False}
            ),
            "streams": (
                self.streams.snapshot()
                if self.streams is not None
                else {"enabled": False}
            ),
        }

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["replica_id"] = self.replica_id
        snap["engine_cache"] = common.ENGINES.stats()
        snap["artifact_cache"] = common.ARTIFACTS.stats()
        snap["resolved_run_configs"] = len(self._resolved)
        snap["trace"] = {
            "spans_enabled": self.recorder.spans_enabled,
            "events_emitted": self.recorder.events_emitted,
        }
        # per-executable identity + cost + roofline: JSON here, labeled
        # gauges under /metrics?format=prom (observability.prom)
        snap["cost_ledger"] = get_ledger().cost_block()
        # per-domain attack quality: JSON here, labeled
        # moeva2_quality_o_rate{domain,objective} gauges under prom
        snap["quality"] = self.quality_snapshot()
        # SLO decomposition: per-(domain, stage) latency histograms +
        # shed attribution — native histogram families
        # (_bucket/_sum/_count) and shed counters under prom
        snap["slo"] = self.slo.snapshot()
        # capacity model: JSON here, labeled capacity gauges under prom
        snap["capacity"] = self.capacity.snapshot()
        # mesh view: device-labeled HBM/balance gauges and the collective
        # census under prom (observability.prom._mesh_lines)
        snap["mesh"] = mesh_snapshot()
        # dispatch-gap view: lifetime totals (per-window wall basis —
        # idle between requests is not a host stall) for the scalar
        # gauges, plus the ring-scoped recent detail whose gap list is
        # attributed against this service's recorded spans (spans off =>
        # honestly unattributed)
        snap["gaps"] = get_gap_tracker().snapshot(
            spans=spans_from_recorder(self.recorder)
        )
        snap["coldstart"] = get_coldstart().cold_block()
        if self.qos is not None:
            snap["qos"] = self.qos_snapshot()
        # incident + flight-recorder state: JSON here, incidents_open /
        # incidents_total{kind} / flight_ring_entries gauges under prom
        snap["incidents"] = incidents_block(self.incidents)
        snap["flight"] = self.flight.snapshot()
        return snap

    def close(self):
        self.batcher.stop()
