"""Offered-load sweep: the serving layer's bench harness.

Drives an in-process :class:`~.service.AttackService` at a ladder of
offered request rates and reports, per level, what the request path
actually delivered: achieved throughput (requests and rows per second),
client-observed latency quantiles, mean batch occupancy (how full the
fixed-shape buckets ran), and reject/timeout/failure counts. No network,
no subprocesses — this is the ``bench.py --serving`` record and the smoke
test's evidence that the microbatcher fills buckets instead of dispatching
per request.

Pacing is open-loop (submit at the offered rate regardless of completions,
the standard serving-bench discipline — closed-loop pacing hides queueing
collapse), with a bounded in-flight window as a safety valve so a
pathological level cannot accumulate unbounded futures. Arrivals default
to a seeded Poisson process (:func:`arrival_offsets`): a uniform
metronome never stacks arrivals and so under-measures queueing exactly
where the knee lives — the committed/gated ``knee_rps`` must be measured
under the memoryless bursts real independent callers produce.
``tools/loadgen.py --arrival`` exposes the same two disciplines over HTTP.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

from ..observability import (
    detect_knee,
    get_ledger,
    incidents_block,
    quality_block,
    slo_block,
    telemetry_block,
    validate_record,
)
from ..utils.observability import arrival_offsets, percentile
from .batcher import DeadlineExceeded, QueueFull, RequestTooLarge
from .service import AttackRequest, AttackService


def run_level(
    service: AttackService,
    make_request: Callable[[int], AttackRequest],
    offered_rps: float,
    n_requests: int,
    *,
    timeout_s: float = 120.0,
    max_in_flight: int = 1024,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    arrival: str = "poisson",
    seed: int = 42,
) -> dict:
    """One offered-load level: submit ``n_requests`` paced at
    ``offered_rps`` under the ``arrival`` process (seeded Poisson by
    default — see :func:`arrival_offsets`), wait for completion, report
    the level record."""
    latencies: list[float] = []
    occupancies: list[float] = []
    rows_done = 0
    rejected = timeouts = failed = 0
    in_flight: list[tuple[float, dict, object, str | None]] = []
    # per-QoS-class mirror of the level counters, keyed by the RESOLVED
    # class (what the service accounted, not the raw request field);
    # stays empty — and off the record — for a classless sweep
    per_class: dict[str, dict] = {}

    def slot(klass: str | None) -> dict | None:
        if klass is None:
            return None
        return per_class.setdefault(
            klass,
            {"completed": 0, "rejected": 0, "deadline_timeouts": 0,
             "failed": 0, "latencies": []},
        )

    def reap(block: bool):
        nonlocal rows_done, timeouts, failed
        remaining = []
        for t_sub, stamp, fut, klass in in_flight:
            if not block and not fut.done():
                remaining.append((t_sub, stamp, fut, klass))
                continue
            c = slot(klass)
            try:
                x_adv, meta = fut.result(timeout=timeout_s)
            except Exception as e:  # noqa: BLE001 — bench counts, not raises
                if isinstance(e, DeadlineExceeded):
                    timeouts += 1
                    if c is not None:
                        c["deadline_timeouts"] += 1
                else:
                    failed += 1
                    if c is not None:
                        c["failed"] += 1
                continue
            # completion was stamped by the done-callback, so lazy reaping
            # cannot inflate the measured latency
            lat = stamp.get("t_done", clock()) - t_sub
            latencies.append(lat)
            occupancies.append(meta["batch_occupancy"])
            rows_done += int(meta["rows"])
            if c is not None:
                c["completed"] += 1
                c["latencies"].append(lat)
        in_flight[:] = remaining

    offsets = arrival_offsets(arrival, offered_rps, n_requests, seed)
    t_start = clock()
    for i in range(n_requests):
        target = t_start + offsets[i]
        delta = target - clock()
        if delta > 0:
            sleep(delta)
        if len(in_flight) >= max_in_flight:
            reap(block=True)
        # latency origin is the SCHEDULED arrival, not the actual submit
        # instant: when the submit loop slips behind schedule (contended
        # host, in-flight reap stall) the backlog wait is latency the
        # offered load experienced — measuring from the submit instant
        # would silently drop it and overstate the knee (the same
        # coordinated-omission trap tools/loadgen.py charges from its
        # schedule to avoid). Unpaced (rate 0) has no schedule: measure
        # from submit, like loadgen's unpaced throughput-probe mode.
        t_sub = target if offered_rps > 0 else clock()
        req = make_request(i)
        # getattr: the SLO tests drive the sweep with minimal fake
        # services that predate the qos attribute
        qos = getattr(service, "qos", None)
        klass = (
            qos.resolve(req.priority, req.tenant).name
            if qos is not None
            else None
        )
        try:
            fut = service.submit(req)
        except (QueueFull, RequestTooLarge):
            rejected += 1
            c = slot(klass)
            if c is not None:
                c["rejected"] += 1
            continue
        stamp: dict = {}
        fut.add_done_callback(
            lambda f, s=stamp: s.__setitem__("t_done", clock())
        )
        in_flight.append((t_sub, stamp, fut, klass))
        if len(in_flight) % 64 == 0:
            reap(block=False)
    reap(block=True)
    duration = max(clock() - t_start, 1e-9)

    lat_sorted = sorted(latencies)
    n_ok = len(latencies)
    return {
        "offered_rps": offered_rps,
        # the arrival process the level was measured under: knees from
        # uniform-metronome levels are optimistic vs bursty traffic, so
        # the record says which discipline produced its numbers
        "arrival": arrival,
        "n_requests": n_requests,
        "completed": n_ok,
        "rejected": rejected,
        "deadline_timeouts": timeouts,
        "failed": failed,
        "duration_s": round(duration, 3),
        "throughput_rps": round(n_ok / duration, 2),
        "throughput_rows_s": round(rows_done / duration, 1),
        # the knee detector's drain-proof linearity basis: duration (and
        # so throughput_rps) includes the blocking drain of in-flight
        # requests after the last submission, which reads as a throughput
        # shortfall at high rates even when the service kept pace with
        # every arrival; the fraction of offered requests that completed
        # has no such tail
        "completion_ratio": round(n_ok / n_requests, 4) if n_requests else None,
        # None, not NaN, when a level completed nothing: the record is
        # strict JSON (RFC 8259 has no NaN) for jq and cross-language readers
        "p50_ms": round(percentile(lat_sorted, 0.50) * 1e3, 2) if n_ok else None,
        "p99_ms": round(percentile(lat_sorted, 0.99) * 1e3, 2) if n_ok else None,
        # the quantiles' sample size, annotated next to them: nearest-rank
        # p99 over n < 10 silently reports the max — consumers judge
        # confidence from n, not from the quantile alone
        "quantiles_n": n_ok,
        "mean_batch_occupancy": round(
            sum(occupancies) / len(occupancies), 4
        )
        if occupancies
        else None,
        # per-resolved-class view of the same level (QoS sweeps only):
        # the bench evidence that interactive held its SLO while the
        # low classes absorbed the overload
        **(
            {
                "by_class": {
                    k: {
                        "completed": c["completed"],
                        "rejected": c["rejected"],
                        "deadline_timeouts": c["deadline_timeouts"],
                        "failed": c["failed"],
                        "p50_ms": round(
                            percentile(sorted(c["latencies"]), 0.50) * 1e3, 2
                        )
                        if c["latencies"]
                        else None,
                        "p99_ms": round(
                            percentile(sorted(c["latencies"]), 0.99) * 1e3, 2
                        )
                        if c["latencies"]
                        else None,
                        "quantiles_n": len(c["latencies"]),
                    }
                    for k, c in sorted(per_class.items())
                }
            }
            if per_class
            else {}
        ),
    }


def offered_load_sweep(
    service: AttackService,
    make_request: Callable[[int], AttackRequest],
    offered_rps_levels: Sequence[float],
    n_requests: int,
    **kw,
) -> dict:
    """Sweep the rate ladder; returns the ``serving`` bench record:
    per-level results plus the service-side counter/cache totals."""
    # cost window: the record's telemetry.cost covers the sweep's own
    # dispatches (warmup compiles paid before this call stay out)
    ledger_mark = get_ledger().mark()
    from ..observability import get_gap_tracker, get_mesh_capture

    mesh_mark = get_mesh_capture().mark()
    # dispatch-gap window, same discipline: the record's telemetry.gaps
    # (overlap ratio + attributed gap stages) covers the sweep's own
    # device timeline, not the warmup's
    gaps_mark = get_gap_tracker().mark()
    # SLO window, same discipline: stage histograms and shed counts in
    # the record cover the sweep's traffic, not the warmup's
    slo_mark = service.slo.mark()
    levels = [
        run_level(service, make_request, rps, n_requests, **kw)
        for rps in offered_rps_levels
    ]
    # saturation knee: the highest offered rate still served linearly —
    # the record's measured max-sustainable-QPS, which bench_diff --slo
    # gates across the committed series
    knee = detect_knee(levels)
    snap = service.metrics_snapshot()
    # mesh identity of the sweep: the first resolved domain running on a
    # >1-device mesh (serving domains share one replica's devices) — a
    # mesh-backed sweep then carries telemetry.mesh like any other
    # multi-device record
    mesh_desc = next(
        (
            m.get("mesh")
            for m in service.healthz()["build"]["meshes"].values()
            if isinstance(m.get("mesh"), dict)
            and int(m["mesh"].get("devices") or 0) > 1
        ),
        None,
    )
    return validate_record(
        {
            "bucket_menu": list(service.menu.sizes),
            "max_delay_s": service.batcher.max_delay_s,
            "levels": levels,
            "counters": snap["counters"],
            "engine_cache": snap["engine_cache"],
            "latency": snap["streams"].get("latency_s"),
            "batch_occupancy": snap["streams"].get("batch_occupancy"),
            # the shared record schema every bench/grid/serving record
            # carries (observability.records)
            "execution": {
                "bucket_menu": list(service.menu.sizes),
                "max_delay_s": service.batcher.max_delay_s,
                "resolved_run_configs": snap["resolved_run_configs"],
                "mesh": mesh_desc,
            },
            # quality: the per-domain engine-judged aggregation the service
            # collected over the sweep's MoEvA batches (empty for a pure
            # PGD sweep — PGD quality lives in the runners' post-hoc rates)
            "telemetry": telemetry_block(
                recorder=service.recorder,
                ledger_since=ledger_mark,
                gaps_since=gaps_mark,
                mesh=mesh_desc,
                mesh_since=mesh_mark,
                quality=dict(
                    quality_block(judged="engine"),
                    **service.quality_snapshot(),
                ),
                # SLO block: per-stage latency histograms, shed/deadline
                # attribution, the detected knee, and the capacity model's
                # per-domain view — required on serving records by
                # validate_record, like telemetry.cost/quality
                slo=slo_block(
                    service.slo,
                    since=slo_mark,
                    knee=knee,
                    capacity=service.capacity.snapshot(),
                ),
                # incident attribution: whatever the service's detector
                # opened during the sweep (slo_breach/shed_spike/...),
                # evidence frozen at open time — required on serving
                # records by validate_record
                incidents=incidents_block(service.incidents),
            ),
        },
        "serving",
    )
