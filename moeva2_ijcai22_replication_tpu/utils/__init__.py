"""Shared experiment utilities.

Parity targets: ``/root/reference/src/utils/__init__.py`` (state filtering,
Lp-ball samplers, MCC threshold sweep, timing) — plus the layered config
system (:mod:`.config`), file IO (:mod:`.in_out`), metrics parsing
(:mod:`.metrics`) and phase timers (:mod:`.observability`).
"""

from __future__ import annotations

import time as _time
from functools import wraps

import numpy as np


def filter_initial_states(x: np.ndarray, start: int, size: int) -> np.ndarray:
    """Offset+count slice of the candidate set; ``size=-1`` keeps all
    (``src/utils/__init__.py:15-19``)."""
    return x[start : start + size] if size > -1 else x


def random_sample_hyperball(rng: np.random.Generator, n: int, d: int) -> np.ndarray:
    """Uniform samples in the unit L2 ball via the (d+2)-Gaussian trick
    (``src/utils/__init__.py:22-27``)."""
    u = rng.normal(0.0, 1.0, (n, d + 2))
    u = u / np.linalg.norm(u, axis=1, keepdims=True)
    return u[:, :d]


def sample_in_norm(
    rng: np.random.Generator, n_samples: int, d: int, eps: float, norm
) -> np.ndarray:
    """Uniform perturbations inside the ε-ball of the given Lp norm
    (``src/utils/__init__.py:30-41``)."""
    if norm in ("2", 2, 2.0):
        return random_sample_hyperball(rng, n_samples, d) * eps
    if norm in ("inf", np.inf):
        return (rng.random((n_samples, d)) * 2.0 - 1.0) * eps
    raise NotImplementedError(f"norm {norm!r}")


def find_best_threshold(y_test, y_proba, metric=None, step: float = 0.01):
    """Sweep decision thresholds, return (best_threshold, best_metric)
    (``src/utils/__init__.py:44-53``; default metric = MCC)."""
    if metric is None:
        from sklearn.metrics import matthews_corrcoef as metric
    nb_steps = int(1 / step)
    values = [
        metric(y_test, (y_proba >= t / nb_steps).astype(int))
        for t in range(nb_steps)
    ]
    best_i = int(np.argmax(values))
    return best_i / nb_steps, values[best_i]


def timing(f):
    """Wall-clock decorator (``src/utils/__init__.py:56-65``)."""

    @wraps(f)
    def wrap(*args, **kw):
        ts = _time.time()
        result = f(*args, **kw)
        print(f"func:{f.__name__!r} took: {_time.time() - ts:2.4f} sec")
        return result

    return wrap
