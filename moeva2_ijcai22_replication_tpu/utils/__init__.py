"""Shared experiment utilities.

Subsystems: layered config + hashing (:mod:`.config`), file IO
(:mod:`.in_out`), metrics-file flattening (:mod:`.metrics`), phase timers and
profiling (:mod:`.observability`). The reference's loose helper grab-bag
(``/root/reference/src/utils/__init__.py``) maps onto the framework as
follows: the Lp-ball samplers live on device in
:mod:`..attacks.moeva.initialisation`, the ``@timing`` decorator is
superseded by :class:`.observability.PhaseTimer`, candidate-set slicing is
runner plumbing (:func:`..experiments.common.load_candidates`), and the
decision-threshold sweep is :func:`best_threshold` below.
"""

from __future__ import annotations

import numpy as np


def best_threshold(y_true, y_proba, step: float = 0.01):
    """Pick the decision threshold maximising MCC, ``(threshold, score)``.

    Capability parity with the reference's per-threshold loop
    (``src/utils/__init__.py:44-53``), computed instead from one vectorised
    confusion-count table: predictions for all thresholds at once via an
    outer comparison, MCC from the four counts in closed form.
    """
    y_true = np.asarray(y_true, dtype=np.float64)
    nb_steps = int(1 / step)
    # i/nb_steps, not i*step: float accumulation would shift grid points
    # (35*0.01 != 0.35) and misclassify probabilities sitting exactly on one
    thresholds = np.arange(nb_steps) / nb_steps
    pred = y_proba[None, :] >= thresholds[:, None]  # (T, N)

    pos = y_true.sum()
    neg = y_true.size - pos
    tp = pred @ y_true
    fp = pred.sum(axis=1) - tp
    fn = pos - tp
    tn = neg - fp
    denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    mcc = np.where(denom > 0, (tp * tn - fp * fn) / np.where(denom > 0, denom, 1.0), 0.0)

    best = int(np.argmax(mcc))
    return float(thresholds[best]), float(mcc[best])
