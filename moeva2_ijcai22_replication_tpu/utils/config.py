"""Layered experiment configuration with hash identity.

Parity with the reference's config system
(``/root/reference/src/config_parser/config_parser.py``): repeatable
``-c`` (YAML/JSON file), ``-j`` (inline JSON), ``-p`` (dotted
``key.sub=value`` with regex-based scalar typing), deep-merged in order with
REPLACE semantics (later sources override; lists replace, dicts recurse);
``get_dict_hash`` = md5 of the sorted-key JSON dump — the experiment
identity used for output filenames and skip-if-done resumability.

Differences by design: configs are plain dicts passed to in-process runner
functions (no global argparse state), so grid runners compose and launch
points without subprocess/reparse round-trips; the hash function is kept
bit-identical so experiment identities survive the port.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re

_NUMBER_RE = re.compile(r"^[-+]?[0-9]*\.?[0-9]+(e[-+]?[0-9]+)?$")


def value_parser(value: str):
    """Scalar typing for ``-p`` values (``config_parser.py:11-16``):
    number-shaped strings become int/float via YAML, all else stays str."""
    if _NUMBER_RE.match(value) is None:
        return str(value)
    import yaml

    return yaml.safe_load(f"v: {value}")["v"]


def merge_config(a: dict, b: dict) -> dict:
    """Deep-merge ``b`` into ``a`` in place (mergedeep REPLACE semantics:
    dicts recurse, any other value — including lists — is replaced)."""
    for k, v in b.items():
        if isinstance(v, dict) and isinstance(a.get(k), dict):
            merge_config(a[k], v)
        else:
            a[k] = v
    return a


def dotted_to_dict(key: str, value) -> dict:
    """``a.b.c=v`` -> {"a": {"b": {"c": v}}} (``StrParser.key_value_to_dict``)."""
    head, _, rest = key.partition(".")
    return {head: dotted_to_dict(rest, value)} if rest else {head: value}


def load_config_file(path: str) -> dict:
    ext = os.path.splitext(path)[1]
    with open(path) as f:
        if ext in (".yaml", ".yml"):
            import yaml

            return yaml.full_load(f)
        if ext == ".json":
            return json.load(f)
    raise ValueError(f"Unknown config extension {ext!r} for {path}")


def parse_config(argv=None) -> dict:
    """Build a config dict from ``-c``/``-j``/``-p`` CLI arguments, merged in
    the order given per flag group (files, then inline JSON, then dotted
    overrides — ``get_config``, ``config_parser.py:70-99``)."""
    parser = argparse.ArgumentParser()
    parser.add_argument("-c", action="append", help="config file (yaml or json)")
    parser.add_argument("-j", action="append", help="inline json")
    parser.add_argument("-p", action="append", help="dotted key.sub=value override")
    args = parser.parse_args(argv)

    config: dict = {}
    for path in args.c or []:
        merge_config(config, load_config_file(path))
    for blob in args.j or []:
        merge_config(config, json.loads(blob))
    for kv in args.p or []:
        key, _, raw = kv.partition("=")
        merge_config(config, dotted_to_dict(key, value_parser(raw)))
    return config


def get_dict_hash(config: dict) -> str:
    """md5 of the sorted-key JSON dump — bit-identical to the reference
    (``config_parser.py:106-109``) so experiment identities match."""
    return hashlib.md5(
        json.dumps(config, sort_keys=True).encode("utf-8")
    ).hexdigest()


def save_config(config: dict, pre_path: str) -> str:
    """Snapshot the config beside its results as ``{pre_path}{hash}.yaml``
    (``config_parser.py:112-114``)."""
    import yaml

    path = f"{pre_path}{get_dict_hash(config)}.yaml"
    with open(path, "w") as f:
        yaml.dump(config, f)
    return path
