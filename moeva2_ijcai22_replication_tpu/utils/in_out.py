"""Result/artifact IO + model-artifact dispatch.

Parity: ``/root/reference/src/utils/in_out.py`` — json/npy/pickle helpers and
``load_model``'s extension dispatch (``:111-127``). The Keras branch returns
our device-native :class:`~moeva2_ijcai22_replication_tpu.models.io.Surrogate`
(imported weights) rather than a TF object; ``.joblib`` sklearn artifacts get
a host-side duck-typed wrapper with the same 1-column probability expansion
as the reference's ``Classifier`` (``moeva2/classifier.py:27-28``).
"""

from __future__ import annotations

import glob
import json
import os
import pickle

import numpy as np


# -- pickle ------------------------------------------------------------------
def pickle_from_file(path: str):
    with open(path, "rb") as f:
        return pickle.load(f)


def pickle_to_file(obj, path: str) -> None:
    with open(path, "wb") as f:
        pickle.dump(obj, f)


# -- numpy -------------------------------------------------------------------
def load_from_file(path: str) -> np.ndarray:
    return np.load(path)


def save_to_file(obj, path: str) -> None:
    with open(path, "wb") as f:
        np.save(f, obj)


def load_from_dir(input_dir: str, handler=None) -> list:
    out = []
    for i, file in enumerate(sorted(glob.glob(input_dir + "/*.npy"))):
        obj = np.load(file)
        out.append(obj if handler is None else handler(i, obj))
    return out


# -- json --------------------------------------------------------------------
def json_from_file(path: str):
    with open(path) as f:
        return json.load(f)


def json_to_file(obj, path: str) -> None:
    with open(path, "w") as f:
        json.dump(obj, f)


def json_from_dir(input_dir: str, handler=None) -> list:
    out = []
    for i, file in enumerate(sorted(glob.glob(input_dir + "/*.json"))):
        with open(file) as f:
            obj = json.load(f)
        out.append(obj if handler is None else handler(i, obj))
    return out


# -- model artifacts ---------------------------------------------------------
class HostClassifier:
    """Duck-typed host-side classifier (sklearn etc.) with the reference
    wrapper's probability-column expansion (``moeva2/classifier.py:4-41``).

    Host-only: cannot serve the jitted attack kernels (those need a
    :class:`Surrogate`); used for post-hoc evaluation of non-neural models.
    """

    def __init__(self, model):
        self.model = model

    def predict_proba(self, x) -> np.ndarray:
        probs = np.asarray(self.model.predict_proba(np.asarray(x)))
        if probs.shape[-1] == 1:
            probs = np.concatenate([1.0 - probs, probs], axis=-1)
        return probs


def load_model(path: str):
    """Extension dispatch (parity ``in_out.load_model``): ``.joblib`` ->
    sklearn host wrapper; ``.model`` dir / ``.msgpack``/``.flax`` ->
    device-native Surrogate."""
    if path.endswith(".joblib"):
        import joblib

        return HostClassifier(joblib.load(path))
    from ..models.io import load_classifier

    return load_classifier(path)


def ensure_dir(path: str) -> str:
    os.makedirs(path, exist_ok=True)
    return path
