"""Flatten ``metrics_*.json`` files into tabular records.

Parity: ``/root/reference/src/utils/metrics.py`` — one record per (run, ε)
for MoEvA (``objectives_list``), one per run for PGD (``objectives``).
"""

from __future__ import annotations


def parse_moeva(metrics: dict) -> list[dict]:
    config = metrics["config"]
    return [
        {
            "attack_name": config["attack_name"],
            "eps": config["eps_list"][i],
            **metrics["objectives_list"][i],
        }
        for i in range(len(metrics["objectives_list"]))
    ]


def parse_pgd(metrics: dict) -> dict:
    config = metrics["config"]
    return {
        "attack_name": config["loss_evaluation"],
        "eps": config["eps"],
        **metrics["objectives"],
    }


def parse_metrics(metrics: dict) -> list[dict]:
    config = metrics["config"]
    parsed = {
        "n_state": config["n_initial_state"],
        "config_hash": metrics["config_hash"],
        "project_name": config["project_name"],
        "budget": config["budget"],
        "time": metrics["time"],
        "model": config["paths"]["model"],
        "reconstruction": config.get("reconstruction", None),
    }
    if config["attack_name"] == "moeva":
        return [{**parsed, **rec} for rec in parse_moeva(metrics)]
    return [{**parsed, **parse_pgd(metrics)}]
