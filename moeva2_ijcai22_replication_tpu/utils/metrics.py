"""Post-hoc analysis: stream attack metrics files as flat tabular records.

Capability parity with the reference's metrics flattener
(``/root/reference/src/utils/metrics.py`` — one row per (run, ε) for MoEvA,
one per run for gradient attacks), reshaped as a single generator over a
results directory so analysis code can do
``pd.DataFrame(records("./out/attacks/lcld/rq1"))`` without touching file
layout details.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterator

#: run-level fields lifted from each metrics JSON into every record;
#: (record key, path into the metrics dict, default)
_RUN_FIELDS = (
    ("config_hash", ("config_hash",), None),
    ("project_name", ("config", "project_name"), None),
    ("n_state", ("config", "n_initial_state"), None),
    ("budget", ("config", "budget"), None),
    ("time", ("time",), None),
    ("model", ("config", "paths", "model"), None),
    ("reconstruction", ("config", "reconstruction"), None),
)


def _dig(tree: dict, path: tuple, default=None):
    for k in path:
        if not isinstance(tree, dict) or k not in tree:
            return default
        tree = tree[k]
    return tree


def iter_records(metrics: dict) -> Iterator[dict]:
    """Yield one flat record per success-rate table in one metrics dict.

    MoEvA runs carry ``objectives_list`` (one entry per ε of ``eps_list``);
    gradient runs carry a single ``objectives`` dict keyed by the loss
    variant. Both flatten to rows with the same columns.
    """
    base = {key: _dig(metrics, path, dflt) for key, path, dflt in _RUN_FIELDS}
    cfg = metrics.get("config", {})
    if "objectives_list" in metrics:
        for eps, objectives in zip(cfg["eps_list"], metrics["objectives_list"]):
            yield {
                **base,
                "attack_name": cfg["attack_name"],
                "eps": eps,
                **objectives,
            }
    else:
        yield {
            **base,
            "attack_name": cfg.get("loss_evaluation", cfg.get("attack_name")),
            "eps": cfg.get("eps"),
            **metrics.get("objectives", {}),
        }


def records(results_dir: str, pattern: str = "metrics_*.json") -> Iterator[dict]:
    """Stream flat records from every metrics file under ``results_dir``."""
    for path in sorted(glob.glob(os.path.join(results_dir, pattern))):
        with open(path) as fh:
            yield from iter_records(json.load(fh))


def main(argv=None):
    """``python -m ...utils.metrics <results_dir> [...]`` — print the flat
    success-rate table for one or more results directories (the post-hoc
    step the reference leaves to ad-hoc notebooks over its flattener)."""
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("dirs", nargs="+", help="results directories to scan")
    args = ap.parse_args(argv)

    rows = [r for d in args.dirs for r in records(d)]
    if not rows:
        print("no metrics files found")
        return
    cols = ["project_name", "attack_name", "budget", "n_state", "eps", "time"]
    header = cols + [f"o{i}" for i in range(1, 8)]
    table = [header] + [
        [
            f"{v:.4f}" if isinstance(v, float) else ("-" if v is None else str(v))
            for v in (r.get(c) for c in header)
        ]
        for r in rows
    ]
    widths = [max(len(row[i]) for row in table) for i in range(len(header))]
    for row in table:
        print("  ".join(cell.rjust(w) for cell, w in zip(row, widths)))


if __name__ == "__main__":
    main()
