"""Per-phase timers, cache/trace counters, and on-demand profiler traces.

The reference's observability is wall-clock spans written into
``metrics_*.json`` plus optional Comet/TensorBoard streams
(``04_moeva.py:70,89``, ``src/utils/comet.py``, SURVEY.md §5). TPU
equivalent: a :class:`PhaseTimer` collecting named spans *and* integer
counters that runners embed in the same metrics JSON (compile vs run vs
eval visible separately, cache hits attributable per point), and a
``jax.profiler`` trace context toggled by config — no external service.

Compile-vs-run attribution: attack engines count program (re)traces
(``engine.trace_count`` — their jitted python bodies run exactly once per
trace), so a runner wraps the attack dispatch in :func:`PhaseTimer.attack`
and the span lands in ``attack_compile`` when the call traced (its wall
clock includes tracing + XLA compilation or a persistent-cache load) and in
``attack_run`` when it re-used an executable. The grid report sums these
across points, which is what makes executable reuse visible: a healthy
ε-sweep shows one ``attack_compile`` span and N-1 ``attack_run`` spans.
"""

from __future__ import annotations

import contextlib
import time


class PhaseTimer:
    """Named wall-clock spans + counters; ``.spans``/``.counters`` are
    JSON-ready."""

    def __init__(self):
        self.spans: dict[str, float] = {}
        self.counters: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.time()
        try:
            yield
        finally:
            self.add(name, time.time() - t0)

    def add(self, name: str, seconds: float):
        self.spans[name] = self.spans.get(name, 0.0) + seconds

    def count(self, name: str, n: int = 1):
        self.counters[name] = self.counters.get(name, 0) + n

    @contextlib.contextmanager
    def attack(self, engine, name: str = "attack"):
        """Time an attack dispatch, splitting the span into
        ``{name}_compile`` / ``{name}_run`` by whether ``engine`` traced a
        new program during the call, and counting the traces."""
        traces0 = getattr(engine, "trace_count", 0)
        t0 = time.time()
        try:
            yield
        finally:
            dt = time.time() - t0
            traced = getattr(engine, "trace_count", 0) - traces0
            self.add(name, dt)
            self.add(f"{name}_compile" if traced else f"{name}_run", dt)
            if traced:
                self.count("traces", traced)


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """``jax.profiler.trace`` context when a directory is given, else no-op.

    Wired to config ``system.profile_dir``; view with TensorBoard or Perfetto.
    """
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
