"""Per-phase timers, cache/trace counters, and on-demand profiler traces.

The reference's observability is wall-clock spans written into
``metrics_*.json`` plus optional Comet/TensorBoard streams
(``04_moeva.py:70,89``, ``src/utils/comet.py``, SURVEY.md §5). TPU
equivalent: a :class:`PhaseTimer` collecting named spans *and* integer
counters that runners embed in the same metrics JSON (compile vs run vs
eval visible separately, cache hits attributable per point), and a
``jax.profiler`` trace context toggled by config — no external service.

Compile-vs-run attribution: attack engines count program (re)traces
(``engine.trace_count`` — their jitted python bodies run exactly once per
trace), so a runner wraps the attack dispatch in :func:`PhaseTimer.attack`
and the span lands in ``attack_compile`` when the call traced (its wall
clock includes tracing + XLA compilation or a persistent-cache load) and in
``attack_run`` when it re-used an executable. The grid report sums these
across points, which is what makes executable reuse visible: a healthy
ε-sweep shows one ``attack_compile`` span and N-1 ``attack_run`` spans.

Both classes are thin facades over the unified tracing subsystem
(``..observability``): a :class:`PhaseTimer` built with a ``trace`` also
emits each span into that run's id-correlated event stream, and a
:class:`ServiceMetrics` built with a ``recorder`` mirrors its counters and
gauges there — grid reports, bench records, and serving metadata share one
recorder. Spans are measured with ``time.perf_counter()`` (monotonic):
wall-clock steps under NTP adjustment must not corrupt a span.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time


class PhaseTimer:
    """Named monotonic-clock spans + counters; ``.spans``/``.counters`` are
    JSON-ready. With a ``trace`` (``observability.Trace``), every span also
    lands in the unified event stream under that run's id."""

    def __init__(self, trace=None):
        self.spans: dict[str, float] = {}
        self.counters: dict[str, int] = {}
        self.trace = trace

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float):
        self.spans[name] = self.spans.get(name, 0.0) + seconds
        if self.trace is not None:
            self.trace.record_span(name, seconds)

    def count(self, name: str, n: int = 1):
        self.counters[name] = self.counters.get(name, 0) + n

    @contextlib.contextmanager
    def attack(self, engine, name: str = "attack"):
        """Time an attack dispatch, splitting the span into
        ``{name}_compile`` / ``{name}_run`` by whether ``engine`` traced a
        new program during the call, and counting the traces."""
        traces0 = getattr(engine, "trace_count", 0)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            traced = getattr(engine, "trace_count", 0) - traces0
            self.add(name, dt)
            self.add(f"{name}_compile" if traced else f"{name}_run", dt)
            if traced:
                self.count("traces", traced)


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile on an already-sorted sample list (NaN when
    empty) — the one quantile definition shared by ServiceMetrics, the
    offered-load sweep, and the loadgen CLI.

    Confidence caveat: nearest-rank p99 over n < 10 samples IS the max
    (rank rounds to the last element) — a tail statistic in name only.
    Every exporter therefore annotates the sample size next to the
    quantile (``window_n`` in metrics snapshots, ``quantiles_n`` in
    sweep levels and loadgen summaries) so consumers judge confidence
    instead of trusting a max dressed as a p99."""
    if not sorted_vals:
        return float("nan")
    i = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def arrival_offsets(
    arrival: str, rps: float, n: int, seed: int = 42
) -> list[float]:
    """Submission-time offsets (seconds from start) for ``n`` requests at
    ``rps`` — the one arrival-process definition shared by the in-process
    offered-load sweep and the loadgen CLI (so HTTP and in-process knees
    are comparable). Computed up front, which is what makes the pacing
    open-loop: a struggling server cannot slow the offered load down.
    ``uniform`` is exact 1/rps spacing (a metronome; never stacks
    arrivals, flatters the queue near saturation); ``poisson`` draws
    seeded exponential inter-arrival gaps at the same mean rate — the
    memoryless bursts real independent callers produce, and the arrival
    process saturation/knee measurement requires. Lives here (not in
    ``serving.sweep``) so the loadgen client can import it without the
    engine stack."""
    period = 1.0 / rps if rps > 0 else 0.0
    if arrival == "poisson" and period > 0:
        import random

        rng = random.Random(seed)
        offsets, t = [], 0.0
        for _ in range(n):
            offsets.append(t)
            t += rng.expovariate(rps)
        return offsets
    return [i * period for i in range(n)]


class ServiceMetrics:
    """Thread-safe counters / gauges / sample streams for the serving layer.

    :class:`PhaseTimer` models one experiment's linear lifecycle; a service
    is concurrent and unbounded, so this keeps monotonic ``counters``
    (requests, rejects, timeouts, batches, compiles), point-in-time
    ``gauges`` (queue depth), and bounded ``observe`` streams (latency,
    batch occupancy) whose quantiles back ``/metrics``, the serving bench
    record, and per-response metadata. Streams keep the most recent
    ``window`` samples (quantiles reflect recent traffic, memory stays
    bounded) plus an unbounded count/sum so rates and means never lose
    history. With a ``recorder`` (``observability.TraceRecorder``), counters
    and gauges are mirrored into the unified stream — the always-on cheap
    instruments of the tracing contract; sample streams stay local (they
    are bounded, quantile-shaped state, not events).
    """

    def __init__(self, window: int = 8192, recorder=None):
        self._lock = threading.Lock()
        self._window = window
        self.recorder = recorder
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        self._samples: dict[str, collections.deque] = {}
        self._totals: dict[str, tuple[int, float]] = {}  # name -> (n, sum)

    def count(self, name: str, n: int = 1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n
        if self.recorder is not None:
            self.recorder.count(name, n)

    def gauge(self, name: str, value: float):
        with self._lock:
            self.gauges[name] = value
        if self.recorder is not None:
            self.recorder.gauge(name, value)

    def observe(self, name: str, value: float):
        with self._lock:
            dq = self._samples.get(name)
            if dq is None:
                dq = self._samples[name] = collections.deque(maxlen=self._window)
            dq.append(float(value))
            n, s = self._totals.get(name, (0, 0.0))
            self._totals[name] = (n + 1, s + float(value))

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            vals = sorted(self._samples.get(name, ()))
        return percentile(vals, q)

    def snapshot(self) -> dict:
        """JSON-ready state: counters, gauges, and per-stream
        ``{count, mean, p50, p99, window_n, max}`` (quantiles over the
        recent window — ``window_n`` is the sample count they were
        computed over, annotated so a p99 over a tiny window reads as
        the max it is; count/mean over the full history)."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            streams = {k: sorted(v) for k, v in self._samples.items()}
            totals = dict(self._totals)
        out: dict = {"counters": counters, "gauges": gauges, "streams": {}}
        for name, vals in streams.items():
            n, s = totals.get(name, (len(vals), sum(vals)))
            out["streams"][name] = {
                "count": n,
                "mean": (s / n) if n else None,
                "p50": percentile(vals, 0.50) if vals else None,
                "p99": percentile(vals, 0.99) if vals else None,
                "window_n": len(vals),
                "max": vals[-1] if vals else None,
            }
        return out


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """``jax.profiler.trace`` context when a directory is given, else no-op.

    Wired to config ``system.profile_dir``; view with TensorBoard or Perfetto.
    """
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
