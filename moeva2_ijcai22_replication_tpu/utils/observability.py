"""Per-phase timers and on-demand profiler traces.

The reference's observability is wall-clock spans written into
``metrics_*.json`` plus optional Comet/TensorBoard streams
(``04_moeva.py:70,89``, ``src/utils/comet.py``, SURVEY.md §5). TPU
equivalent: a :class:`PhaseTimer` collecting named spans that runners embed
in the same metrics JSON (compile vs run vs eval visible separately), and a
``jax.profiler`` trace context toggled by config — no external service.
"""

from __future__ import annotations

import contextlib
import time


class PhaseTimer:
    """Named wall-clock spans; ``.spans`` is JSON-ready."""

    def __init__(self):
        self.spans: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.time()
        try:
            yield
        finally:
            self.spans[name] = self.spans.get(name, 0.0) + time.time() - t0


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """``jax.profiler.trace`` context when a directory is given, else no-op.

    Wired to config ``system.profile_dir``; view with TensorBoard or Perfetto.
    """
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
