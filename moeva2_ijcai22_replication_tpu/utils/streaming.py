"""Experiment metric streaming: the Comet-ML-equivalent event channel.

Capability parity with the reference's observability streams
(``/root/reference/src/utils/comet.py:6-27`` experiment init + the
per-iteration metric logging in ``pgd/classifier.py:183-217,261-331`` and
``atk.py:137-144``) — re-designed for a jit-compiled world: instead of a
per-iteration Python callback into a network SDK (impossible inside a
compiled ``fori_loop``, and the reason the reference's PGD runs at Python
speed), engines record history tensors on device and the runner streams
them *post-hoc* as structured events. The transport is an append-only local
JSONL file — greppable, pandas-loadable, and rsync-able to any dashboard —
rather than a hosted service with an API key.

Events are one JSON object per line:
``{"t": <unix>, "event": "start"|"params"|"metric"|"end", ...}``;
metrics carry ``name``, ``value``, and optional ``step``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Iterator


class ExperimentStream:
    """Append-only JSONL event stream for one experiment run."""

    def __init__(self, path: str, name: str = "", enabled: bool = True):
        self.path = path
        self.enabled = enabled
        self._fh = None
        if enabled:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # overwrite: every sibling artifact (metrics JSON, npy, CSV) is
            # keyed by config hash and overwritten on re-run; a re-run's
            # events must not mix with the previous run's
            self._fh = open(path, "w", buffering=1)
            self._emit({"event": "start", "name": name})

    # -- plumbing -----------------------------------------------------------
    def _emit(self, obj: dict):
        if self._fh is None:
            return
        obj = {"t": round(time.time(), 3), **obj}
        self._fh.write(json.dumps(obj, default=_jsonable) + "\n")

    # -- API (comet.py surface: log_parameters / log_metric) ----------------
    def log_parameters(self, params: dict):
        self._emit({"event": "params", "params": params})

    def log_metric(self, name: str, value, step: int | None = None):
        ev: dict[str, Any] = {"event": "metric", "name": name, "value": value}
        if step is not None:
            ev["step"] = step
        self._emit(ev)

    def log_event(self, kind: str, **fields):
        """Generic structured event (serving uses this as a request log:
        ``{"event": "request", "id": ..., "status": ..., "latency_s": ...}``
        — same transport, same readers as the experiment streams)."""
        self._emit({"event": kind, **fields})

    def log_series(self, name: str, values, start_step: int = 0):
        """Stream a recorded per-step history tensor as one metric event per
        step — the post-hoc equivalent of the reference's per-iteration
        Comet calls from inside the attack loop."""
        for i, v in enumerate(values):
            self.log_metric(name, v, step=start_step + i)

    def end(self):
        if self._fh is not None:
            self._emit({"event": "end"})
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()


def _jsonable(x):
    import numpy as np

    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, np.ndarray):
        return x.tolist()
    return str(x)


def read_events(path: str) -> Iterator[dict]:
    with open(path) as fh:
        for line in fh:
            if line.strip():
                yield json.loads(line)


def stream_for(config: dict, mid_fix: str, config_hash: str) -> ExperimentStream:
    """Runner hook: a stream keyed like the metrics artifacts, enabled by the
    config's ``streaming`` flag (the reference's ``comet:`` toggle)."""
    enabled = bool(config.get("streaming"))
    out_dir = config.get("dirs", {}).get("results", ".")
    return ExperimentStream(
        f"{out_dir}/events_{mid_fix}_{config_hash}.jsonl",
        name=f"{config.get('project_name', '')}:{mid_fix}",
        enabled=enabled,
    )
