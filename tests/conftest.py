"""Test harness: force a virtual 8-device CPU mesh and fp64 before JAX loads.

Multi-device sharding logic is tested hardware-free via
``--xla_force_host_platform_device_count`` (the TPU analog of a fake backend);
fp64 is enabled so constraint kernels can be checked at oracle precision.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
# Hermetic tests: the persistent AOT executable cache must not leak state
# between test sessions (an AOT hit legitimately skips tracing, which
# would flip trace-count assertions depending on what a previous run left
# in .jax_cache/aot). Tests that exercise the cache configure it directly
# (tests/test_aot_cache.py) or strip this var from a subprocess env.
os.environ.setdefault("MOEVA2_AOT_CACHE_DISABLE", "1")

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU platform via env; override
# both config knobs explicitly so tests run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_DATA = "/root/reference/data"


def pytest_addoption(parser):
    parser.addoption(
        "--runslow",
        action="store_true",
        default=False,
        help="also run tests marked slow (full end-to-end/parity tier)",
    )


# Tests measured >10 s on the virtual 8-device CPU mesh (pytest --durations):
# centrally tiered here so the default invocation stays <5 min while every
# subsystem keeps at least one quick representative. Module-local
# ``@pytest.mark.slow`` decorators compose with this list.
SLOW_TESTS = {
    "test_checkpoint.py": {
        "test_resume_is_bit_identical",
        "test_resume_restores_mesh_sharded_carry",
        "test_resume_crosses_mesh_boundaries",
        "test_stale_checkpoint_from_different_run_is_ignored",
        "test_corrupt_checkpoint_falls_back_to_fresh_start",
    },
    "test_survival_pymoo_diff.py": set(),  # slow variants carry their own marker
    "test_moeva_engine.py": {
        "test_archive_appends_columns_and_is_monotone",
        "test_archive_members_track_population_history",
        "test_chunked_history_matches_single_scan",
        "test_mesh_sharded_states",
        "test_mesh_matches_single_device",
        "test_mesh_statistically_equivalent",
        "test_deterministic",
    },
    "test_train.py": {
        "test_class_weights_shift_the_decision",
        "test_roundtrip_and_dispatch",
    },
    "test_runners.py": {
        "test_poisoned_point_continues_in_process",
        "test_moeva_runner_pads_indivisible_candidates",
        "test_pgd_runner_pads_indivisible_candidates",
        "test_rq1_shaped_grid",
        "test_moeva_runner_streams_events",
        "test_end_to_end_and_skip",
        "test_history_artifact",
        "test_moeva_metrics_execution_roundtrip",
    },
    "test_softmax_genes.py": {
        "test_attack_keeps_softmax_population_on_simplex",
    },
    "test_defense.py": {
        "test_artifact_family",
        "test_botnet_knobs_artifact_family",
        "test_iteration",
    },
    "test_parity_botnet.py": {
        "test_cpu_small_run_matches_pinned_rates",
    },
    "test_pgd.py": {
        "test_loss_strategies_all_run",
        "test_restart_history_follows_kept_restart",
        "test_autopgd_random_restarts_run",
    },
    "test_moeva_units.py": {
        "test_survive_batch_matches_vmapped_algorithm",
        "test_select_count_and_elitism",
    },
}


def pytest_collection_modifyitems(config, items):
    """Quick tier by default: the slow end-to-end/parity tests only run under
    ``--runslow`` so the default invocation fits typical CI wall-clock caps
    (the full suite takes ~15 min on the virtual 8-device mesh)."""
    for item in items:
        module = os.path.basename(str(item.fspath))
        name = getattr(item, "originalname", None) or item.name
        if name in SLOW_TESTS.get(module, ()):
            item.add_marker(pytest.mark.slow)
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="slow tier: pass --runslow to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)


@pytest.fixture(scope="session")
def ref_data_dir():
    if not os.path.isdir(REFERENCE_DATA):
        pytest.skip("reference data not available")
    return REFERENCE_DATA


@pytest.fixture(scope="session")
def lcld_paths(ref_data_dir):
    return {
        "features": os.path.join(ref_data_dir, "lcld", "features.csv"),
        "constraints": os.path.join(ref_data_dir, "lcld", "constraints.csv"),
    }


@pytest.fixture(scope="session")
def botnet_paths(ref_data_dir):
    return {
        "features": os.path.join(ref_data_dir, "botnet", "features.csv"),
        "constraints": os.path.join(ref_data_dir, "botnet", "constraints.csv"),
        "candidates": os.path.join(ref_data_dir, "botnet", "x_candidates_common.npy"),
    }


@pytest.fixture(scope="session")
def botnet_candidates(botnet_paths):
    return np.load(botnet_paths["candidates"])
