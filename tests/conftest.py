"""Test harness: force a virtual 8-device CPU mesh and fp64 before JAX loads.

Multi-device sharding logic is tested hardware-free via
``--xla_force_host_platform_device_count`` (the TPU analog of a fake backend);
fp64 is enabled so constraint kernels can be checked at oracle precision.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU platform via env; override
# both config knobs explicitly so tests run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_DATA = "/root/reference/data"


@pytest.fixture(scope="session")
def ref_data_dir():
    if not os.path.isdir(REFERENCE_DATA):
        pytest.skip("reference data not available")
    return REFERENCE_DATA


@pytest.fixture(scope="session")
def lcld_paths(ref_data_dir):
    return {
        "features": os.path.join(ref_data_dir, "lcld", "features.csv"),
        "constraints": os.path.join(ref_data_dir, "lcld", "constraints.csv"),
    }


@pytest.fixture(scope="session")
def botnet_paths(ref_data_dir):
    return {
        "features": os.path.join(ref_data_dir, "botnet", "features.csv"),
        "constraints": os.path.join(ref_data_dir, "botnet", "constraints.csv"),
        "candidates": os.path.join(ref_data_dir, "botnet", "x_candidates_common.npy"),
    }


@pytest.fixture(scope="session")
def botnet_candidates(botnet_paths):
    return np.load(botnet_paths["candidates"])
