"""End-to-end oracle GA: the engine's generation loop with every survival
round replayed through the vendored pymoo R-NSGA-III oracle.

VERDICT r5 named the one remaining epistemic gap: the survival oracle
(``pymoo_rnsga3.py``) validated single rounds, never a *trajectory*, so the
interior budget-100 success rates (exactly where the pre/post-fix kernels
diverged 4.5x) had no reference-side counterpart. This module closes it:
:func:`run_oracle_ga` replays the engine's per-generation loop eagerly —
same key schedule, same operator/evaluation kernels, same
``survive_batch`` — and, at every generation, re-derives the survivor set
through ``oracle.aspiration_survive`` in shared-trace mode (both sides
consume the same two gumbel fields, so the comparison is exact,
index-for-index, through the random niching paths). A trajectory with zero
mismatches means every survival decision of the run was pymoo-semantics
verified, and its final-population success rates are therefore
*oracle-validated interior rates* — what ``tools/oracle_check.py`` commits
as fixtures and ``tools/bench_diff.py`` then guards.

Precision: the loop runs in float64 (pass a ``dtype=jnp.float64`` engine)
so the kernel and the f64 oracle judge identical values — the exact-match
regime the shared-trace fuzz (``test_survival_pymoo_diff.py``) pins. The
production engine runs f32; its rates are compared to the oracle GA's
within seed-noise bands, never bit-for-bit (the trajectories decohere
chaotically, the *distribution* is the claim).

Test-only code, like the oracle it drives: never imported by the package.
"""

from __future__ import annotations

import warnings

import numpy as np

import jax
import jax.numpy as jnp

from moeva2_ijcai22_replication_tpu.attacks.moeva import survival as sv
from moeva2_ijcai22_replication_tpu.attacks.moeva.initialisation import tile_init
from moeva2_ijcai22_replication_tpu.attacks.moeva.operators import make_offspring
from moeva2_ijcai22_replication_tpu.core import codec as codec_lib

from . import pymoo_rnsga3 as oracle

N_OBJ = 3
#: Das-Dennis cluster for pop_per_ref_point=1 (the reference's RNSGA3
#: construction — one direction at the simplex centroid per aspiration).
K1 = np.full((1, N_OBJ), 1.0 / N_OBJ)


def _clone(state: oracle.OracleNormState) -> oracle.OracleNormState:
    st = oracle.OracleNormState(N_OBJ)
    st.ideal_point = state.ideal_point.copy()
    st.worst_point = state.worst_point.copy()
    st.extreme_points = (
        None if state.extreme_points is None else state.extreme_points.copy()
    )
    return st


def _oracle_survive_pinned(f, asp, n_survive, state, gum_cut, gum_mem):
    """One oracle survival round with the solver pinned the way the diff
    test pins it: LAPACK nadir by default, the kernel's Cramer formulation
    inside the ill-conditioned band (1e9 < cond < 1e15) where the two
    solvers legitimately diverge at tolerance boundaries. Runs on clones
    and commits the chosen run's mutated state; returns
    (survivor_indices, committed_state)."""
    st = _clone(state)
    idx, dbg = oracle.aspiration_survive(
        f, asp, K1, n_survive, st, np.random.RandomState(0),
        niche_priority=gum_cut, member_priority=gum_mem,
    )
    cond = np.linalg.cond(dbg["extreme"] - dbg["ideal"])
    if 1e9 < cond < 1e15:
        st = _clone(state)
        idx, dbg = oracle.aspiration_survive(
            f, asp, K1, n_survive, st, np.random.RandomState(0),
            nadir_solver="cramer",
            niche_priority=gum_cut, member_priority=gum_mem,
        )
    return idx, st


def run_oracle_ga(
    moeva,
    x: np.ndarray,
    minimize_class: int = 1,
    *,
    check_oracle: bool = True,
    check_states: np.ndarray | None = None,
):
    """Run the attack trajectory eagerly with oracle-replayed survival.

    ``moeva`` is a configured ``Moeva2`` (``archive_size`` must be 0 —
    pymoo has no elite archive; prefer ``dtype=jnp.float64``). ``x`` the
    (S, D) initial states. ``check_states`` restricts the per-generation
    oracle replay to a subset of state rows (python-loop cost control);
    the kernel still evolves every state.

    Returns ``{"x_ml", "f", "rounds_checked", "mismatches"}`` where
    ``mismatches`` lists every (state, gen) whose kernel survivor set
    differed from the oracle's — an empty list is the parity claim.
    """
    if moeva.archive_size:
        raise ValueError("oracle GA requires archive_size=0 (pymoo semantics)")
    if moeva.init != "tile":
        raise ValueError("oracle GA supports init='tile' only")
    s = x.shape[0]
    dtype = moeva.dtype
    codec = moeva.codec
    pop_size = moeva.pop_size
    asp = jnp.asarray(moeva.asp_points, dtype)
    asp_np = np.asarray(asp, np.float64)
    check_states = (
        np.arange(s) if check_states is None else np.asarray(check_states)
    )

    if isinstance(minimize_class, (int, np.integer)):
        minimize_class = np.full((s,), int(minimize_class))
    xl_ml, xu_ml = moeva.constraints.get_feature_min_max(dynamic_input=x)
    xl_ml = jnp.asarray(
        np.broadcast_to(np.asarray(xl_ml, np.float64), x.shape), dtype
    )
    xu_ml = jnp.asarray(
        np.broadcast_to(np.asarray(xu_ml, np.float64), x.shape), dtype
    )
    x_init_ml = jnp.asarray(x, dtype)
    mc = jnp.asarray(minimize_class, jnp.int32)
    params = jax.tree.map(lambda a: jnp.asarray(a, dtype), moeva.classifier.params)

    xl_gen, xu_gen = codec_lib.genetic_bounds(codec, xl_ml, xu_ml)
    x_init_mm = codec_lib.minmax_normalize(x_init_ml, xl_ml, xu_ml)

    evaluate = jax.jit(
        lambda pop: moeva._evaluate(
            params, pop, x_init_ml, x_init_mm, xl_ml, xu_ml, mc
        )[0]
    )
    offspring = jax.jit(
        lambda k, pop: jax.vmap(
            lambda k1, x1, xl1, xu1: make_offspring(
                k1, moeva.tables, x1, xl1, xu1, moeva.n_offsprings,
                crossover_prob=moeva.crossover_prob,
                eta_mutation=moeva.eta_mutation,
            )
        )(jax.random.split(k, s), pop, xl_gen, xu_gen)
    )
    survive = jax.jit(
        lambda k, f, st: sv.survive_batch(k, f, asp, st, pop_size)
    )

    # -- init: tile + warm-up survival (everyone survives) -----------------
    key = jax.random.PRNGKey(moeva.seed)
    key, k_init, k0 = jax.random.split(key, 3)
    pop_x = tile_init(codec, x_init_ml, pop_size).astype(dtype)
    pop_f = evaluate(pop_x)
    norm0 = jax.vmap(lambda _: sv.NormState.init(N_OBJ, dtype))(jnp.arange(s))
    _, norm_state, _ = survive(k0, pop_f, norm0)

    oracle_states = {int(i): oracle.OracleNormState(N_OBJ) for i in check_states}
    if check_oracle:
        # warm-up round on the oracle side too: M == n_survive, so the
        # selection is trivial but the ideal/worst/extreme memory updates
        f_np = np.asarray(pop_f, np.float64)
        gum_cut, gum_mem = sv._niche_gumbels(k0, (s,), pop_size, pop_size)
        for i in check_states:
            _, oracle_states[int(i)] = _oracle_survive_pinned(
                f_np[i], asp_np, pop_size, oracle_states[int(i)],
                np.asarray(gum_cut[i]), np.asarray(gum_mem[i]),
            )

    mismatches: list[dict] = []
    rounds_checked = 0
    rounds_skipped_nonfinite = 0
    m_tot = pop_size + moeva.n_offsprings
    for gen in range(moeva.n_gen - 1):
        key, k_mate, k_surv = jax.random.split(key, 3)
        off = offspring(k_mate, pop_x)
        off_f = evaluate(off)
        merged_x = jnp.concatenate([pop_x, off], axis=1)
        merged_f = jnp.concatenate([pop_f, off_f], axis=1)
        mask, norm_state, _ = survive(k_surv, merged_f, norm_state)
        mask_np = np.asarray(mask)

        if check_oracle:
            f_np = np.asarray(merged_f, np.float64)
            gum_cut, gum_mem = sv._niche_gumbels(k_surv, (s,), pop_size, m_tot)
            for i in check_states:
                # the oracle round ALWAYS runs (the ideal/worst/extreme
                # memory must track every generation), but the survivor
                # comparison only counts rounds whose merged F is fully
                # finite: domain kernels legitimately emit inf violation
                # sums (e.g. the LCLD amortisation at g == 1), and an inf
                # objective turns the perpendicular-distance association
                # into NaN arithmetic on BOTH sides — a regime where
                # upstream pymoo's own pick order is float noise, not
                # semantics (same class as the BLAS-dependent singular
                # solve the oracle docstring pins)
                finite = bool(np.isfinite(f_np[i]).all())
                with warnings.catch_warnings():
                    if not finite:
                        warnings.simplefilter("ignore", RuntimeWarning)
                    idx_o, oracle_states[int(i)] = _oracle_survive_pinned(
                        f_np[i], asp_np, pop_size, oracle_states[int(i)],
                        np.asarray(gum_cut[i]), np.asarray(gum_mem[i]),
                    )
                if not finite:
                    rounds_skipped_nonfinite += 1
                    continue
                got = sorted(np.where(mask_np[i])[0].tolist())
                want = sorted(np.asarray(idx_o).tolist())
                rounds_checked += 1
                if got != want:
                    mismatches.append(
                        {"state": int(i), "gen": gen + 1,
                         "kernel": got, "oracle": want}
                    )

        # survivors-first, ascending original index — exactly the order the
        # engine's cumsum/scatter permutation produces for the kept columns
        keep = np.stack([np.where(mask_np[i])[0] for i in range(s)])
        keep_j = jnp.asarray(keep)
        pop_x = jnp.take_along_axis(merged_x, keep_j[..., None], axis=1)
        pop_f = jnp.take_along_axis(merged_f, keep_j[..., None], axis=1)

    x_ml = np.asarray(
        codec_lib.genetic_to_ml(codec, pop_x, x_init_ml[:, None, :])
    )
    return {
        "x_ml": x_ml,
        "f": np.asarray(pop_f),
        "rounds_checked": rounds_checked,
        "rounds_skipped_nonfinite": rounds_skipped_nonfinite,
        "mismatches": mismatches,
    }
