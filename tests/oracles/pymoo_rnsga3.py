"""Clean-room numpy transcription of pymoo 0.4.2.2's R-NSGA-III survival.

The reference instantiates ``RNSGA3(ref_points=energy(3, n_pop, seed=1),
pop_per_ref_point=1, ...)`` (``/root/reference/src/attacks/moeva2/moeva2.py:
113-124``), whose selection semantics live in pymoo's
``AspirationPointSurvival._do`` plus the NSGA-III helpers it calls
(``get_extreme_points_c``, ``get_nadir_point``, ``associate_to_niches``,
``niching``, ``calc_niche_count``) and ``get_ref_dirs_from_points``.

pymoo is not installable in this image (SURVEY §7 risk #1 prescribes a
recorded-trace diff; VERDICT r3 item 1 prescribes this vendored oracle as the
fallback), so this module is a direct, loop-for-loop transcription of the
pymoo 0.4.2.2 routines from their published algorithm, kept deliberately
naive — python loops, mutable state, ``np.random.RandomState`` — so that it
is easy to audit against the upstream source and shares no code with the
jitted kernel it validates (``attacks/moeva/survival.py``).

Transcription notes (places where upstream 0.4.2.2 is ambiguous or quirky):

- ``AspirationPointSurvival`` folds the user aspiration points into the
  running ideal/worst updates AND into the extreme-point candidate set
  (unlike plain NSGA-III's ``ReferenceDirectionSurvival``).
- ``get_nadir_point``: on a successful hyperplane solve the nadir is
  *clamped elementwise* to the running worst point ("NOTE: different to the
  proposed version in the paper" upstream); only a failed/degenerate solve
  falls back to worst-of-front, and a too-small range falls back per-axis to
  worst-of-population.
- ``niching`` draws from the global numpy RNG upstream; here every draw goes
  through an explicit ``RandomState`` so the diff test can seed it.
- upstream passes ``worst_of_front``/``worst_of_population`` positionally
  into ``get_nadir_point``; this transcription uses the keyword reading
  (fallback = worst of front, degenerate fill = worst of population), which
  matches the parameter names and the NSGA-III paper.
"""

from __future__ import annotations

import numpy as np


# -- non-dominated sorting ---------------------------------------------------


def dominates(a: np.ndarray, b: np.ndarray) -> bool:
    """Standard Deb domination for minimisation, no epsilon, no constraints
    (pymoo ``Dominator`` with CV-free populations)."""
    return bool(np.all(a <= b) and np.any(a < b))


def fast_non_dominated_sort(F: np.ndarray, n_stop_if_ranked: int | None = None):
    """Front lists by iterative peeling; stops once ``n_stop_if_ranked``
    candidates are ranked (the last front may overshoot). Returns
    ``(fronts, rank)`` with unranked candidates at rank ``len(F)`` (an
    out-of-band sentinel; upstream uses 1e16)."""
    n = len(F)
    if n_stop_if_ranked is None:
        n_stop_if_ranked = n
    dom = np.zeros((n, n), dtype=bool)
    for i in range(n):
        for j in range(n):
            if i != j:
                dom[i, j] = dominates(F[i], F[j])

    remaining = np.ones(n, dtype=bool)
    fronts: list[np.ndarray] = []
    rank = np.full(n, n, dtype=int)
    n_ranked = 0
    r = 0
    while remaining.any() and n_ranked < n_stop_if_ranked:
        n_dominators = (dom & remaining[:, None]).sum(axis=0)
        front = np.where(remaining & (n_dominators == 0))[0]
        fronts.append(front)
        rank[front] = r
        remaining[front] = False
        n_ranked += len(front)
        r += 1
    return fronts, rank


# -- normalisation helpers (nsga3.py) ----------------------------------------


def get_extreme_points_c(F: np.ndarray, ideal_point: np.ndarray, extreme_points=None):
    n_obj = F.shape[1]
    weights = np.eye(n_obj)
    weights[weights == 0] = 1e6

    _F = F
    if extreme_points is not None:
        _F = np.concatenate([extreme_points, _F], axis=0)

    __F = _F - ideal_point
    __F[__F < 1e-3] = 0

    F_asf = np.max(__F * weights[:, None, :], axis=2)
    I = np.argmin(F_asf, axis=1)
    return _F[I, :]


def solve3_cramer(M: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Adjugate/determinant solve — the jitted kernel's formulation
    (``survival._solve3``), exposed so the diff test can PIN both sides to
    one solver inside the ill-conditioned band where LAPACK and Cramer
    legitimately diverge at tolerance boundaries. Diff-test device, not
    upstream pymoo semantics."""
    det = (
        M[0, 0] * (M[1, 1] * M[2, 2] - M[1, 2] * M[2, 1])
        - M[0, 1] * (M[1, 0] * M[2, 2] - M[1, 2] * M[2, 0])
        + M[0, 2] * (M[1, 0] * M[2, 1] - M[1, 1] * M[2, 0])
    )
    adj = np.array(
        [
            [
                M[1, 1] * M[2, 2] - M[1, 2] * M[2, 1],
                M[0, 2] * M[2, 1] - M[0, 1] * M[2, 2],
                M[0, 1] * M[1, 2] - M[0, 2] * M[1, 1],
            ],
            [
                M[1, 2] * M[2, 0] - M[1, 0] * M[2, 2],
                M[0, 0] * M[2, 2] - M[0, 2] * M[2, 0],
                M[0, 2] * M[1, 0] - M[0, 0] * M[1, 2],
            ],
            [
                M[1, 0] * M[2, 1] - M[1, 1] * M[2, 0],
                M[0, 1] * M[2, 0] - M[0, 0] * M[2, 1],
                M[0, 0] * M[1, 1] - M[0, 1] * M[1, 0],
            ],
        ]
    )
    with np.errstate(divide="ignore", invalid="ignore"):
        return (adj @ b) / det


def get_nadir_point(extreme_points, ideal_point, worst_point, worst_of_front, worst_of_population, solver="lapack"):
    """Transcription note: upstream relies on ``np.linalg.LinAlgError`` to
    detect a singular extreme-point matrix. When the matrix has *duplicate
    rows* (the same candidate minimises the ASF on two axes — routine in
    degenerate fronts), the system is exactly singular but consistent, and
    whether LAPACK raises is build-dependent: pivoting rounding residues of
    order 1e-19 can let ``dgesv`` return an arbitrary member of the solution
    family instead of raising (observed on this image's numpy: a duplicate
    extreme matrix from a rank-1 objective cloud solved "successfully" while
    the textbook duplicate-row matrix raised). Upstream behaviour in this
    case is therefore BLAS noise, not semantics. The oracle pins the
    deterministic reading — an explicit condition-number test — and the
    jitted kernel's Cramer solve + consistency check agrees with it (its
    adjugate cancels exactly on duplicate rows, failing the residual
    check)."""
    try:
        M = extreme_points - ideal_point
        b = np.ones(extreme_points.shape[1])
        if solver == "cramer":
            # pinned mode: the kernel's exact arithmetic + success chain
            # (survival._solve3 / _nadir_point) so both sides agree inside
            # the ill-conditioned band
            plane = solve3_cramer(M, b)
            intercepts = 1 / plane
            nadir_point = np.minimum(ideal_point + intercepts, worst_point)
            ok = (
                np.all(np.isfinite(plane))
                and np.allclose(M @ plane, b, rtol=1e-5, atol=1e-8)
                and np.all(intercepts > 1e-6)
                and np.all(np.isfinite(nadir_point))
            )
            if not ok:
                raise np.linalg.LinAlgError()
        else:
            plane = np.linalg.solve(M, b)
            if np.linalg.cond(M) > 1e12:
                raise np.linalg.LinAlgError()
            intercepts = 1 / plane
            nadir_point = ideal_point + intercepts
            if (
                not np.allclose(np.dot(M, plane), b)
                or np.any(intercepts <= 1e-6)
                or np.any(np.isnan(nadir_point))
            ):
                raise np.linalg.LinAlgError()
            # clamp to the running worst point rather than failing (upstream
            # "NOTE: different to the proposed version in the paper")
            b_mask = nadir_point > worst_point
            nadir_point[b_mask] = worst_point[b_mask]
    except np.linalg.LinAlgError:
        nadir_point = np.array(worst_of_front, dtype=float, copy=True)

    b_mask = nadir_point - ideal_point <= 1e-6
    nadir_point[b_mask] = worst_of_population[b_mask]
    return nadir_point


# -- aspiration reference directions (rnsga3.py) -----------------------------


def line_plane_intersection(l0, l1, p0, p_no, epsilon=1e-6):
    l = l1 - l0
    dot = np.dot(l, p_no)
    if abs(dot) > epsilon:
        w = p0 - l0
        d = np.dot(w, p_no) / dot
        return l0 + l * d
    # line parallel to plane: upstream projects l1 onto the plane
    ref_proj = l1 - np.dot(l1 - p0, p_no) * p_no
    return ref_proj


def get_ref_dirs_from_points(ref_point: np.ndarray, ref_dirs: np.ndarray, mu: float = 0.1):
    """Per aspiration point: mu-shrunk copy of the Das-Dennis cluster
    re-centred on the central projection of the point onto the unit-simplex
    plane, octant-clipped; plus the extreme axes."""
    n_obj = ref_point.shape[1]

    val = []
    n_vector = np.ones(n_obj) / np.linalg.norm(np.ones(n_obj))
    point_on_plane = np.eye(n_obj)[0]

    for point in ref_point:
        ref_dir_for_aspiration_point = mu * np.copy(ref_dirs)
        cent = np.mean(ref_dir_for_aspiration_point, axis=0)
        intercept = line_plane_intersection(
            np.zeros(n_obj), point, point_on_plane, n_vector
        )
        shift = intercept - cent
        ref_dir_for_aspiration_point += shift

        if not (ref_dir_for_aspiration_point > 0).min():
            ref_dir_for_aspiration_point[ref_dir_for_aspiration_point < 0] = 0
            ref_dir_for_aspiration_point = (
                ref_dir_for_aspiration_point
                / np.sum(ref_dir_for_aspiration_point, axis=1)[:, None]
            )
        val.extend(ref_dir_for_aspiration_point)

    val.extend(np.eye(n_obj))
    return np.array(val)


# -- association + niching (nsga3.py) ----------------------------------------


def calc_perpendicular_distance(N, ref_dirs):
    u = np.tile(ref_dirs, (len(N), 1))
    v = np.repeat(N, len(ref_dirs), axis=0)
    norm_u = np.linalg.norm(u, axis=1)
    scalar_proj = np.sum(v * u, axis=1) / norm_u
    proj = scalar_proj[:, None] * u / norm_u[:, None]
    val = np.linalg.norm(proj - v, axis=1)
    return np.reshape(val, (len(N), len(ref_dirs)))


def associate_to_niches(F, niches, ideal_point, nadir_point, utopian_epsilon=0.0):
    utopian_point = ideal_point - utopian_epsilon
    denom = nadir_point - utopian_point
    denom[denom == 0] = 1e-12

    N = (F - utopian_point) / denom
    dist_matrix = calc_perpendicular_distance(N, niches)
    niche_of_individuals = np.argmin(dist_matrix, axis=1)
    dist_to_niche = dist_matrix[np.arange(F.shape[0]), niche_of_individuals]
    return niche_of_individuals, dist_to_niche


def calc_niche_count(n_niches, niche_of_individuals):
    niche_count = np.zeros(n_niches, dtype=int)
    index, count = np.unique(niche_of_individuals, return_counts=True)
    niche_count[index] = count
    return niche_count


def niching(F, n_remaining, niche_count, niche_of_individuals, dist_to_niche, rng,
            niche_priority=None, member_priority=None):
    """Upstream pick loop, verbatim dynamics; ``rng`` replaces the global
    numpy RNG. ``F``/``niche_of_individuals``/``dist_to_niche`` are the
    last-front subarrays; returns ``(indices_into_them, deterministic)``.

    ``deterministic`` is instrumentation (not upstream): True iff no RNG
    draw could have changed the returned index set — every sweep used its
    whole min-count cohort (no permutation truncation), every non-empty-niche
    pick had a single candidate, and every empty-niche argmin was tie-free.

    ``niche_priority`` (R,) / ``member_priority`` (len(F),): shared-trace
    mode (diff-test device, not upstream). Uniform-random choices are
    replaced by priority order — cutoff cohort = highest ``niche_priority``
    among eligibles, member pick = LOWEST ``member_priority`` among the
    niche's remaining members (matching the kernel's ascending-gumbel
    within-niche ranking). A random permutation/truncation and a top-k by
    iid continuous keys are the same distribution, and sequential
    without-replacement uniform picks are exactly ascending order of iid
    keys — so feeding both implementations the SAME fields must reproduce
    the same survivor set index-for-index, turning the loop's random paths
    into an exact comparison. The closest-member rule for empty niches is
    upstream behaviour and stays (first-index argmin; no shuffle in this
    mode so ties resolve deterministically on both sides).
    """
    shared_trace = niche_priority is not None
    survivors = []
    mask = np.full(len(F), True)
    deterministic = True

    while len(survivors) < n_remaining:
        n_select = n_remaining - len(survivors)

        next_niches_list = np.unique(niche_of_individuals[mask])
        next_niche_count = niche_count[next_niches_list]
        min_niche_count = next_niche_count.min()
        next_niches = next_niches_list[
            np.where(next_niche_count == min_niche_count)[0]
        ]
        if len(next_niches) > n_select:
            deterministic = False  # random cutoff cohort
        if shared_trace:
            order = np.argsort(-niche_priority[next_niches], kind="stable")
            next_niches = next_niches[order[:n_select]]
        else:
            next_niches = next_niches[rng.permutation(len(next_niches))[:n_select]]

        for next_niche in next_niches:
            next_ind = np.where(
                np.logical_and(niche_of_individuals == next_niche, mask)
            )[0]
            if not shared_trace:
                rng.shuffle(next_ind)

            if niche_count[next_niche] == 0:
                d = dist_to_niche[next_ind]
                if (d == d.min()).sum() > 1:
                    deterministic = False  # argmin tie broken by shuffle
                next_ind = next_ind[np.argmin(d)]
            else:
                if len(next_ind) > 1:
                    deterministic = False  # uniform random member pick
                if shared_trace:
                    next_ind = next_ind[np.argmin(member_priority[next_ind])]
                else:
                    next_ind = next_ind[0]

            mask[next_ind] = False
            survivors.append(int(next_ind))
            niche_count[next_niche] += 1

    return survivors, deterministic


# -- the survival itself (rnsga3.py AspirationPointSurvival._do) -------------


class OracleNormState:
    """ideal/worst/extreme memory carried across generations (the fields
    ``AspirationPointSurvival`` keeps on self)."""

    def __init__(self, n_obj: int):
        self.ideal_point = np.full(n_obj, np.inf)
        self.worst_point = np.full(n_obj, -np.inf)
        self.extreme_points = None


def aspiration_survive(
    F: np.ndarray,  # (M, n_obj) merged population objectives
    ref_points: np.ndarray,  # (A, n_obj) user aspiration points
    aspiration_ref_dirs: np.ndarray,  # (K, n_obj) Das-Dennis cluster
    n_survive: int,
    state: OracleNormState,
    rng: np.random.RandomState,
    mu: float = 0.1,
    nadir_solver: str = "lapack",
    niche_priority: np.ndarray | None = None,  # (R,) shared-trace mode
    member_priority: np.ndarray | None = None,  # (len(F),) original indices
):
    """One ``AspirationPointSurvival._do`` round. Mutates ``state``. Returns
    ``(survivor_indices_into_F, debug)``. ``nadir_solver``/priorities: see
    :func:`solve3_cramer` and :func:`niching` — diff-test pinning devices."""
    F = np.asarray(F, dtype=float)

    state.ideal_point = np.min(
        np.vstack((state.ideal_point, F, ref_points)), axis=0
    )
    state.worst_point = np.max(
        np.vstack((state.worst_point, F, ref_points)), axis=0
    )

    fronts, rank = fast_non_dominated_sort(F, n_stop_if_ranked=n_survive)
    non_dominated = fronts[0]

    state.extreme_points = get_extreme_points_c(
        np.vstack([F[non_dominated], ref_points]),
        state.ideal_point,
        extreme_points=state.extreme_points,
    )

    worst_of_population = np.max(F, axis=0)
    worst_of_front = np.max(F[non_dominated, :], axis=0)

    nadir_point = get_nadir_point(
        state.extreme_points,
        state.ideal_point,
        state.worst_point,
        worst_of_front,
        worst_of_population,
        solver=nadir_solver,
    )

    # restrict to ranked individuals, in front order (upstream re-indexes the
    # population; here we carry original indices alongside)
    I = np.concatenate(fronts).astype(int)
    rank_I = rank[I]
    F_I = F[I]

    # front index lists relative to the truncated population
    counter = 0
    local_fronts = []
    for f in fronts:
        local_fronts.append(np.arange(counter, counter + len(f)))
        counter += len(f)
    last_front = local_fronts[-1]

    denom = nadir_point - state.ideal_point
    denom = np.where(denom == 0, 1e-12, denom)
    unit_ref_points = (ref_points - state.ideal_point) / denom
    ref_dirs = get_ref_dirs_from_points(unit_ref_points, aspiration_ref_dirs, mu=mu)

    niche_of_individuals, dist_to_niche = associate_to_niches(
        F_I, ref_dirs, state.ideal_point, nadir_point
    )

    if len(F_I) > n_survive:
        if len(local_fronts) == 1:
            n_remaining = n_survive
            until_last_front = np.array([], dtype=int)
            niche_count = np.zeros(len(ref_dirs), dtype=int)
        else:
            until_last_front = np.concatenate(local_fronts[:-1])
            niche_count = calc_niche_count(
                len(ref_dirs), niche_of_individuals[until_last_front]
            )
            n_remaining = n_survive - len(until_last_front)

        S, niching_deterministic = niching(
            F_I[last_front, :],
            n_remaining,
            niche_count,
            niche_of_individuals[last_front],
            dist_to_niche[last_front],
            rng,
            niche_priority=niche_priority,
            member_priority=(
                None
                if member_priority is None
                else np.asarray(member_priority)[I[last_front]]
            ),
        )
        survivors_local = np.concatenate(
            (until_last_front, last_front[np.array(S, dtype=int)])
        ).astype(int)
    else:
        survivors_local = np.arange(len(F_I))
        niching_deterministic = True

    debug = {
        "ideal": state.ideal_point.copy(),
        "worst": state.worst_point.copy(),
        "extreme": np.array(state.extreme_points, copy=True),
        "nadir": np.array(nadir_point, copy=True),
        "ref_dirs": ref_dirs,
        "rank": rank,
        "fronts": fronts,
        "niche": niche_of_individuals,
        "dist": dist_to_niche,
        "ranked_idx": I,
        "niching_deterministic": niching_deterministic,
    }
    return I[survivors_local], debug
