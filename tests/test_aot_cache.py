"""Persistent AOT executable cache (observability.aotcache).

The round-10 tentpole's contract: a serialized executable deserialized in
a warm process is BIT-IDENTICAL to a fresh compile for both attack
engines' programs (PGD and the MoEvA init/segment/gate family, including
the donated-carry segment), fingerprint mismatches and corrupt files
degrade to a counted recorder event + recompile (never a crash), and the
cross-process warm-start path — the "second bench process reports >= 90%
of its executables as aot_hit" acceptance criterion — holds through a
subprocess smoke driving ``setup_jax_cache`` exactly like bench/serving
boot does.
"""

import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import (
    synth_lcld,
    synth_lcld_schema,
)
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax
from moeva2_ijcai22_replication_tpu.observability.aotcache import (
    AotExecutableCache,
    backend_fingerprint,
    get_aot_cache,
)
from moeva2_ijcai22_replication_tpu.observability.coldstart import (
    ColdStartLedger,
    get_coldstart,
)
from moeva2_ijcai22_replication_tpu.observability.trace import default_recorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def problem(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("aot")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(8, cons.schema, seed=3)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=7))
    return {
        "constraints": cons,
        "surrogate": sur,
        "scaler": fit_minmax(x.min(0), x.max(0)),
        "x": x,
    }


@pytest.fixture()
def aot_dir(tmp_path):
    """Point the process AOT cache at a fresh dir; restore after. Tests
    configure the cache DIRECTLY (AotExecutableCache.configure) — the
    conftest's MOEVA2_AOT_CACHE_DISABLE only guards the setup_jax_cache
    config path, so other tests stay hermetic."""
    cache = get_aot_cache()
    prev = cache.path
    cache.configure(str(tmp_path / "aot"))
    try:
        yield cache
    finally:
        cache.configure(prev)


def _moeva(problem, **kw):
    kw.setdefault("n_gen", 7)
    kw.setdefault("n_pop", 12)
    kw.setdefault("n_offsprings", 6)
    kw.setdefault("seed", 5)
    kw.setdefault("archive_size", 4)
    return Moeva2(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        ml_scaler=problem["scaler"],
        norm=2,
        **kw,
    )


def _pgd(problem, **kw):
    kw.setdefault("max_iter", 4)
    return ConstrainedPGD(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        scaler=problem["scaler"],
        **kw,
    )


class TestRoundTrip:
    def test_pgd_warm_start_is_bit_identical(self, problem, aot_dir):
        xs = np.asarray(problem["scaler"].transform(problem["x"]))
        y = np.asarray(problem["surrogate"].predict_proba(xs)).argmax(-1)
        fresh = _pgd(problem).generate(xs, y)
        assert aot_dir.stores >= 1
        hits0 = aot_dir.hits
        # a FRESH engine instance (new LedgeredJit, empty in-memory
        # executable cache) must find the serialized executable on disk
        warm_eng = _pgd(problem)
        warm = warm_eng.generate(xs, y)
        assert aot_dir.hits > hits0
        assert warm_eng._jit_attack.last_entry.source == "aot"
        np.testing.assert_array_equal(fresh, warm)

    def test_moeva_program_family_round_trips(self, problem, aot_dir):
        """Init, donated-carry segment, and the packed success-gate
        program all serialize, reload, and reproduce bit-identically
        (early-exit mode so the gate program is exercised too)."""
        kw = dict(early_stop_check_every=2, compaction_buckets=(2, 4, 8))
        fresh = _moeva(problem, **kw).generate(problem["x"], 1)
        stores0 = aot_dir.stores
        assert stores0 >= 3  # init + segment + gate at minimum
        hits0 = aot_dir.hits
        warm_eng = _moeva(problem, **kw)
        warm = warm_eng.generate(problem["x"], 1)
        assert aot_dir.hits >= hits0 + 3
        # an AOT hit never traces: the python program bodies did not run
        assert warm_eng.trace_count == 0
        np.testing.assert_array_equal(fresh.x_gen, warm.x_gen)
        np.testing.assert_array_equal(fresh.f, warm.f)
        np.testing.assert_array_equal(fresh.x_ml, warm.x_ml)
        assert fresh.early_stop["compaction"] == warm.early_stop["compaction"]

    def test_domains_of_equal_shape_do_not_collide(self, problem, aot_dir):
        """The constraint formulas are code traced into the executable:
        the disk key must discriminate constraint sets even at identical
        avals (the identity carries the constraints class + counts)."""
        eng = _pgd(problem)
        ident = eng._ledger_identity()
        assert ident["constraints"] == "LcldConstraints"
        key_a = AotExecutableCache.cache_key(
            "pgd_attack", ident, ((), (), "tree", ("leafsig",))
        )
        ident_b = dict(ident, constraints="BotnetConstraints")
        key_b = AotExecutableCache.cache_key(
            "pgd_attack", ident_b, ((), (), "tree", ("leafsig",))
        )
        assert key_a != key_b
        # ...while the id()-derived engine-cache slot must NOT fragment
        # the key (it is process noise)
        key_c = AotExecutableCache.cache_key(
            "pgd_attack", dict(ident, cache_key="other:123"),
            ((), (), "tree", ("leafsig",)),
        )
        assert key_a == key_c


class TestDegradation:
    def _one_store(self, problem, aot_dir):
        xs = np.asarray(problem["scaler"].transform(problem["x"]))
        y = np.asarray(problem["surrogate"].predict_proba(xs)).argmax(-1)
        out = _pgd(problem).generate(xs, y)
        files = [
            os.path.join(aot_dir.path, f)
            for f in os.listdir(aot_dir.path)
            if f.endswith(".aotx")
        ]
        assert files
        return xs, y, out, files

    def test_corrupt_entry_counts_event_and_recompiles(
        self, problem, aot_dir
    ):
        xs, y, fresh, files = self._one_store(problem, aot_dir)
        for f in files:
            with open(f, "wb") as fh:
                fh.write(b"\x00garbage")
        before = default_recorder().counters.get("aot_cache_load_failures", 0)
        warm = _pgd(problem).generate(xs, y)
        np.testing.assert_array_equal(fresh, warm)
        assert aot_dir.failure_reasons.get("corrupt", 0) >= 1
        assert (
            default_recorder().counters["aot_cache_load_failures"] > before
        )

    def test_fingerprint_mismatch_rejects_and_overwrites(
        self, problem, aot_dir
    ):
        """A stale/foreign entry (different jax, backend, topology, or
        code version) is found, rejected with a counted event, and
        replaced by the fresh compile's store."""
        xs, y, fresh, files = self._one_store(problem, aot_dir)
        for f in files:
            with open(f, "rb") as fh:
                env = pickle.load(fh)
            env["fingerprint"] = dict(
                env["fingerprint"], backend="tpu", jax="0.0.1"
            )
            with open(f, "wb") as fh:
                pickle.dump(env, fh)
        stores0 = aot_dir.stores
        warm = _pgd(problem).generate(xs, y)
        np.testing.assert_array_equal(fresh, warm)
        assert aot_dir.failure_reasons.get("fingerprint", 0) >= 1
        assert aot_dir.stores > stores0  # entry refreshed
        # the refreshed entry loads cleanly now
        hits0 = aot_dir.hits
        _pgd(problem).generate(xs, y)
        assert aot_dir.hits > hits0

    def test_disabled_cache_is_inert(self, problem, tmp_path):
        cache = get_aot_cache()
        assert not cache.enabled  # conftest keeps the config path off
        xs = np.asarray(problem["scaler"].transform(problem["x"]))
        y = np.asarray(problem["surrogate"].predict_proba(xs)).argmax(-1)
        eng = _pgd(problem, eps=0.21)  # distinct program
        eng.generate(xs, y)
        assert eng._jit_attack.last_entry.source is None
        assert not list(tmp_path.iterdir())

    def test_fingerprint_fields(self):
        fp = backend_fingerprint()
        for k in ("jax", "backend", "device_count", "package", "code"):
            assert k in fp
        assert fp["backend"] == "cpu"

    def test_rejected_entry_is_discarded_from_disk(self, aot_dir):
        """Self-healing: a rejected entry is removed at rejection time,
        so a future process whose recompile legitimately skips the
        re-store (jax-cache hit) takes a plain miss instead of paying
        the same counted failure forever."""
        os.makedirs(aot_dir.path, exist_ok=True)
        bad = os.path.join(aot_dir.path, "deadbeef.aotx")
        with open(bad, "wb") as fh:
            fh.write(b"junk")
        assert aot_dir.load("deadbeef") is None
        assert aot_dir.failure_reasons.get("corrupt", 0) >= 1
        assert not os.path.exists(bad)

    def test_store_skipped_on_jax_cache_hit(self, problem, aot_dir):
        """An executable satisfied by the jax persistent cache must NOT
        be serialized: such blobs fail cross-process deserialization
        ("Symbols not found" on CPU PJRT), and the next process would
        load it from the jax cache anyway."""
        import jax

        from moeva2_ijcai22_replication_tpu.observability.ledger import (
            LedgeredJit,
        )

        cs = get_coldstart()
        prev = cs._listener_registered
        cs._listener_registered = True
        try:
            jitted = jax.jit(lambda x: x * 5 + 2)

            class CacheHitJitted:
                """Delegate whose lower() simulates jax's monitoring
                firing a persistent-cache hit event mid-compile."""

                def lower(self, *a, **kw):
                    with cs._lock:
                        cs._jax_hits += 1
                    return jitted.lower(*a, **kw)

                def __call__(self, *a, **kw):
                    return jitted(*a, **kw)

            stores0 = aot_dir.stores
            f = LedgeredJit(
                CacheHitJitted(), producer="hitcase", identity={"k": 1}
            )
            import jax.numpy as jnp

            f(jnp.ones((3,)))
            assert aot_dir.stores == stores0  # store skipped
        finally:
            cs._listener_registered = prev


class TestColdLedgerClassification:
    def test_aot_outcomes_reach_the_cold_block(self, problem, aot_dir):
        cs = get_coldstart()
        # fresh program shape so this test owns its compiles
        kw = dict(n_gen=5, n_pop=10, n_offsprings=4)
        _moeva(problem, **kw).generate(problem["x"], 1)
        block = cs.cold_block()
        outcomes = block["persistent_cache"]["by_outcome"]
        assert outcomes.get("aot_stored", 0) >= 1
        _moeva(problem, **kw).generate(problem["x"], 1)
        outcomes = cs.cold_block()["persistent_cache"]["by_outcome"]
        assert outcomes.get("aot_hit", 0) >= 1
        # the aot-tier state rides build.jax_cache (the healthz surface)
        assert cs.cache_state()["aot"]["hits"] >= 1

    def test_aot_hit_books_aot_load_phase_not_compile(self):
        cs = ColdStartLedger()
        out = cs.note_compile(
            producer="p", key="p#1", lower_s=0.0, compile_s=0.02,
            probe={}, aot_cache="hit",
        )
        assert out == "aot_hit"
        block = cs.cold_block()
        assert block["phases"].get("aot_load") == pytest.approx(0.02)
        assert "xla_compile" not in block["phases"]

    def test_by_outcome_survives_row_eviction(self):
        """The --cold hit-rate gate reads by_outcome: it must count the
        whole process, not the last MAX_EXECUTABLES rows — a boot-time
        aot_hit evicted from the detail ring still counts."""
        from moeva2_ijcai22_replication_tpu.observability.coldstart import (
            MAX_EXECUTABLES,
        )

        cs = ColdStartLedger()
        for i in range(MAX_EXECUTABLES + 10):
            cs.note_compile(
                producer="p", key=f"p#{i}", lower_s=0.0, compile_s=0.0,
                probe={}, aot_cache="hit" if i < 10 else None,
            )
        pc = cs.cold_block()["persistent_cache"]
        assert len(pc["by_executable"]) == MAX_EXECUTABLES
        assert pc["by_outcome"]["aot_hit"] == 10  # evicted yet counted
        assert sum(pc["by_outcome"].values()) == MAX_EXECUTABLES + 10

    def test_stored_outcome_does_not_mask_a_jax_cache_hit(self, tmp_path):
        cs = ColdStartLedger()
        cs.configure_cache(str(tmp_path), True)
        cs._listener_registered = True
        probe = cs.compile_probe()
        cs._jax_hits += 1
        out = cs.note_compile(
            producer="p", key="p#1", lower_s=0.1, compile_s=0.2,
            probe=probe, aot_cache="stored",
        )
        assert out == "hit"  # the compile itself was already amortised


class TestCrossProcessWarmStart:
    SCRIPT = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from moeva2_ijcai22_replication_tpu.experiments.common import setup_jax_cache
from moeva2_ijcai22_replication_tpu.observability.ledger import LedgeredJit
from moeva2_ijcai22_replication_tpu.observability.coldstart import get_coldstart

base = sys.argv[1]
setup_jax_cache({"system": {"jax_cache_dir": os.path.join(base, "jc"),
                            "aot_cache": os.path.join(base, "aot")}})
outs = []
for i, shape in enumerate(((4,), (8,), (16,))):
    f = LedgeredJit(
        jax.jit(lambda x: (x * 2 + 1).sum()),
        producer=f"smoke_{i}", identity={"case": i},
    )
    outs.append(float(f(jnp.ones(shape))))
block = get_coldstart().cold_block()
print(json.dumps({
    "outs": outs,
    "by_outcome": block["persistent_cache"]["by_outcome"],
}))
"""

    @pytest.mark.parametrize("n_programs", [3])
    def test_second_process_is_mostly_aot_hits(self, tmp_path, n_programs):
        """The acceptance criterion: a second process over the same cache
        dirs classifies >= 90% of its executables as warm
        (aot_hit/hit) in the cold ledger — here 100%, since every
        program round-trips the serialized-executable tier."""
        script = tmp_path / "smoke.py"
        script.write_text(self.SCRIPT)
        env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get(
            "PYTHONPATH", ""
        ))
        # the conftest disables the setup_jax_cache AOT path for
        # hermeticity; the subprocess must exercise it for real
        env.pop("MOEVA2_AOT_CACHE_DISABLE", None)

        def run():
            proc = subprocess.run(
                [sys.executable, str(script), str(tmp_path)],
                capture_output=True, text=True, timeout=300, env=env,
            )
            assert proc.returncode == 0, proc.stderr
            return json.loads(proc.stdout.strip().splitlines()[-1])

        first = run()
        assert sum(
            first["by_outcome"].get(k, 0)
            for k in ("aot_stored", "miss_stored", "miss_uncached", "disabled")
        ) == n_programs
        second = run()
        assert second["outs"] == first["outs"]  # cross-process bit-identity
        warm = second["by_outcome"].get("aot_hit", 0) + second[
            "by_outcome"
        ].get("hit", 0)
        total = sum(second["by_outcome"].values())
        assert total == n_programs
        assert warm / total >= 0.9
