"""Mid-attack checkpoint/resume: crash recovery with bit-identical results.

The reference can only restart a crashed attack from generation 0 (config-hash
skip covers completed runs only, ``04_moeva.py:31-36``); the engine's
``checkpoint_every`` closes that gap. Because the checkpoint carries the PRNG
key, a resumed run continues the exact random stream: these tests kill an
attack mid-run with an injected fault and assert the resumed result equals an
uninterrupted run bit for bit, history included.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.moeva.checkpoint import AttackCheckpointer
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax


class _InjectedCrash(RuntimeError):
    pass


@pytest.fixture(scope="module")
def problem(lcld_paths):
    constraints = LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])
    model = lcld_mlp()
    params = init_params(model, constraints.schema.n_features, seed=7)
    surrogate = Surrogate(model=model, params=params)
    x = synth_lcld(4, constraints.schema, seed=3)
    scaler = fit_minmax(x.min(0), x.max(0))
    return constraints, surrogate, x, scaler


def _engine(problem, save_history, seed=11, **kw):
    constraints, surrogate, _, scaler = problem
    return Moeva2(
        classifier=surrogate,
        constraints=constraints,
        ml_scaler=scaler,
        norm=2,
        n_gen=10,
        n_pop=20,
        n_offsprings=10,
        seed=seed,
        archive_size=2,
        save_history=save_history,
        history_chunk=2,
        dtype=jnp.float64,
        **kw,
    )


def _crash_on_call(engine, n):
    """Arm the engine with the real segment program wrapped in a fault that
    fires on the ``n``-th dispatch."""
    engine._jit_init = jax.jit(engine._build_init())
    real_segment = jax.jit(engine._build_segment(), static_argnames="length")
    calls = {"n": 0}

    def crashing(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == n:
            raise _InjectedCrash()
        return real_segment(*args, **kwargs)

    engine._jit_segment = crashing


@pytest.mark.parametrize("save_history", ["reduced", None])
def test_resume_is_bit_identical(problem, tmp_path, save_history):
    _, _, x, _ = problem
    reference = _engine(problem, save_history).generate(x)

    cp_path = str(tmp_path / f"cp_{save_history}.npz")
    crashed = _engine(
        problem, save_history, checkpoint_every=3, checkpoint_path=cp_path
    )
    _crash_on_call(crashed, 3)
    with pytest.raises(_InjectedCrash):
        crashed.generate(x)
    assert os.path.exists(cp_path), "crash after a boundary must leave a checkpoint"

    resumed = _engine(
        problem, save_history, checkpoint_every=3, checkpoint_path=cp_path
    ).generate(x)

    np.testing.assert_array_equal(resumed.x_gen, reference.x_gen)
    np.testing.assert_array_equal(resumed.f, reference.f)
    if save_history:
        # entry 0 = initial population record, then one per generation
        np.testing.assert_array_equal(resumed.history[0], reference.history[0])
        np.testing.assert_array_equal(
            np.stack(resumed.history[1:]), np.stack(reference.history[1:])
        )
    assert not os.path.exists(cp_path), "completed run must clear its checkpoint"
    assert not os.path.isdir(cp_path + ".hist")


def test_stale_checkpoint_from_different_run_is_ignored(problem, tmp_path):
    _, _, x, _ = problem
    cp_path = str(tmp_path / "cp.npz")

    crashed = _engine(problem, None, checkpoint_every=3, checkpoint_path=cp_path)
    _crash_on_call(crashed, 3)
    with pytest.raises(_InjectedCrash):
        crashed.generate(x)
    assert os.path.exists(cp_path)

    # Same path, different seed: the fingerprint differs, so the checkpoint
    # must be ignored — the run starts fresh and matches a checkpoint-free
    # run of the new seed exactly.
    fresh = _engine(problem, None, seed=12).generate(x)
    resumed = _engine(
        problem, None, seed=12, checkpoint_every=3, checkpoint_path=cp_path
    ).generate(x)
    np.testing.assert_array_equal(resumed.x_gen, fresh.x_gen)
    np.testing.assert_array_equal(resumed.f, fresh.f)


def test_fingerprint_covers_model_scaler_bounds_and_inputs(problem):
    constraints, surrogate, x, scaler = problem
    mc = np.ones(len(x), dtype=int)
    xl, xu = constraints.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    base = _engine(problem, None)._fingerprint(x, mc, xl, xu)
    # same knobs, different classifier weights -> different identity
    model = lcld_mlp()
    other = Surrogate(model, init_params(model, constraints.schema.n_features, seed=99))
    retrained = Moeva2(
        classifier=other, constraints=constraints, ml_scaler=scaler,
        norm=2, n_gen=10, n_pop=20, n_offsprings=10, seed=11,
        archive_size=2, dtype=jnp.float64,
    )
    assert retrained._fingerprint(x, mc, xl, xu) != base
    # different inputs or edited feature bounds -> different identity
    assert _engine(problem, None)._fingerprint(x + 1e-3, mc, xl, xu) != base
    assert _engine(problem, None)._fingerprint(x, mc, xl, xu * 1.01) != base
    # identical run -> stable identity
    assert _engine(problem, None)._fingerprint(x, mc, xl, xu) == base


def test_corrupt_checkpoint_falls_back_to_fresh_start(problem, tmp_path):
    _, _, x, _ = problem
    cp_path = str(tmp_path / "cp.npz")
    with open(cp_path, "wb") as fh:
        fh.write(b"not an npz")
    result = _engine(
        problem, None, checkpoint_every=4, checkpoint_path=cp_path
    ).generate(x)
    reference = _engine(problem, None).generate(x)
    np.testing.assert_array_equal(result.x_gen, reference.x_gen)


def test_checkpointer_rejects_wrong_fingerprint(tmp_path):
    path = str(tmp_path / "cp.npz")
    carry = (jnp.arange(3.0), jnp.ones((2, 2)))
    AttackCheckpointer(path, "fp-a").save(carry, done=5, n_hist=0)
    assert AttackCheckpointer(path, "fp-b").load(carry) is None
    restored = AttackCheckpointer(path, "fp-a").load(carry)
    assert restored is not None
    loaded_carry, done, hist = restored
    assert done == 5 and hist == []
    np.testing.assert_array_equal(np.asarray(loaded_carry[0]), np.arange(3.0))


def test_resume_restores_mesh_sharded_carry(problem, tmp_path):
    """Checkpoint + mesh: the restored carry leaves must land back on the
    template's shardings, and the resumed sharded attack must match the
    uninterrupted sharded run bit for bit."""
    from jax.sharding import Mesh

    _, _, x, _ = problem
    x8 = np.concatenate([x, x])  # 8 states: one per virtual device
    mesh = Mesh(np.array(jax.devices()[:8]), ("states",))

    reference = _engine(problem, None, mesh=mesh).generate(x8)

    cp_path = str(tmp_path / "cp.npz")
    crashed = _engine(
        problem, None, mesh=mesh, checkpoint_every=3, checkpoint_path=cp_path
    )
    _crash_on_call(crashed, 3)
    with pytest.raises(_InjectedCrash):
        crashed.generate(x8)
    assert os.path.exists(cp_path)

    resumed = _engine(
        problem, None, mesh=mesh, checkpoint_every=3, checkpoint_path=cp_path
    ).generate(x8)
    np.testing.assert_array_equal(resumed.x_gen, reference.x_gen)
    np.testing.assert_array_equal(resumed.f, reference.f)


def test_resume_crosses_mesh_boundaries(problem, tmp_path):
    """The checkpoint is placement-agnostic (host npz; ``load`` re-places
    leaves onto the template's shardings): the SAME checkpoint must resume
    under a different mesh layout than it was written under — continuing
    mid-run, not restarting — and agree with the same-layout resume.

    The cross-layout comparison is confined to the single post-resume
    generation: the sharded and unsharded XLA programs differ in the last
    ulp of the objectives (see test_moeva_engine.py::test_mesh_matches_
    single_device), so only the pre-bifurcation horizon is bit-comparable."""
    import shutil
    from jax.sharding import Mesh

    _, _, x, _ = problem
    x8 = np.concatenate([x, x])
    mesh = Mesh(np.array(jax.devices()[:8]), ("states",))

    reference = _engine(problem, None).generate(x8)

    # crash a meshless run right after the generation-8 boundary: one
    # generation remains after resume (n_gen=10 -> 9 scan steps)
    cp_path = str(tmp_path / "cp.npz")
    crashed = _engine(
        problem, None, checkpoint_every=4, checkpoint_path=cp_path
    )
    _crash_on_call(crashed, 3)
    with pytest.raises(_InjectedCrash):
        crashed.generate(x8)
    assert os.path.exists(cp_path)
    cp_copy = str(tmp_path / "cp_copy.npz")
    shutil.copy(cp_path, cp_copy)  # completion clears the file; keep a twin

    # resume meshless: must match the uninterrupted run bit for bit
    resumed_1 = _engine(
        problem, None, checkpoint_every=4, checkpoint_path=cp_path
    ).generate(x8)
    np.testing.assert_array_equal(resumed_1.x_gen, reference.x_gen)
    np.testing.assert_array_equal(resumed_1.f, reference.f)

    # resume the SAME checkpoint under the 8-device mesh, with a
    # non-vacuity guard: a fingerprint mismatch would silently restart from
    # generation 0, which is exactly the failure this test must catch
    shutil.copy(cp_copy, cp_path)
    resumed_engine = _engine(
        problem, None, mesh=mesh, checkpoint_every=4, checkpoint_path=cp_path
    )
    resumed_engine._jit_init = jax.jit(resumed_engine._build_init())
    real_segment = jax.jit(
        resumed_engine._build_segment(), static_argnames="length"
    )
    executed = {"gens": 0}

    def counting(*args, **kwargs):
        executed["gens"] += kwargs["length"]
        return real_segment(*args, **kwargs)

    resumed_engine._jit_segment = counting
    resumed_m = resumed_engine.generate(x8)
    assert executed["gens"] == 1, (
        f"mesh resume must continue from generation 8, not restart "
        f"(executed {executed['gens']} of 9 steps)"
    )
    np.testing.assert_array_equal(resumed_m.x_gen, resumed_1.x_gen)
    np.testing.assert_allclose(resumed_m.f, resumed_1.f, rtol=0, atol=1e-12)


def test_chunked_run_resumes_bit_identical(problem, tmp_path):
    """Chunked execution (max_states_per_call) gives every chunk its own
    checkpoint file (``.chunk{i}`` suffix); a crash inside a later chunk must
    resume THAT chunk mid-run — earlier chunks' work is already durable and
    the final result equals an uninterrupted chunked run bit for bit."""
    _, _, x, _ = problem  # 4 states -> chunks of 2

    reference = _engine(problem, None, max_states_per_call=2).generate(x)

    cp_path = str(tmp_path / "cp_chunked.npz")
    crashed = _engine(
        problem, None, max_states_per_call=2,
        checkpoint_every=3, checkpoint_path=cp_path,
    )
    # chunk 0 takes dispatches 1-3 (9 generations in segments of <=3);
    # dispatch 5 lands inside chunk 1, past its first checkpoint boundary
    _crash_on_call(crashed, 5)
    with pytest.raises(_InjectedCrash):
        crashed.generate(x)
    assert os.path.exists(cp_path + ".chunk1"), "chunk 1 must have checkpointed"
    assert not os.path.exists(cp_path + ".chunk0"), "finished chunk cleared"

    resumed = _engine(
        problem, None, max_states_per_call=2,
        checkpoint_every=3, checkpoint_path=cp_path,
    ).generate(x)
    np.testing.assert_array_equal(resumed.x_gen, reference.x_gen)
    np.testing.assert_array_equal(resumed.f, reference.f)
    assert not os.path.exists(cp_path + ".chunk1"), "completed run cleans up"
