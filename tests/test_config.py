"""Config system: merge semantics, scalar typing, hash identity, CLI layering.

Oracle: the reference's config parser behaviour
(``/root/reference/src/config_parser/config_parser.py``).
"""

import json

import pytest

from moeva2_ijcai22_replication_tpu.utils.config import (
    dotted_to_dict,
    get_dict_hash,
    merge_config,
    parse_config,
    save_config,
    value_parser,
)


class TestValueParser:
    def test_ints(self):
        assert value_parser("42") == 42
        assert value_parser("-3") == -3

    def test_floats(self):
        assert value_parser("0.5") == 0.5
        assert value_parser("1.0e-3") == pytest.approx(1e-3)
        assert value_parser("+2.5e+2") == pytest.approx(250.0)
        # YAML 1.1 quirk shared with the reference: exponent floats without a
        # decimal point stay strings.
        assert value_parser("1e-3") == "1e-3"

    def test_strings_stay_strings(self):
        # The reference's regex only types number-shaped values; booleans and
        # words stay strings (config_parser.py:11-16).
        assert value_parser("flip+sat") == "flip+sat"
        assert value_parser("True") == "True"
        assert value_parser("1.2.3") == "1.2.3"


class TestMerge:
    def test_nested_dicts_recurse(self):
        a = {"paths": {"model": "a", "features": "f"}, "seed": 1}
        merge_config(a, {"paths": {"model": "b"}})
        assert a == {"paths": {"model": "b", "features": "f"}, "seed": 1}

    def test_lists_replace(self):
        a = {"eps_list": [0.1, 0.2]}
        merge_config(a, {"eps_list": [4]})
        assert a["eps_list"] == [4]

    def test_later_sources_win(self):
        a = {}
        for b in [{"budget": 100}, {"budget": 1000}]:
            merge_config(a, b)
        assert a["budget"] == 1000

    def test_dotted(self):
        assert dotted_to_dict("a.b.c", 5) == {"a": {"b": {"c": 5}}}


class TestHash:
    def test_key_order_invariant(self):
        assert get_dict_hash({"a": 1, "b": [2]}) == get_dict_hash({"b": [2], "a": 1})

    def test_value_sensitivity(self):
        assert get_dict_hash({"a": 1}) != get_dict_hash({"a": 2})

    def test_known_md5(self):
        # Pin the exact identity function: md5 of sorted-key JSON
        # (config_parser.py:106-109) — experiment hashes must survive the port.
        import hashlib

        d = {"seed": 42, "paths": {"model": "m"}}
        expect = hashlib.md5(
            json.dumps(d, sort_keys=True).encode()
        ).hexdigest()
        assert get_dict_hash(d) == expect


class TestParseConfig:
    def test_layering(self, tmp_path):
        base = tmp_path / "base.yaml"
        base.write_text("budget: 100\npaths:\n  model: base.model\n")
        over = tmp_path / "over.json"
        over.write_text('{"budget": 200}')

        cfg = parse_config(
            [
                "-c", str(base),
                "-c", str(over),
                "-j", '{"eps_list":[0.2]}',
                "-p", "seed=42",
                "-p", "paths.features=f.csv",
                "-p", "loss_evaluation=flip+sat",
            ]
        )
        assert cfg == {
            "budget": 200,
            "paths": {"model": "base.model", "features": "f.csv"},
            "eps_list": [0.2],
            "seed": 42,
            "loss_evaluation": "flip+sat",
        }

    def test_save_roundtrip(self, tmp_path):
        cfg = {"seed": 7, "paths": {"model": "m"}}
        path = save_config(cfg, str(tmp_path) + "/config_moeva_")
        assert path.endswith(get_dict_hash(cfg) + ".yaml")
        import yaml

        with open(path) as f:
            assert yaml.full_load(f) == cfg
