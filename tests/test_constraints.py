import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.core.constraints import ConstraintViolationError
from moeva2_ijcai22_replication_tpu.domains import (
    BotnetConstraints,
    LcldConstraints,
    get_constraints_class,
)
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld


@pytest.fixture(scope="module")
def lcld(lcld_paths):
    return LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])


@pytest.fixture(scope="module")
def botnet(botnet_paths):
    return BotnetConstraints(botnet_paths["features"], botnet_paths["constraints"])


def test_registry():
    assert get_constraints_class("lcld") is LcldConstraints
    with pytest.raises(ValueError):
        get_constraints_class("nope")


def test_lcld_synth_satisfies(lcld):
    x = synth_lcld(256, lcld.schema, seed=0)
    g = np.asarray(lcld.evaluate(jnp.asarray(x)))
    assert g.shape == (256, 10)
    assert np.all(g == 0.0), f"max violation {g.max()} at {np.unravel_index(g.argmax(), g.shape)}"
    lcld.check_constraints_error(x)  # should not raise


def test_lcld_violations_detected(lcld):
    x = synth_lcld(16, lcld.schema, seed=5)
    # Break the installment formula (constraint 0)
    x1 = x.copy()
    x1[:, 3] += 5.0
    g = np.asarray(lcld.evaluate(jnp.asarray(x1)))
    assert np.all(g[:, 0] > 0)
    # Break open_acc <= total_acc (constraint 1)
    x2 = x.copy()
    x2[:, 10] = x2[:, 14] + 3
    g = np.asarray(lcld.evaluate(jnp.asarray(x2)))
    assert np.all(g[:, 1] > 0)
    # Term not in {36, 60} (constraint 3)
    x3 = x.copy()
    x3[:, 1] = 48
    g = np.asarray(lcld.evaluate(jnp.asarray(x3)))
    assert np.all(g[:, 3] > 0)
    with pytest.raises(ConstraintViolationError):
        lcld.check_constraints_error(x3)


def test_lcld_divzero_sentinel(lcld):
    x = synth_lcld(8, lcld.schema, seed=6)
    x[:, 11] = 0.0  # pub_rec = 0
    x[:, 16] = 0.0
    x[:, 23] = x[:, 11] / x[:, 22]
    x[:, 24] = x[:, 16] / x[:, 22]
    x[:, 25] = -1.0  # sentinel expected by the oracle
    g = np.asarray(lcld.evaluate(jnp.asarray(x)))
    assert np.all(g[:, 9] == 0.0)


def test_lcld_repair(lcld):
    x = synth_lcld(32, lcld.schema, seed=7)
    x_broken = x.copy()
    x_broken[:, 1] = 42.0  # invalid term
    x_broken[:, 3] += 30.0  # broken installment
    repaired = np.asarray(lcld.repair(jnp.asarray(x_broken)))
    g = np.asarray(lcld.evaluate(jnp.asarray(repaired)))
    assert np.all(g[:, 0] == 0.0)  # installment formula restored
    assert np.all(g[:, 3] == 0.0)  # term snapped to {36,60}
    assert set(np.unique(repaired[:, 1])) <= {36.0, 60.0}


def test_lcld_smooth_vs_hard(lcld):
    x = synth_lcld(16, lcld.schema, seed=8)
    x[:, 3] += 1.0
    hard = np.asarray(lcld.evaluate(jnp.asarray(x)))
    smooth = np.asarray(lcld.evaluate_smooth(jnp.asarray(x)))
    # hard keeps raw magnitude; smooth shifts by tol — both flag the same set
    assert np.array_equal(hard > 0, smooth > 0)
    np.testing.assert_allclose(hard[hard > 0] - smooth[smooth > 0], lcld.tol, rtol=1e-6)


def test_lcld_gradients(lcld):
    import jax

    x = jnp.asarray(synth_lcld(4, lcld.schema, seed=9))
    loss = lambda z: lcld.evaluate_smooth(z).sum()
    grads = jax.grad(loss)(x + 0.01)
    assert np.all(np.isfinite(np.asarray(grads)))


def test_botnet_real_candidates_satisfy(botnet, botnet_candidates):
    # The reference runs check_constraints_error on this exact set before
    # attacking (04_moeva.py:64) — our kernel must agree it is clean.
    g = np.asarray(botnet.evaluate(jnp.asarray(botnet_candidates)))
    assert g.shape == (387, 360)
    assert np.all(g == 0.0), f"max violation {g.max()}"


def test_botnet_violations_detected(botnet, botnet_candidates):
    x = np.array(botnet_candidates[:8])
    # Violate a min<=max ordering: set a min above its max counterpart.
    lo, up = botnet._orderings[2]
    lo0, up0 = int(np.asarray(lo)[0]), int(np.asarray(up)[0])
    x[:, lo0] = x[:, up0] + 10.0
    g = np.asarray(botnet.evaluate(jnp.asarray(x)))
    assert np.all(g.sum(axis=1) > 0)


def test_botnet_batched_shapes(botnet, botnet_candidates):
    x = jnp.asarray(botnet_candidates[:6]).reshape(2, 3, -1)
    g = np.asarray(botnet.evaluate(x))
    assert g.shape == (2, 3, 360)
