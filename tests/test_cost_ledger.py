"""Executable cost ledger, roofline attribution, and the bench_diff
perf-regression watchdog.

Core (hardware-free): the cost/memory-analysis probes degrade gracefully
on backends without a cost model (satellite: ``cost_available: false``
instead of a crash), LedgeredJit compiles AOT exactly once per argument
signature and dispatches the identical executable, recompile causes name
the key fields that differed, and the roofline math joins model FLOPs
with attributed run seconds.

Producers (tier-1 acceptance): a PGD engine, a MoEvA engine (init +
segment + success-gate programs), and a serving smoke through the
microbatcher all land in the process ledger with identity (rows, loss
strategy, bucket) and compile wall-clock — and the overhead smoke proves
ledger-off runs dispatch the same number of programs and produce
bit-identical outputs.

Watchdog: ``tools/bench_diff.py`` threshold logic on fixture records
(passes on improvement and on cost-explained shape changes, fails on an
injected 2x slowdown) plus the repo check over the committed
``BENCH_r*.json`` series.
"""

import importlib.util
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.observability import (
    LEDGER,
    CostLedger,
    LedgeredJit,
    get_ledger,
    ledger_context,
    telemetry_block,
    validate_record,
)
from moeva2_ijcai22_replication_tpu.observability.ledger import (
    probe_cost_analysis,
    probe_memory_analysis,
)
from moeva2_ijcai22_replication_tpu.observability.prom import prometheus_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def clean_ledger():
    """Each test sees an empty process ledger (engines record into the
    global one; entries from other test modules must not leak in)."""
    LEDGER.reset()
    LEDGER.enabled = True
    yield
    LEDGER.reset()
    LEDGER.enabled = True


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# probes: graceful degradation when the backend has no cost model
# ---------------------------------------------------------------------------


class TestProbes:
    def test_cost_probe_handles_raising_none_and_empty(self):
        class Raises:
            def cost_analysis(self):
                raise NotImplementedError("no cost model on this backend")

        class ReturnsNone:
            def cost_analysis(self):
                return None

        class Empty:
            def cost_analysis(self):
                return []

        assert probe_cost_analysis(Raises()) is None
        assert probe_cost_analysis(ReturnsNone()) is None
        assert probe_cost_analysis(Empty()) is None

    def test_cost_probe_accepts_list_and_dict_shapes(self):
        class AsList:
            def cost_analysis(self):
                return [{"flops": 10.0, "bytes accessed": 40.0}]

        class AsDict:
            def cost_analysis(self):
                return {"flops": 7, "transcendentals": 2}

        assert probe_cost_analysis(AsList()) == {
            "flops": 10.0,
            "bytes_accessed": 40.0,
        }
        assert probe_cost_analysis(AsDict()) == {
            "flops": 7.0,
            "transcendentals": 2.0,
        }

    def test_memory_probe_handles_raising_and_none(self):
        class Raises:
            def memory_analysis(self):
                raise RuntimeError("unimplemented")

        class ReturnsNone:
            def memory_analysis(self):
                return None

        assert probe_memory_analysis(Raises()) is None
        assert probe_memory_analysis(ReturnsNone()) is None

    def test_no_cost_model_records_cost_available_false(self, monkeypatch):
        """The satellite contract: a backend returning no cost model yields
        a ledger entry with ``cost_available: false`` — never a crash, and
        the dispatch result is unaffected."""
        import jax
        import jax.numpy as jnp

        from moeva2_ijcai22_replication_tpu.observability import ledger as L

        monkeypatch.setattr(
            L, "probe_cost_analysis", lambda c: (_ for _ in ()).throw(
                RuntimeError("boom")
            ) if False else None
        )
        monkeypatch.setattr(L, "probe_memory_analysis", lambda c: None)
        led = CostLedger()
        lj = LedgeredJit(
            jax.jit(lambda x: x * 2), producer="p", ledger=led
        )
        out = lj(jnp.arange(4.0))
        np.testing.assert_array_equal(np.asarray(out), np.arange(4.0) * 2)
        (entry,) = led.entries()
        assert entry.cost_available is False
        assert entry.flops is None and entry.memory is None
        assert entry.aot is True


# ---------------------------------------------------------------------------
# LedgeredJit: AOT capture, caching, fallback
# ---------------------------------------------------------------------------


class TestLedgeredJit:
    def test_compiles_once_per_signature_and_records(self):
        import jax
        import jax.numpy as jnp

        led = CostLedger()
        lj = LedgeredJit(
            jax.jit(lambda x: (x * x).sum()),
            producer="toy",
            identity={"family": "square"},
            describe_args=lambda x: {"rows": int(x.shape[0])},
            ledger=led,
        )
        a = jnp.arange(8.0)
        r1 = lj(a)
        r2 = lj(a)
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
        (entry,) = led.entries()
        assert entry.producer == "toy"
        assert entry.identity["family"] == "square"
        assert entry.identity["rows"] == 8
        assert entry.compile_s > 0
        assert entry.dispatches == 2
        assert led.hits == 1 and led.misses == 1
        assert lj.calls == 2
        # CPU in this jax version ships a cost model: the acceptance run
        # records real FLOPs (other backends may legitimately record None)
        assert entry.cost_available in (True, False)

    def test_new_shape_compiles_new_entry_with_recompile_cause(self):
        import jax
        import jax.numpy as jnp

        led = CostLedger()
        lj = LedgeredJit(
            jax.jit(lambda x: x + 1),
            producer="toy",
            describe_args=lambda x: {"rows": int(x.shape[0])},
            ledger=led,
        )
        lj(jnp.arange(8.0))
        lj(jnp.arange(16.0))
        assert len(led.entries()) == 2
        (cause,) = led.recompile_causes
        assert cause["producer"] == "toy"
        assert cause["changed"] == {"rows": {"from": 8, "to": 16}}

    def test_static_kwargs_partition_the_cache(self):
        import jax
        import jax.numpy as jnp

        led = CostLedger()
        lj = LedgeredJit(
            jax.jit(
                lambda x, length: jax.lax.scan(
                    lambda c, _: (c + 1.0, None), x, None, length=length
                )[0],
                static_argnames="length",
            ),
            producer="scan",
            describe_args=lambda x, **kw: {"length": kw.get("length")},
            static_argnames=("length",),
            ledger=led,
        )
        a = jnp.zeros(4)
        out3 = lj(a, length=3)
        out5 = lj(a, length=5)
        assert float(np.asarray(out3)[0]) == 3.0
        assert float(np.asarray(out5)[0]) == 5.0
        assert len(led.entries()) == 2
        (cause,) = led.recompile_causes
        assert "length" in cause["changed"]

    def test_lowering_failure_falls_back_to_jit(self):
        import jax
        import jax.numpy as jnp

        class NoAotJit:
            """A jitted callable whose AOT path is broken (older jax /
            exotic backend): dispatch must fall back to the jit path."""

            def __init__(self, f):
                self._f = jax.jit(f)

            def __call__(self, *a, **k):
                return self._f(*a, **k)

            def lower(self, *a, **k):
                raise RuntimeError("no AOT on this backend")

        led = CostLedger()
        lj = LedgeredJit(NoAotJit(lambda x: x - 1), producer="fallback", ledger=led)
        out = lj(jnp.arange(3.0))
        np.testing.assert_array_equal(np.asarray(out), np.arange(3.0) - 1)
        (entry,) = led.entries()
        assert entry.aot is False and entry.cost_available is False
        assert entry.dispatches == 1
        # the real trace+compile happened inside the first jit call: it is
        # booked as compile (on the entry AND in last_call_compile_s, so
        # engine run attribution keeps compile out of run seconds)
        assert lj.last_call_compile_s > 0
        assert entry.compile_s >= lj.last_call_compile_s * 0.5
        # warm call: no compile consumed
        lj(jnp.arange(3.0))
        assert lj.last_call_compile_s == 0.0

    def test_disabled_ledger_still_dispatches_identically(self):
        import jax
        import jax.numpy as jnp

        led = CostLedger(enabled=False)
        lj = LedgeredJit(jax.jit(lambda x: x * 3), producer="off", ledger=led)
        out = lj(jnp.arange(5.0))
        np.testing.assert_array_equal(np.asarray(out), np.arange(5.0) * 3)
        assert led.entries() == []  # nothing recorded...
        assert led.misses == 1  # ...but the compile still counted
        assert lj.calls == 1

    def test_ledger_context_merges_into_identity(self):
        import jax
        import jax.numpy as jnp

        led = CostLedger()
        lj = LedgeredJit(jax.jit(lambda x: x), producer="ctx", ledger=led)
        with ledger_context(bucket=64, batch_requests=3):
            lj(jnp.arange(2.0))
        (entry,) = led.entries()
        assert entry.identity["bucket"] == 64
        assert entry.identity["batch_requests"] == 3


# ---------------------------------------------------------------------------
# ledger core: roofline math, summaries, cost block
# ---------------------------------------------------------------------------


class TestLedgerCore:
    def _entry(self, led, producer="synth", flops=2e9, bytes_=1e8):
        return led.record_compile(
            producer=producer,
            identity={"rows": 64},
            backend="cpu",
            compile_s=1.5,
            cost={"flops": flops, "bytes_accessed": bytes_},
            memory={"argument_bytes": 1024, "temp_bytes": 256},
        )

    def test_roofline_math_on_synthetic_spans(self):
        """2 GFLOP program, 4 dispatches attributed 2 s of device_run
        spans -> 4 GFLOP/s achieved; intensity = flops / bytes."""
        led = CostLedger()
        e = self._entry(led)
        for _ in range(4):
            led.record_dispatch(e.key)
        led.add_run_seconds(e.key, 1.25)
        led.add_run_seconds(e.key, 0.75)
        r = e.roofline()
        assert r["dispatches"] == 4
        assert r["run_s"] == 2.0
        assert r["achieved_flops_s"] == pytest.approx(4e9)
        assert r["achieved_bytes_s"] == pytest.approx(2e8)
        assert r["arithmetic_intensity"] == pytest.approx(20.0)

    def test_roofline_without_runs_or_cost(self):
        led = CostLedger()
        e = led.record_compile(
            producer="p", identity={}, backend="cpu", compile_s=0.1,
            cost=None, memory=None,
        )
        r = e.roofline()
        assert r["achieved_flops_s"] is None
        assert r["arithmetic_intensity"] is None
        assert e.cost_available is False

    def test_roofline_for_joins_span_duration(self):
        led = CostLedger()
        e1 = self._entry(led, flops=1e9, bytes_=1e8)
        e2 = self._entry(led, flops=3e9, bytes_=1e8)
        r = led.roofline_for([e1.key, e2.key], seconds=2.0)
        assert r["flops"] == pytest.approx(4e9)
        assert r["achieved_flops_s"] == pytest.approx(2e9)
        # dispatch-count mapping: a span chaining one executable 5 times
        # must count its flops 5 times
        r5 = led.roofline_for({e1.key: 5}, seconds=2.0)
        assert r5["flops"] == pytest.approx(5e9)
        assert r5["achieved_flops_s"] == pytest.approx(2.5e9)
        assert led.roofline_for([e1.key], seconds=0.0) is None
        assert led.roofline_for(["missing"], seconds=1.0) is None

    def test_mark_scopes_cost_block_to_the_window(self):
        """A record's telemetry.cost must cover the run that produced it:
        earlier compiles are excluded, re-dispatched warm executables
        appear with compile 0 and delta dispatch/run numbers."""
        led = CostLedger()
        e1 = self._entry(led, flops=1e9)
        led.record_dispatch(e1.key)
        led.add_run_seconds(e1.key, 1.0)
        mark = led.mark()

        # warm re-dispatch of e1 inside the window + one new compile
        led.record_hit()
        led.record_dispatch(e1.key)
        led.add_run_seconds(e1.key, 0.5)
        e2 = self._entry(led, producer="new", flops=2e9)
        led.record_dispatch(e2.key)

        block = led.cost_block(since=mark)
        rows = {r["key"]: r for r in block["entries"]}
        assert set(rows) == {e1.key, e2.key}
        # e1 compiled BEFORE the window: compile charged 0, deltas only
        assert rows[e1.key]["compile_s"] == 0.0
        assert rows[e1.key]["dispatches"] == 1
        assert rows[e1.key]["run_s"] == 0.5
        assert rows[e1.key]["achieved_flops_s"] == pytest.approx(2e9)
        # e2 compiled inside: full compile time
        assert rows[e2.key]["compile_s"] == 1.5
        assert block["compile_s_total"] == 1.5
        assert block["cache_hits"] == 1 and block["cache_misses"] == 1
        assert block["flops_total"] == pytest.approx(1e9 + 2e9)
        # an executable untouched in the window stays out entirely
        mark2 = led.mark()
        assert led.cost_block(since=mark2)["entries"] == []
        assert led.cost_block(since=mark2)["flops_total"] is None

    def test_summary_and_delta(self):
        led = CostLedger()
        e = self._entry(led)
        led.record_dispatch(e.key)
        before = led.summary()
        assert before["executables"] == 1
        assert before["compile_s_total"] == 1.5
        assert before["cost_available"] is True
        e2 = self._entry(led, producer="other")
        led.record_dispatch(e2.key)
        led.record_hit()
        delta = led.summary_delta(before)
        assert delta["executables"] == 1
        assert delta["compile_s_total"] == 1.5
        assert delta["cache_hits"] == 1 and delta["cache_misses"] == 1
        assert delta["cache_hit_ratio"] == 0.5

    def test_cost_block_is_json_ready_and_carries_entries(self):
        led = CostLedger()
        self._entry(led)
        block = led.cost_block()
        json.dumps(block)
        (row,) = block["entries"]
        assert row["identity"]["rows"] == 64
        assert row["memory"]["argument_bytes"] == 1024
        assert {"flops", "compile_s", "achieved_flops_s"} <= set(row)

    def test_recompile_cause_picks_nearest_entry(self):
        led = CostLedger()
        led.record_compile(
            producer="p", identity={"rows": 8, "loss": "flip"},
            backend="cpu", compile_s=0.1, cost=None, memory=None,
        )
        led.record_compile(
            producer="p", identity={"rows": 8, "loss": "constraints"},
            backend="cpu", compile_s=0.1, cost=None, memory=None,
        )
        led.record_compile(
            producer="p", identity={"rows": 16, "loss": "constraints"},
            backend="cpu", compile_s=0.1, cost=None, memory=None,
        )
        assert len(led.recompile_causes) == 2
        # the third compile diffs against its nearest neighbour (entry 2):
        # only `rows` changed, not `loss`
        last = led.recompile_causes[-1]
        assert list(last["changed"]) == ["rows"]
        assert last["changed"]["rows"] == {"from": 8, "to": 16}

    def test_validator_requires_cost_sub_block(self):
        with pytest.raises(ValueError, match="cost"):
            validate_record(
                {"execution": {}, "telemetry": {"hbm": None}}, "bench"
            )
        rec = {"execution": {}, "telemetry": telemetry_block()}
        assert validate_record(rec, "bench") is rec
        assert rec["telemetry"]["cost"]["enabled"] is True


# ---------------------------------------------------------------------------
# producers: engines + serving populate the process ledger
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Synthetic-LCLD artifact family (same shape as test_tracing's) —
    dataset- and hardware-free."""
    import joblib
    from sklearn.preprocessing import MinMaxScaler

    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_lcld,
        synth_lcld_schema,
    )
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp

    tmp = tmp_path_factory.mktemp("ledger_artifacts")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(64, cons.schema, seed=5)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=6))
    save_params(sur, str(tmp / "nn.msgpack"))
    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    joblib.dump(
        MinMaxScaler().fit(np.vstack([x, xl, xu])), tmp / "scaler.joblib"
    )
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    return {
        "pool": x,
        "cons": cons,
        "sur": sur,
        "scaler": fit_minmax(np.vstack([x, xl, xu]).min(0),
                             np.vstack([x, xl, xu]).max(0)),
        "domain": {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": paths["features"],
                "constraints": paths["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
        },
    }


def _pgd(artifacts, **kw):
    from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD

    kw.setdefault("max_iter", 3)
    return ConstrainedPGD(
        classifier=artifacts["sur"],
        constraints=artifacts["cons"],
        scaler=artifacts["scaler"],
        **kw,
    )


class TestProducers:
    def test_pgd_engine_populates_ledger(self, artifacts):
        pgd = _pgd(artifacts)
        xs = np.asarray(artifacts["scaler"].transform(artifacts["pool"][:8]))
        y = np.asarray(artifacts["sur"].predict_proba(xs)).argmax(-1)
        pgd.generate(xs, y)
        pgd.generate(xs, y)  # executable-cache hit, one more dispatch
        (entry,) = [
            e for e in LEDGER.entries() if e.producer == "pgd_attack"
        ]
        assert entry.identity["engine"] == "ConstrainedPGD"
        assert entry.identity["loss_evaluation"] == "flip"
        assert entry.identity["rows"] == 8
        assert entry.compile_s > 0
        assert entry.dispatches == 2
        assert entry.run_s > 0  # attributed at the fetch sync point
        assert pgd.trace_count == 1  # one trace per executable, as before
        assert pgd.last_run_executables == [entry.key]

    def test_moeva_engine_populates_init_segment_success(self, artifacts):
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2

        moeva = Moeva2(
            classifier=artifacts["sur"],
            constraints=artifacts["cons"],
            ml_scaler=artifacts["scaler"],
            norm=2,
            n_gen=5,
            n_pop=8,
            n_offsprings=4,
            seed=11,
            archive_size=2,
            early_stop_check_every=2,
        )
        moeva.generate(artifacts["pool"][:4], 1)
        producers = {e.producer for e in LEDGER.entries()}
        # all three MoEvA program families, including the success-gate probe
        assert {"moeva_init", "moeva_segment", "moeva_success"} <= producers
        seg = next(
            e for e in LEDGER.entries() if e.producer == "moeva_segment"
        )
        assert seg.identity["rows"] == 4
        assert seg.identity["length"] == 2  # gate every 2 generations
        assert seg.identity["n_pop"] == 8
        assert seg.compile_s > 0
        # run attribution covered the whole generate (compile excluded)
        assert sum(e.run_s for e in LEDGER.entries()) > 0
        assert set(moeva.last_run_executables) <= {
            e.key for e in LEDGER.entries()
        }

    def test_serving_microbatcher_bucket_lands_in_identity(self, artifacts):
        from moeva2_ijcai22_replication_tpu.serving import (
            AttackRequest,
            AttackService,
        )

        svc = AttackService(
            {"lcld": artifacts["domain"]},
            bucket_sizes=(8,),
            max_delay_s=0.01,
        )
        try:
            resp = svc.attack(
                AttackRequest(
                    domain="lcld",
                    x=artifacts["pool"][:3],
                    eps=0.2,
                    budget=2,
                ),
                timeout=300.0,
            )
            assert resp.x_adv.shape[0] == 3
            entries = [
                e for e in LEDGER.entries() if e.producer == "pgd_attack"
            ]
            assert entries, "serving dispatch must land in the ledger"
            entry = entries[0]
            # microbatcher context: the executable knows its bucket
            assert entry.identity["bucket"] == 8
            assert entry.identity["rows"] == 8  # padded to the bucket
            assert entry.identity["batch_requests"] == 1
            # engine-cache identity joined in (built through ENGINES)
            assert entry.identity["cache_key"] is not None

            # /healthz: ledger summary + cache introspection next to build
            health = svc.healthz()
            assert health["ledger"]["executables"] >= 1
            assert health["ledger"]["compile_s_total"] > 0
            assert "cache_hit_ratio" in health["ledger"]
            assert "recompile_causes" in health["caches"]["engine"]
            assert "evictions" in health["caches"]["artifact"]

            # /metrics: cost ledger in the JSON snapshot and as labeled
            # Prometheus gauges
            snap = svc.metrics_snapshot()
            assert snap["cost_ledger"]["executables"] >= 1
            text = prometheus_text(snap)
            assert "moeva2_cost_ledger_executables 1" in text
            assert "moeva2_executable_compile_s{" in text
            assert 'producer="pgd_attack"' in text

            # meta.trace roofline: re-request with tracing on (same cached
            # engine/executable — zero new compiles)
            from moeva2_ijcai22_replication_tpu.observability import (
                TraceRecorder,
            )
        finally:
            svc.close()

        rec = TraceRecorder(spans_enabled=True)
        svc2 = AttackService(
            {"lcld": artifacts["domain"]},
            bucket_sizes=(8,),
            max_delay_s=0.01,
            recorder=rec,
        )
        try:
            resp2 = svc2.attack(
                AttackRequest(
                    domain="lcld", x=artifacts["pool"][:3], eps=0.2, budget=2
                ),
                timeout=300.0,
            )
            flat, stack = [], list(resp2.meta["trace"])
            while stack:
                node = stack.pop()
                flat.append(node)
                stack.extend(node.get("children", ()))
            dev = next(
                n
                for n in flat
                if n["name"] in ("device_run", "device_compile")
            )
            assert dev["attrs"]["executables"]
            if LEDGER.entries()[0].flops is not None:
                assert dev["attrs"]["roofline"]["achieved_flops_s"] > 0
        finally:
            svc2.close()

    def test_grid_report_carries_ledger_delta(self):
        from moeva2_ijcai22_replication_tpu.experiments.pipeline import (
            GridPipeline,
        )
        from moeva2_ijcai22_replication_tpu.observability import TraceRecorder

        gp = GridPipeline(recorder=TraceRecorder(spans_enabled=False))
        LEDGER.record_compile(
            producer="p", identity={}, backend="cpu", compile_s=0.5,
            cost=None, memory=None,
        )
        report = gp.finish({"system": {"mesh_devices": 0}}, [])
        assert report["ledger"]["executables"] == 1
        assert report["ledger"]["compile_s_total"] == 0.5
        assert "cost" in report["telemetry"]
        assert validate_record(report, "grid") is report


class TestLedgerOverhead:
    def test_ledger_off_is_bit_identical_with_zero_extra_dispatches(
        self, artifacts
    ):
        """Tier-1 smoke: toggling the ledger changes bookkeeping only —
        same dispatch count, same trace count, bit-identical outputs."""
        xs = np.asarray(artifacts["scaler"].transform(artifacts["pool"][:8]))
        y = np.asarray(artifacts["sur"].predict_proba(xs)).argmax(-1)

        LEDGER.enabled = True
        pgd_on = _pgd(artifacts)
        out_on = pgd_on.generate(xs, y)
        n_entries_on = len(LEDGER.entries())
        assert n_entries_on == 1

        LEDGER.enabled = False
        pgd_off = _pgd(artifacts)
        out_off = pgd_off.generate(xs, y)
        assert len(LEDGER.entries()) == n_entries_on  # nothing new recorded

        # bit-identical numerics
        np.testing.assert_array_equal(out_on, out_off)
        # zero extra dispatches and zero extra compiles either way
        assert pgd_on._jit_attack.calls == pgd_off._jit_attack.calls == 1
        assert pgd_on.trace_count == pgd_off.trace_count == 1

    def test_moeva_ledger_toggle_bit_identical(self, artifacts):
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2

        def run():
            m = Moeva2(
                classifier=artifacts["sur"],
                constraints=artifacts["cons"],
                ml_scaler=artifacts["scaler"],
                norm=2,
                n_gen=4,
                n_pop=8,
                n_offsprings=4,
                seed=13,
            )
            res = m.generate(artifacts["pool"][:4], 1)
            return res, m

        LEDGER.enabled = True
        res_on, m_on = run()
        LEDGER.enabled = False
        res_off, m_off = run()
        np.testing.assert_array_equal(res_on.x_gen, res_off.x_gen)
        np.testing.assert_array_equal(res_on.f, res_off.f)
        assert m_on.trace_count == m_off.trace_count
        assert (
            m_on._jit_segment.calls + m_on._jit_init.calls
            == m_off._jit_segment.calls + m_off._jit_init.calls
        )


# ---------------------------------------------------------------------------
# bench_diff: threshold logic + the repo check
# ---------------------------------------------------------------------------


def _write(tmp_path, name, rec):
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


def _bench_record(steady=10.0, value=50.0, flops=None, shape=(1000, 1000)):
    rec = {
        "steady_s": steady,
        "value": value,
        "execution": {"n_states": shape[0], "n_gen": shape[1]},
        "telemetry": {},
    }
    if flops is not None:
        rec["telemetry"]["cost"] = {"flops_total": flops}
    return rec


class TestBenchDiff:
    @pytest.fixture(scope="class")
    def bench_diff(self):
        return _load_tool("bench_diff")

    def test_passes_on_improvement(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _bench_record(steady=10.0, value=50.0))
        b = _write(tmp_path, "r02.json", _bench_record(steady=9.0, value=55.0))
        assert bench_diff.main([a, b]) == 0

    def test_fails_on_injected_2x_slowdown(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _bench_record(steady=10.0))
        b = _write(tmp_path, "r02.json", _bench_record(steady=20.0))
        assert bench_diff.main([a, b]) == 1

    def test_cost_normalization_explains_shape_changes(
        self, bench_diff, tmp_path
    ):
        """2x wall-clock with 2x ledger FLOPs is NOT a regression — and the
        same wall-clock at constant FLOPs is."""
        a = _write(
            tmp_path, "r01.json", _bench_record(steady=10.0, flops=1e12)
        )
        b = _write(
            tmp_path, "r02.json", _bench_record(steady=20.0, flops=2e12)
        )
        assert bench_diff.main([a, b]) == 0
        c = _write(
            tmp_path, "r03.json", _bench_record(steady=20.0, flops=1e12)
        )
        assert bench_diff.main([a, c]) == 1

    def test_post_ledger_record_still_compares_by_shape(
        self, bench_diff, tmp_path
    ):
        """A record carrying ledger FLOPs must still normalize by shape
        against a pre-ledger record — otherwise an honest shape change
        across the ledger boundary reads as a 2x raw regression."""
        old = _write(
            tmp_path,
            "r01.json",
            _bench_record(steady=10.0, shape=(1000, 1000)),  # pre-ledger
        )
        new = _write(
            tmp_path,
            "r02.json",
            _bench_record(steady=20.0, flops=4e12, shape=(2000, 1000)),
        )
        assert bench_diff.main([old, new]) == 0

    def test_shape_normalization_without_ledger(self, bench_diff, tmp_path):
        a = _write(
            tmp_path,
            "r01.json",
            _bench_record(steady=10.0, shape=(1000, 1000)),
        )
        b = _write(
            tmp_path,
            "r02.json",
            _bench_record(steady=20.0, shape=(2000, 1000)),
        )
        assert bench_diff.main([a, b]) == 0

    def test_threshold_is_configurable(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _bench_record(steady=10.0))
        b = _write(tmp_path, "r02.json", _bench_record(steady=12.0))
        assert bench_diff.main([a, b]) == 0  # 20% < default 25%
        assert bench_diff.main([a, b, "--threshold", "0.1"]) == 1

    def test_wrapper_format_and_crashed_records(self, bench_diff, tmp_path):
        ok = _write(
            tmp_path,
            "r01.json",
            {"n": 1, "rc": 0, "parsed": _bench_record(steady=10.0)},
        )
        crashed = _write(
            tmp_path, "r02.json", {"n": 2, "rc": 1, "parsed": None}
        )
        slow = _write(
            tmp_path,
            "r03.json",
            {"n": 3, "rc": 0, "parsed": _bench_record(steady=30.0)},
        )
        # crashed record is skipped, not treated as evidence
        assert bench_diff.main([ok, crashed, slow]) == 1
        # a single usable record passes trivially
        assert bench_diff.main([ok, crashed]) == 0

    def test_higher_is_better_metrics(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _bench_record(value=80.0))
        b = _write(tmp_path, "r02.json", _bench_record(value=30.0))
        assert bench_diff.main([a, b]) == 1

    def test_argument_order_wins_over_lexical_order(
        self, bench_diff, tmp_path
    ):
        """The CLI contract is oldest-first ARGUMENT order; a lexical
        re-sort would flip before/after pairs whose names don't sort
        chronologically and invert the regression direction."""
        base = _write(tmp_path, "z_before.json", _bench_record(steady=10.0))
        new = _write(tmp_path, "a_after.json", _bench_record(steady=20.0))
        assert bench_diff.main([base, new]) == 1  # 2x slowdown caught
        assert bench_diff.main([new, base]) == 0  # reversed = improvement


class TestBenchDiffRepoCheck:
    def test_committed_series_passes(self):
        """The repo check tier-1 runs: regressions in a future PR's bench
        record fail here. Committed records predate the ledger, so this
        exercises the raw/shape fallback path too. The flag list
        (``--check --slo --mesh --overlap``) lives in ONE place now —
        ``tools/repo_check.py`` — so this test drives the gate through
        the consolidated entrypoint: SLO (knee QPS + p99-at-fixed-load),
        mesh (balance ratio + hot-loop collectives), and overlap (device
        overlap ratio + cold/steady ratio) all arm with the first record
        carrying their telemetry block; pre-capture records skip as
        baselines."""
        import glob as _glob

        series = sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        assert len(series) >= 2
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "repo_check.py"),
             "--only", "bench_diff", "--json"],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "bench_diff: ok" in proc.stdout
        assert "repo_check: ok" in proc.stdout
        payload = json.loads(proc.stdout.strip().splitlines()[-1])
        assert payload["ok"] is True
        assert payload["gates"]["bench_diff"]["ok"] is True
        # the consolidated gate must keep every watchdog armed: the
        # bench_diff invocation it wraps carries all four flags
        verdict = json.loads(
            [
                line
                for line in proc.stdout.splitlines()
                if line.startswith("{") and '"latest"' in line
            ][-1]
        )
        assert verdict["slo"] and verdict["mesh"] and verdict["overlap"]


# ---------------------------------------------------------------------------
# trace_export robustness (satellite): empty / truncated JSONL sinks
# ---------------------------------------------------------------------------


class TestTraceExportRobustness:
    def test_truncated_last_line_is_skipped_with_warning(self, tmp_path):
        from moeva2_ijcai22_replication_tpu.observability.export import (
            read_jsonl,
        )

        p = tmp_path / "trace.jsonl"
        p.write_text(
            json.dumps({"kind": "meta", "t0_wall": 1.0}) + "\n"
            + json.dumps({"kind": "event", "name": "e", "ts": 0.1}) + "\n"
            + '{"kind": "span", "name": "cut-off mid-wr'  # no newline: crash
        )
        with pytest.warns(UserWarning, match="unparseable"):
            events = read_jsonl(str(p))
        assert [e["kind"] for e in events] == ["meta", "event"]
        with pytest.raises(json.JSONDecodeError):
            read_jsonl(str(p), strict=True)

    def test_empty_sink_renders_empty_perfetto_doc(self, tmp_path):
        from moeva2_ijcai22_replication_tpu.observability.export import (
            read_jsonl,
            to_chrome_trace,
        )

        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert read_jsonl(str(p)) == []
        doc = to_chrome_trace([])
        assert doc["traceEvents"] == []
        json.dumps(doc)

    def test_cli_survives_truncated_and_empty_files(self, tmp_path):
        mod = _load_tool("trace_export")
        for name, content in (
            ("empty.jsonl", ""),
            ("trunc.jsonl", '{"kind": "meta", "t0_'),
        ):
            p = tmp_path / name
            p.write_text(content)
            out = str(tmp_path / f"{name}.perfetto.json")
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                assert mod.main([str(p), "-o", out]) == 0
            with open(out) as fh:
                doc = json.load(fh)
            assert doc["traceEvents"] == []
