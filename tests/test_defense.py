"""Defense pipeline end-to-end on small synthetic LCLD data.

Covers the reference's 01_train_robust workflow (scaler, base/augmented/
adversarially-retrained models, importance selection, augmented CSV schema,
candidate construction) plus artifact memoization.
"""

import os

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.domains import get_constraints_class
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.experiments import defense


@pytest.fixture(scope="module")
def pipeline_out(tmp_path_factory, lcld_paths):
    tmp = tmp_path_factory.mktemp("defense")
    cons = LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])
    x_all = synth_lcld(384, cons.schema, seed=11)
    # learnable synthetic target: high interest rate => charged off
    y_all = (x_all[:, 2] > np.median(x_all[:, 2])).astype(np.int64)
    x_train, x_test = x_all[:256], x_all[256:]
    y_train, y_test = y_all[:256], y_all[256:]
    for name, arr in [
        ("x_train", x_train), ("x_test", x_test),
        ("y_train", y_train), ("y_test", y_test),
    ]:
        np.save(tmp / f"{name}.npy", arr)

    config = {
        "project_name": "lcld",
        "paths": {
            "features": lcld_paths["features"],
            "constraints": lcld_paths["constraints"],
            "x_train": str(tmp / "x_train.npy"),
            "x_test": str(tmp / "x_test.npy"),
            "y_train": str(tmp / "y_train.npy"),
            "y_test": str(tmp / "y_test.npy"),
        },
        "dirs": {"data": str(tmp / "data"), "models": str(tmp / "models")},
        "misclassification_threshold": 0.5,
        "norm": 2,
        "eps": 0.5,
        "seed": 42,
        "budget": 3,
        "n_pop": 8,
        "n_offsprings": 4,
        "system": {"n_jobs": 1, "verbose": 0},
        "defense": {"epochs": 4, "balanced_n": 64},
    }
    artifacts = defense.run(config)
    return dict(tmp=tmp, config=config, artifacts=artifacts, cons=cons,
                x_test=x_test, y_test=y_test)


class TestDefensePipeline:
    def test_artifact_family(self, pipeline_out):
        """All five reference artifact groups exist (01_train_robust.py)."""
        a = pipeline_out["artifacts"]
        for key in ("scaler", "nn", "nn_augmented", "nn_moeva", "nn_gradient",
                    "important_features", "x_candidates_common",
                    "x_candidates_common_augmented"):
            assert a[key] and os.path.exists(a[key]), key

    def test_important_features_shape(self, pipeline_out):
        imp = np.load(pipeline_out["artifacts"]["important_features"])
        assert imp.shape == (5, 2)
        cons = pipeline_out["cons"]
        mutable = np.flatnonzero(cons.get_mutable_mask())
        assert set(imp[:, 0].astype(int)) <= set(mutable.tolist())

    def test_augmented_csvs_loadable_by_domain_plugin(self, pipeline_out):
        """The written augmented CSVs must round-trip through the augmented
        constraint plugin (same schema the reference emits)."""
        tmp = pipeline_out["tmp"]
        cls = get_constraints_class("lcld_augmented")
        aug = cls(
            str(tmp / "data" / "features_augmented.csv"),
            str(tmp / "data" / "constraints_augmented.csv"),
            important_features_path=pipeline_out["artifacts"]["important_features"],
        )
        assert aug.schema.n_features == 47 + 10  # comb(5, 2) XOR pairs
        x_aug = np.load(tmp / "data" / "x_test_augmented.npy")
        assert x_aug.shape[1] == 57
        # augmented rows are consistent by construction -> zero violations
        aug.check_constraints_error(x_aug)

    def test_common_candidates_properties(self, pipeline_out):
        """Common candidates: label-1, constraint-satisfying, correctly
        classified by every model (01_train_robust.py:468-491)."""
        from moeva2_ijcai22_replication_tpu.models.io import load_classifier
        import joblib

        a = pipeline_out["artifacts"]
        cons = pipeline_out["cons"]
        x_cand = np.load(a["x_candidates_common"])
        assert x_cand.shape[0] >= 1
        cons.check_constraints_error(x_cand)
        scaler = joblib.load(a["scaler"])
        for key in ("nn", "nn_augmented", "nn_moeva", "nn_gradient"):
            if key == "nn_augmented":
                continue  # judged in augmented space
            sur = load_classifier(a[key])
            proba = np.asarray(sur.predict_proba(scaler.transform(x_cand)))[:, 1]
            assert ((proba >= 0.5) == 1).all(), f"{key} misclassifies candidates"

    def test_memoization_rerun(self, pipeline_out, capsys):
        """A second run loads every artifact instead of recomputing."""
        artifacts = defense.run(pipeline_out["config"])
        assert artifacts == pipeline_out["artifacts"]
        out = capsys.readouterr().out
        assert "exists loading..." in out


class TestRq4Pipeline:
    def test_iteration(self, pipeline_out):
        """RQ4 consumes the defense artifacts and produces the 'best'
        retrained models + rq4 candidate sets (03_train_robust_rq4.py)."""
        from moeva2_ijcai22_replication_tpu.experiments import rq4

        tmp = pipeline_out["tmp"]
        config = dict(pipeline_out["config"])
        config["paths"] = dict(config["paths"])
        config["paths"]["features_augmented"] = str(
            tmp / "data" / "features_augmented.csv"
        )
        config["paths"]["constraints_augmented"] = str(
            tmp / "data" / "constraints_augmented.csv"
        )
        artifacts = rq4.run(config)
        for key, path in artifacts.items():
            assert os.path.exists(path), key
        x_rq4 = np.load(artifacts["x_candidates_rq4_best"])
        x_rq4_aug = np.load(artifacts["x_candidates_rq4_augmented_best"])
        assert x_rq4.shape[1] == 47 and x_rq4_aug.shape[1] == 57
        assert x_rq4.shape[0] == x_rq4_aug.shape[0]
        # rq4 candidates are a subset of the common candidate set
        x_common = np.load(pipeline_out["artifacts"]["x_candidates_common"])
        common_rows = {tuple(r) for r in np.round(x_common, 6)}
        assert all(tuple(r) in common_rows for r in np.round(x_rq4, 6))

    def test_requires_defense_artifacts(self, pipeline_out, tmp_path):
        from moeva2_ijcai22_replication_tpu.experiments import rq4

        config = dict(pipeline_out["config"])
        config["dirs"] = {"data": str(tmp_path), "models": str(tmp_path)}
        with pytest.raises(FileNotFoundError):
            rq4.run(config)


@pytest.fixture(scope="module")
def botnet_pipeline_out(tmp_path_factory, botnet_paths, botnet_candidates):
    """Botnet defense pipeline on the real (constraint-valid) candidate set
    with a synthetic learnable label — exercises the botnet knobs: 19
    important features / ``_19`` artifact suffix, untargeted gradient
    adversarials, no gradient-defended model, and no constraint filter on
    the common candidates (botnet/01_train_robust.py)."""
    tmp = tmp_path_factory.mktemp("botnet_defense")
    x_all = botnet_candidates[:96].astype(float)
    # learnable target: above-median value of the highest-variance mutable col
    from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints

    cons = BotnetConstraints(botnet_paths["features"], botnet_paths["constraints"])
    mut = np.flatnonzero(cons.get_mutable_mask())
    j = mut[np.argmax(x_all[:, mut].std(0))]
    y_all = (x_all[:, j] > np.median(x_all[:, j])).astype(np.int64)
    x_train, x_test = x_all[:64], x_all[64:]
    y_train, y_test = y_all[:64], y_all[64:]
    for name, arr in [
        ("x_train", x_train), ("x_test", x_test),
        ("y_train", y_train), ("y_test", y_test),
    ]:
        np.save(tmp / f"{name}.npy", arr)

    config = {
        "project_name": "botnet",
        "paths": {
            "features": botnet_paths["features"],
            "constraints": botnet_paths["constraints"],
            "x_train": str(tmp / "x_train.npy"),
            "x_test": str(tmp / "x_test.npy"),
            "y_train": str(tmp / "y_train.npy"),
            "y_test": str(tmp / "y_test.npy"),
        },
        "dirs": {"data": str(tmp / "data"), "models": str(tmp / "models")},
        "misclassification_threshold": 0.5,
        "norm": 2,
        "eps": 4.0,
        "seed": 42,
        "budget": 3,
        "n_pop": 8,
        "n_offsprings": 4,
        "system": {"n_jobs": 1, "verbose": 0},
        "defense": {"epochs": 4, "balanced_n": 24},
    }
    artifacts = defense.run(config)
    return dict(tmp=tmp, config=config, artifacts=artifacts, cons=cons,
                x_test=x_test, y_test=y_test)


class TestBotnetDefensePipeline:
    def test_botnet_knobs_artifact_family(self, botnet_pipeline_out):
        a = botnet_pipeline_out["artifacts"]
        tmp = botnet_pipeline_out["tmp"]
        # _19 suffix on importance + augmented artifacts (botnet reference)
        assert a["important_features"].endswith("important_features_19.npy")
        assert a["nn_augmented"].endswith("nn_augmented_19.msgpack")
        assert os.path.exists(tmp / "data" / "features_augmented_19.csv")
        assert os.path.exists(tmp / "models" / "scaler_augmented_19.joblib")
        # botnet trains no gradient-defended model
        assert a["nn_gradient"] is None
        for key in ("scaler", "nn", "nn_augmented", "nn_moeva",
                    "x_candidates_common", "x_candidates_common_augmented"):
            assert a[key] and os.path.exists(a[key]), key

    def test_botnet_importance_19(self, botnet_pipeline_out):
        imp = np.load(botnet_pipeline_out["artifacts"]["important_features"])
        assert imp.shape == (19, 2)
        cons = botnet_pipeline_out["cons"]
        mutable = np.flatnonzero(cons.get_mutable_mask())
        assert set(imp[:, 0].astype(int)) <= set(mutable.tolist())

    def test_botnet_augmented_width(self, botnet_pipeline_out):
        a = botnet_pipeline_out["artifacts"]
        x_aug = np.load(a["x_candidates_common_augmented"])
        # comb(19, 2) = 171 XOR pair features on top of the 756
        assert x_aug.shape[1] == 756 + 171

    def test_botnet_common_candidates(self, botnet_pipeline_out):
        """label-1, correctly classified by every model; the constraint
        filter is OFF for botnet (common_requires_constraints=False)."""
        from moeva2_ijcai22_replication_tpu.models.io import load_classifier
        import joblib

        a = botnet_pipeline_out["artifacts"]
        x_cand = np.load(a["x_candidates_common"])
        assert x_cand.shape[0] >= 1 and x_cand.shape[1] == 756
        scaler = joblib.load(a["scaler"])
        for key in ("nn", "nn_moeva"):
            sur = load_classifier(a[key])
            proba = np.asarray(sur.predict_proba(scaler.transform(x_cand)))[:, 1]
            assert (proba >= 0.5).all(), f"{key} misclassifies candidates"

    def test_botnet_memoization(self, botnet_pipeline_out, capsys):
        artifacts = defense.run(botnet_pipeline_out["config"])
        assert artifacts == botnet_pipeline_out["artifacts"]
        assert "exists loading..." in capsys.readouterr().out
