"""Generation double-buffering (Moeva2.double_buffer) + the packed gate.

The round-10 tentpole's second front: each gate's host-side tail (packed
quality-stats scatter, parked-population fetch + merge, progress events)
defers until the next segment is already enqueued, so it overlaps that
segment's device execution. Contracts pinned here, tier-1:

- double-buffered == serial, bit-identically, in strict-quality AND
  early-exit modes (chunked too) — the schedule never touches device
  programs, dispatch order, decisions, or RNG;
- zero extra compiles and zero extra dispatches between the modes;
- the deferral actually happens (``last_deferred_gate_flushes`` — the
  structural witness that host gate work ran after a newer dispatch was
  enqueued, i.e. the stages PR-9's ``top_gap_stages`` named moved off
  the device's critical path);
- the gate is ONE packed (S, 9) fetch whose o7 column is the success
  mask (the former mask fetch + stats fetch were two round trips).
"""

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.objective import (
    engine_quality_stats,
)
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import (
    synth_lcld,
    synth_lcld_schema,
)
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax
from moeva2_ijcai22_replication_tpu.observability import (
    Trace,
    TraceRecorder,
    get_gap_tracker,
)


@pytest.fixture(scope="module")
def problem(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("dbuf")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(12, cons.schema, seed=3)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=7))
    return {
        "constraints": cons,
        "surrogate": sur,
        "scaler": fit_minmax(x.min(0), x.max(0)),
        "x": x,
    }


def _engine(problem, **kw):
    kw.setdefault("n_gen", 11)
    kw.setdefault("n_pop", 16)
    kw.setdefault("n_offsprings", 8)
    kw.setdefault("seed", 5)
    kw.setdefault("archive_size", 4)
    return Moeva2(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        ml_scaler=problem["scaler"],
        norm=2,
        **kw,
    )


def _run_both(problem, **kw):
    out = {}
    for db in (True, False):
        eng = _engine(problem, double_buffer=db, **kw)
        res = eng.generate(problem["x"], 1)
        out[db] = (eng, res)
    return out


def _assert_bit_identical(res_a, res_b):
    np.testing.assert_array_equal(res_a.x_gen, res_b.x_gen)
    np.testing.assert_array_equal(res_a.f, res_b.f)
    np.testing.assert_array_equal(res_a.x_ml, res_b.x_ml)
    assert res_a.gens_executed == res_b.gens_executed


class TestBitIdentity:
    def test_early_exit_matches_serial(self, problem):
        runs = _run_both(
            problem, early_stop_check_every=2, compaction_buckets=(2, 4, 8, 16)
        )
        (eng_db, res_db), (eng_ser, res_ser) = runs[True], runs[False]
        _assert_bit_identical(res_db, res_ser)
        assert res_db.early_stop["compaction"] == res_ser.early_stop["compaction"]
        # zero extra compiles AND zero extra dispatches across the modes
        for name in ("_jit_init", "_jit_segment", "_jit_success"):
            assert (
                getattr(eng_db, name).calls == getattr(eng_ser, name).calls
            ), name
            assert len(getattr(eng_db, name)._compiled) == len(
                getattr(eng_ser, name)._compiled
            ), name

    def test_strict_quality_matches_serial(self, problem):
        runs = _run_both(
            problem, record_quality=True, quality_every=3, seed=9
        )
        (_, res_db), (_, res_ser) = runs[True], runs[False]
        _assert_bit_identical(res_db, res_ser)
        assert [s["gen"] for s in res_db.quality["samples"]] == [
            s["gen"] for s in res_ser.quality["samples"]
        ]
        for s_db, s_ser in zip(
            res_db.quality["samples"], res_ser.quality["samples"]
        ):
            np.testing.assert_array_equal(
                np.asarray(s_db["per_state"]), np.asarray(s_ser["per_state"])
            )

    def test_chunked_early_exit_matches_serial(self, problem):
        runs = _run_both(
            problem,
            early_stop_check_every=2,
            compaction_buckets=(2, 4, 8),
            max_states_per_call=8,
            record_quality=True,
            seed=11,
        )
        (_, res_db), (_, res_ser) = runs[True], runs[False]
        _assert_bit_identical(res_db, res_ser)
        assert res_db.early_stop == res_ser.early_stop


class TestDeferral:
    def test_double_buffer_defers_gate_flushes(self, problem):
        """The structural witness: with double-buffering, at least one
        gate's host tail ran after a NEWER dispatch was enqueued (the
        overlap); serially, never. Deterministic — host ordering, not
        timing."""
        runs = _run_both(
            problem, early_stop_check_every=2, compaction_buckets=(2, 4, 8, 16),
            seed=13,
        )
        assert runs[True][0].last_deferred_gate_flushes > 0
        assert runs[False][0].last_deferred_gate_flushes == 0

    def test_strict_quality_gates_also_defer(self, problem):
        runs = _run_both(
            problem, record_quality=True, quality_every=2, seed=15
        )
        assert runs[True][0].last_deferred_gate_flushes > 0
        assert runs[False][0].last_deferred_gate_flushes == 0

    def test_gate_events_and_windows_survive_deferral(self, problem):
        """Deferred emission changes WHEN the gate events land, never
        whether: the trace still carries every moeva.gate event and the
        gap tracker still lands the run's window."""
        tracker = get_gap_tracker()
        mark = tracker.mark()
        rec = TraceRecorder(spans_enabled=True)
        eng = _engine(
            problem, early_stop_check_every=2,
            compaction_buckets=(2, 4, 8, 16), seed=17,
        )
        eng.trace = Trace(rec, trace_id="dbuf-test")
        res = eng.generate(problem["x"], 1)
        gates = [
            e for e in rec.events()
            if e.get("kind") == "event" and e.get("name") == "moeva.gate"
        ]
        assert gates, "gate events must survive deferral"
        # every compaction-trace entry has a matching emitted event, in
        # gate order, with the payload intact
        gens = [g["attrs"]["gen"] for g in gates]
        assert gens == sorted(gens)
        assert set(
            t["gen"] for t in res.early_stop["compaction"]
        ) <= set(gens)
        assert all("success_frac" in g["attrs"] for g in gates)
        block = tracker.gaps_block(since=mark)
        assert block["windows"] == 1
        # the deferred host tail emits its spans too (parked_merge or
        # gate_fetch present for the join to attribute gaps against)
        span_names = {
            e.get("name") for e in rec.events() if e.get("kind") == "span"
        }
        assert "gate_fetch" in span_names


class TestPackedGate:
    def test_gate_is_one_packed_stats_array(self, problem):
        """The gate program returns the (S, 9) stats alone; the success
        mask is its o7 column, derived host-side — one fetch per gate."""
        import jax.numpy as jnp

        eng = _engine(problem)
        pop_f = jnp.asarray(
            np.array(
                [
                    # [misclass prob, distance, sum violations]
                    [[0.1, 0.05, 0.0]],  # success: misclassified + feasible
                    [[0.9, 0.05, 0.0]],  # not misclassified
                ],
                np.float32,
            )
        )
        arch_f = jnp.zeros((2, 0, 3), np.float32)
        carry = (None, pop_f, None, arch_f, None, None)
        stats = np.asarray(eng._success_mask(carry))
        assert stats.shape == (2, 9)
        succ = stats[..., 6] > 0
        ref = engine_quality_stats(
            np.asarray(pop_f, np.float64), 0.5, np.inf, xp=np
        )
        np.testing.assert_array_equal(succ, ref[..., 6] > 0)
        assert succ.tolist() == [True, False]
