"""Success-gated early exit + active-set compaction (fixture-free, quick).

The whole module runs on the code-derived synthetic LCLD schema
(``synth_lcld_schema``) — no ``/root/reference`` tree required — and pins
the early-exit contract:

- **strict mode** (``early_stop_check_every=0``, the default) and a
  segmented run whose gate never fires are bit-identical to the one-scan
  program (this also pins carry donation across chained segments);
- a compaction run with ``archive_size > 0`` reaches success rates >= the
  fixed-budget run at the same generation budget (parking freezes observed
  successes; the archive makes the criterion monotone);
- the executable count of a shrinking run is bounded by the bucket-menu
  length (compaction repacks down the shared serving menu, one program per
  menu size actually visited);
- the checkpoint sidecar stores the active-set mapping, so a compacted run
  resumes bit-identically (slow tier, like every checkpoint test);
- runner metrics and serving responses carry the early-exit execution mode.

Engines own their compiled programs, so runs that several tests inspect are
module-scoped fixtures — one compile per engine config for the module.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import (
    synth_lcld,
    synth_lcld_schema,
)
from moeva2_ijcai22_replication_tpu.experiments.common import (
    DEFAULT_BUCKET_SIZES,
    BucketMenu,
)
from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp


@pytest.fixture(scope="module")
def problem(tmp_path_factory):
    import joblib
    from sklearn.preprocessing import MinMaxScaler

    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    tmp = tmp_path_factory.mktemp("early_stop")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(16, cons.schema, seed=3)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=7))
    save_params(sur, str(tmp / "nn.msgpack"))
    np.save(tmp / "x_candidates.npy", x)
    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    joblib.dump(
        MinMaxScaler().fit(np.vstack([x, xl, xu])), tmp / "scaler.joblib"
    )
    return {
        "dir": tmp,
        "paths": paths,
        "constraints": cons,
        "surrogate": sur,
        "scaler": fit_minmax(x.min(0), x.max(0)),
        "x": x,
    }


def _engine(problem, **kw):
    kw.setdefault("n_gen", 21)
    kw.setdefault("n_pop", 16)
    kw.setdefault("n_offsprings", 8)
    kw.setdefault("seed", 11)
    kw.setdefault("archive_size", 4)
    return Moeva2(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        ml_scaler=problem["scaler"],
        norm=2,
        dtype=jnp.float64,
        **kw,
    )


@pytest.fixture(scope="module")
def fixed_run(problem):
    """The fixed-budget baseline: strict mode, full 20-generation scan."""
    eng = _engine(problem)
    return eng, eng.generate(problem["x"], 1)


@pytest.fixture(scope="module")
def early_run(problem):
    """The compaction run every early-exit assertion inspects: same budget
    and seed as ``fixed_run``, gate every 4 generations (dividing the 20
    scan steps so all segments share one compiled length)."""
    eng = _engine(
        problem, early_stop_check_every=4, compaction_buckets=(2, 4, 8, 16)
    )
    return eng, eng.generate(problem["x"], 1)


def _success(res, thr=0.5):
    """Per-state engine-criterion success over the returned populations."""
    f = res.f
    return ((f[..., 0] < thr) & (f[..., 2] <= 0)).any(axis=1)


class TestStrictMode:
    def test_default_is_strict_and_reports_full_budget(self, fixed_run):
        _, res = fixed_run
        assert res.early_stop is None
        assert res.gens_executed == 20  # n_gen - 1

    def test_segmented_never_firing_gate_is_bit_identical(
        self, problem, fixed_run
    ):
        """A gated run whose criterion never fires must equal the one-scan
        strict program bit-for-bit: the check segmentation, the donated
        carry chaining, and the mask fetches change no random draw."""
        _, strict = fixed_run
        gated = _engine(
            problem, early_stop_check_every=4, early_stop_threshold=-1.0
        ).generate(problem["x"], 1)
        np.testing.assert_array_equal(strict.x_gen, gated.x_gen)
        np.testing.assert_array_equal(strict.f, gated.f)
        np.testing.assert_array_equal(strict.x_ml, gated.x_ml)
        assert gated.gens_executed == 20
        assert gated.early_stop["compaction"] == []

    def test_history_and_early_stop_are_rejected(self, problem):
        eng = _engine(problem, save_history="reduced", early_stop_check_every=2)
        with pytest.raises(ValueError, match="save_history"):
            eng.generate(problem["x"], 1)


class TestCompaction:
    def test_success_not_below_fixed_budget_with_archive(
        self, fixed_run, early_run
    ):
        """Parking freezes every observed success and the archive makes the
        criterion monotone, so an early-exit run can only match or beat the
        fixed-budget run under its own criterion (at a budget where the
        search saturates; mid-run RNG divergence is the documented caveat)."""
        _, fixed = fixed_run
        _, early = early_run
        assert _success(early).sum() >= _success(fixed).sum()

    def test_compaction_shrinks_and_merges_back_in_order(
        self, problem, early_run
    ):
        _, res = early_run
        # some state solved early enough to trigger at least one repack
        assert len(res.early_stop["compaction"]) >= 1
        for t in res.early_stop["compaction"]:
            assert t["bucket"] <= 16 and t["gen"] % 4 == 0
        # every state's rows decode against ITS OWN initial state: the
        # immutable features pin the parked/active merge ordering
        immutable = ~problem["constraints"].schema.mutable
        np.testing.assert_allclose(
            res.x_ml[:, :, immutable],
            np.broadcast_to(
                res.x_initial[:, None, immutable],
                res.x_ml[:, :, immutable].shape,
            ),
        )
        assert np.isfinite(res.f).all()
        assert res.gens_executed <= res.early_stop["budget_gens"] == 20

    def test_executable_count_bounded_by_menu_length(self, early_run):
        """A shrinking run dispatches at most one segment program per menu
        size: check_every divides n_gen-1, so every segment shares one
        static length and shapes are the only retrace axis."""
        eng, res = early_run
        menu_len = len(eng._compaction_menu().sizes)
        # trace_count counts init + every distinct segment executable
        assert eng.trace_count - 1 <= menu_len
        assert (
            len({t["bucket"] for t in res.early_stop["compaction"]}) <= menu_len
        )

    def test_full_early_exit_skips_remaining_budget(self, problem):
        """With a vacuous criterion every state succeeds at the first check
        and the remaining budget is never dispatched."""
        res = _engine(
            problem, n_gen=41, early_stop_check_every=2,
            early_stop_threshold=2.0,  # any candidate is 'misclassified'
            early_stop_eps=np.inf,
        ).generate(problem["x"], 1)
        assert res.gens_executed == 2  # one check segment, then exit
        assert res.early_stop["compaction"][-1]["active"] == 0
        assert np.isfinite(res.f).all()

    def test_mesh_sharded_compaction(self, problem):
        """Compaction must keep the states axis mesh-aligned: buckets below
        the mesh size are filtered from the menu, and repacked carries +
        rebuilt dispatch args land back on the mesh. The candidate set is
        built so the repack is deterministic: 10 states the surrogate
        already misclassifies (their initial candidate satisfies the
        criterion, so they park at the first gate) + 6 it does not — the
        active set is <= 6 at generation 2, forcing the 16 -> 8 repack."""
        import jax
        from jax.sharding import Mesh

        cons = problem["constraints"]
        pool = synth_lcld(256, cons.schema, seed=9)
        p1 = np.asarray(
            problem["surrogate"].predict_proba(
                problem["scaler"].transform(pool)
            )
        )[:, 1]
        solved, unsolved = pool[p1 < 0.5], pool[p1 >= 0.5]
        assert len(solved) >= 10 and len(unsolved) >= 6, "degenerate surrogate"
        x = np.concatenate([solved[:10], unsolved[:6]])

        mesh = Mesh(np.array(jax.devices()[:8]), ("states",))
        eng = _engine(
            problem,
            n_gen=9,
            early_stop_check_every=2,
            compaction_buckets=(2, 4, 8, 16),
            mesh=mesh,
        )
        assert eng._compaction_menu().sizes == (8, 16)  # mesh multiples only
        res = eng.generate(x, 1)
        trace = res.early_stop["compaction"]
        assert trace and trace[0] == {"gen": 2, "active": trace[0]["active"], "bucket": 8}
        assert trace[0]["active"] <= 6
        assert np.isfinite(res.f).all()
        # the 10 pre-solved states' frozen results hold the criterion
        assert _success(res)[:10].all()
        # the parked/active merge kept original row order
        immutable = ~cons.schema.mutable
        np.testing.assert_allclose(
            res.x_ml[:, :, immutable],
            np.broadcast_to(
                res.x_initial[:, None, immutable],
                res.x_ml[:, :, immutable].shape,
            ),
        )

    def test_chunked_states_compose_with_early_exit(self, problem):
        res = _engine(
            problem,
            early_stop_check_every=4,
            max_states_per_call=8,
            compaction_buckets=(2, 4, 8),
        ).generate(problem["x"], 1)
        assert res.x_gen.shape[0] == 16
        assert res.early_stop["budget_gens"] == 40  # 2 chunks x 20 steps
        assert 0 < res.gens_executed <= 40
        for t in res.early_stop["compaction"]:
            assert t["chunk"] in (0, 1)


class TestCheckpointActiveSet:
    def test_misaligned_checkpoint_keeps_gate_cadence(self, problem, tmp_path):
        """checkpoint_every not dividing early_stop_check_every shifts
        segment boundaries; the gate must re-align and still fire every
        ``check`` generations (here: a vacuous criterion must exit at the
        FIRST gate, generation 4, not at the first accidental multiple)."""
        res = _engine(
            problem,
            n_gen=41,
            early_stop_check_every=4,
            early_stop_threshold=2.0,
            checkpoint_every=3,
            checkpoint_path=str(tmp_path / "cp_misaligned.npz"),
        ).generate(problem["x"], 1)
        assert res.gens_executed == 4
        assert res.early_stop["compaction"][-1] == {
            "gen": 4, "active": 0, "bucket": 16,
        }

    @pytest.mark.slow
    def test_resume_restores_mapping_and_parked_results(self, problem, tmp_path):
        """Kill a compacted run mid-attack; the resumed run must finish from
        the snapshot — same parked results, same active-set mapping — and
        match the uninterrupted run bit-for-bit (the PRNG key and the
        compaction schedule are both checkpoint state)."""
        kw = dict(
            early_stop_check_every=2,
            compaction_buckets=(2, 4, 8, 16),
            checkpoint_every=4,
        )
        cp_path = str(tmp_path / "cp_early.npz")
        reference = _engine(problem, **kw).generate(problem["x"], 1)

        class Boom(RuntimeError):
            pass

        eng = _engine(problem, **kw, checkpoint_path=cp_path)
        orig = Moeva2._success_mask
        calls = {"n": 0}

        def bomb(self, carry):
            calls["n"] += 1
            if calls["n"] == 5:  # past a checkpoint boundary and a repack
                raise Boom()
            return orig(self, carry)

        import unittest.mock as mock

        with mock.patch.object(Moeva2, "_success_mask", bomb):
            with pytest.raises(Boom):
                eng.generate(problem["x"], 1)

        resumed = _engine(problem, **kw, checkpoint_path=cp_path).generate(
            problem["x"], 1
        )
        np.testing.assert_array_equal(resumed.x_gen, reference.x_gen)
        np.testing.assert_array_equal(resumed.f, reference.f)
        assert (
            resumed.early_stop["compaction"]
            == reference.early_stop["compaction"]
        )


class TestRunnerAndServingPlumbing:
    def _base_config(self, problem, out_dir, **over):
        tmp = problem["dir"]
        cfg = {
            "project_name": "lcld",
            "attack_name": "moeva",
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": problem["paths"]["features"],
                "constraints": problem["paths"]["constraints"],
                "x_candidates": str(tmp / "x_candidates.npy"),
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "dirs": {"results": str(out_dir)},
            "misclassification_threshold": 0.5,
            "norm": 2,
            "n_initial_state": -1,
            "initial_state_offset": 0,
            "system": {"n_jobs": 1, "verbose": 0},
            "save_history": False,
            "reconstruction": False,
            "seed": 42,
            "budget": 5,
            "n_pop": 16,
            "n_offsprings": 8,
            "eps_list": [0.5],
            "archive_size": 4,
        }
        cfg.update(over)
        return cfg

    def test_runner_metrics_carry_early_exit_execution(self, problem, tmp_path):
        from moeva2_ijcai22_replication_tpu.experiments import moeva as moeva_runner

        cfg = self._base_config(
            problem, tmp_path / "out", early_stop_check_every=2
        )
        metrics = moeva_runner.run(cfg)
        ex = metrics["execution"]
        assert ex["early_stop_check_every"] == 2
        assert 0 < ex["gens_executed"] <= 4
        with open(
            tmp_path / "out" / f"metrics_moeva_{metrics['config_hash']}.json"
        ) as f:
            on_disk = json.load(f)
        assert on_disk["execution"] == ex

    def test_serving_per_request_opt_in(self, problem):
        from moeva2_ijcai22_replication_tpu.serving import (
            AttackRequest,
            AttackService,
        )

        tmp = problem["dir"]
        domain = {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": problem["paths"]["features"],
                "constraints": problem["paths"]["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
        }
        svc = AttackService(
            {"lcld": domain}, bucket_sizes=(8, 16), max_delay_s=0.01
        )
        try:
            resp = svc.attack(
                AttackRequest(
                    domain="lcld",
                    x=problem["x"][:3],
                    attack="moeva",
                    budget=5,
                    params={
                        "n_pop": 16,
                        "n_offsprings": 8,
                        "archive_size": 4,
                        "early_stop_check_every": 2,
                    },
                ),
                timeout=600.0,
            )
            assert resp.meta["execution"]["early_stop_check_every"] == 2
            assert resp.x_adv.shape[0] == 3 and resp.x_adv.ndim == 3
        finally:
            svc.close()


class TestMenuSingleSource:
    def test_serving_menu_is_the_shared_menu(self):
        from moeva2_ijcai22_replication_tpu.serving import batcher

        assert batcher.BucketMenu is BucketMenu
        assert batcher.DEFAULT_BUCKET_SIZES is DEFAULT_BUCKET_SIZES

    def test_engine_compaction_consumes_shared_menu(self, problem):
        eng = _engine(problem)
        assert eng._compaction_menu().sizes == tuple(sorted(DEFAULT_BUCKET_SIZES))

    def test_shrink_bucket_semantics(self):
        menu = BucketMenu((8, 16, 32))
        assert menu.shrink_bucket(5, 32) == 8
        assert menu.shrink_bucket(9, 32) == 16
        assert menu.shrink_bucket(9, 16) is None  # no smaller fit
        assert menu.shrink_bucket(40, 32) is None  # above the menu
