"""Fleet-layer tests: replica lifecycle, capacity routing, SLO merge.

The fake tier drives the admit/drain/kill state machine and the router's
headroom ranking + failover budget with an injected clock and scripted
/healthz + /attack endpoints — no subprocesses, no sockets. The slow tier
spawns two real ``tools/serve.py`` replicas over one shared config via
:class:`ReplicaManager`, SIGKILLs one behind the router's back, and proves
the forward fails over to the survivor within the retry budget before the
survivor drains cleanly.
"""

import json
import threading
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.observability import TraceRecorder
from moeva2_ijcai22_replication_tpu.observability.capacity import CapacityModel
from moeva2_ijcai22_replication_tpu.observability.fleetrace import (
    TRACE_HEADER,
    parse_trace_context,
)
from moeva2_ijcai22_replication_tpu.observability.flightrec import (
    load_flight_dump,
)
from moeva2_ijcai22_replication_tpu.observability.slo import (
    SloTracker,
    merge_histogram_snapshots,
    merge_slo_snapshots,
)
from moeva2_ijcai22_replication_tpu.serving import (
    BucketMenu,
    Microbatcher,
    QueueFull,
)
from moeva2_ijcai22_replication_tpu.serving.fleet import (
    BuildMismatch,
    ReplicaHandle,
    ReplicaManager,
    Router,
    serve_router,
)
from moeva2_ijcai22_replication_tpu.utils.observability import ServiceMetrics


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeProc:
    """Popen-shaped test double; ``on_terminate`` observes the call site's
    state at SIGTERM time (the drain-ordering proof)."""

    def __init__(self, pid=4321, on_terminate=None):
        self.pid = pid
        self.returncode = None
        self.terminated = False
        self.killed = False
        self.on_terminate = on_terminate

    def poll(self):
        return self.returncode

    def terminate(self):
        if self.on_terminate:
            self.on_terminate()
        self.terminated = True
        self.returncode = 0

    def kill(self):
        self.killed = True
        self.returncode = -9

    def wait(self, timeout=None):
        return self.returncode


class ScriptedHTTP:
    """url -> scripted responses; the last entry repeats, Exceptions raise."""

    def __init__(self):
        self.scripts = {}
        self.calls = []

    def set(self, url, *responses):
        self.scripts[url] = list(responses)

    def __call__(self, url):
        self.calls.append(url)
        seq = self.scripts[url]
        resp = seq.pop(0) if len(seq) > 1 else seq[0]
        if isinstance(resp, Exception):
            raise resp
        return resp() if callable(resp) else resp


class ScriptedPost:
    """url -> (status, headers, body) | Exception | callable, per /attack.

    Captures the per-attempt request ``headers`` the router stamps (every
    forward now carries ``X-Moeva2-Trace``) next to each call's url."""

    def __init__(self, responses):
        self.responses = dict(responses)
        self.calls = []
        self.headers = []

    def __call__(self, url, body, timeout_s=None, headers=None):
        self.calls.append(url)
        self.headers.append(dict(headers or {}))
        resp = self.responses[url]
        if callable(resp) and not isinstance(resp, Exception):
            resp = resp()
        if isinstance(resp, Exception):
            raise resp
        return resp


def health(
    rid,
    *,
    version="0.1",
    config_hash="abc",
    qps=None,
    age=None,
    headroom=None,
    queue=0,
    ok=True,
):
    h = {
        "ok": ok,
        "replica_id": rid,
        "queue_depth_rows": queue,
        "build": {"version": version, "config_hash": config_hash},
    }
    if qps is not None or headroom is not None:
        block = {}
        if qps is not None:
            block["max_sustainable_qps"] = qps
        if age is not None:
            block["age_s"] = age
        if headroom is not None:
            block["headroom"] = headroom
        h["capacity"] = {"by_domain": {"lcld": block}}
    return h


def make_fleet(healths, clock=None, **mgr_kw):
    """Manager with one adopted (admitted) replica per ``healths`` entry."""
    fc = clock or FakeClock()
    http = ScriptedHTTP()
    for rid, h in healths.items():
        http.set(f"mem://{rid}/healthz", h)
    mgr = ReplicaManager(
        http_get=http, clock=fc, sleep=fc.advance, **mgr_kw
    )
    for rid in healths:
        mgr.adopt(f"mem://{rid}", rid)
    return mgr, http, fc


# ---------------------------------------------------------------------------
# replica lifecycle (fake clock, scripted endpoints)
# ---------------------------------------------------------------------------


class TestReplicaLifecycle:
    def test_add_admits_after_first_healthy_poll(self):
        fc = FakeClock()
        http = ScriptedHTTP()
        # boot sequence: connection refused, then unready, then healthy —
        # the replica must only become routable after the healthy poll
        http.set(
            "mem://r01/healthz",
            ConnectionError("booting"),
            health("r01", ok=False),
            health("r01"),
        )
        proc = FakeProc()
        spawn = lambda rid: ReplicaHandle(
            rid, proc=proc, url="mem://r01", spawned_t=fc()
        )
        mgr = ReplicaManager(
            spawn_fn=spawn, http_get=http, clock=fc, sleep=fc.advance
        )
        h = mgr.add()
        assert h.state == "admitted"
        assert h.poll_errors == 1  # the connection-refused round
        assert h.last_poll_t is not None and h.admitted_t is not None
        assert mgr.routable() == [h]
        # the first admitted replica defines the fleet's build fingerprint
        assert mgr.expected_build == ("0.1", "abc")

    def test_build_mismatch_refused_at_add(self):
        fc = FakeClock()
        http = ScriptedHTTP()
        http.set("mem://r01/healthz", health("r01", config_hash="zzz"))
        proc = FakeProc()
        spawn = lambda rid: ReplicaHandle(
            rid, proc=proc, url="mem://r01", spawned_t=fc()
        )
        mgr = ReplicaManager(
            spawn_fn=spawn,
            http_get=http,
            clock=fc,
            sleep=fc.advance,
            expected_build=("0.1", "abc"),
        )
        with pytest.raises(BuildMismatch, match="refused"):
            mgr.add()
        h = mgr.replicas()[0]
        assert h.state == "refused"
        assert proc.terminated  # a refused spawn is not left running
        assert mgr.routable() == []

    def test_wait_ready_skips_stale_fleet_ready_lines(self, tmp_path):
        # replica logs append across runs, so a restarted fleet sees the
        # PREVIOUS process's fleet_ready line first — discovery must only
        # read bytes written after this spawn's log_start offset
        log = tmp_path / "r01.log"
        stale = json.dumps(
            {"fleet_ready": {"url": "mem://stale", "port": 1}}
        )
        fresh = json.dumps(
            {"fleet_ready": {"url": "mem://fresh", "port": 2}}
        )
        log.write_text(stale + "\n" + fresh + "\n")
        fc = FakeClock()
        mgr = ReplicaManager(clock=fc, sleep=fc.advance)
        h = ReplicaHandle(
            "r01",
            proc=FakeProc(),
            log_path=str(log),
            spawned_t=fc(),
            log_start=len(stale) + 1,
        )
        mgr._wait_ready(h)
        assert h.url == "mem://fresh"

    def test_build_mismatch_refused_at_adoption(self):
        # first adoption defines the fleet build; the second, healthy but
        # differently-built, must be refused — never routed to
        mgr, http, fc = make_fleet({"r01": health("r01")})
        http.set("mem://r02/healthz", health("r02", version="0.2"))
        with pytest.raises(BuildMismatch):
            mgr.adopt("mem://r02", "r02")
        assert mgr.get("r02").state == "refused"
        assert [h.replica_id for h in mgr.routable()] == ["r01"]
        # matching build still admits
        http.set("mem://r03/healthz", health("r03"))
        assert mgr.adopt("mem://r03", "r03").state == "admitted"

    def test_poll_marks_exited_replica_dead(self):
        mgr, http, fc = make_fleet({"r01": health("r01", qps=50.0)})
        h = mgr.get("r01")
        h.proc = FakeProc()
        h.proc.returncode = -9  # process gone
        http.set("mem://r01/healthz", ConnectionError("down"))
        view = mgr.poll()
        assert h.state == "dead"
        assert view["by_state"] == {"dead": 1}
        assert view["routable"] == 0

    def test_poll_failure_records_last_poll_error(self):
        walls = FakeClock(1000.0)
        mgr, http, fc = make_fleet({"r01": health("r01")})
        mgr.wall = walls
        http.set("mem://r01/healthz", ConnectionError("wedged"))
        h = mgr.get("r01")
        mgr.poll()
        # the LAST failure (text + wall timestamp) survives next to the
        # count — the first question in any incident
        assert h.poll_errors == 1
        assert "wedged" in h.last_poll_error["error"]
        assert h.last_poll_error["t_wall"] == 1000.0
        assert h.view()["last_poll_error"] == h.last_poll_error

    def test_poll_measures_clock_offset_from_now_wall(self):
        h_resp = health("r01")
        # replica's own wall clock rides every healthz; against the
        # manager's send/recv bracket [100.0, 100.2] the NTP midpoint
        # rule gives offset = 123.45 - 100.1
        h_resp["now_wall"] = 123.45
        mgr, http, fc = make_fleet({"r01": h_resp})
        wall_times = [100.0, 100.2]
        mgr.wall = lambda: wall_times.pop(0)
        mgr.poll()
        h = mgr.get("r01")
        assert h.clock_offset_s == pytest.approx(23.35)
        assert h.clock_rtt_s == pytest.approx(0.2)
        assert h.view()["clock_offset_s"] == h.clock_offset_s

    def test_fleet_view_aggregates_capacity_and_build(self):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, headroom=0.5),
                "r02": health("r02", qps=40.0, headroom=0.8),
            }
        )
        view = mgr.fleet_view()
        assert view["routable"] == 2
        assert view["fleet_capacity_qps"] == 140.0
        assert view["expected_build"] == ["0.1", "abc"]
        rows = {r["replica_id"]: r for r in view["replicas"]}
        assert rows["r01"]["headroom"] == 0.5
        assert rows["r02"]["build"]["config_hash"] == "abc"
        assert view["policy"]["event_counts"] == {}


class TestDrainAndKill:
    def test_drain_completes_inflight_before_terminate(self):
        mgr, http, fc = make_fleet({"r01": health("r01")})
        h = mgr.get("r01")
        inflight_at_sigterm = []
        h.proc = FakeProc(
            on_terminate=lambda: inflight_at_sigterm.append(h.in_flight)
        )
        mgr.note_inflight("r01", +2)
        # each drain-loop sleep retires one in-flight request
        orig_advance = fc.advance

        def sleep(dt):
            orig_advance(dt)
            if h.in_flight:
                mgr.note_inflight("r01", -1)

        mgr.sleep = sleep
        report = mgr.drain("r01", timeout_s=5.0)
        assert report["drained_clean"] is True
        assert h.state == "terminated"
        # routing stopped first, SIGTERM only once nothing was in flight
        assert inflight_at_sigterm == [0]
        assert mgr.routable() == []

    def test_drain_waits_for_replica_queue_depth(self):
        # in-flight is zero but the replica still holds queued rows: drain
        # must wait for the replica's own queue to empty before SIGTERM
        mgr, http, fc = make_fleet({"r01": health("r01")})
        h = mgr.get("r01")
        h.proc = FakeProc()
        http.set(
            "mem://r01/healthz",
            health("r01", queue=6),
            health("r01", queue=0),
        )
        report = mgr.drain("r01", timeout_s=5.0)
        assert report["drained_clean"] is True
        assert h.state == "terminated" and h.proc.terminated

    def test_drain_timeout_still_terminates_dirty(self):
        mgr, http, fc = make_fleet({"r01": health("r01")})
        h = mgr.get("r01")
        h.proc = FakeProc()
        mgr.note_inflight("r01", +1)  # never retires
        report = mgr.drain("r01", timeout_s=1.0)
        assert report["drained_clean"] is False
        assert h.state == "terminated" and h.proc.terminated

    def test_kill_reports_inflight_and_marks_dead(self):
        mgr, http, fc = make_fleet({"r01": health("r01")})
        h = mgr.get("r01")
        h.proc = FakeProc(pid=777)
        mgr.note_inflight("r01", +3)
        report = mgr.kill("r01")
        assert report == {
            "replica_id": "r01",
            "in_flight_at_kill": 3,
            "pid": 777,
            # the default http_post cannot reach a mem:// replica — the
            # harvest is best-effort and the report says it got nothing
            "flight": None,
        }
        assert h.state == "dead" and h.proc.killed
        with pytest.raises(ValueError, match="state dead"):
            mgr.drain("r01")

    def test_kill_harvests_flight_dump_before_sigkill(self):
        mgr, http, fc = make_fleet({"r01": health("r01")})
        h = mgr.get("r01")
        h.proc = FakeProc()
        posts = []
        harvest = {"path": "/tmp/flight_r01.json", "reason": "x", "entries": 5}

        def http_post(url, payload, timeout_s=None):
            posts.append((url, payload, h.proc.killed))
            return dict(harvest)

        mgr.http_post = http_post
        report = mgr.kill("r01")
        # the black box was pulled over POST /debug/flight BEFORE the
        # SIGKILL landed (SIGKILL leaves the replica no moment to dump)
        assert posts == [
            ("mem://r01/debug/flight", {"reason": "chaos_kill_r01"}, False)
        ]
        assert report["flight"] == harvest
        assert h.flight_dump == harvest
        assert h.state == "dead" and h.proc.killed


# ---------------------------------------------------------------------------
# router: headroom ordering, freshness, failover budget
# ---------------------------------------------------------------------------


def ok_post(rid):
    return (200, {"X-Replica-Id": rid}, json.dumps({"rid": rid}).encode())


class TestRouterOrdering:
    def test_route_prefers_most_predicted_headroom(self):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, age=1.0),
                "r02": health("r02", qps=10.0, age=1.0),
            }
        )
        post = ScriptedPost(
            {"mem://r01/attack": ok_post("r01"), "mem://r02/attack": ok_post("r02")}
        )
        router = Router(mgr, http_post=post, clock=fc)
        status, headers, _ = router.route(b"{}")
        assert status == 200
        assert headers["X-Served-By"] == "r01"  # 100-0 beats 10-0
        assert headers["X-Fleet-Attempts"] == "1"
        # live load flips the ranking: 100-95 < 10-0
        mgr.note_inflight("r01", +95)
        _, headers, _ = router.route(b"{}")
        assert headers["X-Served-By"] == "r02"
        assert router.counters_snapshot()["forwards"] == 2
        # forwards resolved: in-flight bookkeeping returned to baseline
        assert mgr.get("r02").in_flight == 0

    def test_stale_poll_degrades_to_round_robin(self):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, age=1.0),
                "r02": health("r02", qps=10.0, age=1.0),
            }
        )
        post = ScriptedPost(
            {"mem://r01/attack": ok_post("r01"), "mem://r02/attack": ok_post("r02")}
        )
        router = Router(mgr, http_post=post, clock=fc, stale_after_s=10.0)
        fc.advance(60.0)  # both polls stale: capacity no longer trusted
        served = [router.route(b"{}")[1]["X-Served-By"] for _ in range(2)]
        assert set(served) == {"r01", "r02"}  # alternating, not pinned

    def test_aged_capacity_window_degrades_to_round_robin(self):
        # fresh poll but the capacity window itself is old (an idle
        # replica keeps publishing an aging window) — not trusted either
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, age=120.0),
                "r02": health("r02", qps=10.0, age=120.0),
            }
        )
        post = ScriptedPost(
            {"mem://r01/attack": ok_post("r01"), "mem://r02/attack": ok_post("r02")}
        )
        router = Router(mgr, http_post=post, clock=fc, capacity_age_max_s=30.0)
        served = [router.route(b"{}")[1]["X-Served-By"] for _ in range(2)]
        assert set(served) == {"r01", "r02"}

    def test_no_routable_replica_sheds(self):
        mgr = ReplicaManager(http_get=ScriptedHTTP(), clock=FakeClock())
        router = Router(mgr, http_post=ScriptedPost({}))
        status, headers, body = router.route(b"{}")
        assert status == 503
        assert headers["X-Fleet-Attempts"] == "0"
        assert json.loads(body)["error"] == "no routable replica"
        assert router.counters_snapshot()["shed_no_replica"] == 1


class TestRouterFailover:
    def two_replica_router(self, r01_resp, r02_resp, **kw):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, age=1.0),
                "r02": health("r02", qps=10.0, age=1.0),
            }
        )
        post = ScriptedPost(
            {"mem://r01/attack": r01_resp, "mem://r02/attack": r02_resp}
        )
        return Router(mgr, http_post=post, clock=fc, **kw), mgr, post

    def test_connection_failure_fails_over_within_budget(self):
        router, mgr, post = self.two_replica_router(
            ConnectionRefusedError("dead"), ok_post("r02"), retry_budget=2
        )
        status, headers, _ = router.route(b"{}")
        assert status == 200
        assert headers["X-Served-By"] == "r02"
        assert headers["X-Fleet-Attempts"] == "2"
        c = router.counters_snapshot()
        assert c["failover_connection_total"] == 1
        assert c["failover_connection:r01"] == 1
        assert c["retries"] == 1 and c["forwards"] == 1
        # the failed forward's in-flight increment was rolled back
        assert mgr.get("r01").in_flight == 0

    def test_429_fails_over_and_exhaustion_surfaces_retry_after(self):
        reject = lambda rid: (
            429,
            {"Retry-After": "1.500", "X-Replica-Id": rid},
            json.dumps({"error": "queue full"}).encode(),
        )
        router, mgr, post = self.two_replica_router(
            reject("r01"), reject("r02"), retry_budget=1
        )
        status, headers, body = router.route(b"{}")
        assert status == 429
        # budget 1 = one retry after the first attempt; both were tried
        assert headers["X-Fleet-Attempts"] == "2"
        assert len(post.calls) == 2
        # the final upstream 429's honest Retry-After flows through
        assert headers["Retry-After"] == "1.500"
        c = router.counters_snapshot()
        assert c["failover_rejected_total"] == 2
        assert c["shed_budget_exhausted"] == 1
        assert c["forwards"] == 0

    def test_5xx_counts_failed_not_rejected(self):
        router, mgr, post = self.two_replica_router(
            (500, {}, b'{"error":"boom"}'), ok_post("r02"), retry_budget=2
        )
        status, headers, _ = router.route(b"{}")
        assert status == 200 and headers["X-Served-By"] == "r02"
        c = router.counters_snapshot()
        assert c["failover_failed:r01"] == 1
        assert "failover_rejected_total" not in c

    @pytest.mark.parametrize("status", [400, 413, 504])
    def test_client_and_deadline_errors_never_retry(self, status):
        # 400/413 are the caller's problem; a 504 request's deadline is
        # already spent — retrying any of them would double-spend work
        router, mgr, post = self.two_replica_router(
            (status, {}, b'{"error":"no"}'), ok_post("r02"), retry_budget=2
        )
        got, headers, _ = router.route(b"{}")
        assert got == status
        assert headers["X-Fleet-Attempts"] == "1"
        assert len(post.calls) == 1
        assert router.counters_snapshot()["retries"] == 0


def meta_post(rid):
    """A replica-shaped 200: a dict body with a ``meta`` dict — the only
    shape the router's route-meta injection rewrites."""
    return (
        200,
        {"X-Replica-Id": rid},
        json.dumps({"x_adv": [], "meta": {"replica_id": rid}}).encode(),
    )


class TestRouterTracePropagation:
    def two_replica_router(self, r01_resp, r02_resp, **kw):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, age=1.0),
                "r02": health("r02", qps=10.0, age=1.0),
            }
        )
        post = ScriptedPost(
            {"mem://r01/attack": r01_resp, "mem://r02/attack": r02_resp}
        )
        return Router(mgr, http_post=post, clock=fc, **kw), mgr, post

    def test_every_forward_carries_trace_context(self):
        # even with NO span recorder the router propagates identity + hop
        # count (parent 0 = none); a body without a meta dict passes
        # through the route-meta injection untouched
        router, mgr, post = self.two_replica_router(
            ok_post("r01"), ok_post("r02")
        )
        status, _, body = router.route(b"{}")
        assert status == 200
        ctx = parse_trace_context(post.headers[0][TRACE_HEADER])
        assert ctx["trace_id"].startswith("fleet-")
        assert ctx["parent_span"] is None
        assert ctx["hop"] == 1
        assert json.loads(body) == {"rid": "r01"}

    def test_failover_attempts_share_trace_distinct_parent_spans(self):
        rec = TraceRecorder(spans_enabled=True)
        router, mgr, post = self.two_replica_router(
            ConnectionRefusedError("dead"),
            ok_post("r02"),
            retry_budget=2,
            recorder=rec,
        )
        status, headers, _ = router.route(b"{}")
        assert status == 200 and headers["X-Served-By"] == "r02"
        ctxs = [parse_trace_context(h[TRACE_HEADER]) for h in post.headers]
        assert len(ctxs) == 2
        # ONE trace id across the whole failover chain...
        assert ctxs[0]["trace_id"] == ctxs[1]["trace_id"]
        assert [c["hop"] for c in ctxs] == [1, 1]
        # ...with each attempt's own span as the remote parent, so the
        # replica trees compose under the right attempt in a merged doc
        assert ctxs[0]["parent_span"] != ctxs[1]["parent_span"]
        assert all(c["parent_span"] for c in ctxs)
        events = rec.events()
        attempts = [
            e
            for e in events
            if e.get("kind") == "span" and e.get("name") == "attempt"
        ]
        assert [a["attrs"]["replica"] for a in attempts] == ["r01", "r02"]
        assert {a["span"] for a in attempts} == {
            c["parent_span"] for c in ctxs
        }
        assert any(
            e.get("name") == "failover"
            and e["attrs"]["cause"] == "connection"
            for e in events
        )

    def test_upstream_context_adopted_and_hop_incremented(self):
        router, mgr, post = self.two_replica_router(
            ok_post("r01"), ok_post("r02")
        )
        router.route(
            b"{}",
            trace_context={"trace_id": "up-abc", "parent_span": 7, "hop": 2},
        )
        ctx = parse_trace_context(post.headers[0][TRACE_HEADER])
        assert ctx["trace_id"] == "up-abc"  # adopted, not re-minted
        assert ctx["hop"] == 3

    def test_served_meta_carries_per_attempt_route_detail(self):
        router, mgr, post = self.two_replica_router(
            ConnectionRefusedError("dead"), meta_post("r02"), retry_budget=2
        )
        status, headers, body = router.route(b"{}")
        assert status == 200
        route = json.loads(body)["meta"]["route"]
        ctx = parse_trace_context(post.headers[0][TRACE_HEADER])
        assert route["trace_id"] == ctx["trace_id"]
        assert route["hops"] == 1
        att = route["attempts"]
        assert [(a["replica"], a["status"], a["cause"]) for a in att] == [
            ("r01", None, "connection"),
            ("r02", 200, "served"),
        ]
        assert all(a["elapsed_s"] >= 0 for a in att)

    def test_exhausted_budget_response_keeps_upstream_body(self):
        reject = (429, {}, json.dumps({"error": "queue full"}).encode())
        router, mgr, post = self.two_replica_router(
            reject, reject, retry_budget=1
        )
        status, _, body = router.route(b"{}")
        assert status == 429
        # error bodies are never rewritten with route meta
        assert json.loads(body) == {"error": "queue full"}


class TestRouterServedBalance:
    def starved_router(self, **kw):
        """Two routable replicas, every request served by r01 (its
        capacity headroom always ranks first; r02 starves)."""
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, age=1.0),
                "r02": health("r02", qps=10.0, age=1.0),
            }
        )
        post = ScriptedPost(
            {"mem://r01/attack": ok_post("r01"), "mem://r02/attack": ok_post("r02")}
        )
        return Router(mgr, http_post=post, clock=fc, **kw), mgr, post

    def test_unprimed_then_measured_ratio(self):
        router, mgr, post = self.starved_router()
        # no served traffic yet: unprimed, not "perfectly imbalanced"
        assert router.served_balance() is None
        for _ in range(4):
            assert router.route(b"{}")[0] == 200
        bal = router.served_balance()
        # all 4 on r01, r02 at 0: mean/max = (4/2)/4 = 0.5 exactly —
        # with 2 replicas total starvation floors at 0.5, which is why
        # the default floor is 0.5 (< 0.5 needs 3+ replicas skewed)
        assert bal == {"ratio": 0.5, "served": {"r01": 4, "r02": 0}}
        assert router.healthz()["router"]["served_balance"] == bal

    def test_balance_drop_opens_incident_on_healthz_tick(self):
        from moeva2_ijcai22_replication_tpu.observability import (
            IncidentDetector,
        )

        det = IncidentDetector(clock=FakeClock(), balance_drop_floor=0.6)
        router, mgr, post = self.starved_router(incidents=det)
        for _ in range(4):
            router.route(b"{}")
        hz = router.healthz()  # /healthz is the balance tick point
        inc = hz["incidents"]
        assert inc["open"] == 1 and inc["by_kind"] == {"balance_drop": 1}
        rec = inc["incidents"][-1]
        assert rec["kind"] == "balance_drop" and rec["state"] == "open"
        assert rec["frozen"] is True
        assert rec["evidence"]["served"] == {"r01": 4, "r02": 0}
        assert rec["evidence"]["trigger"]["ratio"] == 0.5


class TestRouterAggregation:
    def make_tracker(self, values, bounds=(0.1, 1.0), shed=0):
        st = SloTracker(bounds=bounds)
        for v in values:
            st.observe("lcld", "dispatch", v)
        for _ in range(shed):
            st.shed("lcld", "expired", "queue_wait")
        return st

    def test_healthz_metrics_and_prometheus(self):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=100.0, age=1.0),
                "r02": health("r02", qps=40.0, age=1.0),
            }
        )
        s1 = self.make_tracker([0.05, 0.5], shed=1).snapshot()
        s2 = self.make_tracker([0.05, 0.5]).snapshot()
        http.set("mem://r01/metrics", {"replica_id": "r01", "slo": s1})
        http.set("mem://r02/metrics", {"replica_id": "r02", "slo": s2})
        post = ScriptedPost(
            {"mem://r01/attack": ok_post("r01"), "mem://r02/attack": ok_post("r02")}
        )
        router = Router(mgr, http_post=post, clock=fc)
        router.route(b"{}")

        hz = router.healthz()
        assert hz["ok"] is True
        assert hz["fleet"]["routable"] == 2
        assert hz["router"]["counters"]["forwards"] == 1
        assert set(hz["replicas"]) == {"r01", "r02"}

        snap = router.metrics()
        merged = snap["slo_merged"]
        assert merged["merged_from"] == 2
        assert merged["skipped_mismatched_bounds"] == 0
        # cumulative buckets summed across replicas: 4 observations total
        hist = merged["stages"]["lcld"]["dispatch"]
        assert hist["count"] == 4
        assert merged["shed"]["total"] == 1
        assert set(snap["per_replica"]) == {"r01", "r02"}

        text = router.prometheus_text()
        assert "moeva2_fleet_routable_replicas 2" in text
        assert 'router_events_total{event="forwards"} 1' in text
        assert ":r01" not in text  # per-replica attributions stay JSON-side

    def test_http_front_routes_and_aggregates(self):
        mgr, http, fc = make_fleet({"r01": health("r01", qps=100.0, age=1.0)})
        http.set("mem://r01/metrics", {"replica_id": "r01"})
        post = ScriptedPost({"mem://r01/attack": ok_post("r01")})
        router = Router(mgr, http_post=post, clock=fc)
        httpd = serve_router(router, "127.0.0.1", 0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = "http://127.0.0.1:%d" % httpd.server_address[1]
        try:
            req = urllib.request.Request(
                base + "/attack", data=b'{"domain": "lcld"}'
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["X-Served-By"] == "r01"
                assert resp.headers["X-Fleet-Attempts"] == "1"
            with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
                hz = json.loads(resp.read())
            assert hz["ok"] is True and hz["fleet"]["routable"] == 1
            with urllib.request.urlopen(
                base + "/metrics?format=prom", timeout=10
            ) as resp:
                assert b"moeva2_fleet_routable_replicas 1" in resp.read()
        finally:
            httpd.shutdown()


# ---------------------------------------------------------------------------
# SLO merge primitives (the router's /metrics aggregation contract)
# ---------------------------------------------------------------------------


class TestSloMerge:
    def test_histogram_merge_sums_and_requantiles(self):
        s1 = {"buckets": [[0.1, 5], [1.0, 10]], "sum": 2.0, "count": 10}
        s2 = {"buckets": [[0.1, 0], [1.0, 10]], "sum": 8.0, "count": 10}
        merged = merge_histogram_snapshots([s1, s2])
        assert merged["buckets"] == [[0.1, 5], [1.0, 20]]
        assert merged["count"] == 20 and merged["sum"] == 10.0
        # p50 rank 10: cumulative 5 at 0.1 misses, 20 at 1.0 covers
        assert merged["p50"] == 1.0 and merged["p99"] == 1.0

    def test_histogram_merge_refuses_mismatched_bounds(self):
        s1 = {"buckets": [[0.1, 5], [1.0, 10]], "sum": 2.0, "count": 10}
        s3 = {"buckets": [[0.25, 5], [2.5, 10]], "sum": 2.0, "count": 10}
        assert merge_histogram_snapshots([s1, s3]) is None

    def test_slo_merge_counts_mismatched_families(self):
        a = SloTracker(bounds=(0.1, 1.0))
        b = SloTracker(bounds=(0.25, 2.5))  # different bucket scheme
        for st in (a, b):
            st.observe("lcld", "dispatch", 0.05)
        merged = merge_slo_snapshots([a.snapshot(), b.snapshot()])
        assert merged["skipped_mismatched_bounds"] == 1
        assert merged["stages"] == {}  # the family was dropped, not mixed

    def test_slo_merge_adds_sheds_across_replicas(self):
        a, b = SloTracker(), SloTracker()
        a.shed("lcld", "expired", "queue_wait")
        b.shed("lcld", "expired", "queue_wait")
        b.shed("lcld", "overrun", "submit")
        merged = merge_slo_snapshots([a.snapshot(), b.snapshot()])
        assert merged["shed"]["total"] == 3
        assert merged["shed"]["by_domain"]["lcld"]["expired"]["queue_wait"] == 2


# ---------------------------------------------------------------------------
# capacity freshness fields + derived Retry-After (satellites)
# ---------------------------------------------------------------------------


class TestCapacityFreshness:
    def test_domain_block_publishes_age_and_span(self):
        fc = FakeClock()
        cm = CapacityModel(window=8, clock=fc)
        cm.note_batch(
            "lcld",
            strategy="pgd|flip",
            bucket=8,
            budget=3,
            requests=4,
            rows=8,
            run_s=0.5,
            flops=None,
        )
        fc.advance(5.0)
        block = cm.domain_block("lcld")
        # age = now - the window's last dispatch; span = the window's own
        # wall coverage — the router's two freshness signals
        assert block["age_s"] == 5.0
        assert block["window_span_s"] == 0.5

    def test_retry_after_from_windowed_drain_rate(self):
        fc = FakeClock()
        cm = CapacityModel(clock=fc)
        assert cm.retry_after_s(32) is None  # no live window yet
        cm.note_batch(
            "lcld",
            strategy="pgd|flip",
            bucket=8,
            budget=3,
            requests=8,
            rows=8,
            run_s=0.5,
            flops=None,
        )
        # window drains 16 rows/s => 32 queued rows ~ 2 s
        assert cm.retry_after_s(32) == pytest.approx(2.0)
        assert cm.retry_after_s(0) == pytest.approx(0.001)  # floor
        assert cm.retry_after_s(10**9) == pytest.approx(30.0)  # cap


class TestDerivedRetryAfterHint:
    def make_full_batcher(self, retry_after_fn=None, max_delay_s=0.01):
        clock = FakeClock()
        b = Microbatcher(
            BucketMenu((8,)),
            max_delay_s=max_delay_s,
            max_queue_rows=4,
            metrics=ServiceMetrics(),
            clock=clock,
            start=False,
            retry_after_fn=retry_after_fn,
        )
        b.submit("k", lambda x: x, np.ones((4, 1)))  # fill the queue
        return b

    def reject(self, b):
        with pytest.raises(QueueFull) as ei:
            b.submit("k", lambda x: x, np.ones((1, 1)))
        return ei.value.retry_after_s

    def test_hint_prefers_capacity_prediction(self):
        b = self.make_full_batcher(retry_after_fn=lambda rows: 2.5)
        assert self.reject(b) == pytest.approx(2.5)

    def test_hint_floored_by_next_flush_deadline(self):
        # the device could drain instantly, but admission still waits for
        # the flusher's next obligation — the hint is honest above both
        b = self.make_full_batcher(retry_after_fn=lambda rows: 1e-4)
        assert self.reject(b) == pytest.approx(0.01)

    def test_hint_falls_back_without_prediction(self):
        assert self.reject(self.make_full_batcher()) == pytest.approx(0.01)
        b = self.make_full_batcher(retry_after_fn=lambda rows: None)
        assert self.reject(b) == pytest.approx(0.01)

    def test_broken_hint_never_turns_429_into_500(self):
        def boom(rows):
            raise RuntimeError("broken capacity hook")

        b = self.make_full_batcher(retry_after_fn=boom)
        assert self.reject(b) == pytest.approx(0.01)

    def test_hint_wired_from_capacity_model(self):
        fc = FakeClock()
        cm = CapacityModel(clock=fc)
        cm.note_batch(
            "lcld",
            strategy="pgd|flip",
            bucket=8,
            budget=3,
            requests=8,
            rows=16,
            run_s=1.0,
            flops=None,
        )
        b = self.make_full_batcher(retry_after_fn=cm.retry_after_s)
        # 4 queued rows over a 16 rows/s window, above the 0.01 deadline
        assert self.reject(b) == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# autoscaling-shaped policy hooks (observe + act)
# ---------------------------------------------------------------------------


class TestPolicyHooks:
    AUTOSCALE = {"enabled": True, "sustain_s": 5.0}

    def test_disabled_policy_emits_nothing(self):
        mgr, http, fc = make_fleet({"r01": health("r01", headroom=0.01)})
        assert mgr.policy_tick(now=0.0) == []
        assert mgr.policy_tick(now=100.0) == []

    def test_sustained_headroom_exhaustion_counts_scale_up(self):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", qps=10.0, headroom=0.02),
                "r02": health("r02", qps=10.0, headroom=0.05),
            },
            autoscale=self.AUTOSCALE,
        )
        assert mgr.policy_tick(now=0.0) == []  # exhaustion observed, not yet sustained
        events = mgr.policy_tick(now=6.0)
        assert len(events) == 1
        ev = events[0]
        assert ev["kind"] == "scale_up"
        assert ev["cause"] == "headroom_exhausted"
        assert ev["acted"] is False  # observe mode counts only
        assert mgr.event_counts == {"scale_up:headroom_exhausted": 1}
        assert len(mgr.routable()) == 2  # nothing was spawned
        # one event per sustain window: the very next tick restarts the clock
        assert mgr.policy_tick(now=7.0) == []

    def test_recovered_headroom_resets_the_sustain_clock(self):
        mgr, http, fc = make_fleet(
            {"r01": health("r01", headroom=0.02)}, autoscale=self.AUTOSCALE
        )
        mgr.policy_tick(now=0.0)
        # headroom recovers mid-window: the exhaustion streak is broken
        mgr.get("r01").last_health = health("r01", headroom=0.5)
        assert mgr.policy_tick(now=3.0) == []
        mgr.get("r01").last_health = health("r01", headroom=0.02)
        assert mgr.policy_tick(now=4.0) == []  # streak restarted at 4.0
        assert mgr.policy_tick(now=8.0) == []
        assert mgr.policy_tick(now=9.5)[0]["kind"] == "scale_up"

    def test_sustained_idle_counts_scale_down_with_victim(self):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", headroom=0.99),
                "r02": health("r02", headroom=0.98),
            },
            autoscale=self.AUTOSCALE,
        )
        mgr.note_inflight("r02", +3)  # r01 is the least-loaded victim
        assert mgr.policy_tick(now=0.0) == []
        events = mgr.policy_tick(now=6.0)
        assert [e["kind"] for e in events] == ["scale_down"]
        assert events[0]["cause"] == "sustained_idle"
        assert events[0]["victim"] == "r01"
        assert events[0]["acted"] is False
        assert len(mgr.routable()) == 2  # observe mode: no drain performed

    def test_act_mode_drains_the_idle_victim(self):
        mgr, http, fc = make_fleet(
            {
                "r01": health("r01", headroom=0.99),
                "r02": health("r02", headroom=0.99),
            },
            autoscale={**self.AUTOSCALE, "mode": "act", "min_replicas": 1},
        )
        mgr.policy_tick(now=0.0)
        events = mgr.policy_tick(now=6.0)
        assert events[0]["acted"] is True
        victim = mgr.get(events[0]["victim"])
        assert victim.state == "terminated"  # adopted: drain stops routing
        assert len(mgr.routable()) == 1
        # min_replicas floor: the survivor is never drained away
        mgr.policy_tick(now=12.0)
        assert mgr.policy_tick(now=20.0) == []
        assert len(mgr.routable()) == 1


# ---------------------------------------------------------------------------
# slow tier: two real serve.py replicas, chaos kill, failover, drain
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_artifacts(tmp_path_factory):
    """Same self-contained synthetic LCLD family the serving tests use —
    duplicated here so the fleet module stays independently runnable."""
    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_lcld,
        synth_lcld_schema,
    )
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp

    tmp = tmp_path_factory.mktemp("fleet_artifacts")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(64, cons.schema, seed=5)

    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=2))
    save_params(sur, str(tmp / "nn.msgpack"))

    import joblib
    from sklearn.preprocessing import MinMaxScaler

    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    scaler = MinMaxScaler().fit(np.vstack([x, xl, xu]))
    joblib.dump(scaler, tmp / "scaler.joblib")
    return {
        "pool": x,
        "domain": {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": paths["features"],
                "constraints": paths["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
        },
    }


@pytest.mark.slow
class TestFleetSubprocess:
    def test_failover_and_drain_over_real_replicas(
        self, fleet_artifacts, tmp_path
    ):
        cfg = {
            "domains": {"lcld": fleet_artifacts["domain"]},
            "serving": {
                "bucket_sizes": [4, 8],
                "max_delay_s": 0.05,
                "max_queue_rows": 256,
                "request_timeout_s": 120.0,
                "capacity_window": 64,
                # fleet tracing: one shared config, per-replica sink paths
                # templated by serve.py (trace_r01.jsonl, trace_r02.jsonl)
                "trace_log": str(tmp_path / "trace.jsonl"),
                "flight_dir": str(tmp_path / "flight"),
            },
            "system": {"jax_cache_dir": str(tmp_path / "jax_cache")},
        }
        config_path = tmp_path / "fleet_config.json"
        config_path.write_text(json.dumps(cfg))

        manager = ReplicaManager(
            str(config_path),
            prewarm=False,  # first requests pay the compiles; fine here
            log_dir=str(tmp_path / "logs"),
            boot_timeout_s=300.0,
            poll_timeout_s=180.0,
        )
        try:
            h1 = manager.add()
            h2 = manager.add()
            assert {h1.state, h2.state} == {"admitted"}
            # both replicas share one build fingerprint (same config/version)
            assert h1.fingerprint == h2.fingerprint == tuple(
                manager.expected_build
            )

            router_sink = str(tmp_path / "trace_router.jsonl")
            router = Router(
                manager,
                retry_budget=2,
                request_timeout_s=180.0,
                recorder=TraceRecorder(sink_path=router_sink),
            )
            body = json.dumps(
                {
                    "domain": "lcld",
                    "rows": fleet_artifacts["pool"][:2].tolist(),
                    "attack": "pgd",
                    "loss_evaluation": "flip",
                    "eps": 0.2,
                    "budget": 2,
                }
            ).encode()

            status, headers, resp = router.route(body)
            assert status == 200, resp[:300]
            victim_id = headers["X-Served-By"]
            # the replica stamps its own identity end-to-end
            assert headers.get("X-Replica-Id") == victim_id
            # the routed response's meta carries the routing story AND the
            # replica's own span tree under the router-minted trace id
            meta = json.loads(resp)["meta"]
            assert meta["route"]["hops"] == 1
            assert meta["route"]["attempts"][-1]["cause"] == "served"
            assert "trace" in meta
            victim = manager.get(victim_id)
            survivor = h2 if victim is h1 else h1
            manager.poll()
            # the healthz handshake measured each replica's clock offset
            # (same host: sub-second) — what the fleet merge aligns with
            assert victim.clock_offset_s is not None
            assert abs(victim.clock_offset_s) < 5.0

            # chaos: SIGKILL behind the manager's back — the router still
            # believes the victim is admitted, so a forward can hit the
            # dead socket and must fail over within the retry budget
            victim.proc.kill()
            victim.proc.wait(timeout=15)
            failover_routes = []
            for _ in range(2):  # round-robin puts the corpse first once
                status, headers, resp = router.route(body)
                assert status == 200, resp[:300]
                assert headers["X-Served-By"] == survivor.replica_id
                failover_routes.append(json.loads(resp)["meta"]["route"])
            counters = router.counters_snapshot()
            assert counters["failover_connection_total"] >= 1
            assert counters.get(f"failover_connection:{victim_id}", 0) >= 1
            # at least one forward hit the corpse first: its response meta
            # names the dead replica's connection failure, then the
            # survivor — the per-attempt routing story, client-visible
            chains = [
                [(a["replica"], a["cause"]) for a in r["attempts"]]
                for r in failover_routes
            ]
            assert [
                (victim_id, "connection"),
                (survivor.replica_id, "served"),
            ] in chains
            survived_trace = failover_routes[-1]["trace_id"]

            # black box: the survivor's flight ring holds the journeys it
            # completed; POST /debug/flight dumps them atomically to disk
            from moeva2_ijcai22_replication_tpu.serving.fleet.replica import (
                default_http_post_json,
            )

            harvest = default_http_post_json(
                survivor.url + "/debug/flight", {"reason": "test_harvest"}
            )
            dump = load_flight_dump(harvest["path"])
            assert dump["kind"] == "flight_dump"
            assert dump["replica_id"] == survivor.replica_id
            assert len(dump["entries"]) >= 1
            assert {"inflight", "incidents", "capacity"} <= set(
                dump["extra"]
            )

            # the next poll round notices the corpse; routing excludes it
            manager.poll()
            assert victim.state == "dead"
            assert [h.replica_id for h in manager.routable()] == [
                survivor.replica_id
            ]

            # graceful end: concurrent in-flight requests complete before
            # the survivor's process is terminated
            with ThreadPoolExecutor(2) as pool:
                futs = [pool.submit(router.route, body) for _ in range(2)]
                results = [f.result(timeout=300) for f in futs]
            assert all(r[0] == 200 for r in results)
            report = manager.drain(survivor.replica_id, timeout_s=60.0)
            assert report["drained_clean"] is True
            assert survivor.state == "terminated"
            assert survivor.proc.poll() is not None

            # graceful end leaves the black box on disk: serve.py's
            # SIGTERM handler dumped before exiting
            sigterm_dump = load_flight_dump(
                str(
                    tmp_path
                    / "flight"
                    / f"flight_{survivor.replica_id}_sigterm.json"
                )
            )
            assert sigterm_dump is not None
            assert sigterm_dump["reason"] == "sigterm"

            # fleet trace merge: the router's sink + the survivor's
            # per-replica sink compose into ONE document where the routed
            # trace id appears on BOTH sides of the HTTP hop
            from moeva2_ijcai22_replication_tpu.observability.fleetrace import (
                merge_fleet_traces,
            )

            router.recorder.close()
            survivor_sink = str(
                tmp_path / f"trace_{survivor.replica_id}.jsonl"
            )
            doc = merge_fleet_traces(
                {
                    "router": router_sink,
                    survivor.replica_id: survivor_sink,
                },
                offsets={
                    survivor.replica_id: survivor.clock_offset_s or 0.0
                },
            )
            merge_report = doc["otherData"]["fleet_merge"]
            assert set(merge_report["replicas"]) == {
                "router",
                survivor.replica_id,
            }
            assert merge_report["skipped"] == {}
            by_pid = {
                e["args"]["name"]
                for e in doc["traceEvents"]
                if e.get("ph") == "M" and e.get("name") == "process_name"
            }
            assert survived_trace in by_pid  # one track, two processes' spans
        finally:
            manager.close()
