"""Device-utilization & cold-start observability: dispatch-gap ledger,
overlap attribution, startup-phase decomposition, and the --overlap gate.

Covers the PR-9 layer end to end, fixture-free (code-derived synthetic
LCLD schema):

- ``_window_intervals`` / ``join_gaps_to_spans`` pure units (fake
  timelines, most-specific-span-wins attribution, the unattributed
  bucket);
- :class:`~moeva2_ijcai22_replication_tpu.observability.gaps.GapTracker`
  under a fake clock: busy/idle/compile accounting, the compile-free
  overlap ratio, ``mark()`` windows, inter-window seams, ring bounding;
- the ``telemetry.gaps`` record schema (``telemetry_block`` always
  carries it; ``validate_record`` rejects a record without it);
- the cold-start ledger: phases, persistent-cache classification (hit /
  miss_stored / disabled / fallback paths), the ``setup_jax_cache``
  failure satellite (counted recorder event + surfaced error state);
- engine integration: MoEvA and PGD runs land windows on the process
  timeline at their existing sync points, emit Perfetto gap slices +
  the device-busy counter track when traced — and the tier-1 smoke
  pinning that gap/cold capture on/off is BIT-IDENTICAL with zero extra
  compiles and zero extra dispatches;
- Prometheus exposition of the gaps/coldstart families (HELP/TYPE on
  every family, bounded label sets);
- ``tools/bench_diff.py --overlap``: overlap-ratio drops and
  cold/steady-ratio growth fail, pre-gap records skip as baselines,
  lost capture fails, and the committed series stays green through the
  consolidated ``tools/repo_check.py`` entrypoint.
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import (
    synth_lcld,
    synth_lcld_schema,
)
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax
from moeva2_ijcai22_replication_tpu.observability import (
    Trace,
    TraceRecorder,
    get_coldstart,
    get_gap_tracker,
    join_gaps_to_spans,
    telemetry_block,
    validate_cold,
    validate_gaps,
    validate_record,
)
from moeva2_ijcai22_replication_tpu.observability.coldstart import (
    ColdStartLedger,
)
from moeva2_ijcai22_replication_tpu.observability.export import to_chrome_trace
from moeva2_ijcai22_replication_tpu.observability.gaps import (
    GapTracker,
    _window_intervals,
    emit_window_trace,
    spans_from_trace,
)
from moeva2_ijcai22_replication_tpu.observability.prom import prometheus_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# pure units: interval model + gap↔span join
# ---------------------------------------------------------------------------


class TestWindowIntervals:
    def test_single_dispatch_leading_and_trailing_gap(self):
        busy, comp, gaps = _window_intervals(
            0.0, 10.0, [(2.0, 5.0, 0.0)]
        )
        assert busy == [(2.0, 5.0)]
        assert comp == []
        assert gaps == [(0.0, 2.0), (7.0, 3.0)]

    def test_compile_precedes_enqueue_instant(self):
        """The enqueue timestamp is taken AFTER the compile returns, so
        the compile interval sits immediately before it — charged as
        compile, never as idle."""
        busy, comp, gaps = _window_intervals(
            0.0, 10.0, [(3.0, 4.0, 3.0)]
        )
        assert comp == [(0.0, 3.0)]
        assert busy == [(3.0, 4.0)]
        assert gaps == [(7.0, 3.0)]

    def test_chained_dispatches_show_no_gap(self):
        """Back-to-back async dispatches: the second was enqueued before
        the first finished, so the device queue never drains — zero gap
        between them (the serial-queue model)."""
        busy, comp, gaps = _window_intervals(
            0.0, 10.0, [(1.0, 4.0, 0.0), (2.0, 4.0, 0.0)]
        )
        assert busy == [(1.0, 4.0), (5.0, 4.0)]
        assert gaps == [(0.0, 1.0), (9.0, 1.0)]

    def test_host_stall_between_dispatches_is_a_gap(self):
        busy, comp, gaps = _window_intervals(
            0.0, 10.0, [(0.0, 2.0, 0.0), (6.0, 2.0, 0.0)]
        )
        assert (2.0, 4.0) in gaps

    def test_runs_clamped_to_window(self):
        busy, _, gaps = _window_intervals(0.0, 5.0, [(4.0, 10.0, 0.0)])
        assert busy == [(4.0, 1.0)]
        assert gaps == [(0.0, 4.0)]


class TestJoinGapsToSpans:
    def test_attributes_overlap_seconds_per_span(self):
        out = join_gaps_to_spans(
            [(2.0, 4.0)],
            [{"name": "decode", "start": 3.0, "dur": 2.0}],
        )
        assert out["attributed"] == {"decode": 2.0}
        assert out["unattributed_s"] == pytest.approx(2.0)
        assert out["per_gap"][0]["top"] == "decode"

    def test_most_specific_span_wins(self):
        """A span tree's envelope (long) loses to its child (short) over
        the instants the child covers — 'decode' beats the enclosing
        'dispatch' exactly where decode ran."""
        out = join_gaps_to_spans(
            [(0.0, 10.0)],
            [
                {"name": "dispatch", "start": 0.0, "dur": 10.0},
                {"name": "decode", "start": 4.0, "dur": 2.0},
            ],
        )
        assert out["attributed"]["decode"] == pytest.approx(2.0)
        assert out["attributed"]["dispatch"] == pytest.approx(8.0)
        assert out["unattributed_s"] == 0.0

    def test_no_spans_means_honest_unattributed(self):
        out = join_gaps_to_spans([(0.0, 3.0)], [])
        assert out["attributed"] == {}
        assert out["unattributed_s"] == pytest.approx(3.0)
        assert out["per_gap"][0]["top"] is None

    def test_multiple_gaps_aggregate(self):
        out = join_gaps_to_spans(
            [(0.0, 1.0), (5.0, 1.0)],
            [{"name": "fetch", "start": 0.0, "dur": 10.0}],
        )
        assert out["attributed"]["fetch"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# GapTracker under a fake clock
# ---------------------------------------------------------------------------


@pytest.fixture
def tracker():
    return GapTracker(clock=lambda: 0.0)


class TestGapTracker:
    def test_window_accounting(self, tracker):
        w = tracker.record_window(
            producer="pgd",
            start=0.0,
            end=10.0,
            dispatches=[(3.0, 5.0, 3.0, "pgd_attack#1")],
        )
        assert w.busy_s == pytest.approx(5.0)
        assert w.compile_s == pytest.approx(3.0)
        # overlap ratio excludes compile from the wall: 5 busy over
        # (10 - 3) active seconds, NOT over the raw 10
        assert w.overlap_ratio() == pytest.approx(5.0 / 7.0)

    def test_block_schema_and_ratio(self, tracker):
        tracker.record_window(
            producer="moeva",
            start=0.0,
            end=8.0,
            dispatches=[(0.0, 6.0, 0.0, "moeva_segment#1")],
        )
        block = tracker.gaps_block()
        validate_gaps(block)
        assert block["windows"] == 1
        assert block["overlap_ratio"] == pytest.approx(0.75)
        assert block["idle_s"] == pytest.approx(2.0)
        assert block["by_producer"]["moeva"]["overlap_ratio"] == pytest.approx(
            0.75
        )
        assert block["by_executable"]["moeva_segment#1"]["busy_s"] == (
            pytest.approx(6.0)
        )

    def test_inter_window_seam_counts_as_gap(self, tracker):
        tracker.record_window(
            producer="moeva", start=0.0, end=4.0,
            dispatches=[(0.0, 4.0, 0.0, None)],
        )
        tracker.record_window(
            producer="moeva", start=7.0, end=10.0,
            dispatches=[(7.0, 3.0, 0.0, None)],
        )
        block = tracker.gaps_block(
            spans=[{"name": "grid_write", "start": 4.0, "dur": 3.0}]
        )
        # busy 7 over wall 10 (no compile): the 3s seam between the two
        # windows is idle, attributed to the writer span covering it
        assert block["overlap_ratio"] == pytest.approx(0.7)
        assert block["attributed"] == {"grid_write": 3.0}
        assert block["top_gap_stages"][0][0] == "grid_write"

    def test_mark_scopes_the_block(self, tracker):
        tracker.record_window(
            producer="pgd", start=0.0, end=5.0,
            dispatches=[(0.0, 1.0, 0.0, None)],
        )
        mark = tracker.mark()
        tracker.record_window(
            producer="pgd", start=10.0, end=12.0,
            dispatches=[(10.0, 2.0, 0.0, None)],
        )
        block = tracker.gaps_block(since=mark)
        assert block["windows"] == 1
        assert block["busy_s"] == pytest.approx(2.0)
        assert block["overlap_ratio"] == pytest.approx(1.0)

    def test_empty_window_scope(self, tracker):
        mark = tracker.mark()
        block = tracker.gaps_block(since=mark)
        validate_gaps(block)
        assert block["windows"] == 0 and block["overlap_ratio"] is None

    def test_capture_off(self):
        t = GapTracker(enabled=False)
        assert (
            t.record_window(
                producer="pgd", start=0.0, end=1.0, dispatches=[]
            )
            is None
        )
        block = t.gaps_block()
        assert block == {"enabled": False}
        validate_gaps(block)  # enabled-off block stays schema-valid

    def test_ring_bounded_but_totals_survive(self):
        t = GapTracker(capacity=4, clock=lambda: 0.0)
        for i in range(10):
            t.record_window(
                producer="pgd",
                start=float(i),
                end=float(i) + 1.0,
                dispatches=[(float(i), 1.0, 0.0, None)],
            )
        assert t.gaps_block()["windows"] == 4  # ring keeps the last 4
        snap = t.snapshot()
        assert snap["totals"]["windows"] == 10  # totals never lose history
        assert snap["totals"]["busy_s"] == pytest.approx(10.0)

    def test_totals_keep_lifetime_by_producer_past_eviction(self):
        """The ring-scoped block forgets evicted windows; the lifetime
        totals (and their per-producer view) never do."""
        t = GapTracker(capacity=2, clock=lambda: 0.0)
        for i in range(5):
            t.record_window(
                producer="pgd",
                start=2.0 * i,
                end=2.0 * i + 1.0,
                dispatches=[(2.0 * i, 0.5, 0.0, None)],
            )
        tot = t.totals()
        assert tot["by_producer"]["pgd"]["windows"] == 5
        assert tot["by_producer"]["pgd"]["overlap_ratio"] == pytest.approx(0.5)
        assert t.gaps_block()["windows"] == 2  # ring kept only the last 2

    def test_degenerate_window_ignored(self, tracker):
        assert (
            tracker.record_window(
                producer="pgd", start=5.0, end=5.0, dispatches=[]
            )
            is None
        )


# ---------------------------------------------------------------------------
# record schema: telemetry.gaps is load-bearing
# ---------------------------------------------------------------------------


class TestGapsSchema:
    def test_telemetry_block_carries_gaps(self):
        block = telemetry_block()
        assert "gaps" in block
        validate_gaps(block["gaps"])
        rec = {"execution": {}, "telemetry": block}
        assert validate_record(rec) is rec

    def test_validate_record_rejects_missing_gaps(self):
        block = telemetry_block()
        block.pop("gaps")
        with pytest.raises(ValueError, match="gaps"):
            validate_record({"execution": {}, "telemetry": block}, "bench")

    def test_validate_gaps_rejects_partial_block(self):
        with pytest.raises(ValueError, match="missing"):
            validate_gaps({"windows": 1})
        with pytest.raises(ValueError, match="dict"):
            validate_gaps("nope")

    def test_spans_from_trace_excludes_own_gap_slices(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec, trace_id="x")
        t.record_span("decode", 0.5)
        t.record_span("device_gap", 0.5)
        names = {s["name"] for s in spans_from_trace(t)}
        assert names == {"decode"}

    def test_record_span_at_positions_the_slice(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec, trace_id="x")
        t.record_span("device_gap", 2.0, at=7.25)
        ev = [e for e in t.events if e["kind"] == "span"][0]
        assert ev["ts"] == pytest.approx(7.25)
        assert ev["dur"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# cold-start ledger
# ---------------------------------------------------------------------------


class TestColdStart:
    def test_phases_accumulate(self):
        cs = ColdStartLedger()
        cs.record_phase("artifact_build", 0.5)
        cs.record_phase("artifact_build", 0.25)
        with cs.phase("device_warmup"):
            pass
        block = cs.cold_block()
        validate_cold(block)
        assert block["phases"]["artifact_build"] == pytest.approx(0.75)
        assert block["phase_counts"]["artifact_build"] == 2
        assert "device_warmup" in block["phases"]

    def test_import_noted_once(self):
        cs = ColdStartLedger()
        cs.note_import_complete()
        first = cs.cold_block()["phases"]["import"]
        cs.note_import_complete()
        assert cs.cold_block()["phases"]["import"] == first

    def test_cache_disabled_classification(self):
        cs = ColdStartLedger()
        cs.configure_cache(None, False)
        out = cs.note_compile(
            producer="pgd_attack", key="pgd_attack#1",
            lower_s=0.1, compile_s=0.4, probe=cs.compile_probe(),
        )
        assert out == "disabled"
        block = cs.cold_block()
        assert block["phases"]["trace_lower"] == pytest.approx(0.1)
        assert block["phases"]["xla_compile"] == pytest.approx(0.4)
        pc = block["persistent_cache"]
        assert pc["by_outcome"] == {"disabled": 1}
        assert pc["by_executable"][0]["key"] == "pgd_attack#1"

    def test_miss_stored_via_cache_dir_diff(self, tmp_path):
        cs = ColdStartLedger()
        cs._listener_registered = False  # force the dir-diff path
        cs.configure_cache(str(tmp_path), True)
        probe = cs.compile_probe()
        (tmp_path / "entry0.bin").write_bytes(b"x")  # jax stored an entry
        out = cs.note_compile(
            producer="moeva_segment", key="moeva_segment#1",
            lower_s=0.1, compile_s=2.0, probe=probe,
        )
        assert out == "miss_stored"
        state = cs.cache_state()
        assert state["entries_start"] == 0 and state["entries_added"] == 1

    def test_hit_via_monitoring_counter(self, tmp_path):
        cs = ColdStartLedger()
        cs.configure_cache(str(tmp_path), True)
        cs._listener_registered = True  # monitoring available
        probe = cs.compile_probe()
        cs._jax_hits += 1  # jax fired /jax/compilation_cache/cache_hits
        out = cs.note_compile(
            producer="pgd_attack", key="pgd_attack#2",
            lower_s=0.05, compile_s=0.2, probe=probe,
        )
        assert out == "hit"
        assert cs.cold_block()["persistent_cache"]["hits"] == 1

    def test_miss_uncached_via_monitoring_counter(self, tmp_path):
        cs = ColdStartLedger()
        cs.configure_cache(str(tmp_path), True)
        cs._listener_registered = True
        probe = cs.compile_probe()
        cs._jax_misses += 1
        out = cs.note_compile(
            producer="pgd_attack", key="pgd_attack#3",
            lower_s=0.05, compile_s=0.2, probe=probe,
        )
        assert out == "miss_uncached"

    def test_fallback_outcome(self):
        cs = ColdStartLedger()
        out = cs.note_compile(
            producer="pgd_attack", key=None, lower_s=0.3, compile_s=0.0,
            aot=False,
        )
        assert out == "fallback"

    def test_capture_off_is_inert(self):
        cs = ColdStartLedger(enabled=False)
        cs.record_phase("import", 1.0)
        assert cs.note_compile(
            producer="p", key=None, lower_s=0.1, compile_s=0.1
        ) == "off"
        block = cs.cold_block()
        assert block == {"enabled": False}
        validate_cold(block)

    def test_setup_jax_cache_failure_is_counted_and_surfaced(
        self, monkeypatch, tmp_path
    ):
        """The satellite: a swallowed persistent-cache failure must leave
        a counted recorder event and structured error state, not just a
        bare print."""
        import jax

        from moeva2_ijcai22_replication_tpu.experiments.common import (
            setup_jax_cache,
        )
        from moeva2_ijcai22_replication_tpu.observability.trace import (
            default_recorder,
        )

        cs = get_coldstart()
        before_err = cs.cache_error
        before_count = default_recorder().counters.get(
            "jax_cache_setup_failures", 0
        )

        def boom(name, value):
            raise RuntimeError("no cache for you")

        monkeypatch.setattr(jax.config, "update", boom)
        try:
            setup_jax_cache(
                {"system": {"jax_cache_dir": str(tmp_path / "jc")}}
            )
            assert (
                default_recorder().counters["jax_cache_setup_failures"]
                == before_count + 1
            )
            state = cs.cache_state()
            assert state["enabled"] is False
            assert "no cache for you" in state["error"]
        finally:
            monkeypatch.undo()
            cs.cache_dir = None
            cs.cache_enabled = None
            cs.cache_error = before_err
            cs.cache_entries_start = None


# ---------------------------------------------------------------------------
# engine integration (synthetic problem, tiny shapes)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def problem(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gaps")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(12, cons.schema, seed=3)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=7))
    return {
        "constraints": cons,
        "surrogate": sur,
        "scaler": fit_minmax(x.min(0), x.max(0)),
        "x": x,
    }


def _engine(problem, **kw):
    kw.setdefault("n_gen", 11)
    kw.setdefault("n_pop", 16)
    kw.setdefault("n_offsprings", 8)
    kw.setdefault("seed", 5)
    kw.setdefault("archive_size", 4)
    return Moeva2(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        ml_scaler=problem["scaler"],
        norm=2,
        **kw,
    )


class TestEngineGapCapture:
    def test_moeva_generate_lands_a_window(self, problem):
        tracker = get_gap_tracker()
        mark = tracker.mark()
        eng = _engine(problem)
        eng.generate(problem["x"], 1)
        block = tracker.gaps_block(since=mark)
        assert block["windows"] == 1
        assert block["by_producer"].keys() == {"moeva"}
        assert block["overlap_ratio"] is not None
        assert 0.0 < block["overlap_ratio"] <= 1.0
        # the window names the executables it dispatched (ledger keys)
        assert any(
            k.startswith(("moeva_init", "moeva_segment"))
            for k in block["by_executable"]
        )

    def test_warm_run_has_zero_compile_in_window(self, problem):
        tracker = get_gap_tracker()
        eng = _engine(problem, seed=6)
        eng.generate(problem["x"], 1)  # cold
        mark = tracker.mark()
        eng.generate(problem["x"], 1)  # warm
        block = tracker.gaps_block(since=mark)
        assert block["windows"] == 1
        assert block["compile_s"] == pytest.approx(0.0)

    def test_pgd_generate_lands_a_window(self, problem):
        tracker = get_gap_tracker()
        mark = tracker.mark()
        pgd = ConstrainedPGD(
            classifier=problem["surrogate"],
            constraints=problem["constraints"],
            scaler=problem["scaler"],
            max_iter=4,
        )
        xs = np.asarray(problem["scaler"].transform(problem["x"]))
        y = np.asarray(
            problem["surrogate"].predict_proba(xs)
        ).argmax(-1)
        pgd.generate(xs, y)
        block = tracker.gaps_block(since=mark)
        assert block["windows"] == 1
        assert "pgd" in block["by_producer"]

    def test_traced_run_emits_gap_slices_and_busy_counter(self, problem):
        rec = TraceRecorder(spans_enabled=True)
        eng = _engine(problem, seed=7, record_quality=True, quality_every=5)
        eng.trace = Trace(rec, trace_id="gaps-test")
        eng.generate(problem["x"], 1)
        gauges = [
            e
            for e in rec.events()
            if e.get("kind") == "gauge" and e["name"] == "device_busy_ratio"
        ]
        assert gauges, "device-busy counter sample missing"
        doc = to_chrome_trace(rec.events())
        counters = [
            e for e in doc["traceEvents"] if e.get("ph") == "C"
        ]
        assert any(e["name"] == "device_busy_ratio" for e in counters)
        # gap slices render as X spans named device_gap (placement is the
        # true timeline instant, not the emission instant)
        slices = [
            e
            for e in doc["traceEvents"]
            if e.get("ph") == "X" and e.get("name") == "device_gap"
        ]
        assert slices
        assert all(e["args"].get("producer") == "moeva" for e in slices)

    def test_emit_window_trace_noop_when_untraced(self, tracker=None):
        w = GapTracker(clock=lambda: 0.0).record_window(
            producer="pgd", start=0.0, end=1.0,
            dispatches=[(0.0, 1.0, 0.0, None)],
        )
        emit_window_trace(None, w)  # must not raise
        emit_window_trace(Trace(TraceRecorder(), enabled=False), w)


class TestCaptureToggleSmoke:
    def test_gap_and_cold_capture_toggle_is_bit_identical_zero_overhead(
        self, problem
    ):
        """The tier-1 contract every observability PR keeps: capture
        on/off shares every compile and every dispatch, and the attack
        results are bit-identical."""
        tracker = get_gap_tracker()
        coldstart = get_coldstart()
        x = problem["x"]

        def run(enabled):
            prev_t, prev_c = tracker.enabled, coldstart.enabled
            tracker.enabled = enabled
            coldstart.enabled = enabled
            try:
                eng = _engine(problem, seed=11)
                res = eng.generate(x, 1)
                calls = eng._jit_init.calls + eng._jit_segment.calls
                compiles = len(eng._jit_init._compiled) + len(
                    eng._jit_segment._compiled
                )
            finally:
                tracker.enabled = prev_t
                coldstart.enabled = prev_c
            return res, calls, compiles

        res_on, calls_on, compiles_on = run(True)
        res_off, calls_off, compiles_off = run(False)
        assert calls_on == calls_off
        assert compiles_on == compiles_off
        np.testing.assert_array_equal(res_on.x_gen, res_off.x_gen)
        np.testing.assert_array_equal(res_on.f, res_off.f)
        np.testing.assert_array_equal(res_on.x_ml, res_off.x_ml)

    def test_capture_off_records_nothing(self, problem):
        tracker = get_gap_tracker()
        mark = tracker.mark()
        prev = tracker.enabled
        tracker.enabled = False
        try:
            eng = _engine(problem, seed=12)
            eng.generate(problem["x"], 1)
        finally:
            tracker.enabled = prev
        assert tracker.gaps_block(since=mark)["windows"] == 0


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------


class TestPromExposition:
    def _snapshot(self):
        t = GapTracker(clock=lambda: 0.0)
        t.record_window(
            producer="moeva", start=0.0, end=10.0,
            dispatches=[(1.0, 6.0, 1.0, "moeva_segment#1")],
        )
        cs = ColdStartLedger()
        cs.configure_cache(None, False)
        cs.record_phase("artifact_build", 0.4)
        cs.note_compile(
            producer="moeva_segment", key="moeva_segment#1",
            lower_s=0.2, compile_s=0.8, probe={},
        )
        return {
            "counters": {},
            "gauges": {},
            "streams": {},
            "gaps": t.gaps_block(
                spans=[{"name": "decode", "start": 8.0, "dur": 2.0}]
            ),
            "coldstart": cs.cold_block(),
        }

    def test_families_have_help_and_type(self):
        text = prometheus_text(self._snapshot())
        for family in (
            "moeva2_overlap_ratio",
            "moeva2_device_busy_s",
            "moeva2_device_idle_s",
            "moeva2_gap_attributed_s",
            "moeva2_producer_overlap_ratio",
            "moeva2_coldstart_phase_s",
        ):
            assert f"# HELP {family}" in text, family
            assert f"# TYPE {family}" in text, family

    def test_gap_values_and_labels(self):
        text = prometheus_text(self._snapshot())
        # busy 6 over active wall (10 - 1 compile) = 9
        assert "moeva2_overlap_ratio 0.6667" in text
        assert 'moeva2_gap_attributed_s{stage="decode"} 2' in text
        assert 'moeva2_producer_overlap_ratio{producer="moeva"}' in text
        assert 'moeva2_coldstart_phase_s{phase="artifact_build"} 0.4' in text

    def test_capture_off_emits_no_gap_families(self):
        text = prometheus_text(
            {
                "counters": {},
                "gauges": {},
                "streams": {},
                "gaps": {"enabled": False},
                "coldstart": {"enabled": False},
            }
        )
        assert "overlap_ratio" not in text
        assert "coldstart" not in text


# ---------------------------------------------------------------------------
# bench_diff --overlap + repo_check
# ---------------------------------------------------------------------------


def _interior(o2_100=0.20, o7_100=0.08, o2_300=0.95, o7_300=0.08):
    """Quality interior block mirroring the committed r06 values within
    the drift threshold — a synthetic NEXT record appended after r06 must
    stay comparable on every metric r06 armed (see the committed-series
    tests)."""
    mk = lambda o2, o7: [1.0, o2, 1.0, o7, 1.0, o7, o7]  # noqa: E731
    return {
        "100": {"gen": 100, "o_rates": mk(o2_100, o7_100)},
        "300": {"gen": 300, "o_rates": mk(o2_300, o7_300)},
    }


def _orecord(steady=10.0, overlap=0.9, cold_ratio=1.2, with_gaps=True):
    rec = {
        "metric": "m",
        "value": 80.0,
        "steady_s": steady,
        "cold_s": steady * cold_ratio,
        "execution": {"n_states": 1000, "n_gen": 1000},
        "telemetry": {
            "cost": {"flops_total": 2.51e15},
            "quality": {
                "judged": "engine",
                "samples": 10,
                "curve": [],
                "interior": _interior(),
            },
        },
        # the r06-armed blocks a successor must keep carrying: botnet
        # quality (always-on gate) and the serving slo block (--slo)
        "real_botnet": {
            "steady_s": 21.0,
            "n_states": 387,
            "n_gen": 1000,
            "quality": {
                "judged": "engine",
                "samples": 4,
                "curve": [],
                "interior": _interior(0.199, 0.08, 0.632, 0.245),
            },
        },
        "serving": {
            "levels": [
                {"offered_rps": 16.0, "throughput_rps": 16.0, "p99_ms": 20.0},
                {"offered_rps": 64.0, "throughput_rps": 62.0, "p99_ms": 24.0},
            ],
            "telemetry": {
                "slo": {
                    "stages": {},
                    "shed": {"total": 0, "by_domain": {}},
                    "knee": {"knee_rps": 64.0, "first_saturated_rps": None},
                }
            },
        },
    }
    if with_gaps:
        rec["telemetry"]["gaps"] = {
            "enabled": True,
            "windows": 3,
            "busy_s": overlap * 10.0,
            "overlap_ratio": overlap,
            "attributed": {},
        }
        rec["cold_steady_ratio"] = cold_ratio
        rec["cold"] = {
            "enabled": True,
            "phases": {"xla_compile": 2.0},
            "persistent_cache": {
                "hits": 4,
                "misses": 2,
                "by_outcome": {"aot_hit": 9, "hit": 1, "miss_stored": 2},
            },
            "time_to_first_dispatch_s": 3.0,
        }
    return rec


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


class TestBenchDiffOverlap:
    @pytest.fixture()
    def bench_diff(self):
        return _load_tool("bench_diff")

    def test_overlap_drop_fails(self, bench_diff, tmp_path):
        a = _write(tmp_path, "a.json", _orecord(overlap=0.9))
        b = _write(tmp_path, "b.json", _orecord(overlap=0.5))
        assert bench_diff.main([a, b, "--overlap"]) == 1
        # without the flag the gate stays unarmed (opt-in like --slo)
        assert bench_diff.main([a, b]) == 0

    def test_small_overlap_jitter_passes(self, bench_diff, tmp_path):
        a = _write(tmp_path, "a.json", _orecord(overlap=0.90))
        b = _write(tmp_path, "b.json", _orecord(overlap=0.80))
        assert bench_diff.main([a, b, "--overlap"]) == 0

    def test_cold_ratio_growth_fails(self, bench_diff, tmp_path):
        a = _write(tmp_path, "a.json", _orecord(cold_ratio=1.2))
        b = _write(tmp_path, "b.json", _orecord(cold_ratio=2.4))
        assert bench_diff.main([a, b, "--overlap"]) == 1

    def test_cold_ratio_improvement_passes(self, bench_diff, tmp_path):
        a = _write(tmp_path, "a.json", _orecord(cold_ratio=2.4))
        b = _write(tmp_path, "b.json", _orecord(cold_ratio=1.1))
        assert bench_diff.main([a, b, "--overlap"]) == 0

    def test_pre_gap_baselines_skip(self, bench_diff, tmp_path):
        a = _write(tmp_path, "a.json", _orecord(with_gaps=False))
        b = _write(tmp_path, "b.json", _orecord(overlap=0.4, cold_ratio=3.0))
        # first record carrying the blocks arms the gate without failing
        assert bench_diff.main([a, b, "--overlap"]) == 0

    def test_lost_capture_fails(self, bench_diff, tmp_path):
        a = _write(tmp_path, "a.json", _orecord())
        b = _write(tmp_path, "b.json", _orecord(with_gaps=False))
        assert bench_diff.main([a, b, "--overlap"]) == 1
        # the loss is invisible without the flag (committed series
        # compatibility) — arming is what makes it non-disarmable
        assert bench_diff.main([a, b]) == 0

    def test_bare_cold_s_without_breakdown_is_not_capture(
        self, bench_diff, tmp_path
    ):
        """cold_s/steady_s existed since r01: only the structured cold
        breakdown arms the cold gate, so pre-PR records stay baselines."""
        a = _write(tmp_path, "a.json", _orecord(with_gaps=False))
        assert bench_diff._overlap_points(json.loads(open(a).read())) == {}

    def test_threshold_configurable(self, bench_diff, tmp_path):
        a = _write(tmp_path, "a.json", _orecord(overlap=0.9))
        b = _write(tmp_path, "b.json", _orecord(overlap=0.75))
        assert bench_diff.main([a, b, "--overlap"]) == 0
        assert (
            bench_diff.main(
                [a, b, "--overlap", "--overlap-threshold", "0.1"]
            )
            == 1
        )

    def test_json_line_carries_overlap_verdicts(
        self, bench_diff, tmp_path, capsys
    ):
        a = _write(tmp_path, "a.json", _orecord(overlap=0.9))
        b = _write(tmp_path, "b.json", _orecord(overlap=0.4))
        rc = bench_diff.main([a, b, "--overlap", "--json"])
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 1 and doc["regressed"] and doc["overlap"] is True
        by_metric = {m["metric"]: m for m in doc["metrics"]}
        assert by_metric["gaps.overlap_ratio"]["verdict"] == "regression"

    def test_committed_series_green_with_first_gap_record(
        self, bench_diff, tmp_path
    ):
        """The repo check's exact semantics: the committed pre-gap series
        plus a first gap/cold-bearing record passes — the gate arms from
        that record forward."""
        import glob as _glob
        import shutil

        for p in sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
            shutil.copy(p, tmp_path / os.path.basename(p))
        rec = _orecord(steady=9.0, overlap=0.85, cold_ratio=1.15)
        nxt = _write(
            tmp_path, "BENCH_r99.json", {"n": 99, "rc": 0, "parsed": rec}
        )
        series = sorted(str(p) for p in tmp_path.glob("BENCH_r*.json"))
        assert nxt in series
        assert (
            bench_diff.main(
                ["--check", "--slo", "--mesh", "--overlap", "--cold", *series]
            )
            == 0
        )


class TestRepoCheckEntrypoint:
    def test_failing_gate_propagates_and_summary_names_it(self, tmp_path):
        """A regressing series fails the consolidated entrypoint with a
        per-gate FAIL line — the injected-regression evidence the
        acceptance criteria require, through the same command tier-1
        runs."""
        _write(tmp_path, "BENCH_r01.json", _orecord(overlap=0.9))
        _write(tmp_path, "BENCH_r02.json", _orecord(overlap=0.3))
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "repo_check.py"),
                "--only",
                "bench_diff",
                "--cwd",
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "bench_diff   FAIL" in proc.stdout
        assert "repo_check: FAILING" in proc.stdout
        assert "gaps.overlap_ratio" in proc.stdout

    def test_green_series_passes(self, tmp_path):
        _write(tmp_path, "BENCH_r01.json", _orecord(overlap=0.85))
        _write(tmp_path, "BENCH_r02.json", _orecord(overlap=0.9))
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "repo_check.py"),
                "--only",
                "bench_diff",
                "--json",
                "--cwd",
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        assert summary["ok"] is True
