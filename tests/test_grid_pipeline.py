"""Grid execution pipeline: executable reuse, artifact caching, writer.

Covers the three layers of the grid pipeline (docs/DESIGN.md §"Grid
execution pipeline") without reference data: ε as a runtime argument of the
compiled PGD/AutoPGD programs (bit-identical to baked-in ε, one trace per
static config across an ε sweep), the mtime-keyed artifact cache, the
background writer's ordering/isolation/pending-hash guarantees, and the
engine's mesh-multiple chunk rounding.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.pgd import AutoPGD, ConstrainedPGD
from moeva2_ijcai22_replication_tpu.core.constraints import FunctionalConstraintSet
from moeva2_ijcai22_replication_tpu.core.schema import FeatureSchema
from moeva2_ijcai22_replication_tpu.experiments import common
from moeva2_ijcai22_replication_tpu.experiments.common import ArtifactCache
from moeva2_ijcai22_replication_tpu.experiments.pipeline import GridPipeline
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import MLP, init_params
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax
from moeva2_ijcai22_replication_tpu.utils.observability import PhaseTimer


def _schema(n=6):
    return FeatureSchema(
        names=tuple(f"f{i}" for i in range(n)),
        types=np.array(["real"] * n, dtype=object),
        mutable=np.ones(n, dtype=bool),
        raw_min=np.array([0.0] * n, dtype=object),
        raw_max=np.array([1.0] * n, dtype=object),
        augmentation=np.zeros(n, dtype=bool),
    )


@pytest.fixture(scope="module")
def setup():
    schema = _schema()
    cons = FunctionalConstraintSet(
        schema,
        fn=lambda x: jnp.stack(
            [x[..., 0] - x[..., 1], jnp.abs(x[..., 2] - 0.5) - 0.4], axis=-1
        ),
        n_constraints=2,
    )
    model = MLP(hidden=(8,), n_classes=2)
    sur = Surrogate(model, init_params(model, schema.n_features, seed=0))
    scaler = fit_minmax(np.zeros(6), np.ones(6))
    rng = np.random.default_rng(0)
    x = rng.random((16, 6)).astype(np.float32)
    y = np.zeros(16, dtype=np.int64)
    return cons, sur, scaler, x, y


def _pgd(setup, cls=ConstrainedPGD, **over):
    cons, sur, scaler, x, y = setup
    kw = dict(
        classifier=sur, constraints=cons, scaler=scaler,
        eps=0.3, eps_step=0.05, max_iter=8, norm=2,
        loss_evaluation="constraints+flip", seed=7,
    )
    kw.update(over)
    return cls(**kw)


class TestEpsRuntimeArgument:
    def test_runtime_eps_matches_baked_in_eps(self, setup):
        """An engine constructed with ε=A (the pre-pipeline 'baked-in'
        configuration) and an engine constructed with a different ε but
        dispatched with generate(eps=A) must produce bit-identical output."""
        cons, sur, scaler, x, y = setup
        baked = _pgd(setup, eps=0.2, eps_step=0.05)
        out_baked = baked.generate(x, y)
        swept = _pgd(setup, eps=0.9, eps_step=0.4)  # deliberately wrong defaults
        out_swept = swept.generate(x, y, eps=0.2, eps_step=0.05)
        np.testing.assert_array_equal(out_baked, out_swept)

    def test_runtime_eps_matches_baked_in_autopgd(self, setup):
        baked = _pgd(setup, cls=AutoPGD, eps=0.2, eps_step=0.2 / 3,
                     num_random_init=1)
        out_baked = baked.generate(x_scaled := setup[3], setup[4])
        swept = _pgd(setup, cls=AutoPGD, eps=0.7, eps_step=0.1,
                     num_random_init=1)
        out_swept = swept.generate(x_scaled, setup[4], eps=0.2, eps_step=0.2 / 3)
        np.testing.assert_array_equal(out_baked, out_swept)

    def test_adaptive_step_uses_runtime_eps(self, setup):
        atk = _pgd(
            setup, eps=0.5,
            loss_evaluation="constraints+flip+adaptive_eps_step",
        )
        a = atk.generate(setup[3], setup[4], eps=0.1)
        b = atk.generate(setup[3], setup[4], eps=0.3)
        assert not np.array_equal(a, b)  # ε actually reaches the program

    def test_one_compile_serves_multi_eps_sweep(self, setup):
        """The executable-reuse contract: a fixed-loss multi-ε sweep traces
        (and therefore compiles) exactly one program."""
        atk = _pgd(setup)
        outs = [atk.generate(setup[3], setup[4], eps=e) for e in (0.1, 0.2, 0.3)]
        assert atk.trace_count == 1
        assert not np.array_equal(outs[0], outs[2])  # sweep is real

    def test_restart_path_single_trace(self, setup):
        atk = _pgd(setup, num_random_init=2)
        for e in (0.1, 0.25):
            atk.generate(setup[3], setup[4], eps=e)
        assert atk.trace_count == 1


class TestBudgetRuntimeArgument:
    def test_runtime_budget_matches_baked_in_budget(self, setup):
        """Plain PGD without history takes the budget as a dynamic fori_loop
        trip count: one engine swept over budgets must match per-budget baked
        engines bit-for-bit, with a single trace."""
        eng = _pgd(setup, loss_evaluation="constraints+flip+adaptive_eps_step")
        a8 = eng.generate(setup[3], setup[4], eps=0.2, max_iter=8)
        a20 = eng.generate(setup[3], setup[4], eps=0.2, max_iter=20)
        assert eng.trace_count == 1
        for budget, out in ((8, a8), (20, a20)):
            baked = _pgd(
                setup, eps=0.2, max_iter=budget,
                loss_evaluation="constraints+flip+adaptive_eps_step",
            )
            np.testing.assert_array_equal(baked.generate(setup[3], setup[4]), out)

    def test_history_program_bakes_budget(self, setup):
        """History recording shapes buffers by max_iter at trace time, so the
        budget must stay static: a mismatched runtime budget is rejected, and
        the recorded history keeps the (N, max_iter, C) contract. (The x
        output is compared with tolerance only — recording adds buffer writes
        to the compiled body, which may legally fuse differently from the
        recording-free program.)"""
        rec = _pgd(setup, eps=0.2, record_loss="reduced")
        assert rec._runtime_max_iter() is False
        out = rec.generate(setup[3], setup[4])
        assert rec.loss_history.shape == (16, 8, 3)
        dyn = _pgd(setup)
        np.testing.assert_allclose(
            dyn.generate(setup[3], setup[4], eps=0.2, max_iter=8), out,
            rtol=2e-4, atol=2e-4,
        )
        with pytest.raises(ValueError, match="trace-static budget"):
            rec.generate(setup[3], setup[4], max_iter=9)


class TestEngineCache:
    def test_hit_and_miss_counters(self):
        cache = common.EngineCache()
        built = []
        e1 = cache.get(("a", 1), lambda: built.append(1) or object())
        e2 = cache.get(("a", 1), lambda: built.append(2) or object())
        e3 = cache.get(("a", 2), lambda: built.append(3) or object())
        assert e1 is e2 and e1 is not e3
        assert built == [1, 3]
        assert cache.stats()["hits"] == 1 and cache.stats()["misses"] == 2


class TestArtifactCache:
    def test_same_object_across_lookups(self, tmp_path):
        path = tmp_path / "x.npy"
        np.save(path, np.arange(6.0))
        cache = ArtifactCache()
        a = cache.get("candidates", [str(path)], None, lambda: np.load(path))
        b = cache.get("candidates", [str(path)], None, lambda: np.load(path))
        assert a is b
        assert cache.stats() == {
            "hits": 1,
            "misses": 1,
            "evictions": 0,
            "entries": 1,
        }

    def test_invalidates_on_mtime_change(self, tmp_path):
        path = tmp_path / "x.npy"
        np.save(path, np.arange(6.0))
        cache = ArtifactCache()
        a = cache.get("candidates", [str(path)], None, lambda: np.load(path))
        np.save(path, np.arange(6.0) + 1)  # rewrite: new mtime_ns
        os.utime(path, ns=(time.time_ns(), time.time_ns() + 1))
        b = cache.get("candidates", [str(path)], None, lambda: np.load(path))
        assert a is not b
        np.testing.assert_array_equal(b, np.arange(6.0) + 1)
        assert cache.stats()["misses"] == 2

    def test_extra_key_separates_entries(self, tmp_path):
        path = tmp_path / "x.npy"
        np.save(path, np.arange(4.0))
        cache = ArtifactCache()
        a = cache.get("k", [str(path)], "lcld", lambda: ["a"])
        b = cache.get("k", [str(path)], "botnet", lambda: ["b"])
        assert a == ["a"] and b == ["b"]

    def test_load_candidates_shares_the_disk_read(self, tmp_path):
        """Runner-facing path: grid points slicing the same candidate file
        share one np.load; -1 returns the identical cached object."""
        path = tmp_path / "cand.npy"
        np.save(path, np.arange(20.0).reshape(10, 2))
        cfg = {
            "paths": {"x_candidates": str(path)},
            "initial_state_offset": 0,
            "n_initial_state": -1,
        }
        misses0 = common.ARTIFACTS.misses
        a = common.load_candidates(cfg)
        b = common.load_candidates(cfg)
        assert a is b
        c = common.load_candidates({**cfg, "n_initial_state": 4})
        assert c.shape == (4, 2) and c.base is a
        assert common.ARTIFACTS.misses == misses0 + 1


class TestBackgroundWriter:
    def test_fifo_ordering_and_drain(self):
        pipe = GridPipeline()
        done = []
        for i in range(8):
            pipe.submit(f"p{i}", f"/tmp/metrics_{i}", lambda i=i: done.append(i))
        pipe.drain()
        assert done == list(range(8))  # strict submission order
        pipe.close()

    def test_pending_until_written(self):
        pipe = GridPipeline()
        gate = threading.Event()
        pipe.submit("p", "/x/metrics.json", gate.wait)
        assert pipe.is_pending("/x/metrics.json")
        gate.set()
        pipe.drain()
        assert not pipe.is_pending("/x/metrics.json")
        pipe.close()

    def test_failure_is_isolated_and_reported(self, tmp_path):
        pipe = GridPipeline()
        done = []

        def boom():
            raise RuntimeError("disk on fire")

        pipe.submit("bad", "/x/a", boom)
        pipe.submit("good", "/x/b", lambda: done.append("ok"))
        report = pipe.finish({"grid": 1}, [str(tmp_path)])
        assert done == ["ok"]  # the failure did not kill the writer
        assert report["writer"]["failures"][0]["point"] == "bad"
        assert not pipe.is_pending("/x/a")  # failed writes clear pending too

    def test_should_skip_sees_queued_hashes(self, tmp_path):
        """Config-hash idempotency must hold while the metrics write is
        still queued: a duplicate point skips before the file lands."""
        pipe = GridPipeline()
        cfg = {
            "dirs": {"results": str(tmp_path)},
            "attack_name": "moeva",
        }
        path = common.metrics_path_for(cfg, "moeva")
        gate = threading.Event()
        pipe.submit("moeva", path, gate.wait)
        assert common.should_skip(cfg, "moeva", pipe)
        assert not common.should_skip(cfg, "moeva", None)  # file not yet there
        gate.set()
        pipe.close()

    def test_grid_report_contents(self, tmp_path):
        pipe = GridPipeline()
        timer = PhaseTimer()
        timer.add("attack_compile", 1.5)
        timer.add("attack_run", 0.5)
        timer.count("traces", 1)
        pipe.point("pgd_flip", "abc", timer)
        pipe.point("pgd_flip", "def", None, skipped=True)
        pipe.submit("pgd_flip", "/x/m", lambda: None)
        report = pipe.finish({"seeds": [42]}, [str(tmp_path)])
        assert report["points_total"] == 2
        assert report["points_launched"] == 1
        assert report["points_skipped"] == 1
        assert report["distinct_compiled_programs"] == 1
        assert report["attack_compile_s"] == pytest.approx(1.5)
        assert report["attack_run_s"] == pytest.approx(0.5)
        assert os.path.exists(report["report_path"])
        assert os.path.basename(report["report_path"]) == (
            f"grid_report_{report['grid_config_hash']}.json"
        )


class TestPhaseTimerAttack:
    def test_compile_vs_run_attribution(self):
        class FakeEngine:
            trace_count = 0

        eng = FakeEngine()
        timer = PhaseTimer()
        with timer.attack(eng):
            eng.trace_count += 1  # first dispatch traces
        with timer.attack(eng):
            pass  # steady dispatch
        assert timer.counters["traces"] == 1
        assert set(timer.spans) == {"attack", "attack_compile", "attack_run"}
        assert timer.spans["attack"] == pytest.approx(
            timer.spans["attack_compile"] + timer.spans["attack_run"]
        )


class TestChunkMeshRounding:
    def test_chunk_rounds_down_to_mesh_multiple(self, setup):
        """config/moeva.yaml satellite: a chunk that is not a mesh-size
        multiple is rounded down in the engine (floor at one mesh row)
        instead of raising."""
        from jax.sharding import Mesh

        cons, sur, scaler, x, y = setup
        mesh = Mesh(np.array(jax.devices()[:8]), ("states",))
        moeva = Moeva2(
            classifier=sur, constraints=cons, ml_scaler=scaler,
            norm=2, n_gen=3, n_pop=8, n_offsprings=4, seed=3,
            max_states_per_call=6,  # not a multiple of 8 -> rounds to 8
            mesh=mesh,
        )
        res = moeva.generate(x, 1)  # 16 states: two 8-state chunks
        assert res.x_ml.shape[0] == 16
        assert np.isfinite(res.f).all()
