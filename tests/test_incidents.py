"""Distributed-trace propagation, flight recorder, incident attribution.

Unit tier for the three fleet-observability primitives:

- ``observability.fleetrace`` — the ``X-Moeva2-Trace`` context codec, the
  NTP-midpoint clock-offset estimate, and the N-sink merge that aligns
  per-replica JSONL streams onto one wall-clock Perfetto timeline;
- ``observability.flightrec`` — the bounded ring of completed request
  journeys and its atomic crash-safe dump;
- ``observability.incidents`` — predicate trips (slo_breach, shed_spike,
  capacity_collapse, balance_drop) that freeze correlated evidence at
  open time, with dedupe/cooldown and the ``telemetry.incidents`` record
  block ``validate_record`` requires on serving/fleet records.

All host-side pure-Python — no JAX, no sockets, no subprocesses.
"""

import json

import pytest

from moeva2_ijcai22_replication_tpu.observability.fleetrace import (
    TRACE_HEADER,
    clock_offset,
    format_trace_context,
    merge_fleet_events,
    merge_fleet_traces,
    parse_trace_context,
    replica_sink_path,
)
from moeva2_ijcai22_replication_tpu.observability.flightrec import (
    FlightRecorder,
    load_flight_dump,
)
from moeva2_ijcai22_replication_tpu.observability.incidents import (
    INCIDENT_KEYS,
    IncidentDetector,
    incidents_block,
    validate_incidents,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# trace-context codec + clock offset
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_roundtrip(self):
        hdr = format_trace_context("r01:req-3f2a", parent_span=42, hop=2)
        assert parse_trace_context(hdr) == {
            "trace_id": "r01:req-3f2a",
            "parent_span": 42,
            "hop": 2,
        }

    def test_no_parent_encodes_as_zero_and_parses_as_none(self):
        # a router without a span recorder still propagates identity
        hdr = format_trace_context("fleet-abc")
        assert hdr == "00;fleet-abc;0;0"
        ctx = parse_trace_context(hdr)
        assert ctx["parent_span"] is None and ctx["hop"] == 0

    def test_trace_ids_with_dashes_survive(self):
        # our trace ids legitimately contain dashes (req-<uuid>) — the
        # delimiter is ';', so the id field is never split
        tid = "r02:req-ab-cd-ef"
        assert parse_trace_context(format_trace_context(tid))["trace_id"] == tid

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "01;trace;1;1",  # foreign version
            "00;;1;1",  # empty trace id
            "00;t;x;1",  # non-integer parent
            "00;t;1",  # wrong arity
        ],
    )
    def test_malformed_headers_parse_to_none(self, bad):
        # propagation is best-effort: a bad header must never fail the
        # request it rides on
        assert parse_trace_context(bad) is None

    def test_header_name_is_stable(self):
        # the wire contract the router stamps and replicas parse
        assert TRACE_HEADER == "X-Moeva2-Trace"

    def test_replica_sink_path_templating(self):
        # serve.py writes these paths, the fleet merge reads them back —
        # one function owns the templating so they can never disagree
        assert replica_sink_path("out/trace.jsonl", "r01") == (
            "out/trace_r01.jsonl"
        )
        assert replica_sink_path("out/trace", "r02") == "out/trace_r02.jsonl"
        assert replica_sink_path("out/trace.jsonl", None) == "out/trace.jsonl"


class TestClockOffset:
    def test_midpoint_rule(self):
        off = clock_offset(100.0, 100.2, 123.45)
        assert off["offset_s"] == pytest.approx(23.35)
        assert off["rtt_s"] == pytest.approx(0.2)

    def test_synchronized_clocks_measure_zero(self):
        off = clock_offset(10.0, 10.0, 10.0)
        assert off == {"offset_s": 0.0, "rtt_s": 0.0}

    def test_negative_rtt_clamped(self):
        # wall clocks can step between the two reads; the rtt bound must
        # stay non-negative instead of going nonsensical
        assert clock_offset(10.0, 9.0, 10.0)["rtt_s"] == 0.0


# ---------------------------------------------------------------------------
# fleet sink merge
# ---------------------------------------------------------------------------


def _write_sink(path, t0_wall, events):
    lines = [{"kind": "meta", "t0_wall": t0_wall, "pid": 1}, *events]
    path.write_text("".join(json.dumps(e) + "\n" for e in lines))
    return str(path)


class TestMergeFleet:
    def test_merges_onto_shared_timeline_with_offsets(self, tmp_path):
        # router epoch at wall 1000.0; replica epoch at wall 1002.0 but
        # its clock runs 0.5s ahead — the measured offset corrects it to
        # an effective 1002.5, i.e. 2.5s after the router's epoch
        router = _write_sink(
            tmp_path / "trace_router.jsonl",
            1000.0,
            [{"kind": "span", "name": "attempt", "trace": "t1",
              "span": 1, "parent": None, "ts": 0.1, "dur": 0.2}],
        )
        replica = _write_sink(
            tmp_path / "trace_r01.jsonl",
            1002.0,
            [{"kind": "span", "name": "dispatch", "trace": "t1",
              "span": 2, "parent": 1, "ts": 0.0, "dur": 0.1}],
        )
        events, report = merge_fleet_events(
            {"router": router, "r01": replica}, offsets={"r01": 0.5}
        )
        assert report["skipped"] == {}
        assert report["replicas"]["router"]["shift_s"] == 0.0
        assert report["replicas"]["r01"]["shift_s"] == pytest.approx(2.5)
        by_name = {e["name"]: e for e in events if e.get("kind") == "span"}
        assert by_name["attempt"]["ts"] == pytest.approx(0.1)
        assert by_name["dispatch"]["ts"] == pytest.approx(2.5)
        # merged stream is time-ordered after the leading meta line
        ts = [e["ts"] for e in events[1:]]
        assert ts == sorted(ts)

    def test_gauges_keep_per_replica_tracks(self, tmp_path):
        sinks = {
            rid: _write_sink(
                tmp_path / f"trace_{rid}.jsonl",
                1000.0,
                [{"kind": "gauge", "name": "queue_depth_rows",
                  "value": 3.0, "ts": 0.1}],
            )
            for rid in ("r01", "r02")
        }
        events, _ = merge_fleet_events(sinks)
        tracks = {
            e["trace"] for e in events if e.get("kind") == "gauge"
        }
        # two replicas' queue depths are NOT one counter
        assert tracks == {"r01:gauges", "r02:gauges"}

    def test_missing_and_empty_sinks_reported_not_fatal(self, tmp_path):
        empty = tmp_path / "trace_empty.jsonl"
        empty.write_text("")
        ok = _write_sink(
            tmp_path / "trace_ok.jsonl",
            5.0,
            [{"kind": "event", "name": "x", "trace": "t", "ts": 0.0}],
        )
        events, report = merge_fleet_events(
            {"gone": str(tmp_path / "nope.jsonl"), "empty": str(empty),
             "ok": ok}
        )
        assert report["skipped"] == {
            "gone": "missing sink",
            "empty": "no meta line (empty sink?)",
        }
        assert list(report["replicas"]) == ["ok"]
        assert len(events) == 2  # meta + the one event

    def test_merge_fleet_traces_writes_doc_with_report(self, tmp_path):
        sink = _write_sink(
            tmp_path / "trace_r01.jsonl",
            7.0,
            [{"kind": "span", "name": "s", "trace": "t1", "span": 1,
              "parent": None, "ts": 0.0, "dur": 0.1}],
        )
        out = tmp_path / "fleet.perfetto.json"
        doc = merge_fleet_traces({"r01": sink}, out_path=str(out))
        assert doc["otherData"]["fleet_merge"]["replicas"]["r01"]["events"] == 1
        on_disk = json.loads(out.read_text())
        assert on_disk["traceEvents"] == doc["traceEvents"]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_keeps_last_n(self):
        fr = FlightRecorder(capacity=3, clock=FakeClock(5.0))
        for i in range(5):
            fr.note({"request_id": f"req-{i}"})
        entries = fr.entries()
        assert [e["request_id"] for e in entries] == [
            "req-2", "req-3", "req-4",
        ]
        assert all(e["t_wall"] == 5.0 for e in entries)
        snap = fr.snapshot()
        assert snap["recorded"] == 5 and snap["ring_size"] == 3

    def test_capacity_zero_disables_capture(self):
        fr = FlightRecorder(capacity=0)
        assert fr.enabled is False
        fr.note({"request_id": "x"})
        assert fr.entries() == []
        assert fr.snapshot()["recorded"] == 0

    def test_dump_roundtrips_and_counts(self, tmp_path):
        fr = FlightRecorder(capacity=4, clock=FakeClock(9.0))
        fr.note({"request_id": "req-1", "status": "ok"})
        path = tmp_path / "out" / "flight_r01_test.json"
        summary = fr.dump(
            str(path),
            reason="test",
            replica_id="r01",
            extra={"inflight": {"queued_rows": 2}},
        )
        assert summary["path"] == str(path)
        assert summary["entries"] == 1
        doc = load_flight_dump(str(path))
        assert doc["kind"] == "flight_dump"
        assert doc["reason"] == "test" and doc["replica_id"] == "r01"
        assert doc["entries"][0]["request_id"] == "req-1"
        assert doc["extra"]["inflight"]["queued_rows"] == 2
        assert fr.snapshot()["dumps"] == 1

    def test_dump_is_atomic_no_tmp_left_behind(self, tmp_path):
        fr = FlightRecorder(capacity=2)
        path = tmp_path / "flight.json"
        fr.dump(str(path), reason="x")
        # tmp+os.replace discipline: the only file is the complete dump
        assert [p.name for p in tmp_path.iterdir()] == ["flight.json"]

    def test_load_missing_or_corrupt_returns_none(self, tmp_path):
        assert load_flight_dump(str(tmp_path / "nope.json")) is None
        bad = tmp_path / "bad.json"
        bad.write_text('{"kind": "flight_du')  # cut mid-write
        assert load_flight_dump(str(bad)) is None
        notdict = tmp_path / "list.json"
        notdict.write_text("[1, 2]")
        assert load_flight_dump(str(notdict)) is None


# ---------------------------------------------------------------------------
# incident detector
# ---------------------------------------------------------------------------


def _slo(p99, n=50, shed_total=0):
    return {
        "stages": {"lcld": {"dispatch": {"p99": p99, "n": n}}},
        "shed": {"total": shed_total},
    }


class TestIncidentLifecycle:
    def test_open_freezes_evidence_at_open_time(self):
        clock = FakeClock(10.0)
        det = IncidentDetector(clock=clock)
        evidence = {"shed": {"total": 3}}
        inc = det.open("shed_spike", "shed burst", evidence=evidence)
        evidence["shed"]["total"] = 999  # later tracker mutation
        assert inc["evidence"]["shed"]["total"] == 3  # frozen copy
        assert inc["frozen"] is True
        assert inc["state"] == "open" and inc["t_open"] == 10.0

    def test_unserializable_evidence_degrades_honestly(self):
        det = IncidentDetector()
        inc = det.open("slo_breach", "x", evidence={"obj": object()})
        # default=str serialization keeps SOMETHING; either way the
        # record never claims more than it holds
        assert isinstance(inc["evidence"], dict)

    def test_dedupe_counts_repeats_not_new_incidents(self):
        det = IncidentDetector()
        first = det.open("slo_breach", "a", dedupe_key="k")
        again = det.open("slo_breach", "b", dedupe_key="k")
        assert again is first
        assert first["repeats"] == 1
        snap = det.snapshot()
        assert snap["total"] == 1 and snap["suppressed"] == 1

    def test_cooldown_suppresses_flapping_after_resolve(self):
        clock = FakeClock()
        det = IncidentDetector(clock=clock, cooldown_s=60.0)
        det.open("shed_spike", "a", dedupe_key="k")
        det.resolve("k", "recovered")
        clock.advance(10.0)  # inside the cooldown window
        assert det.open("shed_spike", "b", dedupe_key="k") is None
        assert det.snapshot()["suppressed"] == 1
        clock.advance(60.0)  # window over: a genuinely new incident
        assert det.open("shed_spike", "c", dedupe_key="k") is not None
        assert det.snapshot()["total"] == 2

    def test_resolve_keeps_the_record_with_evidence(self):
        det = IncidentDetector()
        det.open("replica_dead", "r02 killed", evidence={"pid": 7},
                 dedupe_key="replica_dead:r02")
        inc = det.resolve("replica_dead:r02", "survivor recovered")
        assert inc["state"] == "resolved"
        assert inc["resolve_note"] == "survivor recovered"
        assert inc["evidence"] == {"pid": 7}  # evidence outlives resolve
        snap = det.snapshot()
        assert snap["open"] == 0
        assert snap["incidents"][0]["state"] == "resolved"

    def test_disabled_detector_is_inert(self):
        det = IncidentDetector(enabled=False)
        assert det.open("slo_breach", "x") is None
        assert det.tick(slo=_slo(10.0)) == []
        blk = incidents_block(det)
        assert blk["enabled"] is False and blk["incidents"] == []

    def test_history_bounded(self):
        clock = FakeClock()
        det = IncidentDetector(clock=clock, max_history=4, cooldown_s=0.0)
        for i in range(10):
            det.open("shed_spike", f"s{i}", dedupe_key=f"k{i}")
        snap = det.snapshot()
        assert len(snap["incidents"]) == 4
        assert snap["total"] == 10  # the count never loses history


class TestIncidentPredicates:
    def test_slo_breach_trips_against_best_seen_p99(self):
        det = IncidentDetector(p99_factor=3.0, min_samples=20)
        assert det.tick(slo=_slo(0.010)) == []  # establishes the baseline
        assert det.tick(slo=_slo(0.020)) == []  # 2x: under the factor
        opened = det.tick(slo=_slo(0.040), evidence_fn=lambda: {"gap": 1})
        assert [i["kind"] for i in opened] == ["slo_breach"]
        inc = opened[0]
        assert "lcld/dispatch" in inc["summary"]
        assert inc["evidence"]["trigger"]["p99_s"] == 0.040
        assert inc["evidence"]["gap"] == 1  # correlated evidence rode along
        # recovery auto-resolves the open incident
        det.tick(slo=_slo(0.012))
        assert det.snapshot()["open"] == 0

    def test_slo_breach_needs_samples(self):
        det = IncidentDetector(min_samples=20)
        det.tick(slo=_slo(0.010))
        assert det.tick(slo=_slo(10.0, n=5)) == []  # too few to judge

    def test_shed_spike_on_delta_not_level(self):
        det = IncidentDetector(shed_spike_min=8)
        assert det.tick(slo=_slo(0.01, shed_total=100)) == []  # baseline
        assert det.tick(slo=_slo(0.01, shed_total=104)) == []  # trickle
        opened = det.tick(slo=_slo(0.01, shed_total=120))
        assert [i["kind"] for i in opened] == ["shed_spike"]
        assert opened[0]["evidence"]["trigger"]["shed_delta"] == 16

    def test_capacity_collapse_against_best_seen(self):
        cap = lambda qps: {"by_domain": {"lcld": {"max_sustainable_qps": qps}}}
        det = IncidentDetector(capacity_collapse_ratio=0.5)
        assert det.tick(capacity=cap(100.0)) == []
        assert det.tick(capacity=cap(60.0)) == []  # above half of best
        opened = det.tick(capacity=cap(40.0))
        assert [i["kind"] for i in opened] == ["capacity_collapse"]
        # recovery resolves and the best never ratchets down
        det.tick(capacity=cap(90.0))
        assert det.snapshot()["open"] == 0

    def test_balance_drop_under_floor(self):
        det = IncidentDetector(balance_drop_floor=0.5)
        opened = det.tick(balance_ratio=0.25, balance_label="fleet_routable")
        assert [i["kind"] for i in opened] == ["balance_drop"]
        assert "fleet_routable" in opened[0]["summary"]
        det.tick(balance_ratio=0.9, balance_label="fleet_routable")
        assert det.snapshot()["open"] == 0

    def test_retrip_of_open_incident_does_not_reopen(self):
        det = IncidentDetector()
        det.tick(balance_ratio=0.1)
        assert det.tick(balance_ratio=0.1) == []  # same condition, ongoing
        snap = det.snapshot()
        assert snap["total"] == 1
        assert snap["incidents"][0]["repeats"] == 1


class TestIncidentsSchema:
    def test_block_carries_required_keys_and_validates(self):
        det = IncidentDetector()
        det.open("slo_breach", "x", evidence={"a": 1})
        blk = incidents_block(det)
        assert set(INCIDENT_KEYS) <= set(blk)
        assert validate_incidents(blk) is blk
        json.dumps(blk)  # strict JSON, record-ready

    def test_validate_rejects_malformed_blocks(self):
        with pytest.raises(ValueError, match="must be a dict"):
            validate_incidents([])
        with pytest.raises(ValueError, match="missing keys"):
            validate_incidents({"enabled": True})
        blk = incidents_block(None)
        blk["incidents"] = [{"id": 1}]  # hand-rolled incident: refused
        with pytest.raises(ValueError, match="frozen at open time"):
            validate_incidents(blk)

    def test_capture_off_block_is_valid(self):
        blk = incidents_block(None)
        assert blk["enabled"] is False
        assert validate_incidents(blk) is blk
