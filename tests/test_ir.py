"""Constraint-expression IR (``domains/ir/``): the domain-as-data subsystem.

Covers the ISSUE-13 tentpole end to end, dataset-free (code-derived
synthetic schemas + the committed spec package data):

- parser round-trip: spec text -> AST -> canonical text -> AST is a fixed
  point, and the spec hash is formatting-independent but semantics-
  sensitive;
- per-operator jnp == numpy unit semantics (arithmetic, power, guarded
  ratios, YYYYMM date arithmetic, membership, group sums);
- the committed ``lcld``/``botnet`` specs compile to kernels BIT-EXACT
  against the hand-written ``lcld_constraint_terms`` /
  ``BotnetConstraints._raw`` twins;
- the repair backend re-derives dependent features (defining equalities
  land at zero, memberships snap into the value set);
- MILP-backend feasibility: SatAttack solutions built from the spec
  linearization satisfy the spec's own jnp kernel at tolerance;
- seeded generator determinism (same seed -> same spec hash, same bytes);
- registry + provenance: three origins, ledger tags, /healthz
  ``build.domain_origins``;
- the tier-1 smoke: a spec-compiled domain runs MoEvA + PGD + serving
  with ZERO extra compiled executables vs its hand-written twin, and the
  oracle fixture's phishing engine rates reproduce bit-for-bit.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from moeva2_ijcai22_replication_tpu.domains import (
    SPEC_DIR,
    SPEC_DOMAINS,
    domain_origin,
    get_constraints_class,
    spec_domain_dir,
)
from moeva2_ijcai22_replication_tpu.domains.ir import (
    Env,
    compile_spec,
    generate_family,
    load_spec,
    make_spec_sat_builder,
    months,
    parse_constraint,
    parse_expr,
    safe_div,
    sample_family,
    spec_hash,
    validate_spec,
    write_family,
)
from moeva2_ijcai22_replication_tpu.domains.ir.expr import (
    canon_constraint,
    canon_expr,
    eval_expr,
    eval_term,
)
from moeva2_ijcai22_replication_tpu.domains.ir.ops import finite_div
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints, _months
from moeva2_ijcai22_replication_tpu.domains.synth import (
    synth_botnet,
    synth_botnet_schema,
    synth_lcld,
    synth_lcld_schema,
    synth_phishing,
)
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# shared problems (module-scoped: schemas + compiled kernels are reused)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def lcld_pair(tmp_path_factory):
    """(hand-written, spec-compiled) constraint sets on one synthetic
    schema, plus manifold + perturbed sample batches."""
    tmp = tmp_path_factory.mktemp("ir_lcld")
    paths = synth_lcld_schema(str(tmp))
    hand = LcldConstraints(paths["features"], paths["constraints"])
    cls = get_constraints_class("lcld_spec")
    spec_cons = cls(paths["features"], paths["constraints"])
    x = synth_lcld(48, hand.schema, seed=5)
    rng = np.random.default_rng(6)
    x_pert = x * (1.0 + 0.05 * rng.standard_normal(x.shape))
    return hand, spec_cons, x, x_pert, paths


@pytest.fixture(scope="module")
def botnet_pair(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ir_botnet")
    paths = synth_botnet_schema(str(tmp))
    from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints

    hand = BotnetConstraints(paths["features"], paths["constraints"])
    cls = get_constraints_class("botnet_spec")
    spec_cons = cls(paths["features"], paths["constraints"])
    x = synth_botnet(32, hand.schema, seed=5)
    rng = np.random.default_rng(6)
    x_pert = x * (1.0 + 0.05 * rng.standard_normal(x.shape))
    return hand, spec_cons, x, x_pert


@pytest.fixture(scope="module")
def phishing_cons():
    d = spec_domain_dir("phishing")
    return get_constraints_class("phishing")(
        os.path.join(d, "features.csv"), os.path.join(d, "constraints.csv")
    )


# ---------------------------------------------------------------------------
# parser + hashing
# ---------------------------------------------------------------------------


class TestParser:
    def test_round_trip_committed_specs(self):
        """spec -> AST -> canonical text -> AST is a fixed point for every
        committed spec (names, kinds, canonical forms all survive)."""
        for name, rel in SPEC_DOMAINS.items():
            spec = load_spec(os.path.join(SPEC_DIR, rel), name=name)
            assert spec.constraints, name
            for c in spec.constraints:
                text = canon_constraint(c)
                c2 = parse_constraint(c.name, text)
                assert c2.kind == c.kind, (name, c.name)
                assert canon_constraint(c2) == text, (name, c.name)

    def test_precedence_and_associativity(self):
        assert canon_expr(parse_expr("a + b * c")) == canon_expr(
            parse_expr("a + (b * c)")
        )
        assert canon_expr(parse_expr("(a + b) * c")) != canon_expr(
            parse_expr("a + b * c")
        )
        # ^ binds tighter than unary minus and is right-associative
        assert canon_expr(parse_expr("a ^ b ^ c")) == canon_expr(
            parse_expr("a ^ (b ^ c)")
        )
        assert canon_expr(parse_expr("-a ^ 2.0")) == canon_expr(
            parse_expr("-(a ^ 2.0)")
        )

    def test_hash_formatting_independent_semantics_sensitive(self):
        from moeva2_ijcai22_replication_tpu.domains.ir import ConstraintSpec

        def mk(text):
            return spec_hash(
                ConstraintSpec(
                    name="t", constraints=(parse_constraint("c", text),)
                )
            )

        assert mk("x + y*z <= 3.0") == mk("x   +  (y * z) <= 3.0")
        assert mk("x + y*z <= 3.0") != mk("x + y*z <= 4.0")

    def test_committed_spec_hashes_are_stable_objects(self):
        """Loading the same committed file twice yields the same hash;
        the three committed domains have three distinct hashes."""
        hashes = {}
        for name, rel in SPEC_DOMAINS.items():
            p = os.path.join(SPEC_DIR, rel)
            assert spec_hash(load_spec(p, name=name)) == spec_hash(
                load_spec(p, name=name)
            )
            hashes[name] = spec_hash(load_spec(p, name=name))
        assert len(set(hashes.values())) == len(hashes)


# ---------------------------------------------------------------------------
# per-operator unit semantics: jnp == numpy
# ---------------------------------------------------------------------------


class TestOperatorSemantics:
    ENV = Env(
        {"a": 0, "b": 1, "d": 2},
        {"g": np.array([0, 1, 2])},
    )

    X = np.array(
        [[2.0, 3.0, 4.0], [0.5, -1.0, 0.0], [200105.0, 199812.0, 1.0]]
    )

    @pytest.mark.parametrize(
        "text",
        [
            "a + b",
            "a - b",
            "a * b",
            "b / a",
            "a ^ 2.0",
            "abs(a - b)",
            "-a + b",
            "months(a) - months(b)",
            "safe_div(a, d, -7.0)",
            "finite_div(b, d, -7.0)",
            "sum(@g)",
            "sum(@g) / a",
            "@g - a",
        ],
    )
    def test_jnp_equals_numpy(self, text):
        node = parse_expr(text)
        v_np, w_np = eval_expr(node, self.X, self.ENV, np)
        v_j, w_j = eval_expr(node, jnp.asarray(self.X), self.ENV, jnp)
        assert w_np == w_j
        np.testing.assert_allclose(
            np.asarray(v_j, np.float64), np.asarray(v_np, np.float64),
            rtol=0, atol=0,
        )

    @pytest.mark.parametrize(
        "text,kind",
        [
            ("a <= b", "le"),
            ("a == b * d", "eq"),
            ("a in {0.5, 2.0}", "member"),
        ],
    )
    def test_term_semantics(self, text, kind):
        c = parse_constraint("t", text)
        assert c.kind == kind
        v_np, _ = eval_term(c, self.X, self.ENV, np)
        v_j, _ = eval_term(c, jnp.asarray(self.X), self.ENV, jnp)
        np.testing.assert_array_equal(
            np.asarray(v_j, np.float64), np.asarray(v_np, np.float64)
        )

    def test_guarded_ratio_ops(self):
        # zero denominator -> sentinel, no inf/nan escapes
        assert float(safe_div(np.float64(3.0), np.float64(0.0), -7.0)) == -7.0
        assert float(
            finite_div(np.float64(3.0), np.float64(0.0), -7.0)
        ) == -7.0
        assert float(safe_div(np.float64(3.0), np.float64(2.0), -7.0)) == 1.5
        j = safe_div(jnp.asarray(3.0), jnp.asarray(0.0), -7.0)
        assert float(j) == -7.0

    def test_months_single_source(self):
        """One tested definition used by lcld (jnp) and synth (numpy)."""
        f = np.array([200105.0, 199812.0, 202012.0])
        want = np.floor(f / 100.0) * 12.0 + np.mod(f, 100.0)
        np.testing.assert_array_equal(months(f), want)
        np.testing.assert_array_equal(
            np.asarray(months(jnp.asarray(f)), np.float64), want
        )
        # domains.lcld imports THE op (no second copy to drift)
        assert _months is months


# ---------------------------------------------------------------------------
# compiled-vs-handwritten equivalence (the tentpole's proof obligation)
# ---------------------------------------------------------------------------


class TestEquivalence:
    def test_lcld_bit_exact(self, lcld_pair):
        hand, spec_cons, x, x_pert, _ = lcld_pair
        assert spec_cons.n_constraints == hand.n_constraints
        for xx in (x, x_pert):
            a = np.asarray(spec_cons._raw(jnp.asarray(xx)))
            b = np.asarray(hand._raw(jnp.asarray(xx)))
            np.testing.assert_array_equal(a, b)

    def test_botnet_bit_exact(self, botnet_pair):
        hand, spec_cons, x, x_pert = botnet_pair
        assert spec_cons.n_constraints == hand.n_constraints == 360
        for xx in (x, x_pert):
            a = np.asarray(spec_cons._raw(jnp.asarray(xx)))
            b = np.asarray(hand._raw(jnp.asarray(xx)))
            np.testing.assert_array_equal(a, b)

    def test_numpy_twin_agrees(self, lcld_pair):
        """The spec's numpy oracle twin tracks the jnp kernel (f64)."""
        _, spec_cons, x, x_pert, _ = lcld_pair
        for xx in (x, x_pert):
            a = np.asarray(spec_cons._raw(jnp.asarray(xx)), np.float64)
            b = spec_cons.raw_numpy(xx)
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-9)

    def test_lcld_repair_matches_handwritten(self, lcld_pair):
        """The derived repair agrees bit-exactly with the hand-written one
        on every column the hand-written projection touches (term snap,
        installment formula, one-hot hardening); on the rest it is a
        strict superset — it also re-derives the remaining defining
        equalities (the ratio features the hand-written repair leaves
        stale), so its total residual is never worse."""
        hand, spec_cons, _, x_pert, _ = lcld_pair
        a = np.asarray(spec_cons.repair(jnp.asarray(x_pert)))
        b = np.asarray(hand.repair(jnp.asarray(x_pert)))
        touched = {1, 3}
        for grp, mask in zip(np.asarray(hand._ohe_idx), np.asarray(hand._ohe_mask)):
            touched |= set(int(c) for c in grp[mask])
        cols = sorted(touched)
        np.testing.assert_array_equal(a[:, cols], b[:, cols])
        ga = np.asarray(spec_cons.evaluate(jnp.asarray(a))).sum()
        gb = np.asarray(hand.evaluate(jnp.asarray(b))).sum()
        assert ga <= gb + 1e-9

    def test_repair_re_derives_dependents(self, phishing_cons):
        """Defining equalities land at ~0 and memberships snap after the
        compiled repair projection on off-manifold rows."""
        x = synth_phishing(24, phishing_cons.schema, seed=9)
        rng = np.random.default_rng(10)
        x_bad = x * (1.0 + 0.2 * rng.standard_normal(x.shape))
        fixed = np.asarray(phishing_cons.repair(jnp.asarray(x_bad)))
        res = phishing_cons.resolved
        raw = phishing_cons.raw_numpy(fixed)
        col = 0
        for c, w in zip(res.spec.constraints, res.widths):
            if c.kind in ("eq", "member"):
                assert float(np.abs(raw[:, col : col + w]).max()) < 1e-6, c.name
            col += w
        # https snapped into {0, 1}
        hcol = phishing_cons.resolved.env.col("https")
        assert set(np.unique(fixed[:, hcol])) <= {0.0, 1.0}


# ---------------------------------------------------------------------------
# MILP backend feasibility
# ---------------------------------------------------------------------------


class TestMilpBackend:
    @pytest.mark.parametrize("domain", ["phishing", "lcld_spec"])
    def test_sat_solutions_satisfy_kernel(self, domain, lcld_pair, phishing_cons):
        """End-to-end: SatAttack over the spec linearization; every
        returned candidate satisfies the spec's own jnp kernel at the
        evaluator tolerance."""
        from moeva2_ijcai22_replication_tpu.attacks.sat import SatAttack

        if domain == "phishing":
            cons = phishing_cons
            x = synth_phishing(4, cons.schema, seed=11)
        else:
            _, cons, x_all, _, _ = lcld_pair
            x = x_all[:4]
        xl, xu = cons.get_feature_min_max(dynamic_input=x)
        xl = np.broadcast_to(np.asarray(xl, float), x.shape)
        xu = np.broadcast_to(np.asarray(xu, float), x.shape)
        scaler = fit_minmax(
            np.minimum(x.min(0), xl.min(0)), np.maximum(x.max(0), xu.max(0))
        )
        attack = SatAttack(
            constraints=cons,
            sat_rows_builder=make_spec_sat_builder(cons),
            min_max_scaler=scaler,
            eps=0.5,
            norm=np.inf,
            n_sample=4,
        )
        out = attack.generate(x)
        assert out.shape[0] == x.shape[0]
        g = np.asarray(cons.evaluate(jnp.asarray(out.reshape(-1, x.shape[-1]))))
        assert float(np.nanmax(g)) <= 0.05

    def test_builder_shapes(self, phishing_cons):
        b = make_spec_sat_builder(phishing_cons)
        x = synth_phishing(1, phishing_cons.schema, seed=2)[0]
        rows = b(x, x)
        assert rows.feasible
        assert rows.rows  # affine rows emitted
        assert rows.n_extra_bin >= 1  # https membership mode binary


# ---------------------------------------------------------------------------
# generator
# ---------------------------------------------------------------------------


class TestGenerator:
    def test_deterministic_same_seed(self, tmp_path):
        _, _, spec_a, _ = generate_family(13)
        _, _, spec_b, _ = generate_family(13)
        assert spec_hash(spec_a) == spec_hash(spec_b)
        xa, _, _ = sample_family(32, seed=13)
        xb, _, _ = sample_family(32, seed=13)
        np.testing.assert_array_equal(xa, xb)
        da = write_family(str(tmp_path / "a"), 13)
        db = write_family(str(tmp_path / "b"), 13)
        for fn in ("features.csv", "constraints.csv"):
            pa, pb = os.path.join(da, fn), os.path.join(db, fn)
            with open(pa, "rb") as fa, open(pb, "rb") as fb:
                assert fa.read() == fb.read(), fn

    def test_distinct_seeds_distinct_specs(self):
        _, _, a, _ = generate_family(1)
        _, _, b, _ = generate_family(2)
        assert spec_hash(a) != spec_hash(b)

    def test_samples_satisfy_compiled_kernel(self, tmp_path):
        x, schema, spec = sample_family(32, seed=21)
        out = write_family(str(tmp_path), 21)
        cons = compile_spec(spec)(os.path.join(out, "features.csv"), None)
        g = np.asarray(cons.evaluate(jnp.asarray(x)))
        assert float(np.nanmax(g)) == 0.0


# ---------------------------------------------------------------------------
# registry + provenance
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_handwritten_names_unchanged(self):
        assert get_constraints_class("lcld") is LcldConstraints

    def test_origins(self):
        assert domain_origin("lcld") == {
            "origin": "handwritten",
            "spec_hash": None,
        }
        o = domain_origin("lcld_spec")
        assert o["origin"] == "spec" and len(o["spec_hash"]) == 64
        g = domain_origin("family3")
        assert g["origin"] == "generated" and g["spec_hash"]

    def test_unknown_project_raises(self):
        with pytest.raises(ValueError, match="family<seed>"):
            get_constraints_class("nope")

    def test_ledger_tags(self, lcld_pair):
        hand, spec_cons, _, _, _ = lcld_pair
        # hand-written tags are byte-identical to the pre-IR ledger keys
        assert hand.ledger_tag == "LcldConstraints"
        assert spec_cons.ledger_tag.startswith("spec:lcld_spec:")
        assert spec_cons.ledger_tag.split(":")[2] == spec_cons.resolved.hash[:12]

    def test_committed_specs_validate(self, lcld_pair, botnet_pair, phishing_cons):
        """No fatal static findings on any committed spec (the lcld
        non-guarded-denominator warnings are reference-faithful)."""
        hand, spec_cons, _, _, _ = lcld_pair
        findings = validate_spec(spec_cons.spec, hand.schema)
        assert all("non-guarded denominator" in f for f in findings)
        bh, bs, _, _ = botnet_pair
        assert validate_spec(bs.spec, bh.schema) == []
        assert validate_spec(
            phishing_cons.spec, phishing_cons.schema
        ) == []


# ---------------------------------------------------------------------------
# tier-1 smoke: engines + serving with zero extra compiles, fixture repro
# ---------------------------------------------------------------------------


class TestTier1Smoke:
    def test_moeva_pgd_zero_extra_compiles(self, lcld_pair):
        """The spec twin runs MoEvA + PGD compiling EXACTLY as many
        executables as the hand-written domain at the same shapes (and
        produces bit-identical candidates: the kernels, repair, and
        engine identities all line up)."""
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
        from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD
        from moeva2_ijcai22_replication_tpu.observability.ledger import (
            get_ledger,
        )

        hand, spec_cons, x, _, _ = lcld_pair
        x = x[:8]
        model = lcld_mlp()
        sur = Surrogate(model, init_params(model, hand.schema.n_features, seed=1))
        scaler = fit_minmax(x.min(0), x.max(0))
        ledger = get_ledger()

        def run(cons):
            before = {e.key for e in ledger.entries()}
            moeva = Moeva2(
                classifier=sur, constraints=cons, ml_scaler=scaler,
                norm=2, n_gen=4, n_pop=8, n_offsprings=4, seed=0,
            )
            res = moeva.generate(x, minimize_class=1)
            pgd = ConstrainedPGD(
                classifier=sur, constraints=cons, scaler=scaler,
                eps=0.2, eps_step=0.05, max_iter=3,
                loss_evaluation="constraints+flip",
            )
            xs = np.asarray(scaler.transform(x))
            adv = pgd.generate(xs, np.ones(len(xs), dtype=np.int64))
            new = [e for e in ledger.entries() if e.key not in before]
            return np.asarray(res.x_ml), np.asarray(adv), len(new)

        x_hand, adv_hand, n_hand = run(hand)
        x_spec, adv_spec, n_spec = run(spec_cons)
        assert n_spec == n_hand, (
            f"spec domain compiled {n_spec} executables vs the hand-written "
            f"twin's {n_hand} at identical shapes"
        )
        np.testing.assert_array_equal(x_spec, x_hand)
        np.testing.assert_array_equal(adv_spec, adv_hand)

    def test_serving_spec_domain_and_origins(self, lcld_pair, tmp_path):
        """One service, two tenants (hand-written lcld + spec twin served
        through the config ``spec:`` path): both serve the same rows, the
        spec tenant compiles no extra executables for the same bucket, and
        /healthz ``build.domain_origins`` carries the provenance."""
        import joblib
        from sklearn.preprocessing import MinMaxScaler as SkMinMax

        from moeva2_ijcai22_replication_tpu.models.io import save_params
        from moeva2_ijcai22_replication_tpu.observability.ledger import (
            get_ledger,
        )
        from moeva2_ijcai22_replication_tpu.serving import (
            AttackRequest,
            AttackService,
        )

        hand, _, x, _, paths = lcld_pair
        model = lcld_mlp()
        sur = Surrogate(model, init_params(model, hand.schema.n_features, seed=1))
        model_path = str(tmp_path / "nn.msgpack")
        save_params(sur, model_path)
        xl, xu = hand.get_feature_min_max(dynamic_input=x)
        xl = np.broadcast_to(np.asarray(xl, float), x.shape)
        xu = np.broadcast_to(np.asarray(xu, float), x.shape)
        scaler_path = str(tmp_path / "scaler.joblib")
        joblib.dump(SkMinMax().fit(np.vstack([x, xl, xu])), scaler_path)
        base = {
            "norm": 2,
            "paths": {
                "model": model_path,
                "features": paths["features"],
                "constraints": paths["constraints"],
                "ml_scaler": scaler_path,
            },
            "system": {"mesh_devices": 0},
        }
        domains = {
            "lcld": dict(base, project_name="lcld"),
            "lcld_spec": dict(
                base,
                project_name="lcld_spec",
                spec=os.path.join(SPEC_DIR, SPEC_DOMAINS["lcld_spec"]),
            ),
        }
        service = AttackService(domains, bucket_sizes=(8,), max_delay_s=0.002)
        try:
            origins = service.healthz()["build"]["domain_origins"]
            assert origins["lcld"]["origin"] == "handwritten"
            assert origins["lcld_spec"]["origin"] == "spec"
            assert len(origins["lcld_spec"]["spec_hash"]) == 64
            ledger = get_ledger()
            r1 = service.attack(
                AttackRequest(domain="lcld", x=x[:4], eps=0.2, budget=3),
                timeout=300.0,
            )
            before = {e.key for e in ledger.entries()}
            r2 = service.attack(
                AttackRequest(domain="lcld_spec", x=x[:4], eps=0.2, budget=3),
                timeout=300.0,
            )
            new = [e for e in ledger.entries() if e.key not in before]
            n_hand_like = len(
                [e for e in ledger.entries() if e.key in before]
            )
            assert r1.x_adv.shape == r2.x_adv.shape == x[:4].shape
            # the spec tenant's request path compiles the same program
            # count the hand-written tenant needed for this bucket — no
            # spec-compilation overhead leaks into serving
            assert len(new) <= max(1, n_hand_like)
            np.testing.assert_array_equal(r2.x_adv, r1.x_adv)
        finally:
            service.close()

    def test_phishing_fixture_rates_reproduce(self):
        """Quick tier: the committed oracle-fixture budget-100 phishing
        rates (the new data-only domain) reproduce bit-for-bit at seed 42
        — same discipline as lcld_synth."""
        oc = _load_tool("oracle_check")
        with open(os.path.join(FIXTURES, "oracle_interior_rates.json")) as fh:
            fixture = json.load(fh)
        d = fixture["domains"]["phishing"]
        assert d["config"] == oc.DOMAINS["phishing"], (
            "fixture config drifted from tools/oracle_check.py — rerun "
            "--regen and commit"
        )
        problem = oc.build_phishing(oc.DOMAINS["phishing"])
        rates = oc.engine_rates(problem, oc.DOMAINS["phishing"], 42)
        np.testing.assert_allclose(rates, d["engine"]["42"], atol=0)

    @pytest.mark.slow
    def test_phishing_oracle_ga_cross_check(self):
        """Slow tier: the f64 oracle-GA replay for the data-only domain
        — zero survival mismatches, committed rates reproduce."""
        oc = _load_tool("oracle_check")
        with open(os.path.join(FIXTURES, "oracle_interior_rates.json")) as fh:
            fixture = json.load(fh)
        cfg = oc.DOMAINS["phishing"]
        problem = oc.build_phishing(cfg)
        out = oc.oracle_ga_rates(problem, cfg, 42, check_states=np.arange(4))
        want = fixture["domains"]["phishing"]["oracle_ga"]["42"]
        np.testing.assert_allclose(out["o_rates"], want["o_rates"], atol=0)
        assert out["mismatches"] == []
