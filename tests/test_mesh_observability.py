"""Mesh-scale observability: per-device roofline, the collective ledger,
the sharding/transfer lint, and the balance watchdog.

Units (hardware-free): the HLO collective census on synthetic text (op
taxonomy, async start/done dedup, float-vs-control-plane byte split,
replica-group → mesh-axis attribution), the per-device cost split
(partitioned divides, unpartitioned honestly replicates), and the
``MeshCapture`` balance math with mark/window discipline.

Probes (emulated 8-device mesh, conftest's
``xla_force_host_platform_device_count`` recipe): LedgeredJit entries for
states-sharded programs carry device/partition counts, sharding
summaries, and a collective census; single-device entries keep their
pre-mesh JSON schema byte-stable.

Lint (``tools/shard_lint.py``): the pure rules on synthetic entries, the
injected-violation pair the acceptance criteria name — a forced
``all_gather`` of float population data and an implicit host transfer at
dispatch both trip — and the repo-check subprocess that lints the
committed domains green (tier-1, next to ``bench_diff --check --slo``).

Schema + surfaces: ``telemetry.mesh`` assembly and its
``validate_record`` enforcement on multi-device records, device-labeled
Prometheus families (HELP/TYPE on every family, label cardinality
bounded by device ordinals), per-device Perfetto tracks, and the
``bench_diff --mesh`` balance/contract gate.

Overhead (tier-1 acceptance): mesh capture on/off shares every compile
and dispatch and produces bit-identical results on the 8-device mesh.
"""

import json
import os
import subprocess
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from moeva2_ijcai22_replication_tpu.observability import (
    LEDGER,
    MESH,
    CostLedger,
    LedgeredJit,
    MeshCapture,
    mesh_block,
    mesh_snapshot,
    telemetry_block,
    validate_mesh,
    validate_record,
)
from moeva2_ijcai22_replication_tpu.observability.mesh import (
    collective_axes,
    parse_collectives,
    per_device_cost,
    probe_collectives,
)
from moeva2_ijcai22_replication_tpu.observability.prom import prometheus_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def clean_state():
    """Each test sees an empty process ledger and mesh capture, both
    enabled (engines record into the globals; other modules' runs must
    not leak in)."""
    LEDGER.reset()
    LEDGER.enabled = True
    MESH.reset()
    MESH.enabled = True
    yield
    LEDGER.reset()
    LEDGER.enabled = True
    MESH.reset()
    MESH.enabled = True


@pytest.fixture(scope="module")
def mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("states",))


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Synthetic-LCLD artifact family (same shape as test_cost_ledger's)
    — dataset- and hardware-free."""
    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_lcld,
        synth_lcld_schema,
    )
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    tmp = tmp_path_factory.mktemp("mesh_artifacts")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(32, cons.schema, seed=9)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=2))
    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    return {
        "pool": x,
        "cons": cons,
        "sur": sur,
        "scaler": fit_minmax(
            np.vstack([x, xl, xu]).min(0), np.vstack([x, xl, xu]).max(0)
        ),
    }


# ---------------------------------------------------------------------------
# HLO collective census (pure text parsing)
# ---------------------------------------------------------------------------

#: one float all-gather (iota groups), one TUPLE-result async all-gather
#: pair (the TPU/GPU form — the "(" in the result type must not hide the
#: op), one u32 collective-permute (list-form groups), one async
#: all-reduce pair (must count ONCE), and a plain fusion line that must
#: not count at all.
_HLO = """\
HloModule linted, entry_computation_layout={(f32[2,64]{1,0})->f32[16,64]{1,0}}
  %fused = f32[2,64]{1,0} fusion(f32[2,64]{1,0} %x), kind=kLoop
  %ag = f32[16,64]{1,0} all-gather(f32[2,64]{1,0} %x), replica_groups=[1,8]<=[8], dimensions={0}
  %ags = (f32[2,64]{1,0}, f32[16,64]{1,0}) all-gather-start(f32[2,64]{1,0} %x), replica_groups=[1,8]<=[8], dimensions={0}
  %agd = f32[16,64]{1,0} all-gather-done((f32[2,64]{1,0}, f32[16,64]{1,0}) %ags)
  %cp = u32[2]{0} collective-permute(u32[2]{0} %k), source_target_pairs={{0,1},{1,0}}, replica_groups={{0,1,2,3},{4,5,6,7}}
  %ars = f32[8]{0} all-reduce-start(f32[8]{0} %v), replica_groups=[1,8]<=[8], to_apply=%add
  %ard = f32[8]{0} all-reduce-done(f32[8]{0} %ars)
"""


class TestParseCollectives:
    def test_counts_ops_once_and_splits_float_payload(self):
        col = parse_collectives(_HLO)
        # ag + async ag + cp + ar (-done completions are the SAME ops)
        assert col["count"] == 4
        assert set(col["ops"]) == {
            "all-gather",
            "collective-permute",
            "all-reduce",
        }
        assert col["ops"]["all-gather"]["count"] == 2
        assert col["ops"]["all-reduce"]["count"] == 1
        # bytes: result shapes — ag f32[16,64]=4096, async ag's TUPLE
        # (f32[2,64], f32[16,64])=4608, cp u32[2]=8, ar f32[8]=32
        assert col["ops"]["all-gather"]["bytes"] == 4096.0 + 4608.0
        assert col["ops"]["collective-permute"]["bytes"] == 8.0
        assert col["bytes"] == 4096.0 + 4608.0 + 8.0 + 32.0
        # float split: the u32 permute is control-plane, not data
        assert col["float_count"] == 3
        assert col["float_bytes"] == 4096.0 + 4608.0 + 32.0

    def test_tuple_result_async_collective_is_not_missed(self):
        # the TPU/GPU async form: "(" of the tuple result sits BEFORE the
        # op name — a prefix-of-first-paren parse sees zero collectives
        # and would let a hot-loop all-gather through the lint
        col = parse_collectives(
            "%ags = (f32[2,64]{1,0}, f32[16,64]{1,0}) "
            "all-gather-start(f32[2,64]{1,0} %x), replica_groups=[1,8]<=[8]\n"
            "%agd = f32[16,64]{1,0} all-gather-done((f32[2,64]{1,0}, "
            "f32[16,64]{1,0}) %ags)\n"
        )
        assert col["count"] == 1
        assert col["float_count"] == 1
        assert col["ops"]["all-gather"]["count"] == 1

    def test_replica_groups_both_forms(self):
        col = parse_collectives(_HLO)
        # iota [1,8] → size 8 (ag + async ag + ar); list {{0,1,2,3},…} → 4
        assert col["group_sizes"] == {"8": 3, "4": 1}

    def test_collective_free_text_is_empty(self):
        col = parse_collectives("%f = f32[8]{0} fusion(f32[8]{0} %x)\n")
        assert col["count"] == 0
        assert col["bytes"] == 0.0
        assert col["ops"] == {}

    def test_probe_degrades_to_none_without_as_text(self):
        assert probe_collectives(object()) is None

        class Raises:
            def as_text(self):
                raise RuntimeError("backend says no")

        assert probe_collectives(Raises()) is None


class TestPerDeviceCost:
    def test_partitioned_cost_splits(self):
        pd = per_device_cost(8000.0, 1600.0, partitions=8, devices=8)
        assert pd == {
            "devices": 8,
            "partitions": 8,
            "replicated": False,
            "flops": 1000.0,
            "bytes_accessed": 200.0,
        }

    def test_unpartitioned_cost_replicates_not_divides(self):
        # the honest fallback: every device pays the FULL program
        pd = per_device_cost(8000.0, None, partitions=1, devices=8)
        assert pd["replicated"] is True
        assert pd["flops"] == 8000.0
        assert pd["bytes_accessed"] is None


class TestCollectiveAxes:
    DESC = {"devices": 8, "shape": [2, 4], "axes": ["dp", "tp"]}

    def test_group_size_maps_to_unique_axis(self):
        assert collective_axes({"4": 3}, self.DESC) == {"tp": 3}
        assert collective_axes({"2": 1}, self.DESC) == {"dp": 1}

    def test_whole_mesh_group_is_all(self):
        assert collective_axes({"8": 2}, self.DESC) == {"all": 2}

    def test_single_axis_whole_mesh_names_the_axis(self):
        desc = {"devices": 8, "shape": [8], "axes": ["states"]}
        assert collective_axes({"8": 2}, desc) == {"states": 2}

    def test_ambiguous_size_stays_honest(self):
        assert collective_axes({"3": 1}, self.DESC) == {"group3": 1}
        # no mesh description at all: everything is a bare group size
        assert collective_axes({"4": 2}, None) == {"group4": 2}


# ---------------------------------------------------------------------------
# balance capture
# ---------------------------------------------------------------------------


class TestMeshCapture:
    def test_uniform_rows_balance_to_one(self):
        cap = MeshCapture()
        cap.record_balance([2.0] * 8, 4.0)
        block = cap.balance_block()
        assert block["devices"] == 8
        assert block["ratio"] == 1.0
        # SPMD lockstep: every fully-loaded device accrues the whole
        # window's wall-clock as useful seconds
        assert block["per_device_s"] == [4.0] * 8
        assert block["sync_points"] == 1
        assert block["attributed_s"] == 4.0

    def test_skew_attributes_by_live_row_share(self):
        cap = MeshCapture()
        # device 0 carries all live rows: everyone pays the wall-clock,
        # only device 0 does useful work -> ratio 1/8
        cap.record_balance([4, 0, 0, 0, 0, 0, 0, 0], 2.0)
        block = cap.balance_block()
        assert block["per_device_s"][0] == 2.0
        assert sum(block["per_device_s"][1:]) == 0.0
        assert block["ratio"] == pytest.approx(0.125)

    def test_mark_scopes_a_window(self):
        cap = MeshCapture()
        cap.record_balance([1, 1], 10.0)
        mark = cap.mark()
        cap.record_balance([2, 0], 3.0)
        window = cap.balance_block(since=mark)
        assert window["sync_points"] == 1
        assert window["attributed_s"] == 3.0
        assert window["per_device_s"] == [3.0, 0.0]
        assert window["ratio"] == pytest.approx(0.5)
        # cumulative view untouched
        assert cap.balance_block()["sync_points"] == 2

    def test_disabled_and_degenerate_inputs_are_noops(self):
        cap = MeshCapture(enabled=False)
        cap.record_balance([1, 1], 5.0)
        assert cap.balance_block()["sync_points"] == 0
        cap = MeshCapture()
        cap.record_balance([], 5.0)  # no devices
        cap.record_balance([1, 1], 0.0)  # no duration
        cap.record_balance([0, 0], 5.0)  # nothing live
        cap.record_balance("junk", 5.0)  # never raises
        block = cap.balance_block()
        assert block["sync_points"] == 0
        assert block["ratio"] is None


# ---------------------------------------------------------------------------
# compiled-executable probes on the 8-device mesh
# ---------------------------------------------------------------------------


class TestCompiledProbes:
    def test_sharded_program_entry_carries_mesh_payload(self, mesh8):
        led = CostLedger()
        x = jax.device_put(
            jnp.ones((16, 8), jnp.float32), NamedSharding(mesh8, P("states"))
        )
        lj = LedgeredJit(
            jax.jit(lambda x: x * 2 + 1), producer="pgd_attack", ledger=led
        )
        lj(x)
        (entry,) = led.entries()
        assert entry.devices == 8
        assert entry.partitions == 8
        assert entry.sharding["in"]["sharded"] == 1
        assert entry.sharding["in"]["replicated_bytes"] == 0
        # elementwise states-sharded program: zero collectives
        assert entry.collectives is not None
        assert entry.collectives["count"] == 0
        d = entry.as_dict()
        assert d["mesh"]["devices"] == 8
        assert d["mesh"]["per_device"]["replicated"] is False
        if entry.flops is not None:
            assert d["mesh"]["per_device"]["flops"] == pytest.approx(
                entry.flops / 8
            )

    def test_single_device_entry_schema_is_unchanged(self):
        led = CostLedger()
        lj = LedgeredJit(
            jax.jit(lambda x: x + 1), producer="pgd_attack", ledger=led
        )
        lj(jnp.ones((4, 4), jnp.float32))
        (entry,) = led.entries()
        assert entry.devices == 1
        # the pre-mesh ledger JSON stays byte-stable for 1-device programs
        assert "mesh" not in entry.as_dict()

    def test_forced_all_gather_shows_in_census(self, mesh8):
        led = CostLedger()
        x = jax.device_put(
            jnp.ones((16, 64), jnp.float32), NamedSharding(mesh8, P("states"))
        )

        def bad(x):
            g = jax.lax.with_sharding_constraint(
                x * 2.0, NamedSharding(mesh8, P())
            )
            return g - g.mean()

        lj = LedgeredJit(jax.jit(bad), producer="moeva_segment", ledger=led)
        lj(x)
        (entry,) = led.entries()
        col = entry.collectives
        assert col is not None and col["count"] >= 1
        # float population data crossed devices — the contract violation
        assert col["float_count"] >= 1
        assert col["float_bytes"] > 0

    def test_capture_off_skips_the_probe(self, mesh8):
        MESH.enabled = False
        led = CostLedger()
        x = jax.device_put(
            jnp.ones((16, 8), jnp.float32), NamedSharding(mesh8, P("states"))
        )
        lj = LedgeredJit(
            jax.jit(lambda x: x * 3), producer="pgd_attack", ledger=led
        )
        lj(x)
        (entry,) = led.entries()
        assert entry.devices == 1  # no payload recorded
        assert "mesh" not in entry.as_dict()


# ---------------------------------------------------------------------------
# shard lint: pure rules, injected violations, repo check
# ---------------------------------------------------------------------------


def _entry(**kw):
    base = dict(
        producer="moeva_segment",
        key="k#1",
        devices=8,
        partitions=8,
        sharding=None,
        collectives=None,
    )
    base.update(kw)
    return types.SimpleNamespace(**base)


class TestLintRules:
    @pytest.fixture(scope="class")
    def shard_lint(self):
        return _load_tool("shard_lint")

    def test_single_device_entries_lint_clean(self, shard_lint):
        assert shard_lint.lint_entry(_entry(devices=1)) == []

    def test_float_collective_in_hot_loop_trips(self, shard_lint):
        col = {"count": 1, "bytes": 4096.0, "float_count": 1,
               "float_bytes": 4096.0}
        out = shard_lint.lint_entry(_entry(collectives=col))
        assert [v["rule"] for v in out] == ["hot_loop_float_collective"]

    def test_control_plane_collectives_are_tolerated_but_bounded(
        self, shard_lint
    ):
        small = {"count": 2, "bytes": 4500.0, "float_count": 0,
                 "float_bytes": 0.0}
        assert shard_lint.lint_entry(_entry(collectives=small)) == []
        huge = {"count": 2, "bytes": 2.0 * (1 << 20), "float_count": 0,
                "float_bytes": 0.0}
        out = shard_lint.lint_entry(_entry(collectives=huge))
        assert [v["rule"] for v in out] == ["hot_loop_collective_bytes"]

    def test_gate_producer_is_not_hot_loop(self, shard_lint):
        col = {"count": 1, "bytes": 4096.0, "float_count": 1,
               "float_bytes": 4096.0}
        out = shard_lint.lint_entry(
            _entry(producer="moeva_success", collectives=col)
        )
        # not hot-loop, but still an attack producer: only replication
        # rules could apply, and partitions=8 is sharded
        assert out == []

    def test_fully_replicated_program_trips(self, shard_lint):
        out = shard_lint.lint_entry(_entry(partitions=1))
        assert [v["rule"] for v in out] == ["fully_replicated_program"]

    def test_replicated_large_output_trips(self, shard_lint):
        sharding = {
            "in": {
                "sharded_bytes": 8192,
                "largest": {"bytes": 8192, "sharded": True, "spec": "P('states',)"},
            },
            "out": {
                "largest": {"bytes": 8192, "sharded": False, "spec": "P()"},
            },
        }
        out = shard_lint.lint_entry(_entry(sharding=sharding))
        assert [v["rule"] for v in out] == ["replicated_large_output"]

    def test_dispatch_error_classification(self, shard_lint):
        # only transfer-guard trips are the sharding contract; an
        # unrelated engine crash must not masquerade as one
        guard = RuntimeError(
            "INVALID_ARGUMENT: Disallowed host-to-device transfer: "
            "aval=ShapedArray(float32[])"
        )
        assert shard_lint.classify_dispatch_error(guard) == "host_transfer"
        assert (
            shard_lint.classify_dispatch_error(ValueError("bad shape"))
            == "engine_error"
        )

    def test_small_replicated_output_is_fine(self, shard_lint):
        # a scalar/consensus output coming back replicated is normal
        sharding = {
            "in": {
                "sharded_bytes": 8192,
                "largest": {"bytes": 8192, "sharded": True, "spec": "P('states',)"},
            },
            "out": {"largest": {"bytes": 32, "sharded": False, "spec": "P()"}},
        }
        assert shard_lint.lint_entry(_entry(sharding=sharding)) == []


class TestLintInjected:
    """The acceptance pair: the lint must FAIL on an injected all_gather
    and on an injected host transfer — and pass a clean sharded program."""

    @pytest.fixture(scope="class")
    def shard_lint(self):
        return _load_tool("shard_lint")

    def test_injected_all_gather_trips(self, shard_lint, mesh8):
        violations = shard_lint.injected_collective_violations(mesh8)
        assert violations, "forced all-gather must violate the contract"
        assert any(
            v["rule"] in ("hot_loop_float_collective",
                          "hot_loop_collective_bytes",
                          "replicated_large_output")
            for v in violations
        )

    def test_injected_host_transfer_trips(self, shard_lint, mesh8):
        violations = shard_lint.injected_transfer_violation(mesh8)
        assert [v["rule"] for v in violations] == ["host_transfer"]
        assert "pgd_attack" in violations[0]["producer"]

    def test_clean_sharded_program_passes(self, shard_lint, mesh8):
        led = CostLedger()
        x = jax.device_put(
            jnp.ones((16, 8), jnp.float32), NamedSharding(mesh8, P("states"))
        )
        lj = LedgeredJit(
            jax.jit(lambda x: x * 2 + 1), producer="pgd_attack", ledger=led
        )
        lj(x)
        assert shard_lint.lint_entries(led.entries()) == []

    def test_transfer_guard_restores_previous_mode(self, shard_lint, mesh8):
        from moeva2_ijcai22_replication_tpu.observability import ledger as lmod

        assert lmod._dispatch_transfer_guard is None
        shard_lint.injected_transfer_violation(mesh8)
        assert lmod._dispatch_transfer_guard is None


class TestShardLintRepoCheck:
    def test_committed_domains_lint_green_and_selftest_trips(self):
        """The repo check tier-1 runs, through the consolidated
        ``tools/repo_check.py`` entrypoint (one flag list for every call
        site): the committed attack programs must compile clean on the
        emulated 8-device mesh — zero hot-loop data collectives, no
        implicit transfers, no unintended replication — and the selftest
        proves the lint still trips on injected violations."""
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "repo_check.py"),
             "--only", "shard_lint", "--selftest", "--json"],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=560,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "repo_check: ok" in proc.stdout
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        assert summary["ok"] is True
        assert summary["gates"]["shard_lint"]["ok"] is True
        payload = json.loads(
            [
                line
                for line in proc.stdout.splitlines()
                if line.startswith("{") and '"linted"' in line
            ][-1]
        )
        assert payload["ok"] is True
        assert payload["violations"] == []
        assert "lcld_synth" in payload["linted"]
        assert all(payload["selftest"].values())


# ---------------------------------------------------------------------------
# telemetry.mesh assembly + record schema
# ---------------------------------------------------------------------------

_DESC = {"devices": 8, "shape": [8], "axes": ["states"]}


def _seeded_ledger():
    """A ledger holding one hot-loop executable with a known cost and a
    float all-gather census, dispatched twice."""
    led = CostLedger()
    col = parse_collectives(_HLO)
    entry = led.record_compile(
        producer="moeva_segment",
        identity={},
        backend="cpu",
        compile_s=0.1,
        cost={"flops": 800.0, "bytes_accessed": 1600.0},
        memory=None,
        mesh_probe={
            "devices": 8,
            "partitions": 8,
            "sharding": {"devices": 8, "partitions": 8, "in": {}, "out": None},
            "collectives": col,
        },
    )
    led.record_dispatch(entry.key)
    led.record_dispatch(entry.key)
    return led, col


class TestMeshBlock:
    def test_block_joins_cost_balance_and_collectives(self):
        led, col = _seeded_ledger()
        cap = MeshCapture()
        cap.record_balance([2.0] * 8, 4.0)
        block = mesh_block(_DESC, ledger=led, capture=cap)
        assert block["enabled"] is True
        assert block["devices"] == 8
        assert len(block["per_device"]) == 8
        # per-device flops: 800 flops * 2 dispatches / 8 partitions
        assert block["per_device"][0]["flops"] == pytest.approx(200.0)
        assert block["per_device"][0]["run_s"] == 4.0
        assert block["per_device"][0]["achieved_flops_s"] == pytest.approx(
            200.0 / 4.0
        )
        assert block["balance"]["ratio"] == 1.0
        # census is dispatch-weighted; every op here is hot-loop
        assert block["collectives"]["count"] == col["count"] * 2
        assert block["collectives"]["hot_loop"]["float_count"] == (
            col["float_count"] * 2
        )
        # size-8 groups on the 8-device states mesh attribute to the axis
        assert block["collectives"]["by_axis"]["states"] > 0
        cls = block["classification"]
        assert cls["comm_bytes"] == col["bytes"] * 2
        assert 0 < cls["comm_fraction"] < 1
        assert validate_mesh(block) is block

    def test_single_device_entries_stay_out_of_per_device_cost(self):
        """A mixed window (mesh-backed domain + single-device domain in
        one ledger) must not charge the single-device executables' cost
        to every mesh device."""
        led, _ = _seeded_ledger()
        solo = led.record_compile(
            producer="pgd_attack",
            identity={},
            backend="cpu",
            compile_s=0.1,
            cost={"flops": 1e9, "bytes_accessed": 1e9},
            memory=None,
        )
        led.record_dispatch(solo.key)
        cap = MeshCapture()
        cap.record_balance([2.0] * 8, 4.0)
        block = mesh_block(_DESC, ledger=led, capture=cap)
        # still only the mesh entry's 800 flops * 2 dispatches / 8 parts
        assert block["per_device"][0]["flops"] == pytest.approx(200.0)

    def test_capture_off_degrades_to_identity_and_validates(self):
        cap = MeshCapture(enabled=False)
        block = mesh_block(_DESC, capture=cap)
        assert block == {
            "enabled": False,
            "devices": 8,
            "shape": [8],
            "axes": ["states"],
        }
        assert validate_mesh(block) is block

    def test_validate_mesh_rejects_gutted_blocks(self):
        with pytest.raises(ValueError, match="telemetry.mesh"):
            validate_mesh({"enabled": True, "devices": 8})
        with pytest.raises(ValueError, match="must be a dict"):
            validate_mesh("mesh happened")

    def test_mesh_snapshot_process_view(self):
        led, col = _seeded_ledger()
        cap = MeshCapture()
        cap.record_balance([1.0] * 8, 2.0)
        snap = mesh_snapshot(ledger=led, capture=cap)
        assert snap["enabled"] is True
        assert snap["device_count"] == len(jax.devices())
        assert snap["balance"]["ratio"] == 1.0
        assert snap["collectives"]["count"] == col["count"] * 2


class TestRecordSchema:
    def test_multi_device_record_requires_mesh_block(self):
        rec = {
            "execution": {"mesh": dict(_DESC)},
            "telemetry": telemetry_block(),
        }
        rec["telemetry"].pop("mesh", None)
        with pytest.raises(ValueError, match="missing the 'mesh'"):
            validate_record(rec, "bench")

    def test_mesh_devices_count_alone_also_enforces(self):
        rec = {
            "execution": {"mesh_devices": 8},
            "telemetry": telemetry_block(),
        }
        with pytest.raises(ValueError, match="ran on 8 devices"):
            validate_record(rec, "grid")

    def test_telemetry_block_attaches_and_validates(self):
        rec = {
            "execution": {"mesh": dict(_DESC)},
            "telemetry": telemetry_block(mesh=dict(_DESC)),
        }
        assert validate_record(rec, "bench") is rec
        assert rec["telemetry"]["mesh"]["devices"] == 8

    def test_single_device_records_stay_unchanged(self):
        block = telemetry_block(mesh=None)
        assert "mesh" not in block
        block = telemetry_block(mesh={"devices": 1})
        assert "mesh" not in block
        rec = {"execution": {"mesh": None}, "telemetry": telemetry_block()}
        assert validate_record(rec, "bench") is rec

    def test_capture_off_multi_device_record_still_validates(self):
        MESH.enabled = False
        rec = {
            "execution": {"mesh": dict(_DESC)},
            "telemetry": telemetry_block(mesh=dict(_DESC)),
        }
        assert validate_record(rec, "bench") is rec
        assert rec["telemetry"]["mesh"]["enabled"] is False


# ---------------------------------------------------------------------------
# prometheus exposition: device-labeled families
# ---------------------------------------------------------------------------


def _prom_families(text: str):
    families, helped, typed = set(), set(), set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
        elif line.startswith("# TYPE "):
            typed.add(line.split()[2])
        elif line and not line.startswith("#"):
            families.add(line.split("{")[0].split(" ")[0])
    return families, helped, typed


class TestPromMesh:
    def _text(self):
        led, _ = _seeded_ledger()
        cap = MeshCapture()
        cap.record_balance([1.0, 1.0, 1.0, 1.0, 0.0, 0.0, 0.0, 0.0], 2.0)
        snap = mesh_snapshot(ledger=led, capture=cap)
        return prometheus_text({"mesh": snap})

    def test_every_family_has_help_and_type(self):
        text = self._text()
        families, helped, typed = _prom_families(text)
        assert families, "mesh exposition must emit families"
        assert families - helped == set(), f"no HELP: {families - helped}"
        assert families - typed == set(), f"no TYPE: {families - typed}"

    def test_device_labels_are_bounded_ordinals(self):
        text = self._text()
        devices = {
            line.split('device="')[1].split('"')[0]
            for line in text.splitlines()
            if 'device="' in line
        }
        assert devices  # per-device balance gauges present
        # cardinality bounded by local device ordinals, never device ids
        assert devices <= {str(d) for d in range(len(jax.devices()))}
        assert 'moeva2_device_run_s{device="0"}' in text

    def test_balance_and_collective_families(self):
        text = self._text()
        assert "moeva2_mesh_balance_ratio 0.5" in text
        assert "# TYPE moeva2_collective_ops_total counter" in text
        assert 'moeva2_collective_ops_total{op="all-gather"}' in text
        assert "moeva2_collective_hot_loop_ops_total" in text
        # the contract metric an operator alerts on is the FLOAT count
        # (the total legitimately includes control-plane traffic)
        assert "moeva2_collective_hot_loop_float_ops_total" in text
        assert "must be 0" in text.split(
            "collective_hot_loop_float_ops"
        )[1].splitlines()[0]

    def test_ledger_per_device_gauges(self):
        led, _ = _seeded_ledger()
        text = prometheus_text({"cost_ledger": led.cost_block()})
        assert "moeva2_executable_per_device_flops{" in text
        families, helped, typed = _prom_families(text)
        assert families - helped == set()
        assert families - typed == set()


# ---------------------------------------------------------------------------
# perfetto: per-device tracks
# ---------------------------------------------------------------------------


class TestPerfettoDeviceTracks:
    def test_multi_device_run_span_fans_out_per_ordinal(self):
        from moeva2_ijcai22_replication_tpu.observability.export import (
            to_chrome_trace,
        )

        hbm = [{"bytes_in_use": 10 * (d + 1)} for d in range(4)]
        doc = to_chrome_trace(
            [
                {"kind": "meta", "t0_wall": 5.0},
                {
                    "kind": "span",
                    "name": "device_run",
                    "trace": "req-1",
                    "span": "s1",
                    "ts": 0.5,
                    "dur": 0.25,
                    "attrs": {"devices": 4, "hbm_devices": hbm},
                },
            ]
        )
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        # tid 0 carries the trace's other spans — devices offset past it
        assert [e["tid"] for e in xs] == [1, 2, 3, 4]
        assert all(e["name"] == "device_run" for e in xs)
        assert all(e["dur"] == 250000.0 for e in xs)
        assert [e["args"]["device"] for e in xs] == [0, 1, 2, 3]
        assert xs[2]["args"]["hbm"] == {"bytes_in_use": 30}
        # named per-device tracks
        names = [
            e for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
        ]
        assert [n["args"]["name"] for n in names] == [
            f"device {d}" for d in range(4)
        ]

    def test_single_device_span_renders_exactly_as_before(self):
        from moeva2_ijcai22_replication_tpu.observability.export import (
            to_chrome_trace,
        )

        events = [
            {
                "kind": "span",
                "name": "device_run",
                "trace": "req-1",
                "span": "s1",
                "ts": 0.5,
                "dur": 0.25,
                "attrs": {"traces": 1},
            }
        ]
        doc = to_chrome_trace(events)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 1 and xs[0]["tid"] == 0
        assert "device" not in xs[0]["args"]
        assert not any(
            e["name"] == "thread_name" for e in doc["traceEvents"]
        )


# ---------------------------------------------------------------------------
# bench_diff --mesh
# ---------------------------------------------------------------------------


def _bench_rec(path, ratio=None, hot_float=None, mesh=True):
    rec = {"steady_s": 10.0, "execution": {"n_states": 64, "n_gen": 100}}
    if mesh:
        balance = {"ratio": ratio, "sync_points": 3, "attributed_s": 5.0}
        rec["telemetry"] = {
            "mesh": {
                "enabled": True,
                "devices": 8,
                "balance": balance,
                "collectives": {
                    "hot_loop": {"count": 4, "float_count": hot_float or 0}
                },
            }
        }
    path.write_text(json.dumps(rec))
    return str(path)


class TestBenchDiffMesh:
    @pytest.fixture(scope="class")
    def bench_diff(self):
        return _load_tool("bench_diff")

    def test_small_ratio_drop_passes(self, bench_diff, tmp_path):
        a = _bench_rec(tmp_path / "a.json", ratio=0.9)
        b = _bench_rec(tmp_path / "b.json", ratio=0.85)
        assert bench_diff.main([a, b, "--mesh"]) == 0

    def test_large_ratio_drop_fails_only_under_mesh(
        self, bench_diff, tmp_path
    ):
        a = _bench_rec(tmp_path / "a.json", ratio=0.9)
        b = _bench_rec(tmp_path / "b.json", ratio=0.5)  # 44% drop
        assert bench_diff.main([a, b]) == 0  # gate is opt-in
        assert bench_diff.main([a, b, "--mesh"]) == 1
        assert bench_diff.main(
            [a, b, "--mesh", "--mesh-threshold", "0.6"]
        ) == 0

    def test_any_hot_loop_float_collective_growth_fails(
        self, bench_diff, tmp_path
    ):
        a = _bench_rec(tmp_path / "a.json", ratio=0.9, hot_float=0)
        b = _bench_rec(tmp_path / "b.json", ratio=0.9, hot_float=1)
        # the contract gate has NO tolerance to widen
        assert bench_diff.main([a, b, "--mesh"]) == 1
        assert bench_diff.main(
            [a, b, "--mesh", "--mesh-threshold", "100"]
        ) == 1
        assert bench_diff.main([b, a, "--mesh"]) == 0  # shrinking is fine

    def test_losing_mesh_capture_fails(self, bench_diff, tmp_path):
        a = _bench_rec(tmp_path / "a.json", ratio=0.9)
        b = _bench_rec(tmp_path / "b.json", mesh=False)
        assert bench_diff.main([a, b, "--mesh"]) == 1

    def test_pre_mesh_baselines_skip(self, bench_diff, tmp_path):
        a = _bench_rec(tmp_path / "a.json", mesh=False)  # pre-mesh record
        b = _bench_rec(tmp_path / "b.json", ratio=0.9)
        assert bench_diff.main([a, b, "--mesh"]) == 0


# ---------------------------------------------------------------------------
# engines on the mesh: ledger evidence + the on/off overhead smoke
# ---------------------------------------------------------------------------


class TestEngineMeshEvidence:
    def test_pgd_entry_carries_per_device_roofline_and_census(
        self, artifacts, mesh8
    ):
        from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD

        pgd = ConstrainedPGD(
            classifier=artifacts["sur"],
            constraints=artifacts["cons"],
            scaler=artifacts["scaler"],
            max_iter=3,
            mesh=mesh8,
        )
        xs = np.asarray(artifacts["scaler"].transform(artifacts["pool"][:16]))
        y = np.asarray(artifacts["sur"].predict_proba(xs)).argmax(-1)
        pgd.generate(xs, y)
        (entry,) = [e for e in LEDGER.entries() if e.producer == "pgd_attack"]
        assert entry.devices == 8
        assert entry.partitions == 8
        assert entry.collectives is not None
        # the hot loop moves no floating-point payload between devices
        assert entry.collectives["float_count"] == 0
        d = entry.as_dict()
        assert d["mesh"]["per_device"]["flops"] is not None
        # balance: PGD runs every row to the full budget — uniform
        block = MESH.balance_block()
        assert block["sync_points"] == 1
        assert block["ratio"] == 1.0

    def test_moeva_entries_carry_mesh_and_balance(self, artifacts, mesh8):
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2

        moeva = Moeva2(
            classifier=artifacts["sur"],
            constraints=artifacts["cons"],
            ml_scaler=artifacts["scaler"],
            norm=2,
            n_gen=4,
            n_pop=8,
            n_offsprings=4,
            seed=3,
            mesh=mesh8,
        )
        moeva.generate(artifacts["pool"][:16], 1)
        by_producer = {e.producer: e for e in LEDGER.entries()}
        assert {"moeva_init", "moeva_segment"} <= set(by_producer)
        for producer in ("moeva_init", "moeva_segment"):
            e = by_producer[producer]
            assert e.devices == 8, producer
            assert e.partitions == 8, producer
            assert e.collectives is not None, producer
            assert e.collectives["float_count"] == 0, producer
            assert e.as_dict()["mesh"]["per_device"]["flops"] is not None
        block = MESH.balance_block()
        assert block["sync_points"] >= 1
        assert block["ratio"] == 1.0  # strict mode: every row live

    def test_balance_survives_cost_ledger_off(self, artifacts, mesh8):
        """The knobs are independent: cost_ledger off must not silently
        drop the MoEvA balance windows (they need only wall-clock and the
        engine's own segment log)."""
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2

        LEDGER.enabled = False
        moeva = Moeva2(
            classifier=artifacts["sur"],
            constraints=artifacts["cons"],
            ml_scaler=artifacts["scaler"],
            norm=2,
            n_gen=4,
            n_pop=8,
            n_offsprings=4,
            seed=3,
            mesh=mesh8,
        )
        moeva.generate(artifacts["pool"][:16], 1)
        assert not LEDGER.entries()
        block = MESH.balance_block()
        assert block["sync_points"] >= 1
        assert block["ratio"] == 1.0

    def test_mesh_telemetry_record_end_to_end(self, artifacts, mesh8):
        """The MULTICHIP-record shape: run the attack, assemble a record
        through telemetry_block(mesh=...), and validate it — per-device
        roofline, balance, and collective attribution all present."""
        from moeva2_ijcai22_replication_tpu.attacks.sharding import (
            describe_mesh,
        )
        from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD

        ledger_mark = LEDGER.mark()
        mesh_mark = MESH.mark()
        pgd = ConstrainedPGD(
            classifier=artifacts["sur"],
            constraints=artifacts["cons"],
            scaler=artifacts["scaler"],
            max_iter=3,
            mesh=mesh8,
        )
        xs = np.asarray(artifacts["scaler"].transform(artifacts["pool"][:16]))
        y = np.asarray(artifacts["sur"].predict_proba(xs)).argmax(-1)
        pgd.generate(xs, y)
        desc = describe_mesh(mesh8)
        rec = {
            "execution": {"mesh": desc, "n_states": 16},
            "telemetry": telemetry_block(
                ledger_since=ledger_mark, mesh=desc, mesh_since=mesh_mark
            ),
        }
        assert validate_record(rec, "multichip") is rec
        mesh_tel = rec["telemetry"]["mesh"]
        assert mesh_tel["devices"] == 8
        assert len(mesh_tel["per_device"]) == 8
        assert mesh_tel["per_device"][0]["flops"] is not None
        assert mesh_tel["balance"]["ratio"] == 1.0
        assert mesh_tel["collectives"]["hot_loop"]["float_count"] == 0
        assert json.loads(json.dumps(rec, default=str))


class TestMeshOverheadSmoke:
    def test_mesh_capture_toggle_zero_extra_compiles_bit_identical(
        self, artifacts, mesh8
    ):
        """Tier-1 acceptance smoke: mesh capture on/off shares every
        compile and dispatch and produces bit-identical results."""
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2

        def run():
            m = Moeva2(
                classifier=artifacts["sur"],
                constraints=artifacts["cons"],
                ml_scaler=artifacts["scaler"],
                norm=2,
                n_gen=4,
                n_pop=8,
                n_offsprings=4,
                seed=17,
                mesh=mesh8,
            )
            res = m.generate(artifacts["pool"][:16], 1)
            return res, m

        MESH.enabled = True
        res_on, m_on = run()
        assert MESH.balance_block()["sync_points"] >= 1

        MESH.reset()
        MESH.enabled = False
        res_off, m_off = run()
        # capture off: zero balance bookkeeping, zero mesh payloads
        assert MESH.balance_block()["sync_points"] == 0

        # bit-identical numerics
        np.testing.assert_array_equal(res_on.x_gen, res_off.x_gen)
        np.testing.assert_array_equal(res_on.f, res_off.f)
        # zero extra compiles/dispatches either way
        assert m_on.trace_count == m_off.trace_count
        assert m_on._jit_init.calls == m_off._jit_init.calls
        assert m_on._jit_segment.calls == m_off._jit_segment.calls
