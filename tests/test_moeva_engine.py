"""End-to-end MoEvA2 engine tests on synthetic LCLD fixtures (small budgets)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import lcld_mlp, init_params


@pytest.fixture(scope="module")
def lcld_constraints(lcld_paths):
    return LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])


@pytest.fixture(scope="module")
def surrogate(lcld_constraints):
    model = lcld_mlp()
    params = init_params(model, lcld_constraints.schema.n_features, seed=7)
    return Surrogate(model=model, params=params)


def _scaler_for(x):
    # The reference always scales classifier inputs (scaler.joblib); an
    # unscaled random MLP saturates its softmax to exact 0/1 and the attack
    # has no gradient signal to exploit.
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    return fit_minmax(x.min(0), x.max(0))


@pytest.fixture(scope="module")
def attack_result(lcld_constraints, surrogate):
    x = synth_lcld(4, lcld_constraints.schema, seed=3)
    lcld_constraints.check_constraints_error(x)
    moeva = Moeva2(
        classifier=surrogate,
        constraints=lcld_constraints,
        ml_scaler=_scaler_for(x),
        norm=2,
        n_gen=6,
        n_pop=20,
        n_offsprings=10,
        seed=11,
        dtype=jnp.float64,
    )
    return moeva, moeva.generate(x, minimize_class=1)


class TestMoevaEngine:
    def test_shapes(self, attack_result, lcld_constraints):
        moeva, res = attack_result
        d = lcld_constraints.schema.n_features
        assert res.x_gen.shape == (4, moeva.pop_size, moeva.codec.gen_length)
        assert res.f.shape == (4, moeva.pop_size, 3)
        assert res.x_ml.shape == (4, moeva.pop_size, d)
        assert moeva.pop_size == 23  # n_pop ref points + 3 extremes

    def test_immutables_unchanged(self, attack_result, lcld_constraints):
        _, res = attack_result
        immutable = ~lcld_constraints.schema.mutable
        np.testing.assert_allclose(
            res.x_ml[:, :, immutable],
            np.broadcast_to(
                res.x_initial[:, None, immutable], res.x_ml[:, :, immutable].shape
            ),
        )

    def test_bounds_respected(self, attack_result, lcld_constraints):
        _, res = attack_result
        xl, xu = lcld_constraints.get_feature_min_max(dynamic_input=res.x_initial)
        xl = np.broadcast_to(np.asarray(xl), res.x_initial.shape)
        xu = np.broadcast_to(np.asarray(xu), res.x_initial.shape)
        mutable = lcld_constraints.schema.mutable
        x = res.x_ml[:, :, mutable]
        lo = xl[:, None, mutable]
        hi = xu[:, None, mutable]
        assert (x >= lo - 1e-9).all()
        assert (x <= hi + 1e-9).all()

    def test_onehot_validity(self, attack_result, lcld_constraints):
        _, res = attack_result
        for group in lcld_constraints.schema.ohe_groups():
            vals = res.x_ml[:, :, group]
            assert set(np.unique(vals)) <= {0.0, 1.0}
            np.testing.assert_allclose(vals.sum(-1), 1.0)

    def test_int_features_integral(self, attack_result, lcld_constraints):
        _, res = attack_result
        int_feats = [
            i
            for i, t in enumerate(lcld_constraints.schema.types)
            if str(t) == "int" and lcld_constraints.schema.mutable[i]
        ]
        vals = res.x_ml[:, :, int_feats]
        np.testing.assert_allclose(vals, np.round(vals))

    def test_objectives_sane(self, attack_result):
        _, res = attack_result
        assert np.isfinite(res.f).all()
        assert (res.f[..., 0] >= 0).all() and (res.f[..., 0] <= 1).all()  # prob
        assert (res.f[..., 1] >= 0).all()  # distance
        assert (res.f[..., 2] >= 0).all()  # violations

    def test_evolution_moves_population(self, attack_result):
        _, res = attack_result
        # after 5 mating rounds some candidates must differ from the initial
        diff = np.abs(res.x_ml - res.x_initial[:, None, :]).max(-1)
        assert (diff > 0).any(axis=1).all()  # every state explored

    def test_deterministic(self, attack_result, lcld_constraints, surrogate):
        moeva, res = attack_result
        x = res.x_initial
        moeva2 = Moeva2(
            classifier=surrogate,
            constraints=lcld_constraints,
            ml_scaler=_scaler_for(x),
            norm=2,
            n_gen=6,
            n_pop=20,
            n_offsprings=10,
            seed=11,
            dtype=jnp.float64,
        )
        res2 = moeva2.generate(x, minimize_class=1)
        np.testing.assert_allclose(res.x_gen, res2.x_gen)
        np.testing.assert_allclose(res.f, res2.f)


class TestMoevaSharded:
    def test_mesh_sharded_states(self, lcld_constraints, surrogate):
        from jax.sharding import Mesh

        devices = jax.devices()
        assert len(devices) == 8, "conftest must force 8 virtual devices"
        mesh = Mesh(np.array(devices), ("states",))
        x = synth_lcld(8, lcld_constraints.schema, seed=5)
        moeva = Moeva2(
            classifier=surrogate,
            constraints=lcld_constraints,
            ml_scaler=_scaler_for(x),
            norm=2,
            n_gen=3,
            n_pop=10,
            n_offsprings=6,
            seed=1,
            mesh=mesh,
        )
        res = moeva.generate(x, minimize_class=1)
        assert res.x_gen.shape[0] == 8
        assert np.isfinite(res.f).all()

    def test_mesh_matches_single_device(self, lcld_constraints, surrogate):
        """States shard over the mesh with zero hot-loop collectives, so a
        sharded attack must reproduce the unsharded one (the MoEvA
        counterpart of ``test_pgd.py::test_sharded_attack_matches_single_
        device``).

        Horizon note: XLA compiles the sharded and unsharded programs
        separately, and gemm blocking differs with the batch shape, so
        objective values differ in the last ulp between the two programs
        (measured: |Δf| = 1.1e-16 at gen 1 on this instance). Early
        populations cluster within ulps of each other (tiny mutations barely
        move the logit), so such an ulp regularly lands on a survival
        near-tie and bifurcates the trajectories (measured: seed 3 bit-equal
        through gen 2, bifurcates gen 3; seeds 11/29 bifurcate at gen 2).
        The bitwise assertion is therefore pinned to a pre-bifurcation
        (seed, horizon); any *semantic* sharding bug (state mixing, wrong
        niche counts, per-shard RNG skew) shows up grossly at generation 1.
        ``test_mesh_statistically_equivalent`` covers long horizons."""
        from jax.sharding import Mesh

        x = synth_lcld(8, lcld_constraints.schema, seed=5)
        mesh = Mesh(np.array(jax.devices()[:8]), ("states",))

        def run(mesh):
            moeva = Moeva2(
                classifier=surrogate,
                constraints=lcld_constraints,
                ml_scaler=_scaler_for(x),
                norm=2,
                n_gen=2,
                n_pop=12,
                n_offsprings=6,
                seed=3,
                archive_size=2,
                dtype=jnp.float64,
                mesh=mesh,
            )
            return moeva.generate(x, minimize_class=1)

        res_m = run(mesh)
        res_1 = run(None)
        np.testing.assert_array_equal(res_m.x_gen, res_1.x_gen)
        np.testing.assert_array_equal(res_m.x_ml, res_1.x_ml)
        np.testing.assert_allclose(
            res_m.f, res_1.f, rtol=0, atol=1e-12,
            err_msg="objectives diverge beyond ulp noise",
        )

    def test_mesh_statistically_equivalent(self, lcld_constraints, surrogate):
        """Long-horizon mesh equivalence, seed-paired: past the bifurcation
        horizon the sharded/unsharded trajectories are chaotically unrelated
        but must stay *distributionally* identical — a systematic per-shard
        skew (e.g. one device's states degraded) would bias the paired
        per-state outcome statistics, which this asserts are centred."""
        from jax.sharding import Mesh

        x = synth_lcld(8, lcld_constraints.schema, seed=5)
        mesh = Mesh(np.array(jax.devices()[:8]), ("states",))

        def run(mesh, seed):
            moeva = Moeva2(
                classifier=surrogate,
                constraints=lcld_constraints,
                ml_scaler=_scaler_for(x),
                norm=2,
                n_gen=8,
                n_pop=12,
                n_offsprings=6,
                seed=seed,
                dtype=jnp.float64,
                mesh=mesh,
            )
            f = moeva.generate(x, 1).f
            # per-state best misclassification prob and best feasible flag
            return np.asarray(f[..., 0]).min(1), (
                np.asarray(f[..., 2]).min(1) <= 1e-9
            )

        d_f1, d_feas = [], []
        for seed in range(20):
            f1_m, feas_m = run(mesh, seed)
            f1_1, feas_1 = run(None, seed)
            d_f1.append(f1_m - f1_1)
            d_feas.append(feas_m.astype(float) - feas_1.astype(float))
        d_f1 = np.concatenate(d_f1)  # 160 paired (seed, state) outcomes
        d_feas = np.concatenate(d_feas)
        # paired diffs are 0 (no bifurcation) or random-signed; a systematic
        # sharding skew would shift the means away from 0
        assert abs(d_f1.mean()) < 0.05, f"best-f1 skew: {d_f1.mean():+.4f}"
        assert abs(d_feas.mean()) < 0.10, f"feasibility skew: {d_feas.mean():+.4f}"


class TestInitStrategies:
    def _engine(self, lcld_constraints, surrogate, x, init, **kw):
        return Moeva2(
            classifier=surrogate,
            constraints=lcld_constraints,
            ml_scaler=_scaler_for(x),
            norm=2,
            n_gen=1,  # population after generate == the initial sampling
            n_pop=20,
            n_offsprings=10,
            seed=11,
            dtype=jnp.float64,
            init=init,
            **kw,
        )

    def test_lp_ratio_init_perturbs_exactly_the_ratio(
        self, lcld_constraints, surrogate
    ):
        x = synth_lcld(3, lcld_constraints.schema, seed=9)
        moeva = self._engine(
            lcld_constraints, surrogate, x, "lp_ratio", init_eps=0.3, init_ratio=0.5
        )
        res = moeva.generate(x, minimize_class=1)
        tiled = self._engine(lcld_constraints, surrogate, x, "tile").generate(
            x, minimize_class=1
        )
        n_pert = round(0.5 * moeva.pop_size)
        keep = moeva.pop_size - n_pert
        # unperturbed head identical to the tiled population
        np.testing.assert_allclose(res.x_gen[:, :keep], tiled.x_gen[:, :keep])
        # perturbed tail: at least one gene moved for nearly every sample
        moved = np.abs(res.x_gen[:, keep:] - tiled.x_gen[:, keep:]).max(-1) > 0
        assert moved.mean() > 0.9
        # ...and samples are distinct from one another (a real distribution)
        flat = res.x_gen[:, keep:].reshape(3 * n_pert, -1)
        assert len(np.unique(flat, axis=0)) > n_pert

    def test_lp_ratio_init_respects_bounds_and_types(
        self, lcld_constraints, surrogate
    ):
        x = synth_lcld(3, lcld_constraints.schema, seed=9)
        moeva = self._engine(
            lcld_constraints, surrogate, x, "lp_ratio", init_eps=0.5, init_ratio=1.0
        )
        res = moeva.generate(x, minimize_class=1)
        # ML-space invariants survive the perturbed init: bounds + one-hots
        xl, xu = lcld_constraints.get_feature_min_max(dynamic_input=x)
        mutable = lcld_constraints.schema.mutable
        vals = res.x_ml[:, :, mutable]
        assert (vals >= np.broadcast_to(np.asarray(xl), x.shape)[:, None, mutable] - 1e-9).all()
        assert (vals <= np.broadcast_to(np.asarray(xu), x.shape)[:, None, mutable] + 1e-9).all()
        for group in lcld_constraints.schema.ohe_groups():
            np.testing.assert_allclose(res.x_ml[:, :, group].sum(-1), 1.0)

    def test_lp_ratio_init_ball_radius(self, lcld_constraints, surrogate):
        from moeva2_ijcai22_replication_tpu.attacks.moeva.initialisation import (
            ball_sample,
        )

        key = jax.random.PRNGKey(0)
        for norm, eps in [(2, 0.25), (np.inf, 0.1)]:
            s = np.asarray(ball_sample(key, (500, 12), eps, norm))
            r = np.abs(s).max(-1) if norm is np.inf else np.linalg.norm(s, axis=-1)
            assert (r <= eps + 1e-9).all()
            assert r.max() > 0.5 * eps  # actually fills the ball

    def test_rejects_unknown_init(self, lcld_constraints, surrogate):
        x = synth_lcld(2, lcld_constraints.schema, seed=1)
        with pytest.raises(ValueError, match="init"):
            self._engine(lcld_constraints, surrogate, x, "bogus")


class TestHistoryChunking:
    def test_chunked_history_matches_single_scan(
        self, lcld_constraints, surrogate
    ):
        """Host-offloaded segments must reproduce the one-scan program
        bit-for-bit: same populations, same (n_gen-1, S, n_off, C) records."""
        x = synth_lcld(2, lcld_constraints.schema, seed=4)

        def run(chunk):
            moeva = Moeva2(
                classifier=surrogate,
                constraints=lcld_constraints,
                ml_scaler=_scaler_for(x),
                norm=2,
                n_gen=7,
                n_pop=12,
                n_offsprings=6,
                seed=5,
                dtype=jnp.float64,
                save_history="full",
                history_chunk=chunk,
            )
            return moeva.generate(x, minimize_class=1)

        small, big = run(2), run(999)
        assert len(small.history) == 7  # init + 6 generations
        for a, b in zip(small.history, big.history):
            np.testing.assert_allclose(a, b)
        np.testing.assert_allclose(small.x_gen, big.x_gen)
        np.testing.assert_allclose(small.f, big.f)


class TestChunkedStates:
    def test_chunked_run_matches_unchunked_per_chunk(self, lcld_constraints, surrogate):
        """max_states_per_call splits the states axis into sequential
        dispatches of one compiled program. States are independent, so the
        first chunk of a chunked run must equal an unchunked run on exactly
        those states with the chunk's folded key — and the padded tail chunk
        must be trimmed back to the real states."""
        import jax

        x = synth_lcld(10, lcld_constraints.schema, seed=13)
        kw = dict(
            classifier=surrogate, constraints=lcld_constraints,
            ml_scaler=_scaler_for(x), norm=2, n_gen=5, n_pop=20,
            n_offsprings=10, seed=11, dtype=jnp.float64,
        )
        chunked = Moeva2(**kw, max_states_per_call=4).generate(x, 1)
        assert chunked.x_ml.shape[0] == 10  # tail pad (4+4+2) trimmed
        np.testing.assert_array_equal(chunked.x_initial, x)
        assert np.isfinite(chunked.f).all()

        # chunk 0 equals a standalone 4-state attack run with fold_in(key, 0)
        solo = Moeva2(**kw)
        res0 = solo._generate_one(
            x[:4], np.full(4, 1), jax.random.fold_in(jax.random.PRNGKey(11), 0),
            None,
        )
        np.testing.assert_allclose(chunked.x_ml[:4], res0.x_ml)
        np.testing.assert_allclose(chunked.f[:4], res0.f)

    def test_chunked_history_concatenates(self, lcld_constraints, surrogate):
        x = synth_lcld(5, lcld_constraints.schema, seed=14)
        moeva = Moeva2(
            classifier=surrogate, constraints=lcld_constraints,
            ml_scaler=_scaler_for(x), norm=2, n_gen=4, n_pop=12,
            n_offsprings=6, seed=2, dtype=jnp.float64,
            max_states_per_call=2, save_history="reduced",
        )
        res = moeva.generate(x, 1)
        assert len(res.history) == 4  # init + (n_gen-1) per-generation records
        assert res.history[0].shape[0] == 5  # states axis re-assembled
        assert all(h.shape[0] == 5 for h in res.history[1:])


class TestEliteArchive:
    def test_archive_appends_columns_and_is_monotone(
        self, lcld_constraints, surrogate
    ):
        """With archive_size, the result gains archive columns whose best
        feasible-first score can only improve with budget (the guarantee the
        reference's dead pareto-archive code was meant to give)."""
        x = synth_lcld(3, lcld_constraints.schema, seed=7)

        def run(n_gen):
            moeva = Moeva2(
                classifier=surrogate,
                constraints=lcld_constraints,
                ml_scaler=_scaler_for(x),
                norm=2,
                n_gen=n_gen,
                n_pop=16,
                n_offsprings=8,
                seed=2,
                dtype=jnp.float64,
                archive_size=6,
            )
            return moeva, moeva.generate(x, minimize_class=1)

        moeva, short = run(3)
        _, long = run(9)
        assert short.x_gen.shape[1] == moeva.pop_size + 6
        assert short.f.shape[1] == moeva.pop_size + 6
        assert short.x_ml.shape[1] == moeva.pop_size + 6

        def best_score(res):
            f = res.f[:, -6:, :]
            score = np.where(f[..., 2] > 0, 1e9 + f[..., 2], 0.0) + f[..., 0]
            return score.min(axis=1)

        assert (best_score(long) <= best_score(short) + 1e-9).all()

    def test_archive_members_track_population_history(
        self, lcld_constraints, surrogate
    ):
        """Archive rows are real evaluated candidates: re-evaluating their
        ML decode must reproduce the stored objectives."""
        x = synth_lcld(2, lcld_constraints.schema, seed=8)
        moeva = Moeva2(
            classifier=surrogate,
            constraints=lcld_constraints,
            ml_scaler=_scaler_for(x),
            norm=2,
            n_gen=5,
            n_pop=12,
            n_offsprings=6,
            seed=3,
            dtype=jnp.float64,
            archive_size=4,
        )
        res = moeva.generate(x, minimize_class=1)
        arch_ml = res.x_ml[:, -4:, :]
        g = np.asarray(lcld_constraints.evaluate(jnp.asarray(arch_ml)))
        np.testing.assert_allclose(
            g.sum(-1), res.f[:, -4:, 2], atol=1e-8
        )
