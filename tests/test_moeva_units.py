"""Unit tests for the MoEvA2 building blocks (refdirs, NDS, survival, operators)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import nds, operators, refdirs, survival


class TestRefDirs:
    def test_energy_on_simplex(self):
        dirs = refdirs.energy_ref_dirs(3, 50, seed=1)
        assert dirs.shape == (50, 3)
        assert np.allclose(dirs.sum(1), 1.0, atol=1e-5)
        assert (dirs >= 0).all()

    def test_energy_well_spaced(self):
        dirs = refdirs.energy_ref_dirs(3, 30, seed=1)
        d = np.linalg.norm(dirs[:, None] - dirs[None, :], axis=-1)
        np.fill_diagonal(d, np.inf)
        # nearest-neighbour distances should be fairly uniform for an
        # energy-minimising layout
        nn = d.min(1)
        assert nn.min() > 0.3 * nn.max()

    def test_das_dennis_centroid(self):
        assert np.allclose(refdirs.das_dennis(3, 1), [[1 / 3, 1 / 3, 1 / 3]])

    def test_geometry_pop_size(self):
        dirs, pop_size = refdirs.rnsga3_geometry(3, 20)
        # pymoo: pop = n_ref_points * pop_per_ref_point + n_obj
        assert pop_size == 23
        assert dirs.shape == (23, 3)

    def test_aspiration_projection(self):
        pts = np.array([[0.2, 0.2, 0.2], [1.0, 0.0, 0.0]])
        dirs = refdirs.aspiration_ref_dirs(pts)
        # first: projection onto simplex = (1/3, 1/3, 1/3)
        assert np.allclose(dirs[0], [1 / 3, 1 / 3, 1 / 3], atol=1e-12)
        # extremes appended
        assert np.allclose(dirs[-3:], np.eye(3))


class TestNDS:
    def test_simple_fronts(self):
        f = jnp.array(
            [
                [0.0, 0.0],  # dominates everything
                [1.0, 1.0],
                [0.5, 1.5],
                [2.0, 2.0],  # dominated by all but [0.5, 1.5]? no: 1,1 dominates
            ]
        )
        ranks = np.asarray(nds.nd_ranks(f))
        assert ranks[0] == 0
        assert ranks[1] == 1
        assert ranks[2] == 1  # incomparable with [1,1]
        assert ranks[3] == 2

    def test_all_equal_one_front(self):
        f = jnp.ones((5, 3))
        assert (np.asarray(nds.nd_ranks(f)) == 0).all()

    def test_batched_matches_single(self):
        key = jax.random.PRNGKey(0)
        f = jax.random.uniform(key, (4, 20, 3))
        batched = np.asarray(nds.nd_ranks(f))
        for i in range(4):
            single = np.asarray(nds.nd_ranks(f[i]))
            np.testing.assert_array_equal(batched[i], single)

    def test_against_bruteforce(self):
        rng = np.random.default_rng(3)
        f = rng.random((30, 3))
        ranks = np.asarray(nds.nd_ranks(jnp.asarray(f)))

        def brute(f):
            n = len(f)
            dom = np.zeros((n, n), bool)
            for i in range(n):
                for j in range(n):
                    dom[i, j] = (f[i] <= f[j]).all() and (f[i] < f[j]).any()
            ranks = np.full(n, -1)
            r = 0
            remaining = np.ones(n, bool)
            while remaining.any():
                front = remaining & ~(dom & remaining[:, None]).any(0)
                ranks[front] = r
                remaining &= ~front
                r += 1
            return ranks

        np.testing.assert_array_equal(ranks, brute(f))


class TestSurvival:
    def test_select_count_and_elitism(self):
        key = jax.random.PRNGKey(0)
        f = jax.random.uniform(jax.random.PRNGKey(1), (40, 3))
        asp = jnp.asarray(refdirs.energy_ref_dirs(3, 10, seed=1), jnp.float32)
        state = survival.NormState.init(3)
        mask, new_state, ranks = survival.survive(key, f, asp, state, 13)
        mask, ranks = np.asarray(mask), np.asarray(ranks)
        assert mask.sum() == 13
        # elitism: any selected candidate's rank <= any unselected's rank
        assert ranks[mask].max() <= ranks[~mask].min() or (
            ranks[mask].max() == ranks[~mask].min()
        )
        # fronts below the splitting front survive entirely
        split = ranks[mask].max()
        assert mask[ranks < split].all()
        # ideal point updated (pymoo folds the aspiration points in too)
        np.testing.assert_allclose(
            np.asarray(new_state.ideal),
            np.minimum(np.asarray(f).min(0), np.asarray(asp).min(0)),
            rtol=1e-6,
        )

    def test_survive_all_when_exact_fit(self):
        key = jax.random.PRNGKey(0)
        f = jax.random.uniform(jax.random.PRNGKey(2), (10, 3))
        asp = jnp.asarray(refdirs.energy_ref_dirs(3, 5, seed=1), jnp.float32)
        mask, _, _ = survival.survive(key, f, asp, survival.NormState.init(3), 10)
        assert np.asarray(mask).all()

    def test_norm_state_persists_ideal(self):
        asp = jnp.asarray(refdirs.energy_ref_dirs(3, 5, seed=1), jnp.float32)
        st = survival.NormState.init(3)
        f1 = jnp.ones((8, 3)) * 5.0
        _, st, _ = survival.survive(jax.random.PRNGKey(0), f1, asp, st, 8)
        f2 = jnp.ones((8, 3)) * 9.0
        _, st, _ = survival.survive(jax.random.PRNGKey(1), f2, asp, st, 8)
        # ideal/worst fold the aspiration points in (pymoo semantics): with
        # asp on the unit simplex the running ideal is pulled to asp minima
        asp_np = np.asarray(asp)
        np.testing.assert_allclose(
            np.asarray(st.ideal), np.minimum(5.0, asp_np.min(0)), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(st.worst), np.maximum(9.0, asp_np.max(0)), rtol=1e-6
        )

    def test_niching_prefers_spread(self):
        # 1 crowded niche vs empty niches: niching should pick from empties.
        asp = jnp.asarray(np.eye(3, dtype=np.float32) * 0.9 + 0.05)
        # 3 clusters along the axes; all mutually non-dominated
        f = jnp.asarray(
            np.array(
                [[0.01, 1.0, 1.0]] * 6  # cluster at axis 0
                + [[1.0, 0.01, 1.0]] * 2
                + [[1.0, 1.0, 0.01]] * 2,
                dtype=np.float32,
            )
        )
        mask, _, _ = survival.survive(
            jax.random.PRNGKey(0), f, asp, survival.NormState.init(3), 6
        )
        mask = np.asarray(mask)
        # both small clusters must be represented
        assert mask[6:8].any()
        assert mask[8:10].any()


class TestOperators:
    def _tables(self, int_mask):
        from moeva2_ijcai22_replication_tpu.core.codec import Codec

        int_mask = np.asarray(int_mask, bool)
        length = len(int_mask)
        codec = Codec(
            non_ohe_ml_idx=jnp.arange(length, dtype=jnp.int32),
            group_ml_idx=jnp.zeros((0, 1), jnp.int32),
            group_pad_mask=jnp.zeros((0, 1), bool),
            group_sizes=jnp.zeros((0,), jnp.int32),
            int_mask_gen=jnp.asarray(int_mask),
            mutable_mask=jnp.ones((length,), bool),
            n_features=length,
            gen_length=length,
        )
        return operators.make_operator_tables(codec)

    def test_tables(self):
        t = self._tables([False, True, False, True, True])
        np.testing.assert_array_equal(np.asarray(t.type_sizes), [2, 3, 0])
        np.testing.assert_array_equal(np.asarray(t.rank_in_type), [0, 0, 1, 1, 2])
        np.testing.assert_allclose(
            np.asarray(t.mut_prob), [1 / 2, 1 / 3, 1 / 2, 1 / 3, 1 / 3]
        )

    def test_crossover_preserves_multiset(self):
        t = self._tables([False] * 6 + [True] * 4)
        key = jax.random.PRNGKey(0)
        p1 = jnp.arange(10.0)[None, :].repeat(32, 0)
        p2 = (jnp.arange(10.0) + 100)[None, :].repeat(32, 0)
        c1, c2 = operators.two_point_crossover(key, t, p1, p2, prob=1.0)
        # each gene slot holds the pair {i, i+100} across the two children
        np.testing.assert_allclose(np.asarray(c1 + c2), np.asarray(p1 + p2))
        # some but not all genes swapped in at least one mating
        swapped = np.asarray(c1 != p1)
        assert swapped.any() and not swapped.all()

    def test_crossover_segments_contiguous_per_type(self):
        t = self._tables([False] * 8)
        key = jax.random.PRNGKey(1)
        p1 = jnp.zeros((64, 8))
        p2 = jnp.ones((64, 8))
        c1, _ = operators.two_point_crossover(key, t, p1, p2, prob=1.0)
        swaps = np.asarray(c1) == 1.0
        for row in swaps:
            # a contiguous run: at most 2 transitions in the 0/1 pattern
            assert (np.abs(np.diff(row.astype(int))) != 0).sum() <= 2

    def test_mutation_bounds_and_ints(self):
        t = self._tables([False] * 5 + [True] * 5)
        xl = jnp.zeros(10)
        xu = jnp.full((10,), 10.0)
        x = jnp.full((200, 10), 5.0)
        y = operators.polynomial_mutation(jax.random.PRNGKey(0), t, x, xl, xu)
        y = np.asarray(y)
        assert (y >= 0).all() and (y <= 10).all()
        assert np.allclose(y[:, 5:], np.round(y[:, 5:]))
        assert (y != 5.0).any()  # something mutated

    def test_mutation_zero_range_untouched(self):
        t = self._tables([False] * 4)
        xl = xu = jnp.full((4,), 3.0)
        x = jnp.full((50, 4), 3.0)
        y = operators.polynomial_mutation(jax.random.PRNGKey(0), t, x, xl, xu)
        np.testing.assert_allclose(np.asarray(y), 3.0)

    def test_offspring_shape(self):
        t = self._tables([False] * 3 + [True] * 2)
        pop = jax.random.uniform(jax.random.PRNGKey(0), (20, 5)) * 10
        off = operators.make_offspring(
            jax.random.PRNGKey(1), t, pop, jnp.zeros(5), jnp.full((5,), 10.0), 7
        )
        assert off.shape == (7, 5)


class TestReviewRegressions:
    """Regressions for the code-review findings on the first engine version."""

    def test_survival_exact_front_fit(self):
        # front 0 has exactly n_survive members; fronts beyond must not leak in
        rng = np.random.default_rng(0)
        nd = rng.random((13, 3))
        dominated = nd + 1.0  # strictly worse than every nd point
        f = jnp.asarray(np.concatenate([nd, dominated[:27 - 13]]), jnp.float32)
        asp = jnp.asarray(refdirs.energy_ref_dirs(3, 10, seed=1), jnp.float32)
        mask, _, ranks = survival.survive(
            jax.random.PRNGKey(0), f, asp, survival.NormState.init(3), 13
        )
        mask = np.asarray(mask)
        assert mask.sum() == 13
        assert mask[:13].all()

    def test_two_gene_subvector_swaps(self):
        # pymoo pads cuts with n_var: a 2-gene sub-vector always swaps gene 1
        from moeva2_ijcai22_replication_tpu.core.codec import Codec

        int_mask = np.array([False, False])
        codec = Codec(
            non_ohe_ml_idx=jnp.arange(2, dtype=jnp.int32),
            group_ml_idx=jnp.zeros((0, 1), jnp.int32),
            group_pad_mask=jnp.zeros((0, 1), bool),
            group_sizes=jnp.zeros((0,), jnp.int32),
            int_mask_gen=jnp.asarray(int_mask),
            mutable_mask=jnp.ones((2,), bool),
            n_features=2,
            gen_length=2,
        )
        t = operators.make_operator_tables(codec)
        p1 = jnp.zeros((64, 2))
        p2 = jnp.ones((64, 2))
        c1, _ = operators.two_point_crossover(jax.random.PRNGKey(0), t, p1, p2, prob=1.0)
        c1 = np.asarray(c1)
        assert (c1[:, 1] == 1.0).all()  # second gene always swapped
        assert (c1[:, 0] == 0.0).all()  # first gene never swapped

    def test_crossover_types_gate_independently(self):
        t = None
        from moeva2_ijcai22_replication_tpu.core.codec import Codec

        int_mask = np.array([False] * 5 + [True] * 5)
        codec = Codec(
            non_ohe_ml_idx=jnp.arange(10, dtype=jnp.int32),
            group_ml_idx=jnp.zeros((0, 1), jnp.int32),
            group_pad_mask=jnp.zeros((0, 1), bool),
            group_sizes=jnp.zeros((0,), jnp.int32),
            int_mask_gen=jnp.asarray(int_mask),
            mutable_mask=jnp.ones((10,), bool),
            n_features=10,
            gen_length=10,
        )
        t = operators.make_operator_tables(codec)
        p1 = jnp.zeros((512, 10))
        p2 = jnp.ones((512, 10))
        c1, _ = operators.two_point_crossover(jax.random.PRNGKey(3), t, p1, p2, prob=0.5)
        c1 = np.asarray(c1)
        real_crossed = (c1[:, :5] == 1.0).any(1)
        int_crossed = (c1[:, 5:] == 1.0).any(1)
        # with independent 0.5 coins, all four combinations must appear
        assert (real_crossed & ~int_crossed).any()
        assert (~real_crossed & int_crossed).any()
        assert (real_crossed & int_crossed).any()
        assert (~real_crossed & ~int_crossed).any()


class TestSurviveBatch:
    def test_survive_batch_matches_vmapped_algorithm(self):
        """The batched path (association lifted out of the vmap, bulk gumbel
        fields) must equal the per-state algorithm given the SAME random
        fields — the meaningful invariant now that survive_batch draws its
        niching randomness in two global calls instead of per-state keys."""
        import jax
        import jax.numpy as jnp

        from moeva2_ijcai22_replication_tpu.attacks.moeva.survival import (
            NormState,
            _associate,
            _niche_gumbels,
            _survive_post,
            _survive_pre,
            survive_batch,
        )

        key = jax.random.PRNGKey(3)
        S, M, NS = 4, 31, 13
        f = jax.random.uniform(key, (S, M, 3), jnp.float64)
        asp = jax.random.uniform(jax.random.PRNGKey(4), (11, 3), jnp.float64)
        st = jax.vmap(lambda _: NormState.init(3, jnp.float64))(jnp.arange(S))
        kb = jax.random.PRNGKey(5)

        m_b, st_b, r_b = survive_batch(kb, f, asp, st, NS)

        # per-state reference: same algorithm, same gumbel fields
        n_dirs = asp.shape[0] + 3
        gum_cut, gum_mem = _niche_gumbels(kb, (S,), n_dirs, M)

        def one(f1, s1, gc, gm):
            ranks, dirs, nadir, new = _survive_pre(f1, asp, s1, NS)
            niche, dist = _associate(f1, dirs, new.ideal, nadir)
            mask = _survive_post(gc, gm, f1, ranks, niche, dist, dirs.shape[0], NS)
            return mask, new, ranks

        m_v, st_v, r_v = jax.vmap(one)(f, st, gum_cut, gum_mem)
        np.testing.assert_array_equal(np.asarray(m_b), np.asarray(m_v))
        np.testing.assert_array_equal(np.asarray(r_b), np.asarray(r_v))
        for a, b in zip(st_b, st_v):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        # every row still selects exactly NS survivors
        assert (np.asarray(m_b).sum(1) == NS).all()

class TestBlockedAssociation:
    def test_blocked_matches_einsum_bitwise(self):
        """The blocked-scan association (no (S, M, R) HBM temporary) must be
        bit-identical to the one-shot einsum path, including first-index tie
        semantics, across block sizes that do and do not divide R."""
        from moeva2_ijcai22_replication_tpu.attacks.moeva.survival import (
            associate_batch,
        )

        rng = np.random.default_rng(17)
        s, m, r, k = 5, 37, 53, 3
        f = jnp.asarray(rng.uniform(size=(s, m, k)))
        dirs = jnp.asarray(rng.dirichlet(np.ones(k), size=(s, r)))
        # duplicate some directions to force exact proj² ties
        dirs = dirs.at[:, 10].set(dirs[:, 3])
        dirs = dirs.at[:, 48].set(dirs[:, 3])
        ideal = jnp.asarray(rng.uniform(size=(s, k)) * 0.1)
        nadir = ideal + jnp.asarray(rng.uniform(0.5, 2.0, size=(s, k)))

        niche0, dist0 = associate_batch(f, dirs, ideal, nadir)
        for block in (8, 16, 53, 64, 128):
            niche_b, dist_b = associate_batch(
                f, dirs, ideal, nadir, block=block
            )
            np.testing.assert_array_equal(np.asarray(niche_b), np.asarray(niche0))
            np.testing.assert_array_equal(np.asarray(dist_b), np.asarray(dist0))
