"""ObjectiveCalculator tests: o1..o7 semantics vs an independent numpy oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.objective import ObjectiveCalculator
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import lcld_mlp, init_params
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax


@pytest.fixture(scope="module")
def setup(lcld_paths):
    cons = LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=2))
    x = synth_lcld(6, cons.schema, seed=9)
    # scaler over data range so scaled values live in [0, 1]
    scaler = fit_minmax(x.min(0), x.max(0))
    calc = ObjectiveCalculator(
        classifier=sur,
        constraints=cons,
        thresholds={"f1": 0.5, "f2": 0.2},
        min_max_scaler=scaler,
        minimize_class=1,
        norm=2,
        ml_scaler=scaler,
    )
    return cons, sur, x, scaler, calc


class TestObjectives:
    def test_valid_candidates_have_zero_cv(self, setup):
        cons, _, x, _, calc = setup
        pops = np.repeat(x[:, None, :], 3, axis=1)  # population = initial state
        vals = calc.objectives(x, pops)
        np.testing.assert_allclose(vals[..., 0], 0.0)  # constraints hold
        np.testing.assert_allclose(vals[..., 2], 0.0, atol=1e-12)  # zero distance

    def test_oracle_parity(self, setup):
        cons, sur, x, scaler, calc = setup
        rng = np.random.default_rng(0)
        pops = np.repeat(x[:, None, :], 4, axis=1)
        # perturb mutable real features only
        mutable = np.asarray(cons.schema.mutable)
        real = np.array([str(t) == "real" for t in cons.schema.types]) & mutable
        noise = rng.normal(0, 0.05, pops.shape) * pops
        pops[..., real] += noise[..., real]
        # keep inside the fitted scaler range so the [0,1] assert holds
        pops = np.clip(pops, x.min(0), x.max(0))

        vals = calc.objectives(x, pops)

        # independent numpy oracle
        import jax

        g = np.asarray(cons.evaluate(jnp.asarray(pops)))
        ohe_masks = cons.schema.ohe_groups()
        ohe_d = sum(np.abs(1 - pops[..., m].sum(-1)) for m in ohe_masks)
        cv = g.sum(-1) + ohe_d
        np.testing.assert_allclose(vals[..., 0], cv, rtol=1e-6)

        sc = lambda a: np.asarray(a) * np.asarray(scaler.scale) + np.asarray(scaler.min_)
        probs = np.asarray(sur.predict_proba(jnp.asarray(sc(pops))))
        np.testing.assert_allclose(vals[..., 1], probs[..., 1], rtol=1e-5)

        f2 = np.linalg.norm(sc(x)[:, None, :] - sc(pops), ord=2, axis=-1)
        np.testing.assert_allclose(vals[..., 2], f2, rtol=1e-5, atol=1e-8)

    def test_o_columns_logic(self, setup):
        *_, calc = setup
        vals = np.array(
            [
                [[0.0, 0.1, 0.1]],  # C, M, D all hold
                [[1.0, 0.1, 0.1]],  # M, D
                [[0.0, 0.9, 0.1]],  # C, D
                [[0.0, 0.1, 0.9]],  # C, M
            ]
        )
        o = calc.respected(vals)
        np.testing.assert_array_equal(o[0, 0], [1, 1, 1, 1, 1, 1, 1])
        np.testing.assert_array_equal(o[1, 0], [0, 1, 1, 0, 0, 1, 0])
        np.testing.assert_array_equal(o[2, 0], [1, 0, 1, 0, 1, 0, 0])
        np.testing.assert_array_equal(o[3, 0], [1, 1, 0, 1, 0, 0, 0])

    def test_success_rate_3d_any_semantics(self, setup):
        cons, _, x, _, calc = setup
        pops = np.repeat(x[:, None, :], 5, axis=1)
        rates = calc.success_rate_3d(x, pops)
        assert rates.shape == (7,)
        # identical-to-initial populations: constraints + distance hold
        assert rates[0] == 1.0  # o1 = C
        assert rates[2] == 1.0  # o3 = D
        assert rates[4] == 1.0  # o5 = C & D

    def test_success_rate_df_columns(self, setup):
        cons, _, x, _, calc = setup
        pops = np.repeat(x[:, None, :], 2, axis=1)
        df = calc.success_rate_3d_df(x, pops)
        assert list(df.columns) == ["o1", "o2", "o3", "o4", "o5", "o6", "o7"]

    def test_scaling_assert_triggers(self, setup):
        cons, sur, x, scaler, calc = setup
        bad = x.copy()
        bad[:, 0] = x[:, 0].max() * 10  # way out of the scaler's range
        pops = np.repeat(bad[:, None, :], 2, axis=1)
        with pytest.raises(AssertionError):
            calc.objectives(bad, pops)

    def test_get_successful_attacks(self, setup):
        cons, sur, x, scaler, calc = setup
        pops = np.repeat(x[:, None, :], 4, axis=1)
        vals = calc.objectives(x, pops)
        o7 = calc.respected(vals)[..., -1]  # (S, P)
        succ, idx = calc.get_successful_attacks(
            x, pops, max_inputs=1, return_index_success=True
        )
        assert idx.shape == (len(x),)
        assert succ.shape[0] == o7.any(1).sum()
        # every returned attack satisfies constraints
        if len(succ):
            cons.check_constraints_error(succ)
