"""Reference-parity fixtures on the REAL committed botnet artifacts.

The reference itself cannot execute in this image (pymoo/ART/gurobipy are
absent), so parity is pinned operationally, on the reference's own data:
attacks run against ``/root/reference``'s committed 387×756 candidate set,
Keras model, and scaler (`config/rq1.botnet.static.yaml` settings —
threshold 0.5, L2, ε from the rq2/sm1 grids), and the resulting o1..o7
tables (metric definition: ``objective_calculator.py:86-119``) are committed
as fixtures that CI re-derives:

- ``parity_botnet_rq1.json`` — the full-scale run record (387 states ×
  1000 generations, pop 200, seed 42, single TPU chip) plus a pinned
  8-state slice of its attack output (``parity_botnet_{x,adv}.npy``) whose
  o-rates CI recomputes bit-for-bit.
- ``parity_botnet_cpu_small.json`` — a small attack (48 states × 80 gens)
  re-RUN from scratch in CI on the deterministic CPU backend and checked
  against its pinned rates. Its o2/o4 rates are strictly interior in (0, 1)
  BY CONSTRUCTION: the previous 16×40 fixture had fully saturated 0/1 rates
  and passed unchanged through a behaviour-altering survival fix.

Full-scale numbers for the record, REGENERATED round 5 with the corrected
(pymoo-oracle-validated) survival kernel (budget 1000): MoEvA o1..o7 all
1.0 — final population alone AND with the archive (the pre-fix kernel's
converged population lost mid-run constrained adversarials, o4 = 0.0749;
its values are preserved in the fixture under ``pre_fix_r3``); PGD(flip)+
SAT repairs every flip exactly (o7 = 1.0); the rq2 augmented defense and
rq3 retrained model block every flip at budget 100 (o2 = 0) — the
reference paper's qualitative botnet story end to end. All success rates
are f64 judgements (``ObjectiveCalculator(precise=True)``): botnet sum
equalities run at magnitudes (~6e9) beyond f32 ulp resolution.
"""

import json
import os

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.objective import ObjectiveCalculator
from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints
from moeva2_ijcai22_replication_tpu.models.io import load_classifier
from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
REF_MODELS = "/root/reference/models"


@pytest.fixture(scope="module")
def real_botnet(botnet_paths):
    if not os.path.isdir(REF_MODELS):
        pytest.skip("reference models not available")
    cons = BotnetConstraints(botnet_paths["features"], botnet_paths["constraints"])
    sur = load_classifier(f"{REF_MODELS}/botnet/nn.model")
    scaler = load_joblib_scaler(f"{REF_MODELS}/botnet/scaler.joblib")
    return cons, sur, scaler


def make_calc(cons, sur, scaler, thresholds):
    return ObjectiveCalculator(
        classifier=sur, constraints=cons, thresholds=thresholds,
        min_max_scaler=scaler, ml_scaler=scaler, minimize_class=1, norm=2,
    )


class TestMetricPipelinePinned:
    def test_slice_o_rates_bit_for_bit(self, real_botnet):
        """The committed slice of the full-scale TPU attack output must
        reproduce its pinned o1..o7 exactly — pins the entire evaluation
        pipeline (360 constraint kernels, OHE distance, scaler, imported
        Keras forward, thresholds) against the real artifacts."""
        cons, sur, scaler = real_botnet
        rec = json.load(open(f"{FIXTURES}/parity_botnet_rq1.json"))
        x = np.load(f"{FIXTURES}/parity_botnet_x.npy")
        adv = np.load(f"{FIXTURES}/parity_botnet_adv.npy").astype(np.float64)
        calc = make_calc(cons, sur, scaler, rec.get("thresholds", {"f1": 0.5, "f2": 4.0}))
        rates = calc.success_rate_3d(x, adv)
        np.testing.assert_allclose(rates, rec["slice_o_rates"], atol=0)

    def test_full_scale_record_consistency(self):
        rec = json.load(open(f"{FIXTURES}/parity_botnet_rq1.json"))
        o = np.asarray(rec["full_scale"]["o_rates"])
        assert rec["full_scale"]["n_states"] == 387
        assert rec["full_scale"]["n_gen"] == 1000
        # metric algebra: joint rates can never exceed their factors
        assert o[3] <= min(o[0], o[1]) and o[6] <= min(o[3], o[4], o[5])
        # the run found genuine constrained adversarials
        assert o[6] > 0


class TestSmallAttackReproduces:
    def test_cpu_small_run_matches_pinned_rates(self, real_botnet, botnet_candidates):
        """End-to-end determinism fixture: the same small MoEvA attack on the
        first 16 real candidates must land on the pinned o-rates (CPU x64
        backend — the CI platform the fixture was generated on)."""
        cons, sur, scaler = real_botnet
        rec = json.load(open(f"{FIXTURES}/parity_botnet_cpu_small.json"))
        # the fixture must stay SENSITIVE: strictly interior o2/o4 pins so a
        # semantic change to survival/operators moves them (saturated 0/1
        # pins once let a behaviour-altering fix through unnoticed)
        assert 0.0 < rec["o_rates"][1] < 1.0 and 0.0 < rec["o_rates"][3] < 1.0
        x = botnet_candidates[: rec["n_states"]]
        moeva = Moeva2(
            classifier=sur, constraints=cons, ml_scaler=scaler, norm=2,
            n_gen=rec["n_gen"], n_pop=rec["n_pop"],
            n_offsprings=rec["n_offsprings"], seed=rec["seed"],
            archive_size=rec.get("archive_size", 0),
        )
        res = moeva.generate(x, minimize_class=1)
        calc = make_calc(cons, sur, scaler, rec["thresholds"])
        rates = calc.success_rate_3d(x, res.x_ml)
        np.testing.assert_allclose(rates, rec["o_rates"], atol=0)


class TestSatChainReproduces:
    def test_pgd_sat_chain_repairs_every_flip(self, real_botnet, botnet_candidates):
        """Re-derive the pinned flip+sat property on a 16-state subset: the
        MILP repair must return a constraint-satisfying flip inside the
        ε-ball for EVERY state (full-scale record: o7 = 1.0 over all 387)."""
        import jax.numpy as jnp

        from moeva2_ijcai22_replication_tpu.attacks.pgd import (
            ConstrainedPGD,
            round_ints_toward_initial,
        )
        from moeva2_ijcai22_replication_tpu.attacks.sat import SatAttack
        from moeva2_ijcai22_replication_tpu.domains.botnet_sat import (
            make_botnet_sat_builder,
        )

        cons, sur, scaler = real_botnet
        x = botnet_candidates[:16]
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=2 - 1e-6, eps_step=0.1, max_iter=100, norm=2,
            loss_evaluation="flip", seed=42,
        )
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        y = np.asarray(sur.predict_proba(jnp.asarray(xs))).argmax(-1)
        hot = np.asarray(scaler.inverse(jnp.asarray(atk.generate(xs, y))))
        hot = round_ints_toward_initial(hot, x, cons.get_feature_type())
        sat = SatAttack(
            cons, make_botnet_sat_builder(cons), scaler, 2.0, np.inf,
            n_sample=1, n_jobs=1,
        )
        adv = sat.generate(x, hot)

        calc = make_calc(cons, sur, scaler, {"f1": 0.5, "f2": 4.0})
        rates = calc.success_rate_3d(x, adv)
        np.testing.assert_allclose(rates, np.ones(7), atol=0)
