"""Constrained PGD / AutoPGD tests on synthetic LCLD against a trained MLP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.pgd import (
    AutoPGD,
    ConstrainedPGD,
    round_ints_toward_initial,
)
from moeva2_ijcai22_replication_tpu.attacks.pgd.autopgd import checkpoint_schedule
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import lcld_mlp
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax
from moeva2_ijcai22_replication_tpu.models.train import fit_mlp


@pytest.fixture(scope="module")
def setup(lcld_paths):
    cons = LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])
    x = synth_lcld(128, cons.schema, seed=0)
    scaler = fit_minmax(x.min(0), x.max(0))
    xs = np.asarray(scaler.transform(jnp.asarray(x)))
    # a separable-but-learnable synthetic label: above-median interest rate
    y = (x[:, 2] > np.median(x[:, 2])).astype(np.int64)
    fit = fit_mlp(lcld_mlp(), xs, y, epochs=30, batch_size=32, patience=30, seed=1)
    sur = fit.surrogate
    preds = np.asarray(sur.predict_proba(jnp.asarray(xs))).argmax(-1)
    assert (preds == y).mean() > 0.8, "fixture model failed to learn"
    return cons, x, xs, y, scaler, sur


class TestConstrainedPGD:
    def test_flip_attack_flips(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.3, eps_step=0.05, max_iter=50, norm=np.inf,
            loss_evaluation="flip",
        )
        adv = atk.generate(xs, y)
        preds = np.asarray(sur.predict_proba(jnp.asarray(adv))).argmax(-1)
        flip_rate = (preds != y).mean()
        assert flip_rate > 0.5, f"flip rate only {flip_rate}"

    def test_immutable_features_untouched(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.2, eps_step=0.05, max_iter=10, norm=np.inf,
        )
        adv = atk.generate(xs, y)
        immutable = ~np.asarray(cons.schema.mutable)
        np.testing.assert_allclose(adv[:, immutable], xs[:, immutable], atol=1e-7)

    def test_eps_ball_respected(self, setup):
        cons, x, xs, y, scaler, sur = setup
        for norm, eps in [(np.inf, 0.1), (2, 0.5)]:
            atk = ConstrainedPGD(
                classifier=sur, constraints=cons, scaler=scaler,
                eps=eps, eps_step=0.05, max_iter=12, norm=norm,
            )
            adv = atk.generate(xs, y)
            delta = adv - xs
            if norm is np.inf:
                assert np.abs(delta).max() <= eps + 1e-5
            else:
                assert np.linalg.norm(delta, axis=1).max() <= eps + 1e-4

    def test_constraint_loss_reduces_violations(self, setup):
        cons, x, xs, y, scaler, sur = setup
        # start from slightly violating points: perturb installment feature
        xs_bad = xs.copy()
        xs_bad[:, 3] = np.clip(xs_bad[:, 3] + 0.1, 0, 1)
        g0 = np.asarray(
            cons.evaluate(scaler.inverse(jnp.asarray(xs_bad)))
        ).sum(-1)
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.3, eps_step=0.02, max_iter=60, norm=np.inf,
            loss_evaluation="constraints",
        )
        adv = atk.generate(xs_bad, y)
        g1 = np.asarray(cons.evaluate(scaler.inverse(jnp.asarray(adv)))).sum(-1)
        assert g1.mean() < g0.mean() * 0.5, (g0.mean(), g1.mean())

    def test_repair_strategy_satisfies_formula_constraints(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.2, eps_step=0.05, max_iter=8, norm=np.inf,
            loss_evaluation="flip+repair",
        )
        adv = atk.generate(xs, y)
        un = np.asarray(scaler.inverse(jnp.asarray(adv)))
        # repair snaps term to {36, 60} and recomputes installment
        assert set(np.unique(un[:, 1].round(3))) <= {36.0, 60.0}

    def test_loss_strategies_all_run(self, setup):
        cons, x, xs, y, scaler, sur = setup
        for le in [
            "flip",
            "constraints",
            "constraints+flip",
            "constraints+flip+alternate",
            "constraints+flip+constraints",
            "constraints+flip+adaptive_eps_step",
        ]:
            atk = ConstrainedPGD(
                classifier=sur, constraints=cons, scaler=scaler,
                eps=0.1, eps_step=0.05, max_iter=4, norm=np.inf,
                loss_evaluation=le,
            )
            adv = atk.generate(xs[:8], y[:8])
            assert np.isfinite(adv).all(), le

    def test_constraints_optim_variants(self, setup):
        cons, x, xs, y, scaler, sur = setup
        for co in ["sum", "alt_constraints", "single_constraints"]:
            atk = ConstrainedPGD(
                classifier=sur, constraints=cons, scaler=scaler,
                eps=0.1, eps_step=0.05, max_iter=4, norm=np.inf,
                loss_evaluation="constraints+flip", constraints_optim=co,
            )
            adv = atk.generate(xs[:8], y[:8])
            assert np.isfinite(adv).all(), co

    def test_random_restarts(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.2, eps_step=0.05, max_iter=6, norm=2,
            num_random_init=3,
        )
        adv = atk.generate(xs[:16], y[:16])
        assert np.isfinite(adv).all()
        delta = np.linalg.norm(adv - xs[:16], axis=1)
        assert delta.max() <= 0.2 + 1e-4


class TestAutoPGD:
    def test_checkpoint_schedule(self):
        w = checkpoint_schedule(100)
        assert w[0] == 0 and w[1] == 22
        assert all(np.diff(w) >= 3)
        assert w[-1] <= 100

    def test_autopgd_flips(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = AutoPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.3, eps_step=0.1, max_iter=40, norm=np.inf,
            loss_evaluation="flip",
        )
        adv = atk.generate(xs, y)
        preds = np.asarray(sur.predict_proba(jnp.asarray(adv))).argmax(-1)
        assert (preds != y).mean() > 0.4
        delta = np.abs(adv - xs).max()
        assert delta <= 0.3 + 1e-5

    def test_autopgd_never_worse_than_start(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = AutoPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.2, eps_step=0.05, max_iter=20, norm=np.inf,
        )
        adv = atk.generate(xs[:32], y[:32])
        # x_best tracking: CE of returned points >= CE of initial points
        def ce(xv):
            logits = np.asarray(sur.logits(jnp.asarray(xv)))
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            return -np.log(p[np.arange(len(xv)), y[:32]] + 1e-12)

        assert ce(adv).mean() >= ce(xs[:32]).mean() - 1e-5


class TestIntRounding:
    def test_directional_rounding(self):
        types = ["real", "int", "int"]
        x_init = np.array([[1.5, 5.0, 5.0]])
        x_adv = np.array([[2.2, 6.7, 3.2]])
        out = round_ints_toward_initial(x_adv, x_init, types)
        np.testing.assert_allclose(out, [[2.2, 6.0, 4.0]])


class TestAutoPgdReviewRegressions:
    def test_manual_strategy_weights(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = AutoPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.1, eps_step=0.05, max_iter=4, norm=np.inf,
            loss_evaluation="constraints+flip+manual",
        )
        # manual: class-only before iteration 100
        w_class, w_cons = atk._loss_weights(jnp.int32(3), jnp.float32)
        assert float(w_class) == 1.0 and float(w_cons) == 0.0
        w_class, w_cons = atk._loss_weights(jnp.int32(150), jnp.float32)
        assert float(w_class) == 0.0 and float(w_cons) == 1.0
        adv = atk.generate(xs[:8], y[:8])
        assert np.isfinite(adv).all()

    def test_autopgd_random_restarts_run(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = AutoPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.2, eps_step=0.05, max_iter=6, norm=np.inf,
            num_random_init=2,
        )
        adv = atk.generate(xs[:16], y[:16])
        assert np.isfinite(adv).all()
        assert np.abs(adv - xs[:16]).max() <= 0.2 + 1e-5


class TestGradNormHistory:
    def test_grad_norm_column_shape_and_values(self, setup):
        """record_grad_norm adds one per-iteration column (parity with the
        reference's TensorBoard grad-norm stream, atk.py:201-226)."""
        cons, x, xs, y, scaler, sur = setup
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.2, eps_step=0.05, max_iter=7, norm=np.inf,
            loss_evaluation="constraints+flip",
            record_loss="reduced", record_grad_norm=True,
        )
        atk.generate(xs, y)
        hist = atk.loss_history
        assert hist.shape == (xs.shape[0], 7, 4)
        gn = hist[..., 3]
        assert np.isfinite(gn).all() and (gn >= 0).all()
        assert gn.max() > 0  # the loss actually has gradient signal

    def test_grad_norm_column_with_full_history(self, setup):
        cons, x, xs, y, scaler, sur = setup
        atk = AutoPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.2, eps_step=0.06, max_iter=6, norm=np.inf,
            loss_evaluation="constraints+flip",
            record_loss="full", record_grad_norm=True,
        )
        atk.generate(xs, y)
        # [loss, loss_class, cons_sum, grad_norm, g_1..g_10] on LCLD
        assert atk.loss_history.shape == (xs.shape[0], 6, 4 + 10)

    def test_restart_history_follows_kept_restart(self, setup):
        """With restarts, each sample's history must match a full rerun of
        the restart that produced its kept result, not blanket-follow the
        last restart executed."""
        cons, x, xs, y, scaler, sur = setup
        kw = dict(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.25, eps_step=0.05, max_iter=5, norm=np.inf,
            loss_evaluation="flip", record_loss="reduced", seed=3,
        )
        atk = ConstrainedPGD(num_random_init=3, **kw)
        adv = atk.generate(xs, y)
        hist = atk.loss_history

        # replay each restart r alone (same fold_in(key, r) stream) and
        # check every sample's recorded history equals one of the replays
        replays = []
        for r in range(3):
            import jax as _jax
            import jax.numpy as _jnp

            x_start = atk._random_start(
                _jax.random.fold_in(_jax.random.PRNGKey(3), r),
                _jnp.asarray(xs, atk.dtype),
            )
            _, h = _jax.jit(atk._one_run)(
                sur.params, _jnp.asarray(xs, atk.dtype),
                _jnp.asarray(y, _jnp.int32), x_start,
            )
            replays.append(np.swapaxes(np.asarray(h), 0, 1))
        stack = np.stack(replays)  # (R, N, T, C)
        per_sample = np.abs(stack - hist[None]).max(axis=(2, 3))  # (R, N)
        assert (per_sample.min(axis=0) < 1e-6).all()


class TestMeshShardedPGD:
    def test_sharded_attack_matches_single_device(self, setup):
        """The PGD batch axis shards over a device mesh with zero
        collectives (every op is per-sample): results must be bit-identical
        to the unsharded run."""
        import jax
        from jax.sharding import Mesh

        cons, x, xs, y, scaler, sur = setup

        def run(mesh):
            atk = ConstrainedPGD(
                classifier=sur, constraints=cons, scaler=scaler,
                eps=0.3, eps_step=0.05, max_iter=20, norm=np.inf,
                loss_evaluation="constraints+flip", num_random_init=2,
                record_loss="reduced", seed=5, dtype=jnp.float64,
                mesh=mesh,
            )
            adv = atk.generate(xs, y)
            return adv, atk.loss_history

        mesh = Mesh(np.array(jax.devices()[:8]), ("states",))
        adv_m, hist_m = run(mesh)
        adv_1, hist_1 = run(None)
        np.testing.assert_array_equal(adv_m, adv_1)
        np.testing.assert_array_equal(hist_m, hist_1)

    def test_sharded_attack_rejects_indivisible_batch(self, setup):
        import jax
        from jax.sharding import Mesh

        cons, x, xs, y, scaler, sur = setup
        atk = ConstrainedPGD(
            classifier=sur, constraints=cons, scaler=scaler,
            eps=0.3, max_iter=5,
            mesh=Mesh(np.array(jax.devices()[:8]), ("states",)),
        )
        with pytest.raises(ValueError, match="divisible by the mesh size"):
            atk.generate(xs[:3], y[:3])
