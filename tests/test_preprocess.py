"""LCLD raw-data preprocessing tests on a synthetic raw LendingClub sample."""

import numpy as np
import pandas as pd
import pytest

from moeva2_ijcai22_replication_tpu.experiments.preprocess import (
    preprocess_lcld,
    _schema_order,
)


def raw_sample(n=40, seed=0) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    status = rng.choice(["Fully Paid", "Charged Off", "Current"], n)
    term = rng.choice([" 36 months", " 60 months"], n)
    rate = rng.uniform(5.5, 30.0, n).round(2)
    loan = rng.integers(1000, 40000, n).astype(float)
    r = rate / 1200.0
    t = np.where(np.char.find(term.astype(str), "36") >= 0, 36, 60)
    inst = loan * r * (1 + r) ** t / ((1 + r) ** t - 1)
    issue_month = rng.integers(1, 13, n)
    cr_year = rng.integers(1990, 2012, n)
    df = pd.DataFrame(
        {
            "id": np.arange(n),
            "loan_status": status,
            "term": term,
            "int_rate": rate,
            "loan_amnt": loan,
            "installment": inst.round(2),
            "grade": rng.choice(list("ABCDEFG"), n),
            "sub_grade": rng.choice(["A1", "B2"], n),
            "emp_title": "x",
            "emp_length": rng.choice(
                ["10+ years", "< 1 year", "5 years", None], n
            ),
            "home_ownership": rng.choice(
                ["MORTGAGE", "RENT", "OWN", "NONE", "ANY"], n
            ),
            "annual_inc": rng.uniform(2e4, 2e5, n).round(0),
            "verification_status": rng.choice(
                ["Not Verified", "Source Verified", "Verified"], n
            ),
            "issue_d": [f"2015-{m:02d}-01" for m in issue_month],
            "purpose": rng.choice(["car", "credit_card", "wedding"], n),
            "title": "y",
            "zip_code": "123xx",
            "addr_state": "CA",
            "dti": rng.uniform(0, 40, n).round(2),
            "earliest_cr_line": [f"{y}-06-01" for y in cr_year],
            "fico_range_low": rng.integers(620, 800, n).astype(float),
            "fico_range_high": rng.integers(620, 800, n).astype(float) + 4,
            # real exports satisfy open_acc <= total_acc and
            # pub_rec_bankruptcies <= pub_rec by construction
            "open_acc": (open_acc := rng.integers(1, 20, n).astype(float)),
            "pub_rec": (pub_rec := rng.integers(0, 3, n).astype(float)),
            "revol_bal": rng.uniform(0, 5e4, n).round(0),
            "revol_util": rng.uniform(0, 120, n).round(1),
            "total_acc": open_acc + rng.integers(0, 40, n).astype(float),
            "initial_list_status": rng.choice(["w", "f"], n),
            "application_type": rng.choice(["Individual", "Joint App"], n),
            "mort_acc": rng.integers(0, 5, n).astype(float),
            "pub_rec_bankruptcies": np.minimum(
                rng.integers(0, 2, n).astype(float), pub_rec
            ),
        }
    )
    return df


@pytest.fixture(scope="module")
def processed():
    raw = raw_sample()
    return raw, preprocess_lcld(raw)


class TestPreprocess:
    def test_columns_match_committed_schema(self, processed, lcld_paths):
        """Output columns == the reference's features.csv, in order, plus
        the target — the contract the whole artifact family builds on."""
        _, out = processed
        schema_names = pd.read_csv(lcld_paths["features"])["feature"].tolist()
        assert out.columns.tolist() == schema_names + ["charged_off"]
        assert _schema_order() == schema_names

    def test_status_filter_and_target(self, processed):
        raw, out = processed
        kept = raw["loan_status"].isin(["Fully Paid", "Charged Off"])
        assert len(out) <= kept.sum()  # dropna may remove more
        assert set(out["charged_off"].unique()) <= {0, 1}

    def test_scalar_encodings(self, processed):
        _, out = processed
        assert set(out["term"].unique()) <= {36, 60}
        assert out["grade"].between(1, 7).all()
        assert out["emp_length"].between(0, 10).all()
        # YYYYMM ints
        assert (out["issue_d"] // 100 == 2015).all()
        assert out["earliest_cr_line"].mod(100).between(1, 12).all()

    def test_preprocessed_rows_satisfy_lcld_constraints(self, processed, lcld_paths):
        """The derived features ARE the constraint right-hand sides, so a
        preprocessed row must satisfy all 10 LCLD formulas — the domain
        plugin is the oracle (same cross-check the reference performs by
        running check_constraints_error on its candidate sets)."""
        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints

        _, out = processed
        cons = LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])
        x = out.drop(columns="charged_off").to_numpy(dtype=float)
        g = np.asarray(cons.evaluate(x))
        assert g.max() <= 1e-9, g.max(0)

    def test_one_hot_exactness(self, processed):
        raw, out = processed
        ohe = [c for c in out.columns if c.startswith(("home_ownership_",
                                                       "verification_status_",
                                                       "purpose_"))]
        groups = ("home_ownership", "verification_status", "purpose")
        for g in groups:
            cols = [c for c in ohe if c.startswith(g)]
            np.testing.assert_array_equal(out[cols].sum(axis=1), 1)

    def test_pinned_levels_survive_missing_categories(self):
        """A raw sample that lacks a category must still produce the full
        schema width (the reference's get_dummies would silently narrow)."""
        raw = raw_sample(30, seed=3)
        raw["purpose"] = "car"  # single level only
        out = preprocess_lcld(raw)
        assert "purpose_wedding" in out.columns
        assert (out["purpose_wedding"] == 0).all()

    def test_missing_raw_column_raises_cleanly(self):
        raw = raw_sample(20, seed=1).drop(columns=["application_type"])
        with pytest.raises(ValueError, match="application_type"):
            preprocess_lcld(raw)
