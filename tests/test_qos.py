"""QoS layer tests: class lanes, admission pricing, streaming, shed labels.

Everything above the service smoke runs hardware-free on fake clocks:
weighted-fair batch assembly + the starvation bound, strict-priority
preemption at flush, the admission token-bucket arithmetic against a fake
capacity model, ResultStream ordering/early-close and the batcher's
partial-row router, the per-class shed attribution matrix, and the
deadline-attribution regression (expiry after assembly reached a request
must shed as ``batch_wait``, not ``queue_wait``). The final smoke drives
real PGD requests through two services — QoS off vs. on — and pins the
off-switch contract: bit-identical results, zero extra compiles, equal
dispatch counts.
"""

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.observability import (
    TraceRecorder,
    current_ledger_context,
    get_ledger,
)
from moeva2_ijcai22_replication_tpu.observability.slo import SloTracker
from moeva2_ijcai22_replication_tpu.serving import (
    AttackRequest,
    AttackService,
    BucketMenu,
    DeadlineExceeded,
    Microbatcher,
    QosClass,
    QosPolicy,
    ResultStream,
)
from moeva2_ijcai22_replication_tpu.serving.qos.admission import (
    AdmissionController,
    AdmissionDenied,
)
from moeva2_ijcai22_replication_tpu.utils.observability import ServiceMetrics


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def three_tier_policy(**kw):
    """The bench taxonomy: interactive(w4) > batch(w2) > scavenger(w1)."""
    return QosPolicy(
        classes={
            "interactive": QosClass(
                "interactive", priority=0, weight=4.0, rate_share=0.6
            ),
            "batch": QosClass("batch", priority=1, weight=2.0, rate_share=0.3),
            "scavenger": QosClass(
                "scavenger", priority=2, weight=1.0, rate_share=0.1
            ),
        },
        default_class="batch",
        **kw,
    )


def make_batcher(
    sizes=(8,), qos=None, slo=None, max_delay_s=0.01, clock=None
):
    clock = clock or FakeClock()
    b = Microbatcher(
        BucketMenu(sizes),
        max_delay_s=max_delay_s,
        max_queue_rows=256,
        metrics=ServiceMetrics(),
        slo=slo,
        clock=clock,
        start=False,
        qos=qos,
    )
    return b, clock


def class_counts(x):
    """Row values encode the class a request was submitted under
    (0=interactive, 1=batch, 2=scavenger); padding rows are 0-valued
    only past the real batch, so callers slice to rows_total first."""
    vals, counts = np.unique(x[:, 0].astype(int), return_counts=True)
    return dict(zip(vals.tolist(), counts.tolist()))


# ---------------------------------------------------------------------------
# weighted-fair assembly + starvation bound
# ---------------------------------------------------------------------------


class TestWeightedFairness:
    def test_seats_then_priority_fill(self):
        """Capacity 8, weights 4/2/1 all present: guaranteed seats are
        floor(8*w/7) = 4/2/1, the one leftover seat goes to the highest
        priority class — so a backlog of 8 interactive rows still cannot
        push queued batch/scavenger work out of the first batch."""
        b, _ = make_batcher(qos=three_tier_policy())
        captured = []
        disp = lambda x: captured.append(x.copy()) or x  # noqa: E731
        for _ in range(8):
            b.submit("k", disp, np.zeros((1, 1)), qos_class="interactive")
        for _ in range(4):
            b.submit("k", disp, np.ones((1, 1)), qos_class="batch")
        for _ in range(4):
            b.submit("k", disp, np.full((1, 1), 2.0), qos_class="scavenger")

        assert b.flush_due() == 1  # capacity flush, no deadline wait
        assert class_counts(captured[0][:8]) == {0: 5, 1: 2, 2: 1}

    def test_starvation_bound_every_batch_carries_scavenger(self):
        """Scavenger work is guaranteed its slice of EVERY batch its key
        flushes while it has queued rows — not just 'eventually'."""
        b, clock = make_batcher(qos=three_tier_policy())
        captured = []
        disp = lambda x: captured.append(x.copy()) or x  # noqa: E731
        for _ in range(8):
            b.submit("k", disp, np.zeros((1, 1)), qos_class="interactive")
        for _ in range(4):
            b.submit("k", disp, np.ones((1, 1)), qos_class="batch")
        for _ in range(4):
            b.submit("k", disp, np.full((1, 1), 2.0), qos_class="scavenger")

        rows_seen = 0
        while rows_seen < 16:
            clock.advance(0.02)
            assert b.flush_due() >= 1
            rows_seen = sum(c.shape[0] for c in captured)
        # exact drain: [5,2,1] then the leftovers [3,2,3]
        assert [class_counts(c[:8]) for c in captured] == [
            {0: 5, 1: 2, 2: 1},
            {0: 3, 1: 2, 2: 3},
        ]
        assert all(2 in class_counts(c[:8]) for c in captured)

    def test_unknown_class_degrades_to_default_lane(self):
        """Taxonomy drift must degrade, never reject: a bogus class name
        rides the default lane and the result meta says which one."""
        b, _ = make_batcher(qos=three_tier_policy())
        fut = b.submit("k", lambda x: x, np.ones((2, 1)), qos_class="bogus")
        b.flush_due(force=True)
        _, meta = fut.result(timeout=0)
        assert meta["qos_class"] == "batch"


# ---------------------------------------------------------------------------
# strict-priority preemption at flush
# ---------------------------------------------------------------------------


class TestPreemptionAtFlush:
    def test_high_priority_batch_dispatches_first(self):
        """Two keys become flushable in the same pass; the one carrying
        the more urgent rider dispatches first even though the scavenger
        key was enqueued (and assembled) earlier."""
        b, clock = make_batcher(qos=three_tier_policy())
        order = []
        b.submit(
            "low", lambda x: order.append("low") or x, np.ones((4, 1)),
            qos_class="scavenger",
        )
        b.submit(
            "high", lambda x: order.append("high") or x, np.ones((4, 1)),
            qos_class="interactive",
        )
        clock.advance(0.02)
        assert b.flush_due() == 2
        assert order == ["high", "low"]

    def test_equal_priority_keeps_assembly_order(self):
        b, clock = make_batcher(qos=three_tier_policy())
        order = []
        b.submit(
            "first", lambda x: order.append("first") or x, np.ones((4, 1)),
            qos_class="batch",
        )
        b.submit(
            "second", lambda x: order.append("second") or x, np.ones((4, 1)),
            qos_class="batch",
        )
        clock.advance(0.02)
        assert b.flush_due() == 2
        assert order == ["first", "second"]  # stable sort


# ---------------------------------------------------------------------------
# deadline-attribution regression (the batched_at bugfix)
# ---------------------------------------------------------------------------


class TestDeadlineAttribution:
    """A deadline-cancelled request must shed against the stage that
    actually consumed its deadline: once assembly reached it but closed
    the batch without it, the remaining wait is batch formation."""

    def _ab_setup(self, slo):
        b, clock = make_batcher(slo=slo)  # classless path — bugfix is shared
        done = []
        disp = lambda x: done.append(x.shape) or x  # noqa: E731
        fut_a = b.submit("k", disp, np.ones((6, 1)), meta={"domain": "d"})
        fut_b = b.submit(
            "k", disp, np.ones((6, 1)), deadline_s=0.05, meta={"domain": "d"}
        )
        return b, clock, fut_a, fut_b

    def test_expiry_after_assembly_reached_it_is_batch_wait(self):
        slo = SloTracker()
        b, clock, fut_a, fut_b = self._ab_setup(slo)
        # 12 rows ≥ bucket 8: due now. A dispatches alone (B doesn't fit);
        # assembly reached B and stamps batched_at = 0.0 < deadline 0.05.
        assert b.flush_due() == 1
        assert fut_a.result(timeout=0)
        clock.advance(0.06)  # past B's deadline, still pre-dispatch
        b.flush_due()
        with pytest.raises(DeadlineExceeded):
            fut_b.result(timeout=0)
        shed = slo.shed_block()["by_domain"]["d"]
        assert shed == {"expired": {"batch_wait": 1}}

    def test_expiry_before_assembly_ever_reached_it_is_queue_wait(self):
        slo = SloTracker()
        b, clock = make_batcher(slo=slo)
        fut = b.submit(
            "k", lambda x: x, np.ones((2, 1)), deadline_s=0.05,
            meta={"domain": "d"},
        )
        clock.advance(0.06)  # first flush only happens past the deadline
        b.flush_due()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=0)
        shed = slo.shed_block()["by_domain"]["d"]
        assert shed == {"expired": {"queue_wait": 1}}

    def test_deadline_spent_before_batched_at_stays_queue_wait(self):
        """batched_at alone is not enough: if the deadline was already
        gone when assembly first reached the request, the budget was
        consumed queueing — batched_at > deadline_at must NOT relabel."""
        slo = SloTracker()
        b, clock, fut_a, fut_b = self._ab_setup(slo)
        clock.advance(0.10)  # B's deadline passes before any flush runs
        b.flush_due()  # dispatches A; stamps B batched_at = 0.10 > 0.05
        b.flush_due()  # pops B: expired, deadline_at <= batched_at
        with pytest.raises(DeadlineExceeded):
            fut_b.result(timeout=0)
        shed = slo.shed_block()["by_domain"]["d"]
        assert shed == {"expired": {"queue_wait": 1}}


# ---------------------------------------------------------------------------
# cost-predictive admission: token buckets priced by the capacity model
# ---------------------------------------------------------------------------


class FakeCapacity:
    def __init__(self, qps):
        self.qps = qps
        self.calls = 0

    def domain_block(self, domain):
        self.calls += 1
        if self.qps is None:
            return None
        return {"max_sustainable_qps": float(self.qps)}


class TestAdmission:
    def test_bucket_math_against_capacity_model(self):
        """qps 10 x share 0.5 = 5 rps; burst_s 2 => 10 tokens, starting
        full. Denial predicts the exact time until one token exists."""
        policy = QosPolicy(
            classes={"c": QosClass("c", priority=0, rate_share=0.5)},
            default_class="c",
        )
        clock = FakeClock(100.0)
        adm = AdmissionController(
            policy, FakeCapacity(10.0), clock=clock, burst_s=2.0
        )
        for _ in range(10):
            adm.admit("dom", "c")
        with pytest.raises(AdmissionDenied) as ei:
            adm.admit("dom", "c")
        assert ei.value.rate == pytest.approx(5.0)
        assert ei.value.retry_after_s == pytest.approx(1.0 / 5.0)

        # refill: 0.4s * 5 rps = 2 tokens — exactly two more admits
        clock.advance(0.4)
        adm.admit("dom", "c")
        adm.admit("dom", "c")
        with pytest.raises(AdmissionDenied) as ei:
            adm.admit("dom", "c")
        assert ei.value.retry_after_s == pytest.approx(1.0 / 5.0)

        snap = adm.snapshot()
        assert snap["admitted"] == 12 and snap["denied"] == 2
        assert snap["denied_by_class"] == {"c": 2}
        assert snap["buckets"]["dom|c"]["rate_rps"] == pytest.approx(5.0)
        assert snap["buckets"]["dom|c"]["burst"] == pytest.approx(10.0)

    def test_rate_reads_are_cached(self):
        """Pricing is O(1) per request: the capacity model is consulted
        once per cache window, not once per admit."""
        clock = FakeClock(0.0)
        cap = FakeCapacity(10.0)
        adm = AdmissionController(
            three_tier_policy(), cap, clock=clock, burst_s=2.0
        )
        for _ in range(5):
            adm.admit("dom", "interactive")
        assert cap.calls == 1

    def test_small_share_classes_shed_first_by_construction(self):
        """Round-robin overload: scavenger's bucket (share 0.1) drains
        first, then batch (0.3); interactive (0.6) rides through."""
        clock = FakeClock(0.0)
        adm = AdmissionController(
            three_tier_policy(), FakeCapacity(10.0), clock=clock, burst_s=1.0
        )
        first_denied = []
        for _ in range(4):  # 4 rounds at a frozen clock: no refill
            for klass in ("interactive", "batch", "scavenger"):
                try:
                    adm.admit("dom", klass)
                except AdmissionDenied as e:
                    if e.klass not in first_denied:
                        first_denied.append(e.klass)
        assert first_denied == ["scavenger", "batch"]
        assert "interactive" not in adm.denied_by_class

    def test_unprimed_capacity_admits_everything(self):
        """No observations yet (or an unpriceable domain): the bucket
        arms itself from measurement — nothing is rejected blind."""
        adm = AdmissionController(
            three_tier_policy(), FakeCapacity(None), clock=FakeClock(),
            burst_s=1.0,
        )
        for _ in range(100):
            adm.admit("dom", "scavenger")
        snap = adm.snapshot()
        assert snap["admitted"] == 100 and snap["denied"] == 0
        assert snap["buckets"] == {}

    def test_no_capacity_model_admits(self):
        adm = AdmissionController(
            three_tier_policy(), None, clock=FakeClock()
        )
        adm.admit("dom", "scavenger")
        assert adm.admitted == 1


# ---------------------------------------------------------------------------
# streaming: ResultStream semantics + the batcher's partial-row router
# ---------------------------------------------------------------------------


class TestResultStream:
    def test_chunk_ordering_and_first_solved_stamp(self):
        clock = FakeClock(10.0)
        s = ResultStream("r1", 4, clock=clock)
        clock.advance(1.0)
        s.put([0, 1], "x01", 3)
        assert s.t_first_solved == 11.0
        clock.advance(1.0)
        s.put([2], "x2", 7)
        assert s.t_first_solved == 11.0  # first stamp only
        s.finish("final", {"m": 1})

        view = s.poll(0)
        assert view["done"] and not view["failed"]
        assert view["rows_streamed"] == 3 and view["cursor"] == 2
        assert [c["gen"] for c in view["chunks"]] == [3, 7]
        assert [c["rows"] for c in view["chunks"]] == [[0, 1], [2]]
        # incremental poll resumes at the cursor
        assert [c["gen"] for c in s.poll(1)["chunks"]] == [7]

        got = list(s.chunks(timeout=0.1))
        assert [c["gen"] for c in got] == [3, 7]
        assert s.final == {"x_adv": "final", "meta": {"m": 1}}

    def test_put_after_finish_is_dropped(self):
        s = ResultStream("r2", 4, clock=FakeClock())
        s.put([0], "x", 1)
        s.finish("final")
        s.put([1], "late", 2)
        assert s.rows_streamed == 1 and s.poll(0)["cursor"] == 1

    def test_consumer_early_close_discards_quietly(self):
        """A walked-away consumer must never block or fail the producer:
        buffered chunks drop, later puts drop, finish still lands."""
        s = ResultStream("r3", 4, clock=FakeClock())
        s.put([0], "x", 1)
        s.close()
        s.put([1], "x", 2)  # dropped, no error
        assert s.poll(0)["chunks"] == []
        s.finish("final")
        assert s.done and s.final["x_adv"] == "final"


class TestPartialRouter:
    def test_global_rows_route_to_request_local_offsets(self):
        """Batch-global solved-row indices map back to each rider's own
        row numbering; a non-streaming batch-mate and padding rows route
        nowhere; a raising sink never poisons the batch."""
        b, _ = make_batcher()
        a_calls, seen_ctx = [], []

        def sink_a(rows, x_rows, gen):
            a_calls.append((rows, np.asarray(x_rows).copy(), gen))

        def sink_b(rows, x_rows, gen):
            raise ValueError("broken consumer")

        def dispatch(x):
            router = current_ledger_context().get("partial_router")
            seen_ctx.append(router is not None)
            payload = np.arange(3.0).reshape(3, 1) * 10
            router([1, 3, 4], payload, 7)  # row 1 -> A; rows 3,4 -> B
            router([6, 7], np.zeros((2, 1)), 9)  # padding rows: no rider
            return x

        fut_a = b.submit(
            "k", dispatch, np.ones((3, 1)), on_partial=sink_a
        )
        fut_b = b.submit(
            "k", dispatch, np.ones((2, 1)), on_partial=sink_b
        )
        b.flush_due(force=True)
        assert fut_a.result(timeout=0) and fut_b.result(timeout=0)
        assert seen_ctx == [True]
        assert len(a_calls) == 1
        rows, x_rows, gen = a_calls[0]
        assert rows == [1] and gen == 7
        np.testing.assert_array_equal(x_rows, [[0.0]])

    def test_no_rider_streams_no_router(self):
        """The common case carries zero partial plumbing: without an
        on_partial sink the dispatch context has no router at all."""
        b, _ = make_batcher()
        ctxs = []

        def dispatch(x):
            ctxs.append(current_ledger_context().get("partial_router"))
            return x

        fut = b.submit("k", dispatch, np.ones((2, 1)))
        b.flush_due(force=True)
        assert fut.result(timeout=0)
        assert ctxs == [None]


# ---------------------------------------------------------------------------
# per-class shed attribution matrix
# ---------------------------------------------------------------------------


class TestClassShedMatrix:
    def test_matrix_shape_and_counts(self):
        slo = SloTracker()
        slo.shed("d", "expired", "queue_wait", qos_class="scavenger")
        slo.shed("d", "expired", "queue_wait", qos_class="scavenger")
        slo.shed("d", "expired", "batch_wait", qos_class="batch")
        slo.shed("d", "rejected", "admission", qos_class="scavenger")
        slo.shed("d", "rejected", "admission")  # classless: domain-only
        block = slo.shed_block()
        assert block["by_class"] == {
            "batch": {"expired": {"batch_wait": 1}},
            "scavenger": {
                "expired": {"queue_wait": 2},
                "rejected": {"admission": 1},
            },
        }
        assert block["by_domain"]["d"]["rejected"]["admission"] == 2

    def test_batcher_sheds_carry_the_class_label(self):
        """Both shed paths the batcher owns — deadline expiry and a
        poisoned batch — attribute to each rider's own class."""
        slo = SloTracker()
        b, clock = make_batcher(qos=three_tier_policy(), slo=slo)
        fut = b.submit(
            "k", lambda x: x, np.ones((2, 1)), deadline_s=0.01,
            meta={"domain": "d"}, qos_class="scavenger",
        )
        clock.advance(0.02)
        b.flush_due()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=0)

        def boom(x):
            raise RuntimeError("poisoned")

        f1 = b.submit(
            "k2", boom, np.ones((1, 1)), meta={"domain": "d"},
            qos_class="interactive",
        )
        f2 = b.submit(
            "k2", boom, np.ones((1, 1)), meta={"domain": "d"},
            qos_class="batch",
        )
        b.flush_due(force=True)
        assert f1.exception(timeout=0) and f2.exception(timeout=0)

        assert slo.shed_block()["by_class"] == {
            "batch": {"poisoned": {"dispatch": 1}},
            "interactive": {"poisoned": {"dispatch": 1}},
            "scavenger": {"expired": {"queue_wait": 1}},
        }


# ---------------------------------------------------------------------------
# QoS off-switch contract: bit-identical results, zero extra compiles
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def qos_artifacts(tmp_path_factory):
    """Tiny synthetic-LCLD artifact family, same recipe as the serving
    tests' fixture (module-local: fixtures don't cross test files)."""
    from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld_schema

    tmp = tmp_path_factory.mktemp("qos_artifacts")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(64, cons.schema, seed=11)
    cons.check_constraints_error(x)

    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=3))
    save_params(sur, str(tmp / "nn.msgpack"))

    from sklearn.preprocessing import MinMaxScaler
    import joblib

    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    scaler = MinMaxScaler().fit(np.vstack([x, xl, xu]))
    joblib.dump(scaler, tmp / "scaler.joblib")
    return {
        "pool": x,
        "domain": {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": paths["features"],
                "constraints": paths["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
        },
    }


class TestQosOffIdentity:
    def test_qos_off_bit_identical_zero_extra_compiles(self, qos_artifacts):
        """The whole QoS layer is host-side bookkeeping: turning it on
        must change no result bit and add no compiles or dispatches for
        the same request sequence (PGD is per-row deterministic at a
        fixed bucket shape; the engine cache is process-wide, so the
        second service re-uses the first's compiled programs)."""
        pool = qos_artifacts["pool"]
        reqs = [pool[0:5], pool[10:18]]  # both land in the 8-bucket
        led = get_ledger()

        def run(svc):
            mark = led.mark()
            outs = [
                svc.attack(
                    AttackRequest(domain="lcld", x=x, budget=3, eps=0.2),
                    timeout=120.0,
                ).x_adv
                for x in reqs
            ]
            return outs, led.cost_block(since=mark)

        svc_off = AttackService(
            {"lcld": qos_artifacts["domain"]},
            bucket_sizes=(8,), max_delay_s=0.005, qos=None,
        )
        try:
            off_outs, off_cost = run(svc_off)
        finally:
            svc_off.close()

        svc_on = AttackService(
            {"lcld": qos_artifacts["domain"]},
            bucket_sizes=(8,), max_delay_s=0.005, qos=three_tier_policy(),
        )
        try:
            on_outs, on_cost = run(svc_on)
        finally:
            svc_on.close()

        assert all(
            np.array_equal(a, b) for a, b in zip(off_outs, on_outs)
        )
        extra_compiles = sum(
            1 for e in on_cost["entries"] if e.get("compile_s", 0) > 0
        )
        assert extra_compiles == 0
        assert on_cost["dispatches"] == off_cost["dispatches"]


# ---------------------------------------------------------------------------
# streaming + tracing: the request trace rides the FINAL chunk only
# ---------------------------------------------------------------------------


def _find_events(tree, name):
    """Depth-first collect of every event node called ``name``."""
    hits = []
    for node in tree:
        if node.get("kind") == "event" and node.get("name") == name:
            hits.append(node)
        hits.extend(_find_events(node.get("children", []), name))
    return hits


class TestStreamTraceOnFinalChunk:
    def test_trace_and_ttfs_ride_final_chunk_only(self, qos_artifacts):
        """A streamed request's trace (with the ``time_to_first_solved``
        event) is attached to the final payload's meta by the completion
        callback; partial chunks stay trace-free (they are row payloads a
        chunked-HTTP consumer reads mid-flight, not telemetry carriers)."""
        rec = TraceRecorder(spans_enabled=True)
        svc = AttackService(
            {"lcld": qos_artifacts["domain"]},
            # generous flush delay: the hand-parked partial below is
            # guaranteed to land before the batch dispatches
            bucket_sizes=(8,), max_delay_s=0.25,
            qos=three_tier_policy(), recorder=rec,
        )
        try:
            x = qos_artifacts["pool"][0:3]
            stream, fut = svc.submit_stream(
                AttackRequest(domain="lcld", x=x, budget=3, eps=0.2)
            )
            # park one solved row by hand — a deterministic stand-in for
            # the MoEvA early-exit gate (PGD itself streams trivially:
            # no partials, the final result is the first chunk of truth)
            stream.put([0], np.asarray(x[0:1]), gen=1)
            # wait on the STREAM, not the future: finish() runs in the
            # future's done callback, which may fire after result() wakes
            for _ in stream.chunks(timeout=120.0):
                pass
        finally:
            svc.close()

        view = stream.poll(0)
        assert view["done"] and not view["failed"]
        assert view["rows_streamed"] == 1
        # partial chunks are pure row payloads — no trace keys ever
        assert len(view["chunks"]) == 1
        assert set(view["chunks"][0]) == {"rows", "x", "gen", "t"}

        meta = stream.final["meta"]
        assert meta["rows_streamed"] == 1
        assert meta["time_to_first_solved_s"] >= 0.0
        tree = meta["trace"]
        ttfs_events = _find_events(tree, "time_to_first_solved")
        assert len(ttfs_events) == 1
        attrs = ttfs_events[0]["attrs"]
        assert attrs["rows_streamed"] == 1
        assert attrs["seconds"] == meta["time_to_first_solved_s"]
