"""Attack-quality telemetry: convergence curves, schema, watchdog, oracle.

Covers the PR-6 quality-observability layer end to end, fixture-free where
possible (code-derived synthetic LCLD schema):

- ``engine_quality_stats`` / ``sample_from_per_state`` /
  ``interior_summary`` / ``quality_block`` units (one formula, jnp and
  numpy backends);
- the MoEvA engine's quality capture: strict single-sample, the
  ``quality_every`` curve, early-exit gate riding, chunk merging — and the
  tier-1 smoke pinning that quality capture on/off is BIT-IDENTICAL with
  zero extra compiles and zero extra dispatches (the gate program computes
  the stats unconditionally; the knob only changes which fetches are kept);
- full-precision history vs display-rounded event payloads (the
  ``success_frac`` satellite);
- the PGD per-restart quality history;
- the ``telemetry.quality`` record schema, serving gauges//healthz/
  Prometheus exposition (labeled quality gauges + # HELP/# TYPE on every
  family);
- ``tools/bench_diff.py`` as a perf+QUALITY watchdog: interior-rate drift
  past threshold fails exactly like a wall-clock regression, ``--json``
  emits the CI annotation line, pre-quality records skip instead of fail;
- the committed oracle parity fixture
  (``tests/fixtures/oracle_interior_rates.json``): pymoo-oracle seeded
  determinism, quick-tier reproduction of the committed budget-100
  interior rates on the CPU mesh, and (slow tier) the full oracle-GA
  trajectory cross-check with zero survival mismatches.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.objective import (
    QUALITY_STAT_COLUMNS,
    engine_quality_stats,
)
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import (
    synth_lcld,
    synth_lcld_schema,
)
from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.observability import (
    interior_summary,
    quality_block,
    sample_from_per_state,
    telemetry_block,
    validate_quality,
    validate_record,
)
from moeva2_ijcai22_replication_tpu.observability.prom import prometheus_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# shared synthetic problem (module-scoped: engines own compiled programs)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def problem(tmp_path_factory):
    import joblib
    from sklearn.preprocessing import MinMaxScaler

    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    tmp = tmp_path_factory.mktemp("quality")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(12, cons.schema, seed=3)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=7))
    save_params(sur, str(tmp / "nn.msgpack"))
    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    joblib.dump(
        MinMaxScaler().fit(np.vstack([x, xl, xu])), tmp / "scaler.joblib"
    )
    return {
        "dir": tmp,
        "paths": paths,
        "constraints": cons,
        "surrogate": sur,
        "scaler": fit_minmax(x.min(0), x.max(0)),
        "x": x,
    }


def _engine(problem, **kw):
    kw.setdefault("n_gen", 21)
    kw.setdefault("n_pop", 16)
    kw.setdefault("n_offsprings", 8)
    kw.setdefault("seed", 5)
    kw.setdefault("archive_size", 4)
    return Moeva2(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        ml_scaler=problem["scaler"],
        norm=2,
        **kw,
    )


# ---------------------------------------------------------------------------
# units: the stats formula and the block builders
# ---------------------------------------------------------------------------


class TestQualityStats:
    #: f rows: [f1 prob, f2 dist, g sum]
    F = np.array(
        [
            [  # state 0: one full success (row 1)
                [0.9, 0.10, 0.0],
                [0.2, 0.05, 0.0],
                [0.1, 0.50, 2.0],
            ],
            [  # state 1: misclassified xor feasible, never both
                [0.2, 0.30, 1.0],
                [0.9, 0.01, 0.0],
                [0.6, 0.20, 3.0],
            ],
        ]
    )

    def test_numpy_per_state_columns(self):
        out = engine_quality_stats(self.F, 0.5, 0.25, xp=np)
        assert out.shape == (2, 9)
        assert len(QUALITY_STAT_COLUMNS) == 9
        # state 0: c any, m any, d any, cm, cd, md, cmd all true
        np.testing.assert_allclose(out[0, :7], 1.0)
        assert out[0, 7] == 0.0  # best_cv
        assert out[0, 8] == pytest.approx(0.05)  # best c∧m distance
        # state 1: no c∧m candidate -> o4..o7 partially off, dist inf
        np.testing.assert_allclose(out[1, :7], [1, 1, 1, 0, 1, 0, 0])
        assert out[1, 7] == 0.0
        assert np.isinf(out[1, 8])

    def test_jnp_matches_numpy(self):
        a = engine_quality_stats(self.F, 0.5, 0.25, xp=np)
        b = np.asarray(
            engine_quality_stats(jnp.asarray(self.F), 0.5, 0.25, xp=jnp)
        )
        np.testing.assert_allclose(a, b)

    def test_sample_aggregates_full_precision(self):
        ps = engine_quality_stats(self.F, 0.5, 0.25, xp=np)
        s = sample_from_per_state(7, ps)
        assert s["gen"] == 7
        # o7 rate = 1/2, full precision kept (no display rounding)
        assert s["success_frac"] == 0.5
        np.testing.assert_allclose(
            s["o_rates"], [1, 1, 1, 0.5, 1, 0.5, 0.5]
        )
        assert s["best_cv"] == 0.0 and s["mean_cv"] == 0.0
        assert s["best_dist"] == pytest.approx(0.05)
        # inf rows are excluded from the finite mean, not poisoning it
        assert s["mean_best_dist"] == pytest.approx(0.05)
        # the per-state array is a COPY (the engine mutates its buffer)
        ps[0, 0] = -1
        assert s["per_state"][0, 0] == 1.0

    def test_sample_with_no_success_has_null_dist(self):
        ps = engine_quality_stats(self.F[1:], 0.5, 0.25, xp=np)
        s = sample_from_per_state(1, ps)
        assert s["best_dist"] is None and s["mean_best_dist"] is None

    def test_interior_summary_picks_latest_at_or_below_budget(self):
        mk = lambda g: sample_from_per_state(  # noqa: E731
            g, engine_quality_stats(self.F, 0.5, 0.25, xp=np)
        )
        samples = [mk(50), mk(100), mk(250), mk(320)]
        samples.append(dict(mk(320), final=True))
        out = interior_summary(samples, budgets=(100, 300))
        assert out["100"]["gen"] == 100
        assert out["300"]["gen"] == 250  # latest non-final <= 300
        assert out["full"]["final"] is True
        assert all("per_state" not in v for v in out.values())
        # a trajectory that never REACHED a budget reports no point there:
        # labeling a 200-gen run's state as "@300" would compare different
        # budgets across records
        out2 = interior_summary([mk(200)], budgets=(100, 300))
        assert "300" not in out2
        assert "100" not in out2  # no sample at/below 100 either
        out3 = interior_summary([mk(100), mk(200)], budgets=(100, 300))
        assert out3["100"]["gen"] == 100 and "300" not in out3

    def test_quality_block_empty_is_schema_valid(self):
        b = quality_block()
        assert validate_quality(b) is b
        assert b["samples"] == 0 and b["curve"] == [] and b["interior"] == {}
        json.dumps(b)

    def test_quality_block_exports_curve_without_per_state(self):
        ps = engine_quality_stats(self.F, 0.5, 0.25, xp=np)
        eq = {
            "gate_every": 5,
            "threshold": 0.5,
            "eps": float("inf"),
            "archive_size": 2,
            "judged": "engine",
            "samples": [
                sample_from_per_state(5, ps),
                dict(sample_from_per_state(20, ps), final=True),
            ],
        }
        b = quality_block(eq, budgets=(5, 10))
        assert b["judged"] == "engine" and b["samples"] == 2
        assert all("per_state" not in s for s in b["curve"])
        assert b["interior"]["5"]["gen"] == 5
        assert b["eps"] is None  # inf is JSON-hostile; exported as null
        assert b["gate_every"] == 5 and b["archive_size"] == 2
        json.dumps(b)

    def test_quality_block_restart_curve_and_final(self):
        b = quality_block(
            restart_curve=[0.25, 0.5], final={"o_rates": [1] * 7},
            judged="post_hoc_f64",
        )
        assert b["restart_curve"] == [0.25, 0.5]
        assert b["final"]["o_rates"] == [1] * 7
        assert b["judged"] == "post_hoc_f64"

    def test_trim_quality_drops_pad_rows_and_recomputes(self):
        from moeva2_ijcai22_replication_tpu.observability import trim_quality

        ps = engine_quality_stats(self.F, 0.5, 0.25, xp=np)
        # pad row = duplicate of the all-success state 0: untrimmed rates
        # over-count it (the mesh-pad bias the runners must remove)
        padded = np.concatenate([ps, ps[:1]], axis=0)
        q = {
            "gate_every": 0, "judged": "engine",
            "samples": [dict(sample_from_per_state(3, padded), final=True)],
        }
        trimmed = trim_quality(q, 2)
        (s,) = trimmed["samples"]
        assert s["per_state"].shape == (2, 9) and s["final"]
        assert s["success_frac"] == 0.5  # padded would read 2/3
        assert trim_quality(None, 2) is None

    def test_validate_quality_rejects_wrong_shapes(self):
        with pytest.raises(ValueError, match="dict"):
            validate_quality([], "bench")
        with pytest.raises(ValueError, match="interior"):
            validate_quality({"judged": None, "samples": 0, "curve": []})


# ---------------------------------------------------------------------------
# engine capture: curves, bit-identity, the zero-overhead smoke
# ---------------------------------------------------------------------------


class TestEngineQuality:
    def test_strict_records_single_final_sample_bit_identically(self, problem):
        base = _engine(problem).generate(problem["x"], 1)
        assert base.quality is None
        res = _engine(problem, record_quality=True).generate(problem["x"], 1)
        np.testing.assert_array_equal(base.x_gen, res.x_gen)
        np.testing.assert_array_equal(base.f, res.f)
        q = res.quality
        assert q["judged"] == "engine" and q["gate_every"] == 0
        (final,) = q["samples"]
        assert final["final"] and final["gen"] == 20
        assert final["per_state"].shape == (12, 9)
        # final sample judges pop ∪ archive exactly like the result f
        expect = engine_quality_stats(
            np.asarray(res.f, np.float64), 0.5, np.inf, xp=np
        )
        np.testing.assert_allclose(final["per_state"], expect)

    def test_quality_every_curve_is_bit_identical(self, problem):
        base = _engine(problem).generate(problem["x"], 1)
        eng = _engine(problem, record_quality=True, quality_every=5)
        res = eng.generate(problem["x"], 1)
        np.testing.assert_array_equal(base.x_gen, res.x_gen)
        gens = [s["gen"] for s in res.quality["samples"]]
        assert gens == [5, 10, 15, 20]
        assert res.quality["samples"][-1]["final"]
        # success is cumulative under an archive: the curve's success_frac
        # is monotone non-decreasing
        sf = [s["success_frac"] for s in res.quality["samples"]]
        assert all(a <= b + 1e-12 for a, b in zip(sf, sf[1:]))

    def test_quality_toggle_zero_extra_compiles_dispatches(self, problem):
        """THE acceptance smoke: with gates present (early exit), quality
        capture on/off shares every executable and every dispatch, and the
        results are bit-identical — the gate program computes the stats
        unconditionally, the knob only keeps/drops host-side fetches."""
        runs = {}
        for on in (False, True):
            eng = _engine(problem, early_stop_check_every=5,
                          record_quality=on)
            res = eng.generate(problem["x"], 1)
            runs[on] = (eng, res)
        eng_off, res_off = runs[False]
        eng_on, res_on = runs[True]
        np.testing.assert_array_equal(res_off.x_gen, res_on.x_gen)
        np.testing.assert_array_equal(res_off.f, res_on.f)
        # zero extra compiles (trace_count) AND zero extra dispatches
        # (LedgeredJit call counts, per program)
        assert eng_on.trace_count == eng_off.trace_count
        for name in ("_jit_init", "_jit_segment", "_jit_success"):
            assert (
                getattr(eng_on, name).calls == getattr(eng_off, name).calls
            ), name
        assert res_off.quality is None
        assert res_on.quality is not None
        # gate samples ride the early-exit cadence + the final sample
        assert [s["gen"] for s in res_on.quality["samples"][:-1]] == [
            5, 10, 15,
        ]

    def test_history_full_precision_event_rounded(self, problem):
        """Satellite: the recorded history keeps full-precision
        success_frac; the trace-event payload rounds to 4 digits."""
        from moeva2_ijcai22_replication_tpu.observability import (
            Trace,
            TraceRecorder,
        )

        rec = TraceRecorder(spans_enabled=True)
        eng = _engine(problem, record_quality=True, quality_every=5)
        eng.trace = Trace(rec, trace_id="t-qual")
        res = eng.generate(problem["x"], 1)
        sample = res.quality["samples"][0]
        # 12 states: any non-trivial rate has a repeating binary/decimal
        # expansion (k/12) that 4-digit rounding would destroy
        expect = float(sample["per_state"][:, 6].mean())
        assert sample["success_frac"] == expect
        ev = [e for e in rec.events() if e.get("name") == "moeva.quality"]
        assert ev and all(
            e["attrs"]["success_frac"]
            == round(e["attrs"]["success_frac"], 4)
            for e in ev
        )

    def test_chunked_run_merges_per_gate_samples(self, problem):
        eng = _engine(
            problem, record_quality=True, quality_every=5,
            max_states_per_call=8,
        )
        res = eng.generate(problem["x"], 1)
        gens = [s["gen"] for s in res.quality["samples"]]
        assert gens == [5, 10, 15, 20]
        for s in res.quality["samples"]:
            assert s["per_state"].shape == (12, 9)
        # merged aggregate == aggregate of merged per-state rows
        s0 = res.quality["samples"][0]
        np.testing.assert_allclose(
            s0["o_rates"], s0["per_state"][:, :7].mean(axis=0)
        )


class TestPgdQuality:
    def test_restart_curve_monotone(self, problem):
        from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD

        x = problem["x"]
        scaler = problem["scaler"]
        xs = np.asarray(scaler.transform(x))
        y = np.asarray(
            problem["surrogate"].predict_proba(xs)
        ).argmax(-1)
        pgd = ConstrainedPGD(
            classifier=problem["surrogate"],
            constraints=problem["constraints"],
            scaler=scaler, eps=0.3, eps_step=0.1, max_iter=5,
            norm=np.inf, seed=1, num_random_init=3,
        )
        pgd.generate(xs, y)
        curve = pgd.quality_history["restart_flip_frac"]
        assert len(curve) == 3
        assert all(0.0 <= v <= 1.0 for v in curve)
        assert all(a <= b + 1e-9 for a, b in zip(curve, curve[1:]))
        # the per-row mask is exposed so padded batches can be trimmed
        # without bias (runner contract); rows are cumulative-monotone
        succ = pgd.quality_history["restart_success"]
        assert succ.shape == (3, len(xs)) and succ.dtype == bool
        assert (succ[:-1] <= succ[1:]).all()
        np.testing.assert_allclose(curve, succ.mean(axis=1))

    def test_no_restarts_no_history(self, problem):
        from moeva2_ijcai22_replication_tpu.attacks.pgd import ConstrainedPGD

        xs = np.asarray(problem["scaler"].transform(problem["x"]))
        y = np.zeros(len(xs), np.int32)
        pgd = ConstrainedPGD(
            classifier=problem["surrogate"],
            constraints=problem["constraints"],
            scaler=problem["scaler"], eps=0.3, eps_step=0.1, max_iter=5,
            norm=np.inf, seed=1,
        )
        pgd.generate(xs, y)
        assert pgd.quality_history is None


# ---------------------------------------------------------------------------
# record schema + serving surfaces + Prometheus exposition
# ---------------------------------------------------------------------------


class TestQualityRecords:
    def test_telemetry_block_carries_quality_by_default(self):
        block = telemetry_block()
        assert validate_quality(block["quality"])["samples"] == 0
        rec = {"execution": {}, "telemetry": block}
        assert validate_record(rec) is rec

    def test_producers_assemble_quality(self):
        """Repo-source check: every record producer routes a quality block
        into its telemetry — a refactor dropping it fails here before it
        can silently drop it from committed records."""
        producers = (
            "bench.py",
            "moeva2_ijcai22_replication_tpu/experiments/moeva.py",
            "moeva2_ijcai22_replication_tpu/experiments/pgd.py",
            "moeva2_ijcai22_replication_tpu/experiments/pipeline.py",
            "moeva2_ijcai22_replication_tpu/serving/sweep.py",
        )
        for fname in producers:
            with open(os.path.join(REPO, fname)) as fh:
                src = fh.read()
            assert "quality_block(" in src, fname

    def test_serving_quality_gauges_healthz_prom_trace(self, problem):
        from moeva2_ijcai22_replication_tpu.observability import TraceRecorder
        from moeva2_ijcai22_replication_tpu.serving import (
            AttackRequest,
            AttackService,
        )

        tmp = problem["dir"]
        domain = {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": problem["paths"]["features"],
                "constraints": problem["paths"]["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
            "n_pop": 8,
            "n_offsprings": 4,
        }
        rec = TraceRecorder(spans_enabled=True)
        svc = AttackService(
            {"lcld": domain}, bucket_sizes=(4, 8), max_delay_s=0.001,
            recorder=rec,
        )
        try:
            resp = svc.attack(
                AttackRequest(
                    domain="lcld", x=problem["x"][:4], attack="moeva",
                    budget=4,
                ),
                timeout=300.0,
            )
            assert resp.x_adv.shape[0] == 4
            # /healthz + snapshot carry the per-domain quality state
            hq = svc.healthz()["quality"]["by_domain"]["lcld"]
            assert hq["batches"] >= 1
            assert len(hq["last"]["o_rates"]) == 7
            snap = svc.metrics_snapshot()
            assert snap["quality"]["by_domain"]["lcld"]["last"]["gen"] == 3
            assert "quality_success_frac_lcld" in snap["gauges"]
            # labeled Prometheus gauges, with HELP/TYPE headers
            text = prometheus_text(snap)
            assert '# HELP moeva2_quality_o_rate ' in text
            assert '# TYPE moeva2_quality_o_rate gauge' in text
            assert 'moeva2_quality_o_rate{domain="lcld",objective="o7"}' in text
            assert 'moeva2_quality_batches{domain="lcld"}' in text
            # the batch trace carried a quality event (adopted into the
            # request's correlated stream -> meta.trace consumers see it)
            assert any(e.get("name") == "quality" for e in rec.events())
        finally:
            svc.close()

    def test_prom_every_family_has_help_and_type(self):
        snap = {
            "counters": {"requests": 3},
            "gauges": {"queue_depth": 2.0},
            "streams": {"latency_s": {"count": 2, "mean": 0.1, "p50": 0.1,
                                      "p99": 0.2, "max": 0.2}},
            "resolved_run_configs": 1,
            "engine_cache": {"hits": 1, "misses": 2},
            "cost_ledger": {
                "executables": 1,
                "entries": [
                    {"key": "k", "producer": "p", "flops": 1.0,
                     "compile_s": 0.5}
                ],
            },
            "quality": {
                "by_domain": {
                    "lcld": {
                        "batches": 2,
                        "last": {"gen": 9, "o_rates": [1, 0.5, 1, 0.5, 1,
                                                       0.5, 0.25],
                                 "best_cv": 0.0, "mean_cv": 0.1,
                                 "best_dist": 0.05},
                    }
                }
            },
        }
        text = prometheus_text(snap)
        families = set()
        helped, typed = set(), set()
        for line in text.splitlines():
            if line.startswith("# HELP "):
                helped.add(line.split()[2])
            elif line.startswith("# TYPE "):
                typed.add(line.split()[2])
            elif line and not line.startswith("#"):
                name = line.split("{")[0].split(" ")[0]
                # summary sample suffixes belong to the base family
                for suffix in ("_count", "_sum"):
                    if name.endswith(suffix) and name[: -len(suffix)] in typed:
                        name = name[: -len(suffix)]
                families.add(name)
        missing_help = families - helped
        missing_type = families - typed
        assert not missing_help, f"families without # HELP: {missing_help}"
        assert not missing_type, f"families without # TYPE: {missing_type}"
        # and quantile'd summaries render under their base family
        assert 'moeva2_latency_s{quantile="0.5"}' in text


# ---------------------------------------------------------------------------
# bench_diff: the perf+quality watchdog
# ---------------------------------------------------------------------------


def _write(tmp_path, name, rec):
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


def _qrecord(
    steady=10.0, o2_100=0.20, o7_100=0.08, o2_300=0.95, botnet=None, value=50.0
):
    """A bench-shaped record with a quality block at interior budgets."""
    mk = lambda o2, o7: {"gen": 0, "o_rates": [1, o2, 1, o7, 1, o7, o7]}  # noqa: E731
    rec = {
        "steady_s": steady,
        "value": value,
        "execution": {"n_states": 1000, "n_gen": 1000},
        "telemetry": {
            "quality": {
                "judged": "engine",
                "samples": 3,
                "curve": [],
                "interior": {
                    "100": dict(mk(o2_100, o7_100), gen=100),
                    "300": dict(mk(o2_300, o7_100), gen=300),
                    "full": dict(mk(1.0, 1.0), gen=999, final=True),
                },
            }
        },
    }
    if botnet is not None:
        rec["real_botnet"] = {
            "steady_s": 5.0, "n_states": 387, "n_gen": 1000,
            "quality": {
                "judged": "engine", "samples": 2, "curve": [],
                # both interior budgets: the committed r06 botnet block
                # carries @100 AND @300, and a successor must keep every
                # armed metric (absent-in-latest fails as capture loss)
                "interior": {
                    "100": dict(mk(*botnet), gen=100),
                    "300": dict(mk(0.632, 0.245), gen=300),
                },
            },
        }
    return rec


class TestBenchDiffQuality:
    @pytest.fixture(scope="class")
    def bench_diff(self):
        return _load_tool("bench_diff")

    def test_interior_drift_fails_like_a_perf_regression(
        self, bench_diff, tmp_path
    ):
        a = _write(tmp_path, "r01.json", _qrecord(o2_100=0.20))
        b = _write(tmp_path, "r02.json", _qrecord(o2_100=0.05))
        assert bench_diff.main([a, b]) == 1  # 0.15 abs drop > 0.10

    def test_small_drift_within_threshold_passes(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _qrecord(o2_100=0.20))
        b = _write(tmp_path, "r02.json", _qrecord(o2_100=0.15))
        assert bench_diff.main([a, b]) == 0
        assert bench_diff.main([a, b, "--quality-threshold", "0.02"]) == 1

    def test_improvement_passes(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _qrecord(o2_100=0.20))
        b = _write(tmp_path, "r02.json", _qrecord(o2_100=0.60))
        assert bench_diff.main([a, b]) == 0

    def test_real_botnet_quality_is_tracked(self, bench_diff, tmp_path):
        a = _write(
            tmp_path, "r01.json", _qrecord(botnet=(0.199, 0.080))
        )
        b = _write(
            tmp_path, "r02.json", _qrecord(botnet=(0.02, 0.080))
        )
        assert bench_diff.main([a, b]) == 1

    def test_full_budget_rates_are_not_gated(self, bench_diff, tmp_path):
        """The saturated full-budget numbers stay untracked — they are the
        blind spot this watchdog replaces, not a metric."""
        a = _write(tmp_path, "r01.json", _qrecord())
        rec = _qrecord()
        rec["telemetry"]["quality"]["interior"]["full"]["o_rates"] = [0] * 7
        b = _write(tmp_path, "r02.json", rec)
        assert bench_diff.main([a, b]) == 0

    def test_lost_quality_capture_fails(self, bench_diff, tmp_path):
        """Once a baseline carries interior rates, a latest record WITHOUT
        them fails — dropping quality capture must not disarm the gate."""
        a = _write(tmp_path, "r01.json", _qrecord())
        b = _write(
            tmp_path, "r02.json",
            {"steady_s": 10.0, "value": 50.0,
             "execution": {"n_states": 1000, "n_gen": 1000},
             "telemetry": {}},
        )
        assert bench_diff.main([a, b]) == 1

    def test_losing_one_quality_block_fails(self, bench_diff, tmp_path):
        """Per-BLOCK capture loss is caught too: a latest record that kept
        its headline quality but dropped real_botnet.quality (e.g. the
        botnet step crashed and bench silently skipped it) fails — that
        block guards the adjudicated trajectory."""
        a = _write(tmp_path, "r01.json", _qrecord(botnet=(0.199, 0.080)))
        b = _write(tmp_path, "r02.json", _qrecord())  # headline only
        assert bench_diff.main([a, b]) == 1

    def test_sample_gen_mismatch_fails_not_compares(
        self, bench_diff, tmp_path
    ):
        """Samples taken at different generations never compare as one
        metric: a cadence change relabels a gen-150 sample as '@300', which
        would fake (or mask) a drift — the mismatch itself fails."""
        a = _write(tmp_path, "r01.json", _qrecord())
        rec = _qrecord(o2_100=0.20)
        rec["telemetry"]["quality"]["interior"]["100"]["gen"] = 50
        b = _write(tmp_path, "r02.json", rec)
        assert bench_diff.main([a, b]) == 1

    def test_pre_quality_records_skip_not_fail(self, bench_diff, tmp_path):
        old = _write(
            tmp_path, "r01.json",
            {"steady_s": 10.0, "value": 50.0,
             "execution": {"n_states": 1000, "n_gen": 1000},
             "telemetry": {}},
        )
        new = _write(tmp_path, "r02.json", _qrecord(steady=10.0))
        assert bench_diff.main([old, new]) == 0

    def test_json_output_is_machine_readable(
        self, bench_diff, tmp_path, capsys
    ):
        a = _write(tmp_path, "r01.json", _qrecord(o2_100=0.20))
        b = _write(tmp_path, "r02.json", _qrecord(o2_100=0.05, steady=11.0))
        rc = bench_diff.main([a, b, "--json"])
        out = capsys.readouterr().out
        # human lines unchanged, JSON on the last line
        assert "** REGRESSION **" in out
        doc = json.loads(out.strip().splitlines()[-1])
        assert rc == 1 and doc["regressed"] is True
        by_metric = {m["metric"]: m for m in doc["metrics"]}
        q = by_metric["quality.interior@100.o2"]
        assert q["verdict"] == "regression" and q["basis"] == "absolute"
        assert q["delta_abs"] == pytest.approx(-0.15)
        s = by_metric["steady_s"]
        assert s["kind"] == "perf" and "basis" in s and "delta_rel" in s

    def test_committed_series_with_quality_stays_green(
        self, bench_diff, tmp_path
    ):
        """A quality-bearing record appended to the committed (pre-quality)
        series passes: no earlier record is comparable on quality, and the
        perf metrics normalize as before."""
        import glob as _glob
        import shutil

        for p in sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
            shutil.copy(p, tmp_path / os.path.basename(p))
        nxt = _write(
            tmp_path, "BENCH_r99.json",
            {
                "n": 99,
                "rc": 0,
                # botnet quality included: r06 armed that block, and a
                # successor dropping it would fail as capture loss
                "parsed": _qrecord(
                    steady=9.0, value=80.0, botnet=(0.199, 0.080)
                ),
            },
        )
        series = sorted(str(p) for p in tmp_path.glob("BENCH_r*.json"))
        assert nxt in series
        assert bench_diff.main(["--check", *series]) == 0


# ---------------------------------------------------------------------------
# oracle parity: seeded determinism + the committed fixture
# ---------------------------------------------------------------------------


class TestOracleDeterminism:
    def test_same_seed_identical_survival_order(self):
        from oracles import pymoo_rnsga3 as oracle

        rng = np.random.default_rng(17)
        f = rng.uniform(size=(24, 3))
        asp = rng.dirichlet(np.ones(3), size=8)
        k1 = np.full((1, 3), 1.0 / 3)

        def run(seed):
            st = oracle.OracleNormState(3)
            idx, _ = oracle.aspiration_survive(
                f, asp, k1, 12, st, np.random.RandomState(seed)
            )
            return list(idx)

        # same RandomState seed -> identical survivor ORDER (not just set)
        assert run(123) == run(123)
        assert run(7) == run(7)
        # and the RNG actually matters on this random-niching case
        outcomes = {tuple(run(s)) for s in range(6)}
        assert len(outcomes) > 1


@pytest.fixture(scope="module")
def oracle_fixture():
    path = os.path.join(FIXTURES, "oracle_interior_rates.json")
    with open(path) as fh:
        return json.load(fh)


class TestOracleFixture:
    def test_fixture_is_interior_and_parity_holds(self, oracle_fixture):
        """Data pins on the committed numbers themselves: the tracked
        columns are strictly interior (a saturated fixture once let a
        behaviour-altering fix through), every oracle trail has zero
        mismatches, and the engine mean sits inside the oracle band."""
        doms = oracle_fixture["domains"]
        assert "lcld_synth" in doms
        for name, d in doms.items():
            cfg = d["config"]
            for col in cfg["interior_columns"]:
                v = d["engine"]["mean"][col]
                assert 0.0 < v < 1.0, (name, col, v)
            for seed, o in (d.get("oracle_ga") or {}).items():
                if seed == "mean":
                    continue
                assert o["mismatches"] == [], (name, seed)
                assert o["rounds_checked"] > 100
            if "parity" in d:
                assert (
                    d["parity"]["max_abs_mean_delta"]
                    <= d["parity"]["tolerance"]
                )

    def test_lcld_synth_engine_rates_reproduce(self, oracle_fixture):
        """Quick tier: the committed budget-100 interior rates reproduce
        bit-for-bit on the CPU mesh (seed 42; the full seed set runs in
        the slow tier with the oracle)."""
        oc = _load_tool("oracle_check")
        d = oracle_fixture["domains"]["lcld_synth"]
        assert d["config"] == oc.DOMAINS["lcld_synth"], (
            "fixture config drifted from tools/oracle_check.py — rerun "
            "--regen and commit"
        )
        problem = oc.build_lcld_synth(oc.DOMAINS["lcld_synth"])
        rates = oc.engine_rates(problem, oc.DOMAINS["lcld_synth"], 42)
        np.testing.assert_allclose(rates, d["engine"]["42"], atol=0)

    @pytest.mark.slow
    def test_lcld_synth_oracle_ga_cross_check(self, oracle_fixture):
        """Slow tier: rerun the f64 oracle-GA trajectory at seed 42 — the
        final rates must match the committed fixture and every compared
        survival round must match the pymoo oracle exactly (the oracle
        replay is read-only, so checking a state subset still reproduces
        the full rates)."""
        oc = _load_tool("oracle_check")
        cfg = oc.DOMAINS["lcld_synth"]
        problem = oc.build_lcld_synth(cfg)
        out = oc.oracle_ga_rates(
            problem, cfg, 42, check_states=np.arange(4)
        )
        want = oracle_fixture["domains"]["lcld_synth"]["oracle_ga"]["42"]
        np.testing.assert_allclose(out["o_rates"], want["o_rates"], atol=0)
        assert out["mismatches"] == []
        assert out["rounds_checked"] > 100

    @pytest.mark.slow
    def test_botnet_engine_rates_reproduce(self, oracle_fixture):
        """Slow tier: the real-artifact budget-100 botnet rates (48
        states) reproduce on the CPU mesh."""
        oc = _load_tool("oracle_check")
        d = oracle_fixture["domains"].get("botnet")
        if d is None:
            pytest.skip("botnet domain not in fixture (no reference tree)")
        problem = oc.build_botnet(oc.DOMAINS["botnet"])
        if problem is None:
            pytest.skip("reference artifacts not available")
        rates = oc.engine_rates(problem, oc.DOMAINS["botnet"], 42)
        np.testing.assert_allclose(rates, d["engine"]["42"], atol=0)
