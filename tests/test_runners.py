"""End-to-end experiment-runner tests on small synthetic LCLD artifacts.

Covers the L4/L5 parity surface: MoEvA runner (``04_moeva.py``), PGD/SAT
runner (``01_pgd_united.py``), skip-if-done idempotency, metrics JSON
schema, and the RQ grid runner.
"""

import json
import os

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.objective import O_COLUMNS
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.experiments import moeva as moeva_runner
from moeva2_ijcai22_replication_tpu.experiments import pgd as pgd_runner
from moeva2_ijcai22_replication_tpu.experiments import rq as rq_runner
from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.utils.config import get_dict_hash


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory, lcld_paths):
    """Tiny but complete artifact family: candidates, model, scaler."""
    tmp = tmp_path_factory.mktemp("artifacts")
    cons = LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])
    x = synth_lcld(8, cons.schema, seed=3)
    np.save(tmp / "x_candidates.npy", x)

    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=1))
    save_params(sur, str(tmp / "nn.msgpack"))

    # Scaler fit over feature bounds ∪ data (01_train_robust.py:50-66) so
    # attacked points stay inside the unit box.
    from sklearn.preprocessing import MinMaxScaler
    import joblib

    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    scaler = MinMaxScaler().fit(np.vstack([x, xl, xu]))
    joblib.dump(scaler, tmp / "scaler.joblib")
    return dict(dir=tmp, paths=lcld_paths)


def base_config(artifacts, out_dir, **over):
    tmp = artifacts["dir"]
    cfg = {
        "project_name": "lcld",
        "attack_name": "moeva",
        "paths": {
            "model": str(tmp / "nn.msgpack"),
            "features": artifacts["paths"]["features"],
            "constraints": artifacts["paths"]["constraints"],
            "x_candidates": str(tmp / "x_candidates.npy"),
            "ml_scaler": str(tmp / "scaler.joblib"),
        },
        "dirs": {"results": str(out_dir)},
        "misclassification_threshold": 0.25,
        "norm": 2,
        "n_initial_state": -1,
        "initial_state_offset": 0,
        "system": {"n_jobs": 1, "verbose": 0},
        "save_history": False,
        "reconstruction": False,
        "seed": 42,
        "budget": 4,
        "n_pop": 16,
        "n_offsprings": 8,
        "eps_list": [0.5],
    }
    for k, v in over.items():
        cfg[k] = v
    return cfg


class TestMoevaRunner:
    def test_end_to_end_and_skip(self, artifacts, tmp_path):
        cfg = base_config(artifacts, tmp_path / "out")
        metrics = moeva_runner.run(cfg)

        h = get_dict_hash(cfg)
        out = str(tmp_path / "out")
        # metrics JSON schema parity (04_moeva.py:133-139)
        assert set(metrics) >= {"objectives_list", "time", "config", "config_hash"}
        assert metrics["config_hash"] == h
        assert len(metrics["objectives_list"]) == 1
        assert set(metrics["objectives_list"][0]) == set(O_COLUMNS)
        for name in [
            f"metrics_moeva_{h}.json",
            f"x_attacks_moeva_{h}.npy",
            f"config_moeva_{h}.yaml",
        ]:
            assert os.path.exists(os.path.join(out, name)), name

        x_attacks = np.load(os.path.join(out, f"x_attacks_moeva_{h}.npy"))
        assert x_attacks.shape[0] == 8 and x_attacks.ndim == 3

        with open(os.path.join(out, f"metrics_moeva_{h}.json")) as f:
            on_disk = json.load(f)
        assert on_disk["objectives_list"] == metrics["objectives_list"]

        # idempotency: same config hash -> skip (04_moeva.py:31-36)
        assert moeva_runner.run(cfg) is None

    def test_history_artifact(self, artifacts, tmp_path):
        cfg = base_config(artifacts, tmp_path / "out", save_history="reduced")
        metrics = moeva_runner.run(cfg)
        h = metrics["config_hash"]
        hist = np.load(tmp_path / "out" / f"x_history_moeva_{h}.npy")
        # (n_gen-1, S, n_off, 3) objective history per generation
        assert hist.shape == (3, 8, 8, 3)


class TestPgdRunner:
    def test_flip(self, artifacts, tmp_path):
        cfg = base_config(
            artifacts,
            tmp_path / "out",
            attack_name="pgd",
            budget=5,
        )
        cfg["eps"] = 0.2
        cfg["loss_evaluation"] = "flip"
        metrics = pgd_runner.run(cfg)
        h = metrics["config_hash"]
        out = str(tmp_path / "out")
        assert set(metrics["objectives"]) == set(O_COLUMNS)
        for name in [
            f"metrics_pgd_flip_{h}.json",
            f"x_attacks_pgd_flip_{h}.npy",
            f"success_rate_pgd_flip_{h}.csv",
        ]:
            assert os.path.exists(os.path.join(out, name)), name
        x_attacks = np.load(os.path.join(out, f"x_attacks_pgd_flip_{h}.npy"))
        assert x_attacks.shape == (8, 1, 47)
        assert pgd_runner.run(cfg) is None

    def test_flip_sat_chain(self, artifacts, tmp_path):
        """PGD -> SAT hot-start chain with ε-halving (01_pgd_united.py:97-154):
        the SAT stage must return constraint-satisfying candidates."""
        cfg = base_config(
            artifacts,
            tmp_path / "out",
            attack_name="pgd",
            budget=5,
        )
        cfg["eps"] = 0.4
        cfg["loss_evaluation"] = "flip+sat"
        metrics = pgd_runner.run(cfg)
        # o1 (constraint satisfaction) must be perfect after MILP repair
        assert metrics["objectives"]["o1"] == pytest.approx(1.0)

    def test_loss_history(self, artifacts, tmp_path):
        cfg = base_config(
            artifacts,
            tmp_path / "out",
            attack_name="pgd",
            budget=6,
            save_history="full",
        )
        cfg["eps"] = 0.2
        cfg["loss_evaluation"] = "constraints+flip"
        metrics = pgd_runner.run(cfg)
        h = metrics["config_hash"]
        hist = np.load(tmp_path / "out" / f"x_history_{h}.npy")
        # (N, max_iter, 1, C): columns [loss, loss_class, cons_sum, g_1..g_10]
        # for "full" on LCLD (classifier.py:276-296 layout)
        assert hist.shape == (8, 6, 1, 13)
        assert np.isfinite(hist).all()
        # combined loss must equal class - constraints under constraints+flip
        np.testing.assert_allclose(
            hist[..., 0, 0],
            hist[..., 0, 1] - hist[..., 0, 2],
            rtol=1e-5, atol=1e-6,
        )


class TestExecutionMetadata:
    """Every metrics JSON must carry the RNG-affecting execution mode of its
    number (VERDICT r5 item 8): chunk size, mesh shape, and whether the
    reference-schema ``time`` includes compile — round-tripped through the
    on-disk file."""

    def test_pgd_metrics_execution_roundtrip(self, artifacts, tmp_path):
        cfg = base_config(
            artifacts, tmp_path / "out", attack_name="pgd", budget=3
        )
        cfg["eps"] = 0.15
        cfg["loss_evaluation"] = "flip"
        metrics = pgd_runner.run(cfg)
        h = metrics["config_hash"]
        with open(tmp_path / "out" / f"metrics_pgd_flip_{h}.json") as f:
            on_disk = json.load(f)
        for m in (metrics, on_disk):
            # PGD dispatches one batch, no chunking; this config has no mesh
            assert m["execution"] == {"max_states_per_call": None, "mesh": None}
            # the flag must agree with the compile/run span attribution
            # (engine caching makes cold-vs-warm order-dependent, so the
            # test pins consistency, not a specific value)
            assert isinstance(m["includes_compile"], bool)
            assert m["includes_compile"] == ("attack_compile" in m["timings"])
        assert on_disk["execution"] == metrics["execution"]
        assert on_disk["includes_compile"] == metrics["includes_compile"]

    def test_moeva_metrics_execution_roundtrip(self, artifacts, tmp_path):
        cfg = base_config(artifacts, tmp_path / "out", budget=3)
        cfg["max_states_per_call"] = 6
        metrics = moeva_runner.run(cfg)
        h = metrics["config_hash"]
        with open(tmp_path / "out" / f"metrics_moeva_{h}.json") as f:
            on_disk = json.load(f)
        for m in (metrics, on_disk):
            # no mesh -> the configured chunk is used as-is; default strict
            # mode -> every chunk runs its full budget (2 chunks x 2 steps)
            assert m["execution"] == {
                "max_states_per_call": 6,
                "mesh": None,
                "early_stop_check_every": 0,
                "gens_executed": 4,
            }
            assert m["includes_compile"] == ("attack_compile" in m["timings"])

    def test_moeva_early_stop_knob_lands_in_execution(self, artifacts, tmp_path):
        """An early-exit run's metrics carry the knob and the (possibly
        reduced) generation count — the execution mode must travel with the
        committed number exactly like chunk size and mesh shape."""
        cfg = base_config(artifacts, tmp_path / "out", budget=5)
        cfg["early_stop_check_every"] = 2
        cfg["archive_size"] = 4
        metrics = moeva_runner.run(cfg)
        h = metrics["config_hash"]
        with open(tmp_path / "out" / f"metrics_moeva_{h}.json") as f:
            on_disk = json.load(f)
        for m in (metrics, on_disk):
            ex = m["execution"]
            assert ex["early_stop_check_every"] == 2
            assert 0 < ex["gens_executed"] <= 4
        assert on_disk["execution"] == metrics["execution"]


class TestGridRunner:
    def test_rq1_shaped_grid(self, artifacts, tmp_path):
        """Compose attack+project configs per grid point, launch in-process,
        write one metrics file per point (run_rq1.py parity)."""
        import yaml

        config_dir = tmp_path / "config"
        config_dir.mkdir()
        out_dir = tmp_path / "out"

        point = base_config(artifacts, out_dir)
        for key in ("attack_name", "budget", "seed", "eps_list", "n_pop", "n_offsprings"):
            point.pop(key)
        (config_dir / "moeva.yaml").write_text(
            yaml.dump({"attack_name": "moeva", "n_pop": 16, "n_offsprings": 8})
        )
        (config_dir / "pgd.yaml").write_text(
            yaml.dump({"attack_name": "pgd", "constraints_optim": "sum"})
        )
        (config_dir / "proj.static.yaml").write_text(yaml.dump(point))

        grid = {
            "config_dir": str(config_dir),
            "attacks": ["moeva", "pgd"],
            "seeds": [42],
            "projects": ["proj.static"],
            "eps_list": [0.5],
            "budgets": [3],
            "loss_evaluations": ["flip"],
        }
        n = rq_runner.run(grid)
        assert n == 2  # one moeva + one pgd point
        names = os.listdir(out_dir)
        assert sum(s.startswith("metrics_moeva_") for s in names) == 1
        assert sum(s.startswith("metrics_pgd_flip_") for s in names) == 1

        # relaunching the grid skips every point but still counts launches
        assert rq_runner.run(grid) == 2
        assert sum(s.startswith("metrics_") for s in os.listdir(out_dir)) == 2


class TestGridFailureIsolation:
    def test_poisoned_point_continues_in_process(self, artifacts, tmp_path):
        """One broken grid point (bad model path) must not kill the sweep —
        in-process mode now matches subprocess-mode isolation."""
        import yaml

        config_dir = tmp_path / "config"
        config_dir.mkdir()
        out_dir = tmp_path / "out"

        good = base_config(artifacts, out_dir)
        for key in ("attack_name", "budget", "seed", "eps_list", "n_pop", "n_offsprings"):
            good.pop(key)
        bad = dict(good)
        bad["paths"] = dict(good["paths"], model=str(tmp_path / "missing.msgpack"))
        (config_dir / "moeva.yaml").write_text(
            yaml.dump({"attack_name": "moeva", "n_pop": 16, "n_offsprings": 8})
        )
        (config_dir / "poisoned.static.yaml").write_text(yaml.dump(bad))
        (config_dir / "good.static.yaml").write_text(yaml.dump(good))

        grid = {
            "config_dir": str(config_dir),
            "attacks": ["moeva"],
            "seeds": [42],
            "projects": ["poisoned.static", "good.static"],
            "eps_list": [0.5],
            "budgets": [3],
            "loss_evaluations": [],
        }
        n = rq_runner.run(grid)
        assert n == 2
        names = os.listdir(out_dir)
        # the good point produced metrics even though the poisoned one failed
        assert sum(s.startswith("metrics_moeva_") for s in names) == 1


class TestStreaming:
    def test_pgd_runner_streams_events(self, artifacts, tmp_path):
        from moeva2_ijcai22_replication_tpu.utils.streaming import read_events

        cfg = base_config(
            artifacts, tmp_path / "out",
            attack_name="pgd", budget=4,
            save_history="reduced",
        )
        cfg["eps"] = 0.2
        cfg["loss_evaluation"] = "constraints+flip"
        cfg["streaming"] = True
        cfg["save_grad_norm"] = True
        metrics = pgd_runner.run(cfg)
        h = metrics["config_hash"]
        evs = list(
            read_events(tmp_path / "out" / f"events_pgd_constraints+flip_{h}.jsonl")
        )
        names = {e.get("name") for e in evs if e["event"] == "metric"}
        # final rates + the streamed per-iteration curves incl. grad norms
        assert {"o7", "time", "mean_loss", "mean_grad_norm"} <= names
        curve = [e for e in evs if e.get("name") == "mean_loss"]
        assert len(curve) == 4  # one event per iteration

    def test_moeva_runner_streams_events(self, artifacts, tmp_path):
        from moeva2_ijcai22_replication_tpu.utils.streaming import read_events

        cfg = base_config(artifacts, tmp_path / "out", streaming=True)
        metrics = moeva_runner.run(cfg)
        h = metrics["config_hash"]
        evs = list(read_events(tmp_path / "out" / f"events_moeva_{h}.jsonl"))
        names = {e.get("name") for e in evs if e["event"] == "metric"}
        assert "eps0.5_o7" in names and "time" in names


class TestRunAll:
    def test_composition_over_committed_configs(self, monkeypatch):
        """run_all must dispatch every committed grid/rq4 YAML in the
        reference's run_all.sh order (all 12 files must parse)."""
        from moeva2_ijcai22_replication_tpu.experiments import run_all

        calls = []
        monkeypatch.setattr(
            run_all.rq, "run", lambda cfg: calls.append(("rq", cfg.get("projects")))
        )
        monkeypatch.setattr(
            run_all.moeva, "run",
            lambda cfg: calls.append(("moeva", cfg["attack_name"])),
        )
        import pathlib

        config_dir = pathlib.Path(__file__).resolve().parents[1] / "config"
        run_all.run(str(config_dir))
        kinds = [k for k, _ in calls]
        assert kinds == ["rq"] * 6 + ["moeva"] * 2 + ["rq"] * 4
        # every grid carried its project list; rq4 points are moeva attacks
        assert all(p for k, p in calls if k == "rq")


class TestMeshPadding:
    """Data-dependent candidate counts (e.g. the 387-row botnet set) must not
    crash mesh-sharded runs: runners pad the states axis to a mesh multiple
    and trim every per-state artifact back."""

    def test_moeva_runner_pads_indivisible_candidates(self, artifacts, tmp_path):
        cfg = base_config(
            artifacts, tmp_path / "out", n_initial_state=5, save_history="reduced"
        )
        cfg["system"] = {"n_jobs": 1, "verbose": 0, "mesh_devices": -1}
        metrics = moeva_runner.run(cfg)
        assert metrics is not None
        h = get_dict_hash(cfg)
        x_att = np.load(tmp_path / "out" / f"x_attacks_moeva_{h}.npy")
        assert x_att.shape[0] == 5
        hist = np.load(tmp_path / "out" / f"x_history_moeva_{h}.npy")
        assert hist.shape[1] == 5
        # the mesh shape travels with the committed number (VERDICT r5 item 8)
        assert metrics["execution"]["mesh"] == {
            "devices": 8, "shape": [8], "axes": ["states"],
        }

    def test_pgd_runner_pads_indivisible_candidates(self, artifacts, tmp_path):
        cfg = base_config(
            artifacts,
            tmp_path / "out",
            attack_name="pgd",
            budget=3,
            n_initial_state=5,
        )
        cfg["system"] = {"n_jobs": 1, "verbose": 0, "mesh_devices": -1}
        cfg["eps"] = 0.2
        cfg["loss_evaluation"] = "flip"
        metrics = pgd_runner.run(cfg)
        assert metrics is not None
        h = get_dict_hash(cfg)
        x_att = np.load(tmp_path / "out" / f"x_attacks_pgd_flip_{h}.npy")
        assert x_att.shape[0] == 5
