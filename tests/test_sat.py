"""SAT/MIP attack tests: repaired candidates must provably satisfy constraints."""

import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.sat import SatAttack
from moeva2_ijcai22_replication_tpu.attacks.sat.engine import LinearRows
from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints
from moeva2_ijcai22_replication_tpu.domains.botnet_sat import make_botnet_sat_builder
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.lcld_sat import make_lcld_sat_builder
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax


@pytest.fixture(scope="module")
def lcld_setup(lcld_paths):
    cons = LcldConstraints(lcld_paths["features"], lcld_paths["constraints"])
    x = synth_lcld(6, cons.schema, seed=21)
    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    lo = np.minimum(x.min(0), xl.min(0))
    hi = np.maximum(x.max(0), xu.max(0))
    scaler = fit_minmax(lo, hi)
    return cons, x, scaler


class TestLcldSat:
    def test_valid_input_stays_valid(self, lcld_setup):
        cons, x, scaler = lcld_setup
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.3,
            norm=np.inf,
        )
        out = atk.generate(x)
        assert out.shape == (len(x), 1, x.shape[1])
        cons.check_constraints_error(out.reshape(-1, x.shape[1]))

    def test_repairs_perturbed_hot_start(self, lcld_setup):
        cons, x, scaler = lcld_setup
        rng = np.random.default_rng(0)
        hot = x.copy()
        # corrupt mutable derived features (the PGD-output scenario)
        hot[:, 3] += 40.0  # installment off-formula
        hot[:, 20] += 0.05  # ratio off
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.5,
            norm=np.inf,
        )
        out = atk.generate(x, hot_start=hot)[:, 0, :]
        g = np.asarray(cons.evaluate(jnp.asarray(out)))
        assert (g.sum(-1) == 0).all(), g.sum(-1)
        # repaired points stay near the hot start on untouched features
        assert np.abs(out[:, 0] - x[:, 0]).mean() < np.abs(
            out[:, 0] - np.zeros_like(out[:, 0])
        ).mean()

    def test_immutables_fixed(self, lcld_setup):
        cons, x, scaler = lcld_setup
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.3,
            norm=np.inf,
        )
        out = atk.generate(x)[:, 0, :]
        imm = ~np.asarray(cons.schema.mutable)
        np.testing.assert_allclose(out[:, imm], x[:, imm], atol=1e-9)

    def test_int_and_ohe_valid(self, lcld_setup):
        cons, x, scaler = lcld_setup
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.4,
            norm=np.inf,
        )
        out = atk.generate(x)[:, 0, :]
        int_feats = [
            i for i, t in enumerate(cons.schema.types) if str(t) != "real"
        ]
        np.testing.assert_allclose(out[:, int_feats], np.round(out[:, int_feats]))
        for g in cons.schema.ohe_groups():
            np.testing.assert_allclose(out[:, g].sum(-1), 1.0)

    def test_l2_ball_inscribed(self, lcld_setup):
        cons, x, scaler = lcld_setup
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.2,
            norm=2,
        )
        out = atk.generate(x)[:, 0, :]
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        os_ = np.asarray(scaler.transform(jnp.asarray(out)))
        assert np.linalg.norm(os_ - xs, axis=1).max() <= 0.2 + 1e-6

    def test_l2_box_is_directional_toward_hot_start(self, lcld_setup):
        """A hot start concentrated on one feature must keep (almost) the
        full ε budget there: the directional inscribed box admits moves far
        beyond the uniform ε/√D sliver, while every solution stays a valid
        L2-ball member."""
        cons, x, scaler = lcld_setup
        eps = 0.2
        feat = 12  # revol_bal: mutable, continuous, in no LCLD constraint
        scale = np.asarray(scaler.scale)

        # push 90% of ε onto the one feature, toward whichever side of the
        # (scaled) range has headroom so feature bounds cannot clamp the move
        xs0 = np.asarray(scaler.transform(jnp.asarray(x)))
        sign = np.where(xs0[:, feat] < 0.5, 1.0, -1.0)
        hot = x.copy()
        hot[:, feat] += sign * 0.9 * eps / scale[feat]

        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=eps,
            norm=2,
        )
        out = atk.generate(x, hot_start=hot)[:, 0, :]
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        os_ = np.asarray(scaler.transform(jnp.asarray(out)))
        # still inside the L2 ball ...
        assert np.linalg.norm(os_ - xs, axis=1).max() <= eps + 1e-6
        # ... yet the moved feature retains far more than the uniform
        # inscribed box could ever allow (ε/√D ≈ 0.029 ≪ 0.8ε)
        moved = np.abs(os_[:, feat] - xs[:, feat])
        assert moved.min() >= 0.8 * eps

    def test_l2_box_radii_budget_and_noise_floor(self, lcld_setup):
        """Radii spend the ε budget only on movable features (Σ r² = ε²
        over mutables, zero on immutables), and a noise-scale hot-start
        displacement must not steer the box away from uniform."""
        cons, x, scaler = lcld_setup
        eps = 0.2
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=eps,
            norm=2,
        )
        movable = atk._mutable & (np.asarray(scaler.scale) != 0)
        m = movable.sum()

        # no hot start: uniform ε/√m over movables, zero on pinned dims
        r = atk._box_radii(x[0], x[0])
        assert np.allclose(r[movable], eps / np.sqrt(m))
        assert np.all(r[~movable] == 0)
        assert np.isclose((r**2).sum(), eps**2)

        # float-noise displacement (PGD converged at x_init): still uniform
        hot = x[0].copy()
        hot[np.flatnonzero(movable)[0]] += 1e-12
        np.testing.assert_allclose(atk._box_radii(x[0], hot), r)

        # a real displacement concentrates budget but keeps Σ r² = ε²
        hot = x[0].copy()
        feat = 12  # revol_bal
        hot[feat] += 0.5 * eps / np.asarray(scaler.scale)[feat]
        r_dir = atk._box_radii(x[0], hot)
        assert r_dir[feat] > 3 * r[feat]
        assert np.isclose((r_dir**2).sum(), eps**2)


class TestBotnetSat:
    def test_real_candidates_stay_valid(self, botnet_paths, botnet_candidates):
        cons = BotnetConstraints(
            botnet_paths["features"], botnet_paths["constraints"]
        )
        x = botnet_candidates[:4].astype(float)
        xl, xu = cons.get_feature_min_max(dynamic_input=x)
        lo = np.minimum(x.min(0), np.asarray(xl, float).min(0))
        hi = np.maximum(x.max(0), np.asarray(xu, float).max(0))
        scaler = fit_minmax(lo, hi)
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_botnet_sat_builder(cons),
            min_max_scaler=scaler,
            eps=4.0,
            norm=2,
            time_limit=60.0,
        )
        hot = x.copy()
        # corrupt a sum-equality participant
        flows = cons.feat_idx["udp_sum_s_idx"]
        hot[:, flows[0]] += 3.0
        out = atk.generate(x, hot_start=hot)[:, 0, :]
        g = np.asarray(cons.evaluate(jnp.asarray(out)))
        assert (g.sum(-1) == 0).all()


class TestSatReviewRegressions:
    def test_unreachable_mode_stays_in_ball(self, lcld_setup):
        cons, x, scaler = lcld_setup
        # tiny eps: the hot start's drifted term mode (60 vs 36) is outside
        # the ball, so the mode search must settle on the reachable mode —
        # solutions stay valid and never escape the ball
        hot = x.copy()
        hot[:, 1] = np.where(x[:, 1] == 36.0, 60.0, 36.0)  # flip the mode
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.01,
            norm=np.inf,
        )
        out = atk.generate(x, hot_start=hot)[:, 0, :]
        np.testing.assert_allclose(out[:, 1], x[:, 1])  # original mode kept
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        os_ = np.asarray(scaler.transform(jnp.asarray(out)))
        assert np.abs(os_ - xs).max() <= 0.01 + 1e-6

    def test_solutions_stay_in_eps_box(self, lcld_setup):
        cons, x, scaler = lcld_setup
        rng = np.random.default_rng(3)
        hot = x + rng.normal(0, 0.02, x.shape) * np.abs(x)
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.15,
            norm=np.inf,
        )
        import jax.numpy as jnp

        out = atk.generate(x, hot_start=hot)[:, 0, :]
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        os_ = np.asarray(scaler.transform(jnp.asarray(out)))
        assert np.abs(os_ - xs).max() <= 0.15 + 1e-6


class TestLcldModeSearchAndPool:
    def _attack(self, cons, scaler, **kw):
        # eps > 1 scaled: the SAFETY_DELTA-shrunk box must still contain the
        # far term mode / raised one-hot flags
        kw.setdefault("eps", 2.0)
        kw.setdefault("norm", np.inf)
        return SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            **kw,
        )

    def test_term_mode_flips_to_hot_start(self, lcld_setup):
        """Standalone SAT must *search* term (lcld_constraints_sat.py:25-36):
        with the whole box reachable and a hot start amortised at the other
        mode, the MILP flips term rather than snapping back."""
        cons, x, scaler = lcld_setup
        from moeva2_ijcai22_replication_tpu.domains.lcld_sat import (
            _amortisation_factor,
        )

        sel = x[:, 1] == 36.0
        assert sel.any(), "fixture needs at least one term=36 state"
        x36 = x[sel]
        hot = x36.copy()
        hot[:, 1] = 60.0
        hot[:, 3] = [
            _amortisation_factor(r, 60.0) * loan
            for r, loan in zip(x36[:, 2], x36[:, 0])
        ]
        out = self._attack(cons, scaler).generate(x36, hot_start=hot)[:, 0, :]
        assert (out[:, 1] == 60.0).all(), out[:, 1]
        g = np.asarray(cons.evaluate(jnp.asarray(out)))
        assert (g.sum(-1) == 0).all()

    def test_solution_pool_returns_distinct_candidates(self, lcld_setup):
        cons, x, scaler = lcld_setup
        out = self._attack(cons, scaler, n_sample=3).generate(x[:3])
        assert out.shape == (3, 3, x.shape[1])
        for s in range(3):
            uniq = np.unique(out[s], axis=0)
            assert len(uniq) == 3, f"state {s}: pool not distinct"
        # every pool member is constraint-valid
        cons.check_constraints_error(out.reshape(-1, x.shape[1]))

    def test_zero_total_acc_hot_start_recovers(self, lcld_setup):
        """A zero hot-start denominator must not poison the program: the
        grid search drops the zero candidate (no inf coefficient) and still
        finds a valid repair from the remaining candidates — stronger than
        the old pin semantics, which could only fall back to x_init."""
        cons, x, scaler = lcld_setup
        hot = x.copy()
        hot[:, 14] = 0.0  # g6 denominator
        out = self._attack(cons, scaler).generate(x, hot_start=hot)[:, 0, :]
        cons.check_constraints_error(out)
        assert (out[:, 14] != 0).all()

    def test_denominator_mode_search_tracks_hot_start(self, lcld_setup):
        """annual_inc is searched, not pinned: with a hot start whose
        annual_inc moved and whose ratio is consistent, the MILP selects the
        hot-start grid candidate instead of snapping back to x_init."""
        cons, x, scaler = lcld_setup
        hot = x.copy()
        hot[:, 6] = x[:, 6] * 1.2
        hot[:, 20] = hot[:, 0] / hot[:, 6]
        out = self._attack(cons, scaler).generate(x, hot_start=hot)[:, 0, :]
        cons.check_constraints_error(out)
        np.testing.assert_allclose(out[:, 6], hot[:, 6], rtol=1e-6)
        # the old pin-at-hot behaviour also satisfied this; the searched
        # version must in addition keep the consistent ratio
        np.testing.assert_allclose(out[:, 20], hot[:, 20], atol=2e-4)

    def test_zero_month_diff_pin_falls_back(self, lcld_setup):
        cons, x, scaler = lcld_setup
        hot = x.copy()
        hot[:, 9] = hot[:, 7]  # earliest_cr_line == issue_d -> diff = 0
        out = self._attack(cons, scaler).generate(x, hot_start=hot)[:, 0, :]
        np.testing.assert_allclose(out, x)


class TestL2ExactBall:
    """Outer-approximation cuts (``l2_cut_rounds``): the exact scaled-L2 ball
    vs the inscribed directional box. Reference: Gurobi encodes the ball as a
    quadratic pow-constraint directly (``sat.py:101-121``); the cut path
    recovers that capability inside the linear solver."""

    def test_repairs_displacement_the_inscribed_box_rejects(self, lcld_setup):
        """A constraint forcing a 0.9ε displacement on one feature is L2-ball
        feasible but far beyond the uniform inscribed radius ε/√m — the cut
        path must repair it; the box-only attack can only fall back."""
        cons, x, scaler = lcld_setup
        eps = 0.2
        scale = np.asarray(scaler.scale)
        feat = 12  # revol_bal: mutable, continuous, in no LCLD constraint

        def builder(x_init, hot, box=None):
            lo = x_init[feat] + 0.9 * eps / scale[feat]
            return LinearRows(rows=[([feat], [1.0], lo, np.inf)], fixes={})

        def attack(rounds):
            return SatAttack(
                constraints=cons, sat_rows_builder=builder,
                min_max_scaler=scaler, eps=eps, norm=2,
                l2_cut_rounds=rounds,
            )

        out = attack(12).generate(x)[:, 0, :]
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        os_ = np.asarray(scaler.transform(jnp.asarray(out)))
        assert (os_[:, feat] - xs[:, feat]).min() >= 0.9 * eps - 1e-6
        assert np.linalg.norm(os_ - xs, axis=1).max() <= eps + 1e-6
        # the inscribed box alone cannot express this repair: x_init fallback
        np.testing.assert_allclose(attack(0).generate(x)[:, 0, :], x)

    def test_cut_loop_converges_inside_the_ball(self, lcld_setup):
        """Hot start displaced diagonally BEYOND the ball on two free
        features: the circumscribed box's first incumbent (= the hot start)
        is out of ball, so acceptance requires actual cutting-plane rounds.
        The accepted solution must be ball-valid and no farther from the hot
        start than the inscribed-box solution."""
        cons, x, scaler = lcld_setup
        eps = 0.2
        scale = np.asarray(scaler.scale)
        f1, f2 = 12, 13  # revol_bal, revol_util: free continuous mutables
        hot = x.copy()
        hot[:, f1] += 0.9 * eps / scale[f1]
        hot[:, f2] += 0.9 * eps / scale[f2]

        def builder(x_init, h, box=None):
            return LinearRows(rows=[], fixes={})

        def attack(rounds):
            return SatAttack(
                constraints=cons, sat_rows_builder=builder,
                min_max_scaler=scaler, eps=eps, norm=2,
                l2_cut_rounds=rounds,
            )

        out_c = attack(12).generate(x, hot_start=hot)[:, 0, :]
        out_b = attack(0).generate(x, hot_start=hot)[:, 0, :]
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        hs = np.asarray(scaler.transform(jnp.asarray(hot)))
        oc = np.asarray(scaler.transform(jnp.asarray(out_c)))
        ob = np.asarray(scaler.transform(jnp.asarray(out_b)))
        assert np.linalg.norm(oc - xs, axis=1).max() <= eps + 1e-6
        assert np.linalg.norm(ob - xs, axis=1).max() <= eps + 1e-6
        # the cut solution moved meaningfully toward the hot start on both
        # features (the L1-optimal ball point sits near 0.707ε per feature)
        assert (oc[:, f1] - xs[:, f1]).min() >= 0.5 * eps
        assert (oc[:, f2] - xs[:, f2]).min() >= 0.5 * eps
        l1_c = np.abs(oc - hs).sum(1)
        l1_b = np.abs(ob - hs).sum(1)
        assert (l1_c <= l1_b + 1e-4).all(), (l1_c, l1_b)

    def test_production_lcld_l2_still_valid_with_cuts(self, lcld_setup):
        """The default (cuts-on) LCLD L2 attack repairs a corrupted hot start
        to full constraint validity without ever leaving the ball."""
        cons, x, scaler = lcld_setup
        hot = x.copy()
        hot[:, 3] += 40.0
        hot[:, 20] += 0.05
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=make_lcld_sat_builder(cons.schema),
            min_max_scaler=scaler,
            eps=0.5,
            norm=2,
            refine_rounds=2,
        )
        out = atk.generate(x, hot_start=hot)[:, 0, :]
        g = np.asarray(cons.evaluate(jnp.asarray(out)))
        assert (g.sum(-1) == 0).all(), g.sum(-1)
        xs = np.asarray(scaler.transform(jnp.asarray(x)))
        os_ = np.asarray(scaler.transform(jnp.asarray(out)))
        assert np.linalg.norm(os_ - xs, axis=1).max() <= 0.5 + 1e-6


class TestGridRefinement:
    """`refine_rounds` vs a dense-grid brute-force oracle (VERDICT r3 item 6:
    the 5-point denominator grids were the one place the rebuild was strictly
    less capable than Gurobi's continuous nonconvex search)."""

    def _objective(self, scaler, mutable, sol, hot):
        w = np.abs(np.asarray(scaler.scale))
        w = np.where(w == 0, 1.0, w)
        return float(np.sum(w[mutable] * np.abs((sol - hot)[mutable])))

    def test_refined_matches_dense_grid_oracle(self, lcld_setup):
        cons, x, scaler = lcld_setup
        mutable = np.asarray(cons.schema.mutable, bool)
        rng = np.random.default_rng(9)

        # Hot starts engineered so the cheapest repair needs an *off-grid*
        # denominator: annual_inc displaced beyond the ε-box (the grid's
        # hot candidate clamps to the box edge) while the recorded ratio is
        # consistent with an interior denominator between base grid points.
        hot = x.copy()
        hot[:, 6] = x[:, 6] * (1.0 + rng.uniform(0.3, 0.6, len(x)))
        den_star = x[:, 6] * (1.0 + rng.uniform(0.04, 0.11, len(x)))
        hot[:, 20] = x[:, 0] / den_star

        def attack(refine_rounds, grid_points=5):
            return SatAttack(
                constraints=cons,
                sat_rows_builder=make_lcld_sat_builder(
                    cons.schema, grid_points=grid_points
                ),
                min_max_scaler=scaler,
                eps=0.2,
                norm=np.inf,
                refine_rounds=refine_rounds,
            )

        base = attack(0).generate(x, hot_start=hot)[:, 0, :]
        refined = attack(2).generate(x, hot_start=hot)[:, 0, :]
        dense = attack(0, grid_points=129).generate(x, hot_start=hot)[:, 0, :]
        for out in (base, refined, dense):
            cons.check_constraints_error(out)

        obj_b = [self._objective(scaler, mutable, base[i], hot[i]) for i in range(len(x))]
        obj_r = [self._objective(scaler, mutable, refined[i], hot[i]) for i in range(len(x))]
        obj_d = [self._objective(scaler, mutable, dense[i], hot[i]) for i in range(len(x))]

        for i in range(len(x)):
            # monotone: the incumbent stays in every refined grid
            assert obj_r[i] <= obj_b[i] + 1e-9, (i, obj_r[i], obj_b[i])
            # within noise of the 129-point brute-force oracle (refined
            # resolution box/64 ~ oracle spacing box/128)
            assert obj_r[i] <= obj_d[i] + 0.05 * max(obj_d[i], 1e-6) + 1e-6, (
                i, obj_r[i], obj_d[i],
            )
        # the construction must actually exercise refinement: at least one
        # state strictly improves on the 5-point grid
        assert any(r < b - 1e-6 for r, b in zip(obj_r, obj_b)), (obj_r, obj_b)
