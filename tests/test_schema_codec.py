import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.core import FeatureSchema, make_codec
from moeva2_ijcai22_replication_tpu.core import codec as C
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld


@pytest.fixture(scope="module")
def lcld_schema(lcld_paths):
    return FeatureSchema.from_csv(lcld_paths["features"])


def test_lcld_schema_shape(lcld_schema):
    assert lcld_schema.n_features == 47
    assert lcld_schema.mutable.sum() == 28
    assert len(lcld_schema.ohe_groups()) == 3
    assert not lcld_schema.has_dynamic_bounds


def test_botnet_schema_dynamic(botnet_paths):
    schema = FeatureSchema.from_csv(botnet_paths["features"])
    assert schema.n_features == 756
    assert schema.mutable.sum() == 432
    assert schema.has_dynamic_bounds
    # dynamic bounds resolve from the input sample
    x = np.arange(756, dtype=float)
    xl, xu = schema.bounds(x)
    assert np.all(xl[schema.min_dynamic] == x[schema.min_dynamic])
    assert np.all(xu[schema.max_dynamic] == x[schema.max_dynamic])
    # batched resolution
    xb = np.stack([x, x + 1.0])
    xlb, xub = schema.bounds(xb)
    assert xlb.shape == (2, 756)
    assert np.all(xub[1, schema.max_dynamic] == xb[1, schema.max_dynamic])


def test_lcld_codec_structure(lcld_schema):
    codec = make_codec(lcld_schema)
    # 28 mutable features, 1 mutable OHE group (purpose, 14 members):
    # 14 mutable non-OHE? -> genetic length = n_non_ohe + n_groups
    n_mutable_ohe_members = sum(
        len(g) for g in lcld_schema.ohe_groups() if lcld_schema.mutable[g[0]]
    )
    expected = int(lcld_schema.mutable.sum()) - n_mutable_ohe_members + 1
    assert codec.gen_length == expected
    assert codec.n_groups == 1


def test_roundtrip_ml_genetic(lcld_schema):
    codec = make_codec(lcld_schema)
    x = synth_lcld(32, lcld_schema, seed=1)
    x_gen = C.ml_to_genetic(codec, jnp.asarray(x))
    x_back = C.genetic_to_ml(codec, x_gen, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(x_back), x, rtol=0, atol=1e-12)


def test_genetic_to_ml_keeps_immutables(lcld_schema):
    codec = make_codec(lcld_schema)
    x = synth_lcld(8, lcld_schema, seed=2)
    x_gen = C.ml_to_genetic(codec, jnp.asarray(x))
    # Perturb all genes; immutable ML features must not move.
    x_gen2 = x_gen + 0.37
    x_ml2 = np.asarray(C.genetic_to_ml(codec, x_gen2, jnp.asarray(x)))
    immutable = ~lcld_schema.mutable
    np.testing.assert_array_equal(x_ml2[:, immutable], x[:, immutable])


def test_ohe_validity_by_construction(lcld_schema):
    codec = make_codec(lcld_schema)
    x = synth_lcld(8, lcld_schema, seed=3)
    x_gen = C.ml_to_genetic(codec, jnp.asarray(x))
    # Push categorical gene through its full range: decoded group stays one-hot.
    mutable_groups = [
        g for g in lcld_schema.ohe_groups() if lcld_schema.mutable[g[0]]
    ]
    for cat in range(len(mutable_groups[0])):
        x_gen2 = x_gen.at[:, -1].set(float(cat))
        x_ml2 = np.asarray(C.genetic_to_ml(codec, x_gen2, jnp.asarray(x)))
        group = mutable_groups[0]
        np.testing.assert_allclose(x_ml2[:, group].sum(axis=1), 1.0)
        assert np.all(x_ml2[:, group[cat]] == 1.0)


def test_genetic_bounds(lcld_schema):
    codec = make_codec(lcld_schema)
    xl_ml, xu_ml = lcld_schema.bounds()
    xl, xu = C.genetic_bounds(codec, xl_ml, xu_ml)
    assert xl.shape == (codec.gen_length,)
    assert np.all(np.asarray(xu) >= np.asarray(xl))
    # categorical gene bound = group size - 1 (purpose group: 14 members)
    assert float(xu[-1]) == 13.0


def test_minmax_semantics():
    xl = jnp.asarray([0.0, 5.0, 2.0])
    xu = jnp.asarray([1.0, 5.0, 4.0])  # middle feature degenerate
    x = jnp.asarray([[0.5, 5.0, 3.0]])
    norm = np.asarray(C.minmax_normalize(x, xl, xu))
    np.testing.assert_allclose(norm, [[0.5, 0.0, 0.5]])
    back = np.asarray(C.minmax_denormalize(jnp.asarray(norm), xl, xu))
    np.testing.assert_allclose(back, np.asarray(x))


def test_ohe_distance(lcld_schema):
    codec = make_codec(lcld_schema)
    x = synth_lcld(4, lcld_schema, seed=4)
    d0 = np.asarray(C.ohe_distance(codec, jnp.asarray(x)))
    np.testing.assert_allclose(d0, 0.0, atol=1e-12)
    # Break one OHE member -> distance grows by that amount.
    group = [g for g in lcld_schema.ohe_groups() if lcld_schema.mutable[g[0]]][0]
    x2 = x.copy()
    x2[:, group] = 0.0
    d2 = np.asarray(C.ohe_distance(codec, jnp.asarray(x2)))
    np.testing.assert_allclose(d2, 1.0, atol=1e-12)
