"""Serving-layer tests: microbatcher semantics + attack-service contracts.

The batcher core is exercised hardware-free with numpy dispatch functions
and a fake clock (bucketing, FIFO fairness, deadline flush, backpressure,
timeout cancellation, poisoned-batch isolation). The tier-1 smoke drives
>= 64 concurrent mixed-size PGD requests through a live threaded service
and pins the serving contract: results bit-identical to direct engine
calls, a bounded compile count (at most one program per (loss-strategy,
bucket-size)), and a populated offered-load serving record. The HTTP front
+ loadgen end-to-end ride in the slow tier.
"""

import json
import threading
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.serving import (
    AttackRequest,
    AttackService,
    BatchExecutionError,
    BucketMenu,
    DeadlineExceeded,
    Microbatcher,
    QueueFull,
    RequestTooLarge,
)
from moeva2_ijcai22_replication_tpu.utils.observability import ServiceMetrics


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# batcher core (no engines, no jax programs)
# ---------------------------------------------------------------------------


def make_batcher(sizes=(8,), max_delay_s=0.01, max_queue_rows=64, clock=None):
    metrics = ServiceMetrics()
    b = Microbatcher(
        BucketMenu(sizes),
        max_delay_s=max_delay_s,
        max_queue_rows=max_queue_rows,
        metrics=metrics,
        clock=clock or FakeClock(),
        start=False,
    )
    return b, metrics


class TestBucketMenu:
    def test_smallest_fit_and_too_large(self):
        menu = BucketMenu((8, 16, 32))
        assert [menu.bucket_for(n) for n in (1, 8, 9, 16, 32)] == [8, 8, 16, 16, 32]
        with pytest.raises(RequestTooLarge):
            menu.bucket_for(33)

    def test_mesh_alignment_enforced(self):
        BucketMenu((8, 16), mesh_size=8)
        with pytest.raises(ValueError, match="mesh"):
            BucketMenu((8, 12), mesh_size=8)


class TestBatcherCore:
    def test_deadline_flush_with_fake_clock(self):
        clock = FakeClock()
        b, metrics = make_batcher(max_delay_s=0.01, clock=clock)
        batches = []
        fut = b.submit("k", lambda x: batches.append(x.shape) or x, np.ones((2, 3)))
        # before the flush deadline nothing dispatches
        clock.advance(0.005)
        assert b.flush_due() == 0 and not fut.done()
        # past it, the lone request pads to the bucket and dispatches
        clock.advance(0.006)
        assert b.flush_due() == 1
        out, meta = fut.result(timeout=0)
        assert batches == [(8, 3)]  # padded to the bucket shape
        assert out.shape == (2, 3)  # trimmed back to the request rows
        assert meta["bucket_size"] == 8 and meta["batch_occupancy"] == 2 / 8

    def test_capacity_flush_before_deadline(self):
        clock = FakeClock()
        b, _ = make_batcher(sizes=(4,), clock=clock)
        futs = [b.submit("k", lambda x: x, np.ones((2, 1))) for _ in range(2)]
        # a full largest bucket is due immediately, no deadline wait
        assert b.flush_due() == 1
        assert all(f.done() for f in futs)

    def test_fifo_fairness_within_key(self):
        """Assembly never skips past a request that doesn't fit: B (4 rows)
        blocks C (2 rows) even though C alone would fit next to A."""
        clock = FakeClock()
        b, _ = make_batcher(sizes=(8,), clock=clock)
        rows = lambda n, v: np.full((n, 1), v, dtype=float)
        fa = b.submit("k", lambda x: x, rows(5, 1))
        fb = b.submit("k", lambda x: x, rows(4, 2))
        fc = b.submit("k", lambda x: x, rows(2, 3))
        clock.advance(0.02)
        assert b.flush_due() == 1  # batch 1: [A] (B does not fit 5+4 > 8)
        assert b.flush_due() == 1  # batch 2: [B, C]
        seq_a = fa.result(timeout=0)[1]["batch_seq"]
        meta_b = fb.result(timeout=0)[1]
        meta_c = fc.result(timeout=0)[1]
        assert meta_b["batch_seq"] == meta_c["batch_seq"] == seq_a + 1
        assert meta_b["batch_requests"] == 2 and meta_b["batch_rows"] == 6

    def test_scatter_returns_each_requests_rows(self):
        clock = FakeClock()
        b, _ = make_batcher(sizes=(8,), clock=clock)
        fa = b.submit("k", lambda x: x * 10, np.arange(6).reshape(3, 2) * 1.0)
        fb = b.submit("k", lambda x: x * 10, np.arange(4).reshape(2, 2) + 100.0)
        clock.advance(0.02)
        b.flush_due()
        np.testing.assert_array_equal(
            fa.result(timeout=0)[0], np.arange(6).reshape(3, 2) * 10.0
        )
        np.testing.assert_array_equal(
            fb.result(timeout=0)[0], (np.arange(4).reshape(2, 2) + 100.0) * 10.0
        )

    def test_backpressure_rejects_with_retry_after(self):
        b, metrics = make_batcher(max_queue_rows=8)
        b.submit("k", lambda x: x, np.ones((6, 1)))
        with pytest.raises(QueueFull) as ei:
            b.submit("k", lambda x: x, np.ones((3, 1)))
        assert ei.value.retry_after_s > 0
        assert metrics.counters["rejected"] == 1

    def test_expired_request_cancelled_before_dispatch(self):
        clock = FakeClock()
        b, metrics = make_batcher(clock=clock)
        calls = []
        fut = b.submit(
            "k", lambda x: calls.append(1) or x, np.ones((2, 1)), deadline_s=0.5
        )
        clock.advance(1.0)
        b.flush_due()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=0)
        assert calls == []  # never consumed device time
        assert metrics.counters["timeouts"] == 1

    def test_poisoned_batch_fails_its_mates_not_the_batcher(self):
        clock = FakeClock()
        b, metrics = make_batcher(clock=clock)

        def dispatch(x):
            if np.isnan(x).any():
                raise ValueError("poison")
            return x

        f1 = b.submit("k", dispatch, np.ones((2, 1)))
        f2 = b.submit("k", dispatch, np.full((2, 1), np.nan))
        clock.advance(0.02)
        b.flush_due()
        for f in (f1, f2):
            with pytest.raises(BatchExecutionError, match="poison"):
                f.result(timeout=0)
        assert metrics.counters["batch_failures"] == 1
        # the batcher survives: the next clean batch goes through
        f3 = b.submit("k", dispatch, np.ones((3, 1)))
        clock.advance(0.02)
        b.flush_due()
        assert f3.result(timeout=0)[0].shape == (3, 1)

    def test_request_larger_than_menu_rejected(self):
        b, _ = make_batcher(sizes=(8, 16))
        with pytest.raises(RequestTooLarge):
            b.submit("k", lambda x: x, np.ones((17, 1)))

    def test_keys_do_not_coalesce(self):
        clock = FakeClock()
        b, _ = make_batcher(clock=clock)
        fa = b.submit("k1", lambda x: x + 1, np.zeros((2, 1)))
        fb = b.submit("k2", lambda x: x + 2, np.zeros((2, 1)))
        clock.advance(0.02)
        assert b.flush_due() == 2  # one batch per key
        assert fa.result(timeout=0)[0][0, 0] == 1
        assert fb.result(timeout=0)[0][0, 0] == 2


# ---------------------------------------------------------------------------
# service over real engines (tiny synthetic LCLD artifact family)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Self-contained artifact family: the serving tests run hardware- and
    dataset-free on the synthetic LCLD schema (``synth_lcld_schema`` — the
    same code-derived schema ``bench.py --serving`` falls back to)."""
    from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld_schema

    tmp = tmp_path_factory.mktemp("serving_artifacts")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(256, cons.schema, seed=5)
    cons.check_constraints_error(x)  # the fixture must be constraint-valid

    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=2))
    save_params(sur, str(tmp / "nn.msgpack"))

    from sklearn.preprocessing import MinMaxScaler
    import joblib

    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    scaler = MinMaxScaler().fit(np.vstack([x, xl, xu]))
    joblib.dump(scaler, tmp / "scaler.joblib")
    return {
        "pool": x,
        "domain": {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": paths["features"],
                "constraints": paths["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
        },
    }


def make_service(artifacts, **kw):
    kw.setdefault("bucket_sizes", (8, 16))
    kw.setdefault("max_delay_s", 0.05)
    kw.setdefault("max_queue_rows", 1024)
    return AttackService({"lcld": artifacts["domain"]}, **kw)


class TestServiceValidation:
    def test_unknown_domain_and_family_and_shape(self, artifacts):
        svc = make_service(artifacts, start=False)
        from moeva2_ijcai22_replication_tpu.serving import InvalidRequest

        pool = artifacts["pool"]
        with pytest.raises(InvalidRequest, match="unknown domain"):
            svc.submit(AttackRequest(domain="nope", x=pool[:2]))
        with pytest.raises(InvalidRequest, match="attack family"):
            svc.submit(AttackRequest(domain="lcld", x=pool[:2], attack="zap"))
        with pytest.raises(InvalidRequest, match="MILP"):
            svc.submit(
                AttackRequest(domain="lcld", x=pool[:2], loss_evaluation="flip+sat")
            )
        with pytest.raises(InvalidRequest, match="features"):
            svc.submit(AttackRequest(domain="lcld", x=pool[:2, :10]))
        svc.close()


class TestServingSmoke:
    """Tier-1 acceptance: >= 64 concurrent mixed-size PGD requests through
    the microbatcher, bit-identical to direct engine calls, with at most
    one compiled program per (loss-strategy, bucket-size), and a populated
    serving bench record."""

    SIZES = [1, 2, 3, 5, 8, 13]  # mixed request sizes (6 distinct shapes)
    STRATEGIES = ["flip", "constraints+flip"]
    EPS = [0.2, 0.3]  # runtime ε: distinct batch keys, same executables

    def _request(self, artifacts, i):
        n = self.SIZES[i % len(self.SIZES)]
        start = (i * 29) % (artifacts["pool"].shape[0] - n)
        return AttackRequest(
            domain="lcld",
            x=artifacts["pool"][start : start + n],
            attack="pgd",
            loss_evaluation=self.STRATEGIES[i % 2],
            eps=self.EPS[(i // 2) % 2],
            budget=3,
        )

    def test_64_concurrent_requests_bit_identical_and_bounded_compiles(
        self, artifacts
    ):
        svc = make_service(artifacts, max_delay_s=0.05)
        n_requests = 64
        reqs = [self._request(artifacts, i) for i in range(n_requests)]
        with ThreadPoolExecutor(16) as pool:
            resps = list(
                pool.map(lambda r: svc.attack(r, timeout=300.0), reqs)
            )
        assert len(resps) == n_requests

        # -- compile bound: at most one program per (strategy, bucket-size).
        # ε and budget are runtime scalars, so the extra ε key must not add
        # programs; bucket shapes used come from the response metadata.
        buckets_used = {
            (r.meta["loss_evaluation"], r.meta["bucket_size"]) for r in resps
        }
        compiles = svc.metrics.counters.get("compiles", 0)
        assert 0 < compiles <= len(buckets_used), (
            f"{compiles} compiled programs for {len(buckets_used)} "
            f"(loss-strategy, bucket-size) pairs: {sorted(buckets_used)}"
        )

        # -- microbatching actually happened: fewer batches than requests
        assert svc.metrics.counters["batches"] < n_requests
        occ = [r.meta["batch_occupancy"] for r in resps]
        assert all(0 < o <= 1 for o in occ)

        # -- response metadata carries the execution mode
        meta = resps[0].meta
        assert meta["bit_identical"] is True
        assert meta["execution"] == {
            "max_states_per_call": None,
            "mesh": None,
            "bucket_menu": [8, 16],
        }

        # -- bit-identity: every request's rows match a direct engine call
        # dispatched ALONE at the same bucket shape — coalescing with other
        # requests and pad rows must change nothing, bit for bit
        svc.close()  # drain; engines now free for direct dispatch
        for req, resp in zip(reqs, resps):
            direct = svc.execute_direct(req, bucket=resp.meta["bucket_size"])
            np.testing.assert_array_equal(
                resp.x_adv, direct,
                err_msg=f"rows={req.x.shape[0]} le={req.loss_evaluation} "
                        f"eps={req.eps} bucket={resp.meta['bucket_size']}",
            )

        # -- across shapes (request at its own un-bucketed shape) XLA may
        # tile kernels differently; the engine-level drift stays tiny and
        # the serving layer documents it rather than hiding it
        for req, resp in list(zip(reqs, resps))[:2]:
            own_shape = svc.execute_direct(req)
            np.testing.assert_allclose(
                resp.x_adv, own_shape, rtol=1e-5, atol=1e-3
            )

    def test_offered_load_sweep_record_populated(self, artifacts):
        from moeva2_ijcai22_replication_tpu.serving.sweep import offered_load_sweep

        svc = make_service(artifacts, max_delay_s=0.01)
        # warm the two bucket shapes so the record measures steady serving
        for n in (8, 16):
            svc.attack(
                AttackRequest(
                    domain="lcld", x=artifacts["pool"][:n], eps=0.2, budget=3
                ),
                timeout=300.0,
            )
        record = offered_load_sweep(
            svc,
            lambda i: AttackRequest(
                domain="lcld",
                x=artifacts["pool"][: 1 + i % 8],
                eps=0.2,
                budget=3,
            ),
            offered_rps_levels=[200.0],
            n_requests=32,
        )
        svc.close()
        level = record["levels"][0]
        assert level["completed"] == 32 and level["failed"] == 0
        assert level["throughput_rps"] > 0
        assert np.isfinite(level["p50_ms"]) and np.isfinite(level["p99_ms"])
        assert level["p99_ms"] >= level["p50_ms"]
        assert 0 < level["mean_batch_occupancy"] <= 1
        assert record["batch_occupancy"]["count"] > 0
        assert record["engine_cache"]["engines"] >= 1


class TestServicePoisonIsolation:
    def test_constraint_violating_request_fails_batch_not_service(
        self, artifacts
    ):
        svc = make_service(artifacts, start=False, clock=FakeClock())
        clock = svc.clock
        pool = artifacts["pool"]
        poison = pool[:2].copy()
        poison[:, 0] = 1e9  # breaks the installment/loan-amount constraint
        good_req = AttackRequest(domain="lcld", x=pool[:3], eps=0.2, budget=2)
        f_good = svc.submit(good_req)
        f_poison = svc.submit(
            AttackRequest(domain="lcld", x=poison, eps=0.2, budget=2)
        )
        clock.advance(0.1)
        svc.batcher.flush_due()
        # same batch key -> the poison fails its batch-mates too
        for f in (f_good, f_poison):
            with pytest.raises(BatchExecutionError):
                f.result(timeout=0)
        assert svc.metrics.counters["batch_failures"] == 1
        # the service survives: a clean retry succeeds
        f_retry = svc.submit(good_req)
        clock.advance(0.1)
        svc.batcher.flush_due()
        x_adv, meta = f_retry.result(timeout=0)
        assert x_adv.shape == (3, pool.shape[1])
        svc.close()


@pytest.mark.slow
class TestHTTPEndToEnd:
    def test_server_and_loadgen(self, artifacts, tmp_path):
        import yaml

        from moeva2_ijcai22_replication_tpu.serving.server import serve

        svc = make_service(artifacts, max_delay_s=0.02)
        httpd = serve(svc, "127.0.0.1", 0, request_timeout_s=300.0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        url = f"http://127.0.0.1:{port}"
        try:
            # healthz + metrics
            with urllib.request.urlopen(f"{url}/healthz", timeout=10) as r:
                health = json.loads(r.read())
            assert health["ok"] and health["domains"] == ["lcld"]

            # one real attack over the wire
            rows = artifacts["pool"][:3].tolist()
            body = json.dumps(
                {"domain": "lcld", "rows": rows, "eps": 0.2, "budget": 2}
            ).encode()
            req = urllib.request.Request(
                f"{url}/attack", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as r:
                resp = json.loads(r.read())
            assert np.asarray(resp["x_adv"]).shape == (3, 47)
            assert resp["meta"]["bucket_size"] == 8

            # error mapping: unknown domain -> 400
            bad = urllib.request.Request(
                f"{url}/attack",
                data=json.dumps({"domain": "nope", "rows": rows}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=10)
            assert ei.value.code == 400
            ei.value.read()

            with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
                snap = json.loads(r.read())
            assert snap["counters"]["completed"] >= 1

            # loadgen end-to-end (subprocess, the documented quickstart path)
            import subprocess
            import sys as _sys
            import os as _os

            cfg_path = tmp_path / "serving.yaml"
            cfg_path.write_text(
                yaml.dump({"domains": {"lcld": artifacts["domain"]}})
            )
            repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
            out = subprocess.run(
                [
                    _sys.executable, _os.path.join(repo, "tools", "loadgen.py"),
                    "--url", url, "--config", str(cfg_path),
                    "--requests", "8", "--concurrency", "4",
                    "--rows-min", "1", "--rows-max", "4",
                    "--eps", "0.2", "--budget", "2",
                ],
                capture_output=True, text=True, timeout=600,
                env=dict(_os.environ, JAX_PLATFORMS="cpu"),
            )
            assert out.returncode == 0, out.stderr[-500:]
            summary = json.loads(out.stdout.strip().splitlines()[-1])
            assert summary["statuses"].get("ok") == 8
            assert summary["throughput_rps"] > 0
        finally:
            httpd.shutdown()
            svc.close()


@pytest.mark.slow
class TestMoevaServing:
    def test_moeva_request_round_trip(self, artifacts):
        svc = make_service(artifacts, max_delay_s=0.02)
        resp = svc.attack(
            AttackRequest(
                domain="lcld",
                x=artifacts["pool"][:3],
                attack="moeva",
                budget=2,
                params={"n_pop": 16, "n_offsprings": 8},
            ),
            timeout=600.0,
        )
        # (rows, population, features) — the runner's x_attacks layout
        assert resp.x_adv.shape[0] == 3 and resp.x_adv.ndim == 3
        assert resp.x_adv.shape[2] == 47
        # batch-shape-keyed RNG: explicitly NOT bit-identical across shapes
        assert resp.meta["bit_identical"] is False
        svc.close()
