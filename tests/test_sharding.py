"""``attacks/sharding.py``: the states-axis sharding contract itself.

The module every mesh-backed engine routes placements through had no
dedicated tests — its divisibility contract, its replicated-vs-sharded
placements, and the JSON mesh identity every committed record embeds are
pinned here on the emulated 8-device CPU mesh (conftest forces
``xla_force_host_platform_device_count=8``).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from moeva2_ijcai22_replication_tpu.attacks.sharding import (
    describe_mesh,
    shard_states_args,
)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("states",))


class TestShardStatesArgs:
    def test_divisibility_violation_raises_with_remedy(self, mesh):
        bad = jnp.ones((10, 4), jnp.float32)  # 10 % 8 != 0
        with pytest.raises(ValueError, match="divisible by the mesh size"):
            shard_states_args(mesh, "states", (), (bad,))
        # the error must name the remedy the runners use
        with pytest.raises(ValueError, match="pad_states"):
            shard_states_args(mesh, "states", (), (bad,))

    def test_sharded_arrays_split_leading_axis_over_devices(self, mesh):
        x = jnp.arange(16 * 4, dtype=jnp.float32).reshape(16, 4)
        _, (xs,) = shard_states_args(mesh, "states", (), (x,))
        assert xs.sharding == NamedSharding(mesh, P("states"))
        shards = xs.addressable_shards
        assert len(shards) == 8
        # each device owns a contiguous 2-row slab, in ordinal order
        for shard in shards:
            assert shard.data.shape == (2, 4)
        np.testing.assert_array_equal(np.asarray(xs), np.asarray(x))

    def test_replicated_pytrees_land_on_every_device_in_full(self, mesh):
        params = {"w": jnp.ones((3, 5)), "b": jnp.zeros((5,))}
        key = jax.random.PRNGKey(0)
        x = jnp.ones((8, 4), jnp.float32)
        (params_r, key_r), (xs,) = shard_states_args(
            mesh, "states", (params, key), (x,)
        )
        repl = NamedSharding(mesh, P())
        assert key_r.sharding == repl
        for leaf in jax.tree_util.tree_leaves(params_r):
            assert leaf.sharding == repl
            shards = leaf.addressable_shards
            assert len(shards) == 8
            # replication: every device holds the FULL array
            for shard in shards:
                assert shard.data.shape == leaf.shape
        # structures are preserved
        assert set(params_r) == {"w", "b"}
        assert xs.shape == x.shape

    def test_multiple_sharded_arrays_share_the_placement(self, mesh):
        a = jnp.ones((8, 3), jnp.float32)
        b = jnp.zeros((8, 7, 2), jnp.float32)
        _, (a_s, b_s) = shard_states_args(mesh, "states", (), (a, b))
        for arr in (a_s, b_s):
            assert arr.sharding == NamedSharding(mesh, P("states"))
            assert arr.addressable_shards[0].data.shape[0] == 1


class TestDescribeMesh:
    def test_none_mesh_describes_as_none(self):
        assert describe_mesh(None) is None

    def test_json_round_trip(self, mesh):
        desc = describe_mesh(mesh)
        assert desc == {"devices": 8, "shape": [8], "axes": ["states"]}
        # every committed record embeds this dict: it must survive JSON
        # byte-exactly (plain ints/strs, no numpy scalars)
        assert json.loads(json.dumps(desc)) == desc

    def test_multi_axis_mesh(self):
        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        m = Mesh(devs, ("dp", "tp"))
        desc = describe_mesh(m)
        assert desc == {"devices": 8, "shape": [2, 4], "axes": ["dp", "tp"]}
        assert json.loads(json.dumps(desc)) == desc
