"""Serving SLO observability: histograms, capacity model, shed, knee, gate.

Covers the SLO & capacity layer end to end, fixture-free (code-derived
synthetic LCLD schema, no hardware assumptions):

- :class:`~moeva2_ijcai22_replication_tpu.observability.Histogram` /
  :class:`SloTracker` units: bucket assignment, monotone cumulative
  export, quantile estimates with their sample ``n``, mark/delta
  windowing, shed-cause aggregation, the disabled no-op;
- :func:`detect_knee` on synthetic offered-load ladders;
- :class:`CapacityModel` math on synthetic batches: predicted
  FLOPs/request, achieved FLOP/s, max sustainable QPS, utilization
  headroom, calibration error, and the run-seconds degradation when the
  cost model is absent;
- the ``telemetry.slo`` schema: ``slo_block``/``validate_slo``,
  ``telemetry_block(slo=...)``, and ``validate_record`` enforcing the
  block on serving records only;
- Prometheus native-histogram exposition lint: every family carries
  ``# HELP``/``# TYPE``, ``_bucket`` series are monotone cumulative and
  end at ``le="+Inf"`` == ``_count``, shed counters and capacity gauges
  render labeled;
- the live service: all six stages populated per domain, the /healthz
  capacity block, shed attribution for expired/rejected/poisoned/
  overrun, the sweep record's ``telemetry.slo`` (with knee and
  ``quantiles_n``), and the tier-1 overhead smoke — SLO capture on adds
  ZERO compiles and is bit-identical to capture off;
- ``tools/bench_diff.py --slo``: knee-QPS and p99-at-fixed-load
  regressions fail, reshaped ladders and pre-SLO baselines skip, lost
  SLO capture fails, and the flag off leaves the legacy behavior
  untouched.
"""

import importlib.util
import json
import math
import os

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import (
    synth_lcld,
    synth_lcld_schema,
)
from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
from moeva2_ijcai22_replication_tpu.observability import (
    CapacityModel,
    Histogram,
    SloTracker,
    detect_knee,
    incidents_block,
    slo_block,
    telemetry_block,
    validate_record,
    validate_slo,
)
from moeva2_ijcai22_replication_tpu.observability.prom import prometheus_text
from moeva2_ijcai22_replication_tpu.serving import (
    AttackRequest,
    AttackService,
    BatchExecutionError,
    BucketMenu,
    DeadlineExceeded,
    Microbatcher,
    QueueFull,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# histogram + tracker units
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_bucket_assignment_and_cumulative_export(self):
        h = Histogram((0.001, 0.01, 0.1))
        for v in (0.0005, 0.001, 0.005, 0.05, 5.0):
            h.observe(v)
        snap = h.snapshot()
        # a value AT a bound lands in that bound's bucket (le semantics)
        assert snap["buckets"] == [
            [0.001, 2], [0.01, 3], [0.1, 4], ["+Inf", 5],
        ]
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(5.0565)
        # cumulative monotone, +Inf equals count — the mergeability
        # contract Prometheus histograms rely on
        cums = [c for _, c in snap["buckets"]]
        assert cums == sorted(cums) and cums[-1] == snap["count"]

    def test_quantiles_annotated_with_n(self):
        h = Histogram((0.01, 0.1, 1.0))
        for _ in range(98):
            h.observe(0.005)
        h.observe(0.5)
        h.observe(0.5)
        snap = h.snapshot()
        # p99 rank (99 of 100) falls past the 98 fast samples — it lands
        # in the 1.0 bucket holding the two slow ones
        assert snap["p50"] == 0.01 and snap["p99"] == 1.0
        assert snap["n"] == 100
        empty = Histogram((1.0,)).snapshot()
        assert empty["p50"] is None and empty["p99"] is None
        assert empty["n"] == 0

    def test_overflow_quantile_reports_inf_marker(self):
        """A rank in the +Inf overflow reports "+Inf", not the largest
        finite bound: when every observation lands past the bucket
        scheme's max, a numeric p99 of bounds[-1] would dress an
        unbounded tail as the scheme's cap (promql's trap)."""
        h = Histogram((0.01,))
        h.observe(99.0)
        snap = h.snapshot()
        assert snap["p99"] == "+Inf" and snap["p50"] == "+Inf"
        json.dumps(snap)  # strict-JSON safe, like the buckets key

    def test_observe_count_weights_per_batch_stages(self):
        """A per-batch duration folded in with count=k (the requests that
        rode the batch) weighs like k identical per-request observations
        — the request-weighting that keeps every stage in one family
        over the same population."""
        h = Histogram((0.01, 1.0))
        h.observe(0.5, count=3)
        snap = h.snapshot()
        assert snap["count"] == snap["n"] == 3
        assert snap["sum"] == pytest.approx(1.5)
        assert snap["buckets"] == [[0.01, 0], [1.0, 3], ["+Inf", 3]]
        t = SloTracker(bounds=(0.01, 1.0))
        t.observe("d", "device_run", 0.5, count=4)
        assert t.snapshot()["stages"]["d"]["device_run"]["count"] == 4

    def test_rejects_unsorted_or_duplicate_bounds(self):
        with pytest.raises(ValueError):
            Histogram((0.1, 0.01))
        with pytest.raises(ValueError):
            Histogram((0.1, 0.1))
        with pytest.raises(ValueError):
            Histogram(())


class TestSloTracker:
    def test_observe_shed_and_windowing(self):
        t = SloTracker(bounds=(0.01, 1.0))
        t.observe("lcld", "queue_wait", 0.005)
        t.shed("lcld", "rejected", "queue_wait")
        mark = t.mark()
        t.observe("lcld", "queue_wait", 0.5)
        t.observe("lcld", "device_run", 0.2)
        t.shed("lcld", "expired", "queue_wait")
        full = t.snapshot()
        assert full["stages"]["lcld"]["queue_wait"]["count"] == 2
        assert full["shed"]["total"] == 2
        # windowed: only post-mark traffic
        win = t.snapshot(since=mark)
        qw = win["stages"]["lcld"]["queue_wait"]
        assert qw["count"] == 1 and qw["buckets"][0][1] == 0
        assert win["stages"]["lcld"]["device_run"]["count"] == 1
        assert win["shed"] == {
            "total": 1, "by_domain": {"lcld": {"expired": {"queue_wait": 1}}}
        }

    def test_snapshot_is_torn_read_safe_under_concurrent_observes(self):
        """A scrape racing observations must never export a torn
        histogram: the +Inf cumulative bucket always equals count (the
        mergeability invariant), even mid-observe."""
        import threading

        t = SloTracker(bounds=(0.01, 1.0))
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                t.observe("d", "dispatch", 0.005)

        threads = [threading.Thread(target=writer) for _ in range(3)]
        for th in threads:
            th.start()
        try:
            for _ in range(200):
                snap = t.snapshot()
                stages = snap["stages"].get("d")
                if not stages:
                    continue
                h = stages["dispatch"]
                assert h["buckets"][-1][1] == h["count"] == h["n"]
        finally:
            stop.set()
            for th in threads:
                th.join()

    def test_disabled_tracker_is_a_no_op(self):
        t = SloTracker(enabled=False)
        t.observe("d", "validate", 1.0)
        t.shed("d", "rejected", "queue_wait")
        snap = t.snapshot()
        assert snap["enabled"] is False
        assert snap["stages"] == {} and snap["shed"]["total"] == 0

    def test_bad_bounds_rejected_at_construction(self):
        """A bad serving.slo_histogram_buckets config must fail the boot,
        not 500 the first request."""
        with pytest.raises(ValueError):
            SloTracker(bounds=(0.1, 0.01))
        with pytest.raises(ValueError):
            SloTracker(bounds=(0.1, 0.1))


class TestDetectKnee:
    def test_linear_ladder_knee_is_max_offered(self):
        levels = [
            {"offered_rps": r, "throughput_rps": r * 0.98, "p99_ms": 10 + r / 100}
            for r in (16, 64, 256)
        ]
        knee = detect_knee(levels)
        assert knee["knee_rps"] == 256
        assert knee["first_saturated_rps"] is None
        assert knee["baseline_p99_ms"] == levels[0]["p99_ms"]
        assert knee["levels_n"] == 3

    def test_p99_departure_marks_the_knee(self):
        levels = [
            {"offered_rps": 16, "throughput_rps": 16, "p99_ms": 10},
            {"offered_rps": 64, "throughput_rps": 63, "p99_ms": 14},
            {"offered_rps": 256, "throughput_rps": 250, "p99_ms": 400},
        ]
        knee = detect_knee(levels)
        assert knee["knee_rps"] == 64 and knee["first_saturated_rps"] == 256

    def test_throughput_collapse_marks_the_knee(self):
        levels = [
            {"offered_rps": 16, "throughput_rps": 16, "p99_ms": 10},
            {"offered_rps": 64, "throughput_rps": 30, "p99_ms": 12},
        ]
        knee = detect_knee(levels)
        assert knee["knee_rps"] == 16 and knee["first_saturated_rps"] == 64

    def test_level_that_completed_nothing_is_saturated(self):
        levels = [
            {"offered_rps": 16, "throughput_rps": 16, "p99_ms": 10},
            {"offered_rps": 64, "throughput_rps": None, "p99_ms": None},
        ]
        assert detect_knee(levels)["first_saturated_rps"] == 64

    def test_empty_sweep(self):
        knee = detect_knee([])
        assert knee["knee_rps"] is None and knee["levels_n"] == 0

    def test_completion_ratio_beats_drain_biased_throughput(self):
        """A level whose measured throughput dips below the floor only
        because duration includes the blocking drain tail stays linear
        when its completion_ratio says every offered request completed."""
        levels = [
            {"offered_rps": 16, "throughput_rps": 13.0, "p99_ms": 10,
             "completion_ratio": 1.0},
            {"offered_rps": 64, "throughput_rps": 50.0, "p99_ms": 12,
             "completion_ratio": 0.98},
        ]
        knee = detect_knee(levels)
        assert knee["knee_rps"] == 64 and knee["first_saturated_rps"] is None
        # real shortfall still saturates: rejects drop the ratio
        levels[1]["completion_ratio"] = 0.6
        assert detect_knee(levels)["first_saturated_rps"] == 64

    def test_run_level_charges_latency_from_scheduled_arrival(self):
        """When the submit loop slips behind schedule, the backlog wait
        is latency the offered load experienced — measuring from the
        actual submit instant would drop it (coordinated omission) and
        overstate the knee."""
        from concurrent.futures import Future

        from moeva2_ijcai22_replication_tpu.serving.sweep import run_level

        clock = FakeClock()

        class SlowSubmitService:
            def submit(self, req):
                clock.advance(0.5)  # the loop slips 0.5s per submit
                f = Future()
                f.set_result((None, {"batch_occupancy": 1.0, "rows": 1}))
                return f

        lv = run_level(
            SlowSubmitService(), lambda i: None,
            offered_rps=10.0, n_requests=3,
            clock=clock, sleep=lambda s: clock.advance(s),
            arrival="uniform",
        )
        # scheduled at 0/0.1/0.2, completed at 0.5/1.0/1.5 — latencies
        # 0.5/0.9/1.3 include the slip; submit-instant origin would have
        # reported ~0 for all three
        assert lv["completed"] == 3
        assert lv["p50_ms"] == pytest.approx(900.0)
        assert lv["arrival"] == "uniform"

    def test_knee_never_advances_past_saturation(self):
        """A noisy higher level sneaking back under the bounds after a
        saturated one must not inflate the knee: 'served linearly up to
        here' cannot be claimed above a rate that already failed."""
        levels = [
            {"offered_rps": 16, "throughput_rps": 16, "p99_ms": 10},
            {"offered_rps": 64, "throughput_rps": 63, "p99_ms": 40},
            {"offered_rps": 256, "throughput_rps": 250, "p99_ms": 29},
        ]
        knee = detect_knee(levels)
        assert knee["knee_rps"] == 16 and knee["first_saturated_rps"] == 64


# ---------------------------------------------------------------------------
# capacity model math
# ---------------------------------------------------------------------------


class TestCapacityModel:
    def test_flops_basis_math_is_exact(self):
        clock = FakeClock()
        c = CapacityModel(window=16, clock=clock)
        # 4 batches, 2 requests each, 1e9 FLOPs per dispatch, 0.5s run
        for _ in range(4):
            c.note_batch(
                "lcld", strategy="flip", bucket=8, budget=10,
                requests=2, rows=6, run_s=0.5, flops=1e9,
            )
            clock.advance(1.0)
        blk = c.domain_block("lcld")
        assert blk["basis"] == "ledger_flops"
        assert blk["predicted_flops_per_request"] == pytest.approx(5e8)
        assert blk["achieved_flops_s"] == pytest.approx(2e9)
        # max QPS = achieved FLOP/s / predicted FLOPs/request = 4
        assert blk["max_sustainable_qps"] == pytest.approx(4.0)
        # 2.0s of device time over a 3.5s window span (export rounds to 4)
        assert blk["utilization"] == pytest.approx(2.0 / 3.5, abs=1e-4)
        assert blk["headroom"] == pytest.approx(1 - 2.0 / 3.5, abs=1e-4)
        # homogeneous classes: FLOPs predict time perfectly
        assert blk["calibration"]["mean_abs_rel_err"] == 0.0
        assert blk["calibration"]["n"] == 4
        cls = blk["per_class"]["flip|b8|g10"]
        assert cls["dispatches"] == 4 and cls["requests"] == 8
        assert cls["predicted_flops_per_request"] == pytest.approx(5e8)

    def test_calibration_sees_roofline_dispersion(self):
        """Two classes with equal FLOPs but 4x different run time: the
        FLOPs model cannot predict both — calibration error is the
        witness (the DESIGN § SLO & capacity roofline caveat)."""
        c = CapacityModel(window=16, clock=FakeClock())
        c.note_batch("d", strategy="a", bucket=8, budget=10,
                     requests=1, rows=1, run_s=0.1, flops=1e9)
        c.note_batch("d", strategy="b", bucket=8, budget=10,
                     requests=1, rows=1, run_s=0.4, flops=1e9)
        cal = c.domain_block("d")["calibration"]
        assert cal["mean_abs_rel_err"] > 0.5
        assert cal["max_abs_rel_err"] >= cal["mean_abs_rel_err"]

    def test_run_seconds_fallback_without_cost_model(self):
        clock = FakeClock()
        c = CapacityModel(window=8, clock=clock)
        for _ in range(2):
            c.note_batch("d", strategy="flip", bucket=8, budget=10,
                         requests=4, rows=8, run_s=0.5, flops=None)
            clock.advance(1.0)
        blk = c.domain_block("d")
        assert blk["basis"] == "run_seconds"
        assert blk["predicted_flops_per_request"] is None
        assert blk["achieved_flops_s"] is None
        assert blk["calibration"] is None
        # max QPS still honest: 8 requests over 1.0s of device time
        assert blk["max_sustainable_qps"] == pytest.approx(8.0)

    def test_per_class_prediction_not_diluted_by_flops_less_dispatches(self):
        """A class mixing flops-bearing and flops-less observations must
        divide FLOPs by the requests on flops-BEARING dispatches only
        (mirroring the domain-level req_flops denominator): diluting by
        all requests would under-price that traffic for admission
        control."""
        c = CapacityModel(window=8, clock=FakeClock())
        c.note_batch("d", strategy="s", bucket=8, budget=1,
                     requests=1, rows=1, run_s=0.5, flops=1e9)
        c.note_batch("d", strategy="s", bucket=8, budget=1,
                     requests=1, rows=1, run_s=0.5, flops=None)
        cls = c.domain_block("d")["per_class"]["s|b8|g1"]
        assert cls["flops_known"] == 1 and cls["requests"] == 2
        assert cls["predicted_flops_per_request"] == pytest.approx(1e9)

    def test_window_evicts_old_batches(self):
        c = CapacityModel(window=2, clock=FakeClock())
        for flops in (1e9, 2e9, 4e9):
            c.note_batch("d", strategy="s", bucket=8, budget=1,
                         requests=1, rows=1, run_s=1.0, flops=flops)
        blk = c.domain_block("d")
        assert blk["window_batches"] == 2
        assert blk["predicted_flops_per_request"] == pytest.approx(3e9)

    def test_wall_span_starts_at_first_dispatch_start(self):
        """The window span runs first dispatch START -> last completion:
        a slow first batch followed by fast ones must not halve the span
        (obs.t is completion time, so the FIRST batch's run_s extends the
        span backwards, not the last's)."""
        clock = FakeClock(10.0)  # first batch completes at t=10
        c = CapacityModel(window=8, clock=clock)
        c.note_batch("d", strategy="s", bucket=8, budget=1,
                     requests=1, rows=1, run_s=10.0, flops=None)
        clock.advance(10.0)  # fast batch completes at t=20
        c.note_batch("d", strategy="s", bucket=8, budget=1,
                     requests=1, rows=1, run_s=0.1, flops=None)
        blk = c.domain_block("d")
        # 10.1s of device time over the 20s span (t=0 .. t=20)
        assert blk["utilization"] == pytest.approx(10.1 / 20.0, abs=1e-4)

    def test_single_batch_has_no_utilization(self):
        c = CapacityModel(clock=FakeClock())
        c.note_batch("d", strategy="s", bucket=8, budget=1,
                     requests=1, rows=1, run_s=0.5, flops=1e9)
        blk = c.domain_block("d")
        assert blk["utilization"] is None and blk["headroom"] is None

    def test_compile_and_empty_inputs_ignored(self):
        c = CapacityModel(clock=FakeClock())
        c.note_batch("d", strategy="s", bucket=8, budget=1,
                     requests=0, rows=0, run_s=0.5, flops=1e9)
        c.note_batch("d", strategy="s", bucket=8, budget=1,
                     requests=1, rows=1, run_s=0.0, flops=1e9)
        assert c.domain_block("d") is None
        assert c.snapshot()["by_domain"] == {}


# ---------------------------------------------------------------------------
# schema: slo_block / validate_slo / validate_record
# ---------------------------------------------------------------------------


class TestSloSchema:
    def test_empty_block_is_schema_valid(self):
        blk = slo_block()
        validate_slo(blk)
        assert blk["stages"] == {} and blk["shed"]["total"] == 0
        assert blk["knee"] == {}

    def test_validate_slo_rejects_missing_keys(self):
        with pytest.raises(ValueError, match="telemetry.slo"):
            validate_slo({"stages": {}})
        with pytest.raises(ValueError, match="must be a dict"):
            validate_slo([])

    def test_telemetry_block_carries_slo_only_when_given(self):
        assert "slo" not in telemetry_block()
        blk = telemetry_block(slo=slo_block())
        validate_slo(blk["slo"])

    def test_serving_records_require_slo_others_do_not(self):
        base = {
            "execution": {},
            "telemetry": telemetry_block(),
        }
        validate_record(dict(base), "bench")  # no slo needed
        with pytest.raises(ValueError, match="slo"):
            validate_record(dict(base), "serving")
        # slo alone is no longer enough: serving/fleet records also carry
        # incident attribution (a capture-off block is honest and valid)
        with_slo = {
            "execution": {},
            "telemetry": telemetry_block(slo=slo_block()),
        }
        with pytest.raises(ValueError, match="incidents"):
            validate_record(dict(with_slo), "serving")
        ok = {
            "execution": {},
            "telemetry": telemetry_block(
                slo=slo_block(), incidents=incidents_block(None)
            ),
        }
        validate_record(ok, "serving")


# ---------------------------------------------------------------------------
# prometheus exposition: native histograms + shed counters + capacity
# ---------------------------------------------------------------------------


def _prom_families(text: str):
    """(families seen in samples, helped, typed) with histogram/summary
    suffixes folded into their base family."""
    families, helped, typed = set(), set(), set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
        elif line.startswith("# TYPE "):
            typed.add(line.split()[2])
        elif line and not line.startswith("#"):
            name = line.split("{")[0].split(" ")[0]
            for suffix in ("_bucket", "_count", "_sum"):
                if name.endswith(suffix) and name[: -len(suffix)] in typed:
                    name = name[: -len(suffix)]
            families.add(name)
    return families, helped, typed


class TestPromExposition:
    def _snapshot(self):
        t = SloTracker(bounds=(0.01, 0.1))
        for v in (0.005, 0.05, 0.5):
            t.observe("lcld", "queue_wait", v)
        t.observe("lcld", "device_run", 0.02)
        t.shed("lcld", "expired", "queue_wait")
        t.shed("botnet", "rejected", "queue_wait")
        clock = FakeClock()
        c = CapacityModel(window=8, clock=clock)
        for _ in range(2):
            c.note_batch("lcld", strategy="flip", bucket=8, budget=10,
                         requests=2, rows=4, run_s=0.5, flops=1e9)
            clock.advance(1.0)
        return {
            "counters": {"requests": 4},
            "gauges": {},
            "streams": {},
            "slo": t.snapshot(),
            "capacity": c.snapshot(),
        }

    def test_every_family_has_help_and_type(self):
        text = prometheus_text(self._snapshot())
        families, helped, typed = _prom_families(text)
        assert families - helped == set(), f"no HELP: {families - helped}"
        assert families - typed == set(), f"no TYPE: {families - typed}"
        assert "# TYPE moeva2_stage_latency_seconds histogram" in text
        assert "# TYPE moeva2_shed_requests_total counter" in text

    def test_histogram_buckets_monotone_and_close_at_inf(self):
        text = prometheus_text(self._snapshot())
        # group _bucket samples per label set; the cumulative series must
        # be monotone and its +Inf sample must equal _count
        series: dict[str, list[tuple[str, int]]] = {}
        counts: dict[str, int] = {}
        for line in text.splitlines():
            if line.startswith("moeva2_stage_latency_seconds_bucket{"):
                labels, value = line.split("} ")
                le = labels.split('le="')[1].rstrip('"')
                key = labels.split(',le="')[0]
                series.setdefault(key, []).append((le, int(value)))
            elif line.startswith("moeva2_stage_latency_seconds_count{"):
                labels, value = line.split("} ")
                counts[labels] = int(value)
        assert series, "no histogram bucket samples rendered"
        for key, rows in series.items():
            vals = [v for _, v in rows]
            assert vals == sorted(vals), f"non-monotone buckets for {key}"
            assert rows[-1][0] == "+Inf"
            count_key = key.replace("_bucket{", "_count{")
            assert counts.get(count_key) == vals[-1], (
                f"+Inf bucket != _count for {key}"
            )
        qw = next(k for k in series if 'stage="queue_wait"' in k)
        assert [v for _, v in series[qw]] == [1, 2, 3]

    def test_shed_and_capacity_lines_are_labeled(self):
        text = prometheus_text(self._snapshot())
        assert (
            'moeva2_shed_requests_total{domain="lcld",cause="expired",'
            'stage="queue_wait"} 1' in text
        )
        assert 'moeva2_capacity_max_sustainable_qps{domain="lcld"} 4' in text
        assert 'moeva2_capacity_headroom{domain="lcld"}' in text
        assert (
            'moeva2_capacity_calibration_error{domain="lcld"} 0' in text
        )


# ---------------------------------------------------------------------------
# batcher-level shed attribution (fake clock, no engines)
# ---------------------------------------------------------------------------


class TestBatcherSheds:
    def _batcher(self, clock, slo, sizes=(8,)):
        return Microbatcher(
            BucketMenu(sizes),
            max_delay_s=0.01,
            max_queue_rows=64,
            slo=slo,
            clock=clock,
            start=False,
        )

    def test_expired_attributed_to_queue_wait(self):
        clock, slo = FakeClock(), SloTracker()
        b = self._batcher(clock, slo)
        fut = b.submit(
            "k", lambda x: x, np.ones((2, 1)),
            deadline_s=0.5, meta={"domain": "lcld"},
        )
        clock.advance(1.0)
        b.flush_due()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=0)
        assert slo.shed_block()["by_domain"] == {
            "lcld": {"expired": {"queue_wait": 1}}
        }

    def test_overrun_attributed_to_the_stage_the_deadline_fell_in(self):
        """A request whose deadline passes DURING device execution
        completes (no post-dispatch cancellation) but counts as an
        overrun against device_run — the signal that the bucket/budget,
        not the queue, ate the deadline."""
        clock, slo = FakeClock(), SloTracker()
        b = self._batcher(clock, slo)

        def slow_dispatch(x):
            clock.advance(1.0)  # the "device" consumes the deadline
            return x

        fut = b.submit(
            "k", slow_dispatch, np.ones((2, 1)),
            deadline_s=0.5, meta={"domain": "lcld"},
        )
        clock.advance(0.02)  # past flush delay, before the deadline
        b.flush_due()
        fut.result(timeout=0)  # completed fine
        assert slo.shed_block()["by_domain"] == {
            "lcld": {"overrun": {"device_run": 1}}
        }

    def test_poisoned_batch_attributed_per_request(self):
        clock, slo = FakeClock(), SloTracker()
        b = self._batcher(clock, slo)

        def poison(x):
            raise ValueError("poison")

        f1 = b.submit("k", poison, np.ones((2, 1)), meta={"domain": "lcld"})
        f2 = b.submit("k", poison, np.ones((2, 1)), meta={"domain": "lcld"})
        clock.advance(0.02)
        b.flush_due()
        for f in (f1, f2):
            with pytest.raises(BatchExecutionError):
                f.result(timeout=0)
        assert slo.shed_block()["by_domain"]["lcld"]["poisoned"] == {
            "dispatch": 2
        }

    def test_wait_stages_and_meta_annotations(self):
        clock, slo = FakeClock(), SloTracker()
        b = self._batcher(clock, slo)
        fut = b.submit("k", lambda x: x, np.ones((2, 1)), meta={"domain": "d"})
        clock.advance(0.02)
        b.flush_due()
        _, meta = fut.result(timeout=0)
        assert meta["queue_wait_s"] == pytest.approx(0.02)
        assert meta["batch_wait_s"] == 0.0
        stages = slo.snapshot()["stages"]["d"]
        for stage in ("queue_wait", "batch_wait", "dispatch"):
            assert stages[stage]["count"] == 1

    def test_ledger_context_carries_real_batch_rows(self):
        """The dispatch closure only ever sees the bucket-padded array;
        the ambient ledger context must carry the REAL row count (what
        the capacity model counts as served) next to bucket and
        batch_requests."""
        from moeva2_ijcai22_replication_tpu.observability import (
            current_ledger_context,
        )

        clock, slo = FakeClock(), SloTracker()
        b = self._batcher(clock, slo)
        seen = {}

        def dispatch(x):
            seen.update(current_ledger_context())
            seen["padded_rows"] = x.shape[0]
            return x

        f1 = b.submit("k", dispatch, np.ones((1, 1)), meta={"domain": "d"})
        f2 = b.submit("k", dispatch, np.ones((2, 1)), meta={"domain": "d"})
        clock.advance(0.02)
        b.flush_due()
        f1.result(timeout=0), f2.result(timeout=0)
        assert seen["padded_rows"] == 8  # bucket-padded view
        assert seen["batch_rows"] == 3  # what was actually requested
        assert seen["batch_requests"] == 2 and seen["bucket"] == 8


# ---------------------------------------------------------------------------
# live service (synthetic LCLD artifacts, real engines)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Same self-contained artifact family as tests/test_serving.py."""
    import joblib
    from sklearn.preprocessing import MinMaxScaler

    tmp = tmp_path_factory.mktemp("slo_artifacts")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(256, cons.schema, seed=5)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=2))
    save_params(sur, str(tmp / "nn.msgpack"))
    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    joblib.dump(
        MinMaxScaler().fit(np.vstack([x, xl, xu])), tmp / "scaler.joblib"
    )
    return {
        "pool": x,
        "domain": {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": paths["features"],
                "constraints": paths["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
        },
    }


def make_service(artifacts, **kw):
    kw.setdefault("bucket_sizes", (8, 16))
    kw.setdefault("max_delay_s", 0.02)
    kw.setdefault("max_queue_rows", 1024)
    return AttackService({"lcld": artifacts["domain"]}, **kw)


class TestServiceSlo:
    def test_stages_capacity_and_prom_after_traffic(self, artifacts):
        svc = make_service(artifacts)
        try:
            # first request compiles (device_run skips it), the rest are
            # pure-run dispatches that feed device_run + the capacity model
            for i in range(6):
                svc.attack(
                    AttackRequest(
                        domain="lcld",
                        x=artifacts["pool"][i * 7 : i * 7 + 3 + i],
                        eps=0.2,
                        budget=3,
                    ),
                    timeout=300.0,
                )
            snap = svc.metrics_snapshot()
            stages = snap["slo"]["stages"]["lcld"]
            for stage in (
                "validate", "queue_wait", "batch_wait",
                "dispatch", "device_run", "decode",
            ):
                assert stages[stage]["count"] >= 1, stage
                assert stages[stage]["n"] == stages[stage]["count"]
            # device_run excludes the compile-bearing dispatch
            assert stages["device_run"]["count"] < stages["dispatch"]["count"]

            # the capacity model shares the service's injectable clock:
            # completion timestamps and run_s must live in one clock
            # domain or the utilization span mixes bases
            assert svc.capacity.clock is svc.clock

            # the execute_direct ORACLE is not serving traffic: its
            # padded, un-coalesced dispatches must not land in the stage
            # histograms or the capacity window
            before_dev = stages["device_run"]["count"]
            before_cap = svc.healthz()["capacity"]["by_domain"]["lcld"]
            svc.execute_direct(
                AttackRequest(
                    domain="lcld", x=artifacts["pool"][:3], eps=0.2, budget=3
                ),
                bucket=8,
            )
            snap2 = svc.metrics_snapshot()
            assert (
                snap2["slo"]["stages"]["lcld"]["device_run"]["count"]
                == before_dev
            )
            cap2 = svc.healthz()["capacity"]["by_domain"]["lcld"]
            assert cap2["window_batches"] == before_cap["window_batches"]
            assert cap2["rows"] == before_cap["rows"]

            health = svc.healthz()
            cap = health["capacity"]["by_domain"]["lcld"]
            for key in (
                "predicted_flops_per_request", "achieved_flops_s",
                "max_sustainable_qps", "utilization", "headroom",
                "calibration", "basis", "per_class",
            ):
                assert key in cap, key
            assert cap["max_sustainable_qps"] > 0
            assert cap["window_batches"] >= 1
            assert health["slo"]["enabled"] is True

            text = prometheus_text(snap)
            assert "moeva2_stage_latency_seconds_bucket{" in text
            assert 'moeva2_capacity_max_sustainable_qps{domain="lcld"}' in text
            families, helped, typed = _prom_families(text)
            assert families - helped == set() and families - typed == set()
        finally:
            svc.close()

    def test_shed_attribution_expired_rejected_poisoned(self, artifacts):
        svc = make_service(
            artifacts, start=False, clock=FakeClock(), max_queue_rows=8
        )
        clock = svc.clock
        pool = artifacts["pool"]
        try:
            # expired: queued past its deadline, cancelled at assembly
            fut = svc.submit(
                AttackRequest(
                    domain="lcld", x=pool[:2], eps=0.2, budget=2,
                    deadline_s=0.5,
                )
            )
            clock.advance(1.0)
            svc.batcher.flush_due()
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=0)
            # rejected: backpressure past max_queue_rows
            svc.submit(
                AttackRequest(domain="lcld", x=pool[:6], eps=0.2, budget=2)
            )
            with pytest.raises(QueueFull):
                svc.submit(
                    AttackRequest(domain="lcld", x=pool[:6], eps=0.2, budget=2)
                )
            clock.advance(1.0)
            svc.batcher.flush_due()
            # poisoned: constraint-invalid rows fail their batch
            poison = pool[:2].copy()
            poison[:, 0] = 1e9
            f_poison = svc.submit(
                AttackRequest(domain="lcld", x=poison, eps=0.2, budget=2)
            )
            clock.advance(1.0)
            svc.batcher.flush_due()
            with pytest.raises(BatchExecutionError):
                f_poison.result(timeout=0)
            # invalid: unknown domain
            from moeva2_ijcai22_replication_tpu.serving import InvalidRequest

            with pytest.raises(InvalidRequest):
                svc.submit(AttackRequest(domain="nope", x=pool[:2]))

            # unknown-domain sheds fold under a sentinel: client-chosen
            # strings must not mint unbounded shed keys / label series
            with pytest.raises(InvalidRequest):
                svc.submit(AttackRequest(domain="other-junk", x=pool[:2]))

            shed = svc.slo.shed_block()["by_domain"]
            assert shed["lcld"]["expired"] == {"queue_wait": 1}
            assert shed["lcld"]["rejected"] == {"queue_wait": 1}
            assert shed["lcld"]["poisoned"] == {"dispatch": 1}
            assert shed["(unknown)"]["invalid"] == {"validate": 2}
            assert "nope" not in shed and "other-junk" not in shed
            # the counters also ride /healthz and /metrics
            assert svc.healthz()["slo"]["shed"]["total"] == 5
            assert svc.metrics_snapshot()["slo"]["shed"]["total"] == 5
        finally:
            svc.close()

    def test_slo_capture_zero_extra_compiles_and_bit_identical(
        self, artifacts
    ):
        """The tier-1 overhead smoke (same bar as tracing/ledger/quality
        off): SLO capture off pays the compiles, capture on must then add
        ZERO new compiles — same engines, same executables — and return
        bit-identical bytes for the same requests."""
        reqs = [
            AttackRequest(
                domain="lcld",
                x=artifacts["pool"][i * 11 : i * 11 + 2 + i],
                eps=0.25,
                budget=3,
            )
            for i in range(4)
        ]
        svc_off = make_service(artifacts, slo_capture=False)
        try:
            off = [svc_off.attack(r, timeout=300.0) for r in reqs]
            assert svc_off.metrics_snapshot()["slo"]["stages"] == {}
        finally:
            svc_off.close()
        svc_on = make_service(artifacts, slo_capture=True)
        try:
            on = [svc_on.attack(r, timeout=300.0) for r in reqs]
            assert svc_on.metrics.counters.get("compiles", 0) == 0, (
                "SLO capture must not add compiles"
            )
            assert svc_on.metrics_snapshot()["slo"]["stages"], (
                "capture on must actually record stages"
            )
        finally:
            svc_on.close()
        for a, b in zip(off, on):
            np.testing.assert_array_equal(a.x_adv, b.x_adv)
            assert a.meta["bucket_size"] == b.meta["bucket_size"]

    def test_flight_and_incident_capture_zero_overhead_bit_identical(
        self, artifacts
    ):
        """Same tier-1 bar for the black box + incident detector: capture
        OFF (flight_ring=0, incident_detection=False) pays the compiles;
        capture ON must add ZERO new compiles, the same dispatch count,
        and bit-identical bytes — the ring and the predicate pass are
        host-side dict work only."""
        reqs = [
            AttackRequest(
                domain="lcld",
                x=artifacts["pool"][i * 11 : i * 11 + 2 + i],
                eps=0.25,
                budget=3,
            )
            for i in range(4)
        ]
        svc_off = make_service(
            artifacts, flight_ring=0, incident_detection=False
        )
        try:
            off = [svc_off.attack(r, timeout=300.0) for r in reqs]
            snap = svc_off.metrics_snapshot()
            assert snap["flight"]["enabled"] is False
            assert snap["flight"]["recorded"] == 0
            assert snap["incidents"]["enabled"] is False
        finally:
            svc_off.close()
        batches_off = svc_off.metrics.counters["batches"]
        svc_on = make_service(artifacts)  # defaults: both captures on
        try:
            on = [svc_on.attack(r, timeout=300.0) for r in reqs]
            assert svc_on.metrics.counters.get("compiles", 0) == 0, (
                "flight/incident capture must not add compiles"
            )
            assert svc_on.metrics.counters["batches"] == batches_off
            snap = svc_on.metrics_snapshot()
            # capture on actually recorded the journeys
            assert snap["flight"]["recorded"] == len(reqs)
            entries = svc_on.flight.entries()
            assert {e["status"] for e in entries} == {"ok"}
            assert all(
                {"request_id", "trace_id", "domain", "latency_s",
                 "batch_seq"} <= set(e)
                for e in entries
            )
            assert snap["incidents"]["enabled"] is True
        finally:
            svc_on.close()
        for a, b in zip(off, on):
            np.testing.assert_array_equal(a.x_adv, b.x_adv)
            assert a.meta["bucket_size"] == b.meta["bucket_size"]

    def test_sweep_record_carries_slo_block(self, artifacts):
        from moeva2_ijcai22_replication_tpu.serving.sweep import (
            offered_load_sweep,
        )

        svc = make_service(artifacts, max_delay_s=0.01)
        try:
            # warm the bucket so the sweep measures steady serving
            svc.attack(
                AttackRequest(
                    domain="lcld", x=artifacts["pool"][:8], eps=0.2, budget=3
                ),
                timeout=300.0,
            )
            record = offered_load_sweep(
                svc,
                lambda i: AttackRequest(
                    domain="lcld",
                    x=artifacts["pool"][: 1 + i % 4],
                    eps=0.2,
                    budget=3,
                ),
                offered_rps_levels=[100.0],
                n_requests=16,
            )
        finally:
            svc.close()
        validate_record(record, "serving")
        slo = record["telemetry"]["slo"]
        validate_slo(slo)
        # the sweep's own traffic populated the windowed stage histograms
        assert slo["stages"]["lcld"]["queue_wait"]["count"] >= 16
        assert slo["knee"]["levels_n"] == 1
        assert slo["knee"]["knee_rps"] in (100.0, None)
        assert "capacity" in slo
        level = record["levels"][0]
        assert level["quantiles_n"] == level["completed"] == 16
        # the committed/gated knee is measured under Poisson arrivals by
        # default (a uniform metronome never stacks arrivals and reads
        # optimistically near saturation), and the level says so
        assert level["arrival"] == "poisson"
        # ServiceMetrics streams annotate their window sample count too
        assert record["latency"]["window_n"] >= 16
        json.dumps(record)  # strict JSON, no numpy leaks

    def test_sweep_record_is_strict_json_clean(self, artifacts):
        """Histogram bounds with +Inf markers and capacity Nones must
        survive json round-trip (RFC 8259: no NaN/Inf literals)."""
        t = SloTracker(bounds=(0.01,))
        t.observe("d", "validate", 99.0)
        blk = slo_block(t, knee=detect_knee([]))
        text = json.dumps(blk)
        assert "Infinity" not in text and "NaN" not in text


# ---------------------------------------------------------------------------
# bench_diff --slo gate
# ---------------------------------------------------------------------------


def _write(tmp_path, name, rec):
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


def _srecord(knee_rps=64.0, p99s=((16, 10.0), (64, 14.0)), steady=10.0):
    """A bench-shaped record whose serving block carries telemetry.slo."""
    levels = [
        {
            "offered_rps": float(r),
            "throughput_rps": float(r),
            "p99_ms": float(p),
            "quantiles_n": 50,
        }
        for r, p in p99s
    ]
    return {
        "steady_s": steady,
        "value": 50.0,
        "execution": {"n_states": 1000, "n_gen": 1000},
        "telemetry": {},
        "serving": {
            "levels": levels,
            "telemetry": {
                "slo": {
                    "stages": {},
                    "shed": {"total": 0, "by_domain": {}},
                    "knee": {
                        "knee_rps": knee_rps,
                        "first_saturated_rps": None,
                    },
                }
            },
        },
    }


class TestBenchDiffSlo:
    @pytest.fixture(scope="class")
    def bench_diff(self):
        return _load_tool("bench_diff")

    def test_knee_regression_fails_only_with_flag(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _srecord(knee_rps=64.0))
        b = _write(tmp_path, "r02.json", _srecord(knee_rps=16.0))
        assert bench_diff.main([a, b]) == 0  # legacy behavior untouched
        assert bench_diff.main([a, b, "--slo"]) == 1  # 75% knee drop

    def test_p99_at_fixed_load_regression_fails(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _srecord(p99s=((16, 10.0),)))
        b = _write(tmp_path, "r02.json", _srecord(p99s=((16, 25.0),)))
        assert bench_diff.main([a, b, "--slo"]) == 1

    def test_threshold_is_configurable_and_improvement_passes(
        self, bench_diff, tmp_path
    ):
        a = _write(tmp_path, "r01.json", _srecord(p99s=((16, 10.0),)))
        b = _write(tmp_path, "r02.json", _srecord(p99s=((16, 13.0),)))
        assert bench_diff.main([a, b, "--slo"]) == 0  # 30% < default 0.5
        assert bench_diff.main(
            [a, b, "--slo", "--slo-threshold", "0.2"]
        ) == 1
        better = _write(tmp_path, "r03.json", _srecord(p99s=((16, 5.0),)))
        assert bench_diff.main([a, better, "--slo"]) == 0

    def test_reshaped_ladder_skips_not_fails(self, bench_diff, tmp_path):
        a = _write(tmp_path, "r01.json", _srecord(p99s=((16, 10.0),)))
        b = _write(tmp_path, "r02.json", _srecord(p99s=((32, 500.0),)))
        # no shared offered level -> p99 not comparable; knee unchanged
        assert bench_diff.main([a, b, "--slo"]) == 0

    def test_pre_slo_baselines_skip(self, bench_diff, tmp_path):
        old = _write(
            tmp_path, "r01.json",
            {
                "steady_s": 10.0, "value": 50.0,
                "execution": {"n_states": 1000, "n_gen": 1000},
                "telemetry": {},
                # a PR-2-era serving block: levels but no telemetry.slo —
                # measured without the SLO discipline, not a baseline
                "serving": {"levels": [
                    {"offered_rps": 16.0, "throughput_rps": 16.0,
                     "p99_ms": 1.0}
                ]},
            },
        )
        new = _write(tmp_path, "r02.json", _srecord(p99s=((16, 500.0),)))
        assert bench_diff.main([old, new, "--slo"]) == 0

    def test_knee_degraded_to_null_fails(self, bench_diff, tmp_path, capsys):
        """A knee of None means NO level served linearly — worse than any
        number; it must fail against a numeric baseline, not silently
        vanish from the comparison."""
        a = _write(tmp_path, "r01.json", _srecord(knee_rps=64.0))
        b = _write(tmp_path, "r02.json", _srecord(knee_rps=None))
        assert bench_diff.main([a, b, "--slo"]) == 1
        assert "degraded to null" in capsys.readouterr().out
        assert bench_diff.main([a, b]) == 0  # flag off untouched

    def test_level_p99_degraded_to_null_fails(self, bench_diff, tmp_path):
        """A shared offered level whose p99 became null (completed zero
        requests) is a collapse at that rate, not a reshaped ladder."""
        a = _write(tmp_path, "r01.json", _srecord(p99s=((16, 10.0),)))
        rec = _srecord(p99s=())
        rec["serving"]["levels"] = [
            {"offered_rps": 16.0, "throughput_rps": 0.0, "p99_ms": None}
        ]
        b = _write(tmp_path, "r02.json", rec)
        assert bench_diff.main([a, b, "--slo"]) == 1

    def test_lost_slo_capture_fails(self, bench_diff, tmp_path, capsys):
        a = _write(tmp_path, "r01.json", _srecord())
        b = _write(
            tmp_path, "r02.json",
            {
                "steady_s": 10.0, "value": 50.0,
                "execution": {"n_states": 1000, "n_gen": 1000},
                "telemetry": {},
            },
        )
        assert bench_diff.main([a, b, "--slo"]) == 1
        assert "SLO capture was lost" in capsys.readouterr().out
        assert bench_diff.main([a, b]) == 0  # flag off: legacy behavior

    def test_json_line_carries_slo_verdicts(
        self, bench_diff, tmp_path, capsys
    ):
        a = _write(tmp_path, "r01.json", _srecord(knee_rps=64.0))
        b = _write(tmp_path, "r02.json", _srecord(knee_rps=16.0))
        rc = bench_diff.main([a, b, "--slo", "--json"])
        out = capsys.readouterr().out
        doc = json.loads(out.strip().splitlines()[-1])
        assert rc == 1 and doc["regressed"] is True and doc["slo"] is True
        by_metric = {m["metric"]: m for m in doc["metrics"]}
        k = by_metric["serving.slo.knee_rps"]
        assert k["kind"] == "slo" and k["verdict"] == "regression"
        assert k["delta_rel"] == pytest.approx(0.75)

    def test_committed_series_green_with_slo_flag(self, bench_diff, tmp_path):
        """The repo check's exact invocation: the committed series plus a
        first SLO-bearing record passes — pre-SLO records skip as
        baselines, the gate arms from this record forward."""
        import glob as _glob
        import shutil

        for p in sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
            shutil.copy(p, tmp_path / os.path.basename(p))
        rec = _srecord(steady=9.0)
        rec["value"] = 80.0
        # quality blocks mirroring the committed r06 values: the quality
        # gate is always-on, so a successor record appended after r06
        # must keep carrying the interior rates r06 armed (headline AND
        # real_botnet) or it fails as capture loss
        mk = lambda o2, o7: [1.0, o2, 1.0, o7, 1.0, o7, o7]  # noqa: E731
        rec["telemetry"]["quality"] = {
            "judged": "engine", "samples": 10, "curve": [],
            "interior": {
                "100": {"gen": 100, "o_rates": mk(0.20, 0.08)},
                "300": {"gen": 300, "o_rates": mk(0.95, 0.08)},
            },
        }
        rec["real_botnet"] = {
            "steady_s": 21.0, "n_states": 387, "n_gen": 1000,
            "quality": {
                "judged": "engine", "samples": 4, "curve": [],
                "interior": {
                    "100": {"gen": 100, "o_rates": mk(0.199, 0.08)},
                    "300": {"gen": 300, "o_rates": mk(0.632, 0.245)},
                },
            },
        }
        nxt = _write(
            tmp_path, "BENCH_r99.json", {"n": 99, "rc": 0, "parsed": rec}
        )
        series = sorted(str(p) for p in tmp_path.glob("BENCH_r*.json"))
        assert nxt in series
        assert bench_diff.main(["--check", "--slo", *series]) == 0


# ---------------------------------------------------------------------------
# quantile-n annotation (the tiny-sample guard satellite)
# ---------------------------------------------------------------------------


class TestQuantileConfidence:
    def test_service_metrics_streams_annotate_window_n(self):
        from moeva2_ijcai22_replication_tpu.utils.observability import (
            ServiceMetrics,
        )

        m = ServiceMetrics(window=4)
        for v in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0):
            m.observe("latency_s", v)
        s = m.snapshot()["streams"]["latency_s"]
        # quantiles over the window (last 4), history count over all 6
        assert s["count"] == 6 and s["window_n"] == 4
        # and the p99 over this tiny window IS the max — which is exactly
        # why window_n must ride next to it
        assert s["p99"] == s["max"] == 6.0

    def test_loadgen_poisson_arrivals_are_seeded_open_loop(self):
        """--arrival poisson draws seeded exponential inter-arrival gaps
        at the offered mean rate — reproducible bursts, not a metronome.
        Exercises the REAL ``tools/loadgen.py::arrival_offsets`` (the
        schedule ``run()`` submits on; the HTTP end-to-end rides the slow
        tier)."""
        from moeva2_ijcai22_replication_tpu.utils.observability import (
            arrival_offsets,
        )

        loadgen = _load_tool("loadgen")
        # ONE arrival-process definition: the loadgen CLI paces on the
        # same helper the in-process sweep does, so HTTP and in-process
        # knees are measured under comparable arrivals
        assert loadgen.arrival_offsets is arrival_offsets
        a = loadgen.arrival_offsets("poisson", 100.0, 200, seed=7)
        b = loadgen.arrival_offsets("poisson", 100.0, 200, seed=7)
        assert a == b  # seeded: a rerun offers the identical schedule
        gaps = [y - x for x, y in zip(a, a[1:])]
        mean_gap = sum(gaps) / len(gaps)
        assert 0.005 < mean_gap < 0.02  # mean ~ 1/rps
        assert len({round(g, 9) for g in gaps}) > 100  # not a metronome
        assert a != loadgen.arrival_offsets("poisson", 100.0, 200, seed=8)
        # uniform stays the metronome, precomputed the same open-loop way
        u = loadgen.arrival_offsets("uniform", 100.0, 5, seed=7)
        assert u == pytest.approx([0.0, 0.01, 0.02, 0.03, 0.04])
        assert loadgen.arrival_offsets("poisson", 0.0, 3, seed=7) == [0, 0, 0]

    def test_loadgen_latency_measured_from_scheduled_arrival(self):
        """post_attack charges latency from the request's SCHEDULED
        arrival time (t0), not from when a worker thread picked it up:
        excluding executor-queue wait would reintroduce coordinated
        omission through the thread pool."""
        import time as _time

        loadgen = _load_tool("loadgen")
        # nothing listens on this port — the request itself fails in ~ms,
        # so any seconds in the sample came from the scheduled backlog
        t_sched = _time.monotonic() - 5.0
        status, dt = loadgen.post_attack(
            "http://127.0.0.1:9", {"domain": "d"}, timeout=2.0, t0=t_sched
        )
        assert status.startswith("error:")
        assert dt >= 5.0
        # without t0 the clock starts at the call (the direct-use default)
        status, dt = loadgen.post_attack(
            "http://127.0.0.1:9", {"domain": "d"}, timeout=2.0
        )
        assert status.startswith("error:") and dt < 5.0

    def test_loadgen_cli_exposes_arrival_and_seed(self):
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--help"],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert out.returncode == 0
        assert "--arrival" in out.stdout and "poisson" in out.stdout
        assert "open-loop" in out.stdout or "open-" in out.stdout
        assert "--seed" in out.stdout
