"""Softmax ("simplex") gene type: codec tables, operator renormalisation,
and an end-to-end micro-attack.

Reference parity: ``SoftmaxPointCrossover`` / ``SoftmaxPolynomialMutation``
(``/root/reference/src/attacks/moeva2/softmax_{crossover,mutation}.py``) —
dormant there (no shipped dataset declares the type), first-class here: a
schema may type genes "softmax", and the operator stack keeps that sub-vector
on the probability simplex (crossover renorm for crossed matings, mutation
renorm for every row).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.moeva import operators
from moeva2_ijcai22_replication_tpu.core.codec import make_codec
from moeva2_ijcai22_replication_tpu.core.constraints import FunctionalConstraintSet
from moeva2_ijcai22_replication_tpu.core.schema import FeatureSchema
from moeva2_ijcai22_replication_tpu.models.io import Surrogate
from moeva2_ijcai22_replication_tpu.models.mlp import MLP, init_params


def _schema():
    """2 real + 4 softmax + 1 int + one 2-member OHE group (9 features)."""
    types = ["real", "real", "softmax", "softmax", "softmax", "softmax",
             "int", "ohe0", "ohe0"]
    n = len(types)
    return FeatureSchema(
        names=tuple(f"f{i}" for i in range(n)),
        types=np.array(types, dtype=object),
        mutable=np.ones(n, dtype=bool),
        raw_min=np.array([0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0], dtype=object),
        raw_max=np.array([1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 5.0, 1.0, 1.0], dtype=object),
        augmentation=np.zeros(n, dtype=bool),
    )


SOFTMAX_GENES = slice(2, 6)  # genetic layout: non-OHE genes first, in order


@pytest.fixture(scope="module")
def codec():
    return make_codec(_schema())


@pytest.fixture(scope="module")
def tables(codec):
    return operators.make_operator_tables(codec)


def _population(key, codec, n):
    """Random valid genetic population: softmax genes on the simplex."""
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.uniform(k1, (n, codec.gen_length))
    sm = np.asarray(codec.softmax_mask_gen)
    simplex = jax.random.dirichlet(k2, jnp.ones(int(sm.sum())), (n,))
    x = x.at[:, np.flatnonzero(sm)].set(simplex)
    x = x.at[:, 6].set(jnp.round(x[:, 6] * 5))  # int gene
    x = x.at[:, 7].set(jnp.round(jax.random.uniform(k3, (n,))))  # cat gene
    return x


class TestTables:
    def test_codec_masks(self, codec):
        # genetic layout: 7 non-OHE genes + 1 categorical group gene
        assert codec.gen_length == 8
        sm = np.asarray(codec.softmax_mask_gen)
        assert np.flatnonzero(sm).tolist() == [2, 3, 4, 5]
        # softmax genes are continuous: no integer rounding
        assert not np.asarray(codec.int_mask_gen)[sm].any()
        assert np.asarray(codec.int_mask_gen).tolist() == (
            [False, False, False, False, False, False, True, True]
        )

    def test_type_families(self, tables):
        assert tables.has_softmax
        assert np.asarray(tables.type_sizes).tolist() == [2, 2, 4]
        assert np.asarray(tables.type_id).tolist() == [0, 0, 2, 2, 2, 2, 1, 1]
        # per-type mutation prob: 1/n_type (pymoo sub-problem contract)
        np.testing.assert_allclose(
            np.asarray(tables.mut_prob), [0.5, 0.5, 0.25, 0.25, 0.25, 0.25, 0.5, 0.5]
        )

    def test_no_softmax_schema_unchanged(self):
        types = np.array(["real", "int"], dtype=object)
        schema = FeatureSchema(
            names=("a", "b"),
            types=types,
            mutable=np.ones(2, dtype=bool),
            raw_min=np.array([0.0, 0.0], dtype=object),
            raw_max=np.array([1.0, 5.0], dtype=object),
            augmentation=np.zeros(2, dtype=bool),
        )
        t = operators.make_operator_tables(make_codec(schema))
        assert not t.has_softmax
        assert np.asarray(t.type_sizes).tolist() == [1, 1, 0]


class TestOperatorsKeepSimplex:
    def test_crossover_renormalises_crossed_matings(self, codec, tables):
        key = jax.random.PRNGKey(0)
        p1 = _population(jax.random.PRNGKey(1), codec, 128)
        p2 = _population(jax.random.PRNGKey(2), codec, 128)
        c1, c2 = operators.two_point_crossover(key, tables, p1, p2, prob=1.0)
        for c in (np.asarray(c1), np.asarray(c2)):
            s = c[:, SOFTMAX_GENES]
            np.testing.assert_allclose(s.sum(1), 1.0, atol=1e-6)
            assert (s > 0).all()
            # non-softmax genes are pure swaps of parent genes
            both = np.stack([np.asarray(p1)[:, :2], np.asarray(p2)[:, :2]])
            assert np.all((c[:, :2] == both[0]) | (c[:, :2] == both[1]))

    def test_crossover_prob_zero_copies_parents_verbatim(self, codec, tables):
        p1 = _population(jax.random.PRNGKey(3), codec, 64)
        p2 = _population(jax.random.PRNGKey(4), codec, 64)
        c1, c2 = operators.two_point_crossover(
            jax.random.PRNGKey(5), tables, p1, p2, prob=0.0
        )
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(p1))
        np.testing.assert_array_equal(np.asarray(c2), np.asarray(p2))

    def test_mutation_renormalises_every_row(self, codec, tables):
        x = _population(jax.random.PRNGKey(6), codec, 256)
        xl = jnp.zeros(codec.gen_length)
        xu = jnp.asarray([1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 5.0, 1.0])
        y = np.asarray(
            operators.polynomial_mutation(jax.random.PRNGKey(7), tables, x, xl, xu)
        )
        s = y[:, SOFTMAX_GENES]
        np.testing.assert_allclose(s.sum(1), 1.0, atol=1e-6)
        assert (s > 0).all()
        # int gene still integral and in bounds
        assert np.all(y[:, 6] == np.round(y[:, 6]))
        assert y[:, 6].min() >= 0 and y[:, 6].max() <= 5

    def test_renorm_helper_leaves_other_genes_alone(self, tables):
        x = jnp.asarray(np.arange(16, dtype=float).reshape(2, 8))
        y = np.asarray(operators.softmax_renorm(tables.softmax_mask, x))
        np.testing.assert_array_equal(y[:, [0, 1, 6, 7]], np.asarray(x)[:, [0, 1, 6, 7]])
        np.testing.assert_allclose(y[:, SOFTMAX_GENES].sum(1), 1.0, atol=1e-6)


class TestOtherConsumers:
    def test_schema_rejects_unknown_type(self):
        with pytest.raises(ValueError, match="unknown feature type"):
            FeatureSchema(
                names=("a",),
                types=np.array(["sofmax"], dtype=object),  # typo must fail at load
                mutable=np.ones(1, dtype=bool),
                raw_min=np.array([0.0], dtype=object),
                raw_max=np.array([1.0], dtype=object),
                augmentation=np.zeros(1, dtype=bool),
            )

    def test_pgd_rounding_skips_softmax_features(self):
        from moeva2_ijcai22_replication_tpu.attacks.pgd.engine import (
            round_ints_toward_initial,
        )

        schema = _schema()
        x0 = np.array([[0.5, 0.5, 0.25, 0.25, 0.25, 0.25, 2.0, 1.0, 0.0]])
        xa = np.array([[0.7, 0.5, 0.4, 0.2, 0.2, 0.2, 2.6, 0.4, 0.6]])
        out = round_ints_toward_initial(xa, x0, schema.types)
        # softmax block untouched (continuous simplex), int/ohe rounded
        np.testing.assert_array_equal(out[0, 2:6], xa[0, 2:6])
        assert out[0, 6] == 2.0  # int moved up -> floored
        np.testing.assert_array_equal(out[0, 7:], [1.0, 0.0])

    def test_sat_repair_keeps_simplex(self):
        from moeva2_ijcai22_replication_tpu.attacks.sat.engine import (
            LinearRows,
            SatAttack,
        )
        from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

        schema = _schema()
        cons = FunctionalConstraintSet(
            schema,
            fn=lambda x: jnp.abs(1.0 - x[..., 2:6].sum(-1))[..., None],
            n_constraints=1,
        )
        atk = SatAttack(
            constraints=cons,
            sat_rows_builder=lambda x, h, box: LinearRows(rows=[], fixes={}),
            min_max_scaler=fit_minmax(
                np.zeros(9), np.array([1, 1, 1, 1, 1, 1, 5, 1, 1.0])
            ),
            eps=0.5,
            norm=np.inf,
        )
        x = np.array([[0.5, 0.5, 0.25, 0.25, 0.25, 0.25, 2.0, 1.0, 0.0]])
        # hot start off the simplex: the engine's auto-derived Σ=1 row must
        # pull the repair back onto it even with no domain rows at all
        hot = np.array([[0.5, 0.5, 0.45, 0.45, 0.25, 0.25, 2.0, 1.0, 0.0]])
        out = atk.generate(x, hot_start=hot)[:, 0, :]
        np.testing.assert_allclose(out[:, 2:6].sum(-1), 1.0, atol=1e-6)
        assert cons.check_constraints_error(out) is None


class TestEndToEnd:
    def test_attack_keeps_softmax_population_on_simplex(self):
        schema = _schema()
        cons = FunctionalConstraintSet(
            schema,
            fn=lambda x: jnp.zeros(x.shape[:-1] + (1,)),
            n_constraints=1,
        )
        model = MLP(hidden=(8,), n_classes=2)
        sur = Surrogate(model, init_params(model, schema.n_features, seed=0))

        codec = make_codec(schema)
        x_gen = _population(jax.random.PRNGKey(8), codec, 3)
        # ML space: genetic non-OHE genes map 1:1; expand the cat gene
        x = np.zeros((3, schema.n_features))
        x[:, :7] = np.asarray(x_gen)[:, :7]
        x[:, 7] = (np.asarray(x_gen)[:, 7] == 0).astype(float)
        x[:, 8] = (np.asarray(x_gen)[:, 7] == 1).astype(float)

        moeva = Moeva2(
            classifier=sur,
            constraints=cons,
            norm=2,
            n_gen=5,
            n_pop=12,
            n_offsprings=6,
            seed=9,
            dtype=jnp.float64,
        )
        res = moeva.generate(x, minimize_class=1)
        s = res.x_ml[..., 2:6]
        np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-6)
        assert (s >= 0).all()
        # the evolved populations actually moved
        assert not np.allclose(res.x_ml, np.broadcast_to(x[:, None, :], res.x_ml.shape))
