"""Differential validation of the jitted R-NSGA-III survival against a
vendored pymoo-0.4.2.2 oracle (``tests/oracles/pymoo_rnsga3.py``).

SURVEY §7 risk #1 / VERDICT r3 item 1: ``attacks/moeva/survival.py`` is the
most semantics-dense module in the tree and had no external check. pymoo is
not installable here, so the oracle is a clean-room numpy transcription of
``AspirationPointSurvival._do`` and its helpers; this test fuzzes both
implementations over >1000 cases and compares

- the normalisation geometry exactly (ideal/worst/extreme points, nadir,
  survival reference directions, per-candidate niche + distance),
- the survivor multiset exactly wherever the oracle is deterministic
  (same answer across oracle RNG seeds),
- the per-candidate survival *frequency* distributionally where the pymoo
  pick loop is genuinely random (cutoff cohorts, random member picks).

Cases cover degenerate fronts (totally-ordered rank-1 objectives), duplicate
rows, discrete objectives with mass ties, constant columns (degenerate
ranges), disjoint F/aspiration ranges, warm vs fresh normalisation state.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.attacks.moeva import survival as sv
from oracles import pymoo_rnsga3 as oracle

N_OBJ = 3
K1 = np.full((1, N_OBJ), 1.0 / N_OBJ)  # Das-Dennis cluster, pop_per_ref_point=1


# -- jitted wrappers (compiled once per shape) -------------------------------


@partial(jax.jit, static_argnums=(3,))
def _jax_geometry(f, asp, state, n_survive):
    ranks, dirs, nadir, new_state = sv._survive_pre(f, asp, state, n_survive)
    niche, dist = sv._associate(f, dirs, new_state.ideal, nadir)
    return ranks, dirs, nadir, new_state, niche, dist


@partial(jax.jit, static_argnums=(4,))
def _jax_survive(key, f, asp, state, n_survive):
    return sv.survive(key, f, asp, state, n_survive)


# -- case generation ---------------------------------------------------------


def _asp_points(rng, a):
    """Aspiration points on the unit simplex (what the engine feeds: energy
    reference directions)."""
    p = rng.dirichlet(np.ones(N_OBJ), size=a)
    return p


def _gen_f(rng, m, kind):
    f = rng.uniform(size=(m, N_OBJ))
    if kind == "uniform":
        return f
    if kind == "scaled":
        return f * rng.uniform(0.5, 20.0, size=N_OBJ) + rng.uniform(
            -5.0, 5.0, size=N_OBJ
        )
    if kind == "dup":
        k = max(1, m // 3)
        f[m - k :] = f[:k]
        return f
    if kind == "rank1":  # totally ordered: every front is a single point
        return rng.uniform(0.1, 1.0, size=(m, 1)) * rng.uniform(
            0.2, 2.0, size=(1, N_OBJ)
        )
    if kind == "discrete":  # mass ties and duplicated fronts
        return rng.integers(0, 3, size=(m, N_OBJ)).astype(float)
    if kind == "const_col":
        f[:, rng.integers(0, N_OBJ)] = 0.7
        return f
    if kind == "tiny_range":
        return 0.5 + 1e-9 * f
    if kind == "neg":
        return f - 2.0
    raise ValueError(kind)


KINDS = [
    "uniform",
    "scaled",
    "dup",
    "rank1",
    "discrete",
    "const_col",
    "tiny_range",
    "neg",
]
# (M merged, n_survive, A aspiration points); the first is engine-like
# geometry (n_survive = A + n_obj, M = n_survive + n_offsprings)
SHAPES = [(18, 11, 8), (12, 6, 5), (28, 14, 12)]


def _case_stream(n_cases, seed0):
    i = 0
    c = 0
    while c < n_cases:
        kind = KINDS[i % len(KINDS)]
        m, n_survive, a = SHAPES[(i // len(KINDS)) % len(SHAPES)]
        yield i, kind, m, n_survive, a, seed0 + i
        i += 1
        c += 1


def _rows_multiset(f, idx, tol_digits=10):
    return sorted(tuple(np.round(f[j], tol_digits)) for j in idx)


def _clone_oracle_state(state_proto):
    st = oracle.OracleNormState(N_OBJ)
    st.ideal_point = state_proto.ideal_point.copy()
    st.worst_point = state_proto.worst_point.copy()
    st.extreme_points = (
        None
        if state_proto.extreme_points is None
        else state_proto.extreme_points.copy()
    )
    return st


def _oracle_deterministic(f, asp, n_survive, state_proto, seed=1000, solver="lapack"):
    """One oracle selection round; report ``(is_deterministic, multiset)``.
    Determinism comes from the oracle's own instrumentation of the niching
    loop (exact: True iff no RNG draw could change the index set), not from
    sampling seeds — sampling misclassifies p≈0.5 coin-flip cases."""
    idx, dbg = oracle.aspiration_survive(
        f, asp, K1, n_survive, _clone_oracle_state(state_proto),
        np.random.RandomState(seed), nadir_solver=solver,
    )
    return dbg["niching_deterministic"], _rows_multiset(f, idx)


def _to_jax_state(st_oracle_prev, dtype=jnp.float64):
    """NormState mirroring an oracle state *before* a survival round."""
    if st_oracle_prev is None:
        return sv.NormState.init(N_OBJ, dtype)
    ext = (
        jnp.full((N_OBJ, N_OBJ), sv._BIG, dtype)
        if st_oracle_prev.extreme_points is None
        else jnp.asarray(st_oracle_prev.extreme_points, dtype)
    )
    return sv.NormState(
        ideal=jnp.asarray(st_oracle_prev.ideal_point, dtype),
        worst=jnp.asarray(st_oracle_prev.worst_point, dtype),
        extreme=ext,
    )


def _run_diff_case(case_seed, kind, m, n_survive, a, n_generations=3):
    """Run a multi-generation sequence through oracle and kernel, comparing
    geometry each generation; returns per-generation records for the
    selection comparison."""
    rng = np.random.default_rng(case_seed)
    asp = _asp_points(rng, a)
    asp_j = jnp.asarray(asp)

    st_o = oracle.OracleNormState(N_OBJ)
    st_j = sv.NormState.init(N_OBJ, jnp.float64)
    records = []

    for gen in range(n_generations):
        # generation 0 mirrors the engine's warm-up round: M == n_survive
        m_gen = n_survive if gen == 0 else m
        f = _gen_f(rng, m_gen, kind)

        st_o_before = oracle.OracleNormState(N_OBJ)
        st_o_before.ideal_point = st_o.ideal_point.copy()
        st_o_before.worst_point = st_o.worst_point.copy()
        st_o_before.extreme_points = (
            None if st_o.extreme_points is None else st_o.extreme_points.copy()
        )

        idx_o, dbg = oracle.aspiration_survive(
            f, asp, K1, n_survive, st_o, np.random.RandomState(case_seed + gen)
        )

        f_j = jnp.asarray(f)
        ranks, dirs, nadir, st_j_new, niche, dist = _jax_geometry(
            f_j, asp_j, st_j, n_survive
        )

        # --- geometry must match exactly (up to fp64 noise) ---
        np.testing.assert_allclose(
            np.asarray(st_j_new.ideal), dbg["ideal"], rtol=1e-9, atol=1e-12,
            err_msg=f"ideal mismatch (kind={kind} gen={gen})",
        )
        np.testing.assert_allclose(
            np.asarray(st_j_new.worst), dbg["worst"], rtol=1e-9, atol=1e-12,
            err_msg=f"worst mismatch (kind={kind} gen={gen})",
        )
        np.testing.assert_allclose(
            np.asarray(st_j_new.extreme), dbg["extreme"], rtol=1e-7, atol=1e-9,
            err_msg=f"extreme points mismatch (kind={kind} gen={gen})",
        )
        # An ill-conditioned (but not deterministically-singular) extreme
        # matrix sits in the band where the oracle's LAPACK solve and the
        # kernel's Cramer solve legitimately disagree at the tolerance
        # boundary (see the oracle's get_nadir_point note). Rather than skip
        # (the r4 blind band), PIN the oracle to the kernel's Cramer
        # formulation there and keep comparing everything downstream — the
        # LAPACK-vs-Cramer residual is solver noise, the geometry pipeline
        # under one solver is semantics. Deterministically-singular systems
        # (cond>=1e15, duplicate extreme rows) take the same fallback on
        # both sides under either solver.
        cond = np.linalg.cond(dbg["extreme"] - dbg["ideal"])
        borderline = 1e9 < cond < 1e15
        if borderline:
            idx_o, dbg = oracle.aspiration_survive(
                f, asp, K1, n_survive, _clone_oracle_state(st_o_before),
                np.random.RandomState(case_seed + gen),
                nadir_solver="cramer",
            )
        np.testing.assert_allclose(
            np.asarray(nadir), dbg["nadir"], rtol=1e-7, atol=1e-9,
            err_msg=f"nadir mismatch (kind={kind} gen={gen}, cond={cond:.2e}, "
                    f"borderline={borderline})",
        )
        np.testing.assert_allclose(
            np.asarray(dirs), dbg["ref_dirs"], rtol=1e-7, atol=1e-9,
            err_msg=f"ref dirs mismatch (kind={kind} gen={gen})",
        )

        # ranks agree on every candidate the oracle ranked (the kernel's
        # unranked tail keeps a sentinel; the oracle's keeps len(F))
        ranks_np = np.asarray(ranks)
        ranked = dbg["rank"] < len(f)
        kernel_ranked = ranks_np != np.iinfo(np.int32).max
        assert (ranked == kernel_ranked).all(), f"ranked-set mismatch ({kind})"
        assert (ranks_np[ranked] == dbg["rank"][ranked]).all(), (
            f"front ranks mismatch (kind={kind} gen={gen})"
        )

        # niche association: oracle reports the ranked subset in front
        # order; distances are tie-invariant so compare them always
        ranked_idx = dbg["ranked_idx"]
        np.testing.assert_allclose(
            np.asarray(dist)[ranked_idx], dbg["dist"], rtol=1e-6, atol=1e-9,
            err_msg=f"niche distance mismatch (kind={kind} gen={gen})",
        )
        records.append(
            {
                "f": f,
                "st_o_before": st_o_before,
                "st_j_before": st_j,
                "idx_o": idx_o,
                "solver": "cramer" if borderline else "lapack",
                "n_dirs": np.asarray(dirs).shape[0],
            }
        )
        st_j = st_j_new

    return asp, records


# -- tests -------------------------------------------------------------------


def _diff_fuzz(n_cases, seed0):
    n_det = n_rand = 0
    for i, kind, m, n_survive, a, seed in _case_stream(n_cases, seed0):
        asp, records = _run_diff_case(seed, kind, m, n_survive, a)
        asp_j = jnp.asarray(asp)
        for gen, rec in enumerate(records):
            f = rec["f"]
            det, surv_o = _oracle_deterministic(
                f, asp_j.__array__(), n_survive, rec["st_o_before"],
                solver=rec["solver"],
            )
            for key_i in range(2):
                key = jax.random.PRNGKey(seed * 7 + gen * 3 + key_i)
                mask, _, _ = _jax_survive(
                    key, jnp.asarray(f), asp_j, rec["st_j_before"], n_survive
                )
                mask = np.asarray(mask)
                assert mask.sum() == n_survive, (
                    f"survivor count {mask.sum()} != {n_survive} "
                    f"(kind={kind} case={i} gen={gen})"
                )
                if det:
                    got = _rows_multiset(f, np.where(mask)[0])
                    assert got == surv_o, (
                        f"deterministic survivor set mismatch "
                        f"(kind={kind} case={i} gen={gen})"
                    )
                    n_det += 1
                else:
                    n_rand += 1
    # the stream must actually exercise the deterministic comparison
    assert n_det > n_cases, f"too few deterministic checks: {n_det}"


def test_survival_matches_pymoo_oracle_quick():
    _diff_fuzz(n_cases=60, seed0=20_000)


@pytest.mark.slow
def test_survival_matches_pymoo_oracle_full():
    _diff_fuzz(n_cases=400, seed0=50_000)


def _shared_trace_fuzz(n_cases, seed0, min_random):
    """EXACT survivor-set comparison through the RANDOM niching paths: both
    implementations consume the same two gumbel fields (the kernel natively;
    the oracle via priority-injected niching — a random permutation/truncation
    is distributionally a top-k by iid keys, and sequential uniform
    without-replacement picks are exactly ascending iid-key order), so the
    water-filling + vectorised ranking must reproduce pymoo's sequential pick
    loop index-for-index, not just in distribution."""
    n_random = n_checked = 0
    for i, kind, m, n_survive, a, seed in _case_stream(n_cases, seed0):
        asp, records = _run_diff_case(seed, kind, m, n_survive, a)
        asp_j = jnp.asarray(asp)
        for gen, rec in enumerate(records):
            f = rec["f"]
            det, _ = _oracle_deterministic(
                f, asp, n_survive, rec["st_o_before"], solver=rec["solver"]
            )
            key = jax.random.PRNGKey(seed * 11 + gen)
            mask, _, _ = _jax_survive(
                key, jnp.asarray(f), asp_j, rec["st_j_before"], n_survive
            )
            gum_cut, gum_mem = sv._niche_gumbels(
                key, (), rec["n_dirs"], f.shape[0]
            )
            idx_o, _ = oracle.aspiration_survive(
                f, asp, K1, n_survive, _clone_oracle_state(rec["st_o_before"]),
                np.random.RandomState(0),
                nadir_solver=rec["solver"],
                niche_priority=np.asarray(gum_cut),
                member_priority=np.asarray(gum_mem),
            )
            got = sorted(np.where(np.asarray(mask))[0].tolist())
            want = sorted(np.asarray(idx_o).tolist())
            assert got == want, (
                f"shared-trace survivor mismatch (kind={kind} case={i} "
                f"gen={gen} det={det}): kernel={got} oracle={want}"
            )
            n_checked += 1
            if not det:
                n_random += 1
    # the point of this fuzz is the RANDOM paths — require real coverage
    assert n_random >= min_random, (
        f"only {n_random} random-niching cases exercised ({n_checked} total)"
    )


def test_survival_shared_trace_exact_quick():
    _shared_trace_fuzz(n_cases=40, seed0=130_000, min_random=8)


@pytest.mark.slow
def test_survival_shared_trace_exact_full():
    _shared_trace_fuzz(n_cases=240, seed0=160_000, min_random=40)


@pytest.mark.slow
def test_survival_random_cutoff_distribution():
    """Where the pymoo niching is random (cutoff cohorts / member picks),
    compare per-candidate survival frequencies over many seeds."""
    n_draws = 260
    checked = 0
    for i, kind, m, n_survive, a, seed in _case_stream(40, 90_000):
        if kind in ("dup", "discrete", "rank1"):
            continue  # duplicate rows make index-marginals incomparable
        asp, records = _run_diff_case(seed, kind, m, n_survive, a)
        asp_j = jnp.asarray(asp)
        rec = records[-1]
        f = rec["f"]
        det, _ = _oracle_deterministic(f, asp, n_survive, rec["st_o_before"])
        if det:
            continue
        # oracle marginals
        freq_o = np.zeros(len(f))
        for s in range(n_draws):
            st = oracle.OracleNormState(N_OBJ)
            st.ideal_point = rec["st_o_before"].ideal_point.copy()
            st.worst_point = rec["st_o_before"].worst_point.copy()
            st.extreme_points = (
                None
                if rec["st_o_before"].extreme_points is None
                else rec["st_o_before"].extreme_points.copy()
            )
            idx, _ = oracle.aspiration_survive(
                f, asp, K1, n_survive, st, np.random.RandomState(3_000 + s)
            )
            freq_o[idx] += 1.0
        freq_o /= n_draws
        # kernel marginals
        freq_j = np.zeros(len(f))
        f_j = jnp.asarray(f)
        for s in range(n_draws):
            key = jax.random.PRNGKey(600_000 + s)
            mask, _, _ = _jax_survive(key, f_j, asp_j, rec["st_j_before"], n_survive)
            freq_j += np.asarray(mask)
        freq_j /= n_draws
        # binomial noise at n=260 is sigma <= 0.031 per side
        assert np.abs(freq_o - freq_j).max() < 0.15, (
            f"survival frequency diverges (kind={kind} case={i}): "
            f"max|Δ|={np.abs(freq_o - freq_j).max():.3f}"
        )
        assert np.abs(freq_o - freq_j).mean() < 0.03
        checked += 1
        if checked >= 8:
            break
    assert checked >= 3, "fuzz stream produced too few random-cutoff cases"
