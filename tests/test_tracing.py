"""Unified tracing & telemetry subsystem tests.

Core (hardware-free): span nesting/ids and per-thread parentage, explicit-
duration spans, the buffer-only/adopt composition the microbatcher uses,
ring-buffer bounding, always-on counters vs opt-in spans, JSONL round-trip
through the Perfetto exporter (library + ``tools/trace_export.py`` CLI),
and Prometheus text exposition.

Serving (tier-1 acceptance): one request driven through a traced
``AttackService`` yields a single correlated span tree covering
validate -> queue_wait -> batch_wait -> dispatch -> device -> decode,
exportable to valid Chrome/Perfetto trace-event JSON — and the overhead
smoke proves tracing-off is a no-op (zero span events, zero extra
dispatches) while tracing-on adds no compiles and leaves results
bit-identical.

Plus the satellite contracts: PhaseTimer spans survive wall-clock steps
(perf_counter), ServiceMetrics mirrors into the recorder, ``/healthz``
carries build/config identity, and the shared record schema
(``execution`` + ``telemetry``) is enforced at every record producer.
"""

import json
import os
import time

import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.observability import (
    REQUIRED_RECORD_KEYS,
    Trace,
    TraceRecorder,
    build_identity,
    current_trace,
    device_memory_stats,
    maybe_span,
    recorder_for,
    telemetry_block,
    use_trace,
    validate_record,
)
from moeva2_ijcai22_replication_tpu.observability.export import (
    read_jsonl,
    to_chrome_trace,
)
from moeva2_ijcai22_replication_tpu.observability.prom import prometheus_text
from moeva2_ijcai22_replication_tpu.utils.observability import (
    PhaseTimer,
    ServiceMetrics,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# trace core
# ---------------------------------------------------------------------------


class TestTraceCore:
    def test_span_nesting_ids_and_events(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec, trace_id="t1")
        with t.span("outer") as outer_id:
            with t.span("inner", k=1) as inner_id:
                t.event("tick", x=2)
        assert outer_id != inner_id
        by_name = {e["name"]: e for e in t.events}
        assert by_name["outer"]["parent"] is None
        assert by_name["inner"]["parent"] == outer_id
        assert by_name["inner"]["attrs"] == {"k": 1}
        assert by_name["tick"]["parent"] == inner_id
        assert all(e["trace"] == "t1" for e in t.events)
        assert all(e["dur"] >= 0 for e in t.events if e["kind"] == "span")
        # same events landed in the recorder ring
        assert [e["name"] for e in rec.events()] == ["tick", "inner", "outer"]

    def test_tree_nests_children_in_ts_order(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec)
        with t.span("root"):
            with t.span("a"):
                pass
            with t.span("b"):
                t.event("e")
        (root,) = t.tree()
        assert root["name"] == "root"
        assert [c["name"] for c in root["children"]] == ["a", "b"]
        assert [c["name"] for c in root["children"][1]["children"]] == ["e"]

    def test_record_span_explicit_duration_parents_under_current(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec)
        with t.span("dispatch") as did:
            t.record_span("device_run", 0.25, traces=0)
        dev = next(e for e in t.events if e["name"] == "device_run")
        assert dev["parent"] == did
        assert dev["dur"] == 0.25
        assert dev["ts"] >= 0

    def test_disabled_recorder_spans_are_noops_counters_stay_on(self):
        rec = TraceRecorder(spans_enabled=False)
        t = Trace(rec)
        with t.span("x") as sid:
            t.event("y")
        assert sid is None
        assert t.events == [] and rec.events() == []
        assert rec.events_emitted == 0
        rec.count("requests", 2)
        rec.gauge("depth", 7)
        assert rec.counters["requests"] == 2 and rec.gauges["depth"] == 7.0
        # gauges emit no events while spans are off
        assert rec.events() == []

    def test_gauge_emits_counter_event_when_spans_enabled(self):
        rec = TraceRecorder(spans_enabled=True)
        rec.gauge("queue_depth", 3)
        (ev,) = rec.events()
        assert ev["kind"] == "gauge" and ev["value"] == 3.0

    def test_ring_buffer_bounded_but_count_unbounded(self):
        rec = TraceRecorder(capacity=8, spans_enabled=True)
        t = Trace(rec)
        for i in range(20):
            t.event(f"e{i}")
        assert len(rec.events()) == 8
        assert rec.events_emitted == 20
        assert [e["name"] for e in rec.events()] == [
            f"e{i}" for i in range(12, 20)
        ]

    def test_adopt_restamps_buffer_only_trace(self):
        rec = TraceRecorder(spans_enabled=True)
        batch = Trace(rec, trace_id="batch-1", record=False)
        with batch.span("dispatch"):
            batch.record_span("device_run", 0.1)
        assert rec.events() == []  # buffer-only: nothing recorded yet
        req = Trace(rec, trace_id="req-1")
        root = req.record_span("queue_wait", 0.01)
        req.adopt(batch, parent=root)
        assert {e["trace"] for e in rec.events()} == {"req-1"}
        names = {e["name"] for e in rec.events()}
        assert names == {"queue_wait", "dispatch", "device_run"}
        # the adopted dispatch span hangs under the request's root
        dispatch = next(e for e in req.events if e["name"] == "dispatch")
        assert dispatch["parent"] == root

    def test_ambient_trace_helpers(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec)
        assert current_trace() is None
        with use_trace(t):
            assert current_trace() is t
            with maybe_span(current_trace(), "s"):
                pass
        assert current_trace() is None
        assert [e["name"] for e in t.events] == ["s"]
        # maybe_span on None is a no-op context
        with maybe_span(None, "nothing"):
            pass

    def test_recorder_for_config_and_default(self, tmp_path):
        assert recorder_for(None) is recorder_for({})
        assert not recorder_for({}).spans_enabled
        path = str(tmp_path / "t.jsonl")
        rec = recorder_for({"system": {"trace_log": path}})
        assert rec.spans_enabled and rec.sink_path == path
        # memoized per path: every run in the process appends to one stream
        assert recorder_for({"system": {"trace_log": path}}) is rec

    def test_device_memory_stats_never_raises(self):
        # CPU backend exposes no allocator stats -> None; must not raise
        assert device_memory_stats() is None or isinstance(
            device_memory_stats(), dict
        )


# ---------------------------------------------------------------------------
# JSONL sink -> Perfetto export (library + CLI)
# ---------------------------------------------------------------------------


class TestJsonlExport:
    def _sink(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        rec = TraceRecorder(sink_path=path)
        t = Trace(rec, trace_id="run-abc")
        with t.span("attack", eps=0.2):
            with t.span("device"):
                t.event("moeva.gate", gen=10, active=4)
        rec.gauge("grid_writer_queue_depth", 2)
        rec.close()
        return path

    def test_jsonl_roundtrip_to_chrome_trace(self, tmp_path):
        path = self._sink(tmp_path)
        events = read_jsonl(path)
        assert events[0]["kind"] == "meta" and "t0_wall" in events[0]
        doc = to_chrome_trace(events)
        json.loads(json.dumps(doc))  # strictly serializable
        tevs = doc["traceEvents"]
        spans = [e for e in tevs if e["ph"] == "X"]
        assert {e["name"] for e in spans} == {"attack", "device"}
        assert all(
            isinstance(e["ts"], float) and e["dur"] >= 0 for e in spans
        )
        (inst,) = [e for e in tevs if e["ph"] == "i"]
        assert inst["name"] == "moeva.gate" and inst["args"]["gen"] == 10
        (counter,) = [e for e in tevs if e["ph"] == "C"]
        assert counter["args"]["value"] == 2.0
        # all events of one trace share one pid; its process_name metadata
        # names the trace id
        pids = {e["pid"] for e in spans}
        assert len(pids) == 1
        names = [
            e
            for e in tevs
            if e["ph"] == "M" and e["args"]["name"] == "run-abc"
        ]
        assert len(names) == 1 and names[0]["pid"] in pids

    def test_cli_tool(self, tmp_path):
        import importlib.util

        path = self._sink(tmp_path)
        spec = importlib.util.spec_from_file_location(
            "trace_export_cli", os.path.join(REPO, "tools", "trace_export.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = str(tmp_path / "out.json")
        assert mod.main([path, "-o", out]) == 0
        with open(out) as fh:
            doc = json.load(fh)
        assert any(e["ph"] == "X" for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


class TestPrometheus:
    def test_counters_gauges_summaries_and_extras(self):
        m = ServiceMetrics(window=16)
        m.count("requests", 5)
        m.count("batch_failures")
        m.gauge("queue_depth_rows", 12)
        for v in (0.1, 0.2, 0.3):
            m.observe("latency_s", v)
        snap = m.snapshot()
        snap["engine_cache"] = {"hits": 3, "misses": 1}
        snap["resolved_run_configs"] = 2
        text = prometheus_text(snap)
        assert "# TYPE moeva2_requests_total counter" in text
        assert "moeva2_requests_total 5" in text
        assert "# TYPE moeva2_queue_depth_rows gauge" in text
        assert 'moeva2_latency_s{quantile="0.5"} 0.2' in text
        assert "moeva2_latency_s_count 3" in text
        assert "moeva2_engine_cache_hits 3" in text
        assert "moeva2_resolved_run_configs 2" in text
        # every sample line parses as `name[{labels}] <float>`
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            name, value = line.rsplit(" ", 1)
            assert name and float(value) == float(value)  # no NaN leakage

    def test_empty_stream_renders_zero_sum(self):
        m = ServiceMetrics()
        text = prometheus_text(m.snapshot())
        assert text.endswith("\n")


# ---------------------------------------------------------------------------
# facades (satellites): perf_counter timing + recorder mirroring
# ---------------------------------------------------------------------------


class TestFacades:
    def test_phase_timer_survives_wall_clock_steps(self, monkeypatch):
        # simulate an NTP step: time.time jumps backwards mid-span; spans
        # are perf_counter-based so the recorded duration stays sane
        steps = iter([1e9, 12.0, -5.0])
        monkeypatch.setattr(time, "time", lambda: next(steps, -1.0))
        timer = PhaseTimer()
        with timer.phase("x"):
            pass
        assert 0 <= timer.spans["x"] < 10

    def test_attack_split_survives_wall_clock_steps(self, monkeypatch):
        steps = iter([1e9, -1e9])
        monkeypatch.setattr(time, "time", lambda: next(steps, 0.0))

        class Engine:
            trace_count = 0

        timer = PhaseTimer()
        with timer.attack(Engine()):
            pass
        assert 0 <= timer.spans["attack_run"] < 10

    def test_phase_timer_emits_into_trace(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec, trace_id="run-1")
        timer = PhaseTimer(trace=t)
        with timer.phase("setup"):
            pass

        class Engine:
            trace_count = 0

            def bump(self):
                self.trace_count += 1

        eng = Engine()
        with timer.attack(eng):
            eng.bump()
        names = [e["name"] for e in t.events]
        assert "setup" in names and "attack" in names
        assert "attack_compile" in names  # the dispatch traced
        assert timer.counters["traces"] == 1

    def test_service_metrics_mirror_into_recorder(self):
        rec = TraceRecorder(spans_enabled=False)
        m = ServiceMetrics(recorder=rec)
        m.count("requests", 3)
        m.gauge("depth", 4)
        m.observe("latency_s", 0.1)  # streams stay local
        assert rec.counters == {"requests": 3}
        assert rec.gauges == {"depth": 4.0}


# ---------------------------------------------------------------------------
# shared record schema
# ---------------------------------------------------------------------------


class TestRecordSchema:
    def test_validate_record_rejects_missing_keys(self):
        from moeva2_ijcai22_replication_tpu.observability import quality_block

        with pytest.raises(ValueError, match="telemetry"):
            validate_record({"execution": {}}, "bench")
        # PR-5 cost ledger: telemetry must carry the cost sub-block too
        with pytest.raises(ValueError, match="cost"):
            validate_record({"execution": {}, "telemetry": {}}, "bench")
        # PR-6 quality telemetry: and the quality sub-block
        with pytest.raises(ValueError, match="quality"):
            validate_record({"execution": {}, "telemetry": {"cost": {}}}, "bench")
        with pytest.raises(ValueError, match="interior"):
            validate_record(
                {"execution": {}, "telemetry": {"cost": {}, "quality": {}}},
                "bench",
            )
        # PR-9 dispatch-gap ledger: and the gaps sub-block
        with pytest.raises(ValueError, match="gaps"):
            validate_record(
                {
                    "execution": {},
                    "telemetry": {"cost": {}, "quality": quality_block()},
                },
                "bench",
            )
        rec = {
            "execution": {},
            "telemetry": {
                "cost": {},
                "quality": quality_block(),
                "gaps": {"enabled": False},
            },
        }
        assert validate_record(rec) is rec
        assert set(REQUIRED_RECORD_KEYS) == {"execution", "telemetry"}

    def test_telemetry_block_shape(self):
        rec = TraceRecorder(spans_enabled=True)
        t = Trace(rec)
        t.event("e")
        timer = PhaseTimer()
        with timer.phase("setup"):
            pass
        block = telemetry_block(recorder=rec, timer=timer, trace=t)
        assert block["events"] == 1 and block["trace_id"] == t.id
        assert "setup" in block["spans_s"]
        assert block["events_emitted"] == 1
        assert "hbm" in block
        json.dumps(block)  # JSON-ready

    def test_grid_report_carries_schema_keys(self):
        from moeva2_ijcai22_replication_tpu.experiments.pipeline import (
            GridPipeline,
        )

        gp = GridPipeline(recorder=TraceRecorder(spans_enabled=False))
        report = gp.finish({"seeds": [1], "system": {"mesh_devices": 0}}, [])
        assert validate_record(report, "grid") is report
        assert report["execution"]["pipeline"] is True
        assert "hbm" in report["telemetry"]

    def test_record_producers_keep_calling_the_validator(self):
        """Repo check: the three record producers (bench, serving sweep,
        grid pipeline) must keep assembling the shared schema through
        observability.records — a refactor dropping the keys fails here
        before it can silently drop them from committed records."""
        producers = {
            "bench.py": ("validate_record", "telemetry"),
            "moeva2_ijcai22_replication_tpu/serving/sweep.py": (
                "validate_record",
                "telemetry_block",
            ),
            "moeva2_ijcai22_replication_tpu/experiments/pipeline.py": (
                "validate_record",
                "telemetry_block",
            ),
            # runner metrics embed the telemetry block next to `execution`
            "moeva2_ijcai22_replication_tpu/experiments/moeva.py": (
                "telemetry_block",
            ),
            "moeva2_ijcai22_replication_tpu/experiments/pgd.py": (
                "telemetry_block",
            ),
        }
        for fname, needles in producers.items():
            with open(os.path.join(REPO, fname)) as fh:
                src = fh.read()
            for needle in needles:
                assert needle in src, f"{fname} no longer references {needle}"

    def test_build_identity(self):
        ident = build_identity({"a": 1})
        assert set(ident) >= {"git", "version", "config_hash"}
        from moeva2_ijcai22_replication_tpu.utils.config import get_dict_hash

        assert ident["config_hash"] == get_dict_hash({"a": 1})


# ---------------------------------------------------------------------------
# serving: traced request lifecycle + the tier-1 overhead smoke
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Synthetic-LCLD artifact family (same shape as test_serving's): the
    tracing acceptance tests run dataset- and hardware-free."""
    import joblib
    from sklearn.preprocessing import MinMaxScaler

    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_lcld,
        synth_lcld_schema,
    )
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp

    tmp = tmp_path_factory.mktemp("tracing_artifacts")
    paths = synth_lcld_schema(str(tmp))
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(64, cons.schema, seed=9)
    cons.check_constraints_error(x)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=4))
    save_params(sur, str(tmp / "nn.msgpack"))
    xl, xu = cons.get_feature_min_max(dynamic_input=x)
    xl = np.broadcast_to(np.asarray(xl, float), x.shape)
    xu = np.broadcast_to(np.asarray(xu, float), x.shape)
    joblib.dump(
        MinMaxScaler().fit(np.vstack([x, xl, xu])), tmp / "scaler.joblib"
    )
    return {
        "pool": x,
        "domain": {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": str(tmp / "nn.msgpack"),
                "features": paths["features"],
                "constraints": paths["constraints"],
                "ml_scaler": str(tmp / "scaler.joblib"),
            },
            "system": {"mesh_devices": 0},
        },
    }


def make_service(artifacts, **kw):
    from moeva2_ijcai22_replication_tpu.serving import AttackService

    kw.setdefault("bucket_sizes", (8,))
    kw.setdefault("max_delay_s", 0.01)
    return AttackService({"lcld": artifacts["domain"]}, **kw)


def _requests(artifacts, n=6, budget=2):
    from moeva2_ijcai22_replication_tpu.serving import AttackRequest

    pool = artifacts["pool"]
    sizes = [1, 2, 3]
    out = []
    for i in range(n):
        rows = sizes[i % len(sizes)]
        start = (i * 7) % (pool.shape[0] - rows)
        out.append(
            AttackRequest(
                domain="lcld",
                x=pool[start : start + rows],
                eps=0.2,
                budget=budget,
            )
        )
    return out


def _span_names(tree):
    names = set()
    stack = list(tree)
    while stack:
        node = stack.pop()
        names.add(node["name"])
        stack.extend(node.get("children", ()))
    return names


class TestServingTraced:
    def test_request_trace_covers_lifecycle_and_exports(self, artifacts):
        """Acceptance: one request through AttackService yields a single
        correlated trace covering queue_wait -> batch -> device -> decode,
        exportable to valid Perfetto trace-event JSON."""
        rec = TraceRecorder(spans_enabled=True)
        svc = make_service(artifacts, recorder=rec)
        try:
            (req,) = _requests(artifacts, n=1)
            resp = svc.attack(req, timeout=300.0)
        finally:
            svc.close()

        tree = resp.meta["trace"]
        names = _span_names(tree)
        assert {
            "validate",
            "queue_wait",
            "batch_wait",
            "dispatch",
            "decode",
        } <= names
        assert "device_compile" in names or "device_run" in names
        # device + decode hang under the adopted dispatch span
        dispatch = next(
            n
            for n in (t for t in tree)
            if n["name"] == "dispatch"
        )
        children = {c["name"] for c in dispatch["children"]}
        assert "decode" in children
        assert children & {"device_compile", "device_run"}

        # single correlated stream: every recorded event of this request
        # carries the request's trace id
        rid = resp.meta["request_id"]
        req_events = [
            e for e in rec.events() if e.get("trace") == f"req-{rid}"
        ]
        assert {"queue_wait", "dispatch"} <= {
            e.get("name") for e in req_events
        }

        # exportable: valid Chrome/Perfetto trace-event JSON
        doc = to_chrome_trace(rec.events())
        json.loads(json.dumps(doc))
        assert any(e["ph"] == "X" for e in doc["traceEvents"])

        # /metrics?format=prom serves the same counters in text exposition
        text = prometheus_text(svc.metrics_snapshot())
        assert "moeva2_requests_total 1" in text
        assert "moeva2_batches_total 1" in text

    def test_tracing_overhead_contract(self, artifacts):
        """Tier-1 overhead smoke: tracing disabled is a no-op (zero span
        events), and enabling it adds zero compiles, zero extra dispatches,
        and leaves served numbers bit-identical."""
        # 1) tracing OFF — the baseline; first service pays the compiles
        rec_off = TraceRecorder(spans_enabled=False)
        svc_off = make_service(artifacts, recorder=rec_off)
        try:
            resps_off = [
                svc_off.attack(r, timeout=300.0) for r in _requests(artifacts)
            ]
        finally:
            svc_off.close()
        batches_off = svc_off.metrics.counters["batches"]
        assert rec_off.events() == []  # no span/event work at all
        assert all("trace" not in r.meta for r in resps_off)

        # 2) tracing ON — same engines via the process-wide caches
        rec_on = TraceRecorder(spans_enabled=True)
        svc_on = make_service(artifacts, recorder=rec_on)
        try:
            resps_on = [
                svc_on.attack(r, timeout=300.0) for r in _requests(artifacts)
            ]
        finally:
            svc_on.close()

        # no new compiled programs: tracing must not perturb shapes/keys
        assert svc_on.metrics.counters.get("compiles", 0) == 0
        # no extra dispatches for the same workload
        assert svc_on.metrics.counters["batches"] == batches_off
        # numerics untouched, bit for bit
        for off_r, on_r in zip(resps_off, resps_on):
            np.testing.assert_array_equal(off_r.x_adv, on_r.x_adv)
        # and the traced run actually recorded the lifecycle
        assert all("trace" in r.meta for r in resps_on)
        assert rec_on.events_emitted > 0

    def test_healthz_build_and_mesh_identity(self, artifacts):
        svc = make_service(artifacts, start=False)
        try:
            health = svc.healthz()
            build = health["build"]
            assert set(build) >= {"git", "version", "config_hash", "meshes"}
            from moeva2_ijcai22_replication_tpu.utils.config import (
                get_dict_hash,
            )

            assert build["config_hash"] == get_dict_hash(svc.domains)
            mesh = build["meshes"]["lcld"]
            assert mesh == {
                "mesh_devices": 0,
                "mesh": None,
                "resolved": False,
            }
        finally:
            svc.close()


class TestMoevaGateEvents:
    def test_engine_emits_init_gate_done_events(self, tmp_path):
        """The early-exit scan's between-gates visibility: per-gate progress
        events (generation index, success fraction, active set, bucket) and
        per-phase HBM watermarks land in the attached trace."""
        import joblib  # noqa: F401 — parity with serving fixtures
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
        from moeva2_ijcai22_replication_tpu.domains.synth import (
            synth_lcld,
            synth_lcld_schema,
        )
        from moeva2_ijcai22_replication_tpu.models.io import Surrogate
        from moeva2_ijcai22_replication_tpu.models.mlp import (
            init_params,
            lcld_mlp,
        )
        from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

        paths = synth_lcld_schema(str(tmp_path))
        cons = LcldConstraints(paths["features"], paths["constraints"])
        x = synth_lcld(4, cons.schema, seed=3)
        model = lcld_mlp()
        sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=7))
        rec = TraceRecorder(spans_enabled=True)
        trace = Trace(rec, trace_id="run-gate-test")
        moeva = Moeva2(
            classifier=sur,
            constraints=cons,
            ml_scaler=fit_minmax(x.min(0), x.max(0)),
            norm=2,
            n_gen=5,
            n_pop=8,
            n_offsprings=4,
            seed=11,
            archive_size=2,
            early_stop_check_every=2,
            trace=trace,
        )
        moeva.generate(x, 1)
        by_name = {}
        for e in trace.events:
            by_name.setdefault(e["name"], []).append(e)
        assert "moeva.init" in by_name
        assert by_name["moeva.init"][0]["attrs"]["states"] == 4
        assert "moeva.gate" in by_name  # 4 scan steps, gate every 2
        gate = by_name["moeva.gate"][0]["attrs"]
        assert set(gate) >= {
            "gen",
            "active",
            "parked",
            "success_frac",
            "bucket",
            "hbm",
        }
        assert 0.0 <= gate["success_frac"] <= 1.0
        (done,) = by_name["moeva.done"]
        assert done["attrs"]["budget_gens"] == 4
        # strict mode without a trace stays silent (and cannot crash)
        moeva.trace = None
        moeva.early_stop_check_every = 0
        moeva.generate(x, 1)
