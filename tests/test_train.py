"""Training-loop tests: early stopping, class/sample weights, auroc, DP mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moeva2_ijcai22_replication_tpu.models.mlp import MLP, lcld_mlp
from moeva2_ijcai22_replication_tpu.models.train import auroc, ce_loss, fit_mlp


def _blobs(n=256, d=8, seed=0):
    """Linearly separable-ish two-class data."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n)
    x = rng.normal(0, 1, (n, d)) + y[:, None] * 2.0
    return x.astype(np.float64), y.astype(np.int64)


class TestFit:
    def test_learns_separable_data(self):
        x, y = _blobs()
        fit = fit_mlp(lcld_mlp(), x, y, epochs=40, batch_size=64, seed=1)
        preds = np.asarray(
            fit.surrogate.predict_proba(jnp.asarray(x))
        ).argmax(-1)
        assert (preds == y).mean() > 0.9

    def test_early_stopping_halts_on_plateau(self):
        x, y = _blobs(128)
        # validation set the model cannot improve on: random labels
        rng = np.random.default_rng(3)
        xv = rng.normal(0, 1, (64, x.shape[1]))
        yv = rng.integers(0, 2, 64)
        fit = fit_mlp(
            lcld_mlp(), x, y, x_val=xv, y_val=yv,
            epochs=200, batch_size=64, patience=3, seed=1,
        )
        # must stop long before the epoch budget
        assert len(fit.history) < 200
        last_epoch = fit.history[-1][0]
        best_epoch = int(np.argmin([h[2] for h in fit.history]))
        assert last_epoch - best_epoch >= 3  # exactly the patience window
        # the kept parameters are the best-val ones, not the last ones
        vl = float(
            ce_loss(
                fit.surrogate.model, fit.surrogate.params,
                jnp.asarray(xv), jnp.asarray(yv),
            )
        )
        np.testing.assert_allclose(vl, fit.best_val_loss, rtol=1e-6)

    def test_class_weights_shift_the_decision(self):
        """A 9:1 imbalanced problem: upweighting the minority class must
        recover minority recall that the unweighted fit sacrifices."""
        rng = np.random.default_rng(5)
        n = 400
        y = (rng.random(n) < 0.1).astype(np.int64)
        # weakly separated: overlap forces a trade-off
        x = rng.normal(0, 1.2, (n, 6)) + y[:, None] * 1.2
        plain = fit_mlp(lcld_mlp(), x, y, epochs=30, batch_size=64, seed=2)
        weighted = fit_mlp(
            lcld_mlp(), x, y, epochs=30, batch_size=64, seed=2,
            class_weight={0: 1.0, 1: 9.0},
        )

        def recall(fit):
            p = np.asarray(fit.surrogate.predict_proba(jnp.asarray(x))).argmax(-1)
            return (p[y == 1] == 1).mean()

        assert recall(weighted) > recall(plain)

    def test_zero_weight_padding_is_inert(self):
        """ce_loss with weight-0 rows must equal the loss without them —
        the padding contract the batcher relies on."""
        x, y = _blobs(32)
        model = lcld_mlp()
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, x.shape[1])))
        base = float(ce_loss(model, params, jnp.asarray(x), jnp.asarray(y)))
        x_pad = jnp.asarray(np.vstack([x, np.zeros((8, x.shape[1]))]))
        y_pad = jnp.asarray(np.concatenate([y, np.zeros(8, np.int64)]))
        w = jnp.asarray(np.concatenate([np.ones(32), np.zeros(8)]).astype(np.float32))
        padded = float(ce_loss(model, params, x_pad, y_pad, sample_weight=w))
        np.testing.assert_allclose(padded, base, rtol=1e-6)

    def test_uneven_batches_cover_every_sample(self):
        # n=70 with batch_size=32 -> partial final batch; must still train
        x, y = _blobs(70)
        fit = fit_mlp(lcld_mlp(), x, y, epochs=25, batch_size=32, seed=4)
        preds = np.asarray(fit.surrogate.predict_proba(jnp.asarray(x))).argmax(-1)
        assert (preds == y).mean() > 0.85


class TestAuroc:
    def test_matches_quadratic_oracle(self):
        rng = np.random.default_rng(7)
        y = rng.integers(0, 2, 200)
        p = np.clip(y * 0.3 + rng.random(200) * 0.8, 0, 1)
        p = np.round(p, 2)  # force ties to exercise midranks

        # O(n^2) oracle: P(score_pos > score_neg) + 0.5 P(equal)
        pos, neg = p[y == 1], p[y == 0]
        gt = (pos[:, None] > neg[None, :]).mean()
        eq = (pos[:, None] == neg[None, :]).mean()
        np.testing.assert_allclose(auroc(p, y), gt + 0.5 * eq, rtol=1e-12)

    def test_degenerate_single_class(self):
        assert np.isnan(auroc(np.linspace(0, 1, 5), np.ones(5, np.int64)))


class TestDataParallelMesh:
    def test_dp_fit_matches_single_device(self):
        from jax.sharding import Mesh

        x, y = _blobs(128)
        mesh = Mesh(np.array(jax.devices()), ("dp",))
        single = fit_mlp(lcld_mlp(), x, y, epochs=8, batch_size=64, seed=6)
        dp = fit_mlp(
            lcld_mlp(), x, y, epochs=8, batch_size=64, seed=6, mesh=mesh
        )
        # same data order (seeded) + weight-0 padding => same training curve
        np.testing.assert_allclose(
            [h[1] for h in single.history], [h[1] for h in dp.history],
            rtol=1e-4,
        )
        a = np.asarray(single.surrogate.predict_proba(jnp.asarray(x)))
        b = np.asarray(dp.surrogate.predict_proba(jnp.asarray(x)))
        np.testing.assert_allclose(a, b, atol=1e-4)


class TestOrbaxCheckpoint:
    def test_roundtrip_and_dispatch(self, tmp_path):
        """Orbax params checkpoint (SURVEY §5's suggested TPU-native model
        format): save → load via both the io dispatcher and the generic
        load_model entry point, bitwise-equal forward passes."""
        from moeva2_ijcai22_replication_tpu.models.io import (
            Surrogate, load_classifier, save_orbax,
        )
        from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
        from moeva2_ijcai22_replication_tpu.utils.in_out import load_model

        model = lcld_mlp()
        sur = Surrogate(model, init_params(model, 47, seed=1))
        path = str(tmp_path / "nn.orbax")
        save_orbax(sur, path)

        x = jnp.asarray(np.random.default_rng(0).uniform(size=(5, 47)))
        want = np.asarray(sur.predict_proba(x))
        for loaded in (load_classifier(path), load_model(path)):
            assert loaded.model.hidden == model.hidden
            np.testing.assert_array_equal(np.asarray(loaded.predict_proba(x)), want)
