"""Utility-layer tests: threshold sweep and metrics-record streaming."""

import json

import numpy as np

from moeva2_ijcai22_replication_tpu.utils import best_threshold
from moeva2_ijcai22_replication_tpu.utils.metrics import iter_records, records


class TestBestThreshold:
    def test_matches_per_threshold_mcc_loop(self):
        from sklearn.metrics import matthews_corrcoef

        rng = np.random.default_rng(0)
        y = rng.integers(0, 2, 300)
        proba = np.clip(y * 0.4 + rng.random(300) * 0.6, 0, 1)

        t, score = best_threshold(y, proba)
        # oracle: the reference's explicit loop (src/utils/__init__.py:44-53)
        grid = [i / 100 for i in range(100)]
        oracle = [matthews_corrcoef(y, (proba >= g).astype(int)) for g in grid]
        assert score == max(oracle)
        assert t == grid[int(np.argmax(oracle))]

    def test_degenerate_all_one_class(self):
        t, score = best_threshold(np.zeros(10), np.linspace(0, 1, 10))
        assert score == 0.0 and 0.0 <= t < 1.0


class TestMetricsRecords:
    def _moeva_metrics(self):
        return {
            "config_hash": "abc",
            "time": 1.5,
            "config": {
                "attack_name": "moeva",
                "project_name": "lcld",
                "n_initial_state": 4,
                "budget": 100,
                "eps_list": [0.1, 0.2],
                "paths": {"model": "m.msgpack"},
                "reconstruction": False,
            },
            "objectives_list": [{"o1": 1.0}, {"o1": 0.5}],
        }

    def _pgd_metrics(self):
        return {
            "config_hash": "def",
            "time": 2.0,
            "config": {
                "attack_name": "pgd",
                "loss_evaluation": "constraints+flip",
                "project_name": "botnet",
                "n_initial_state": -1,
                "budget": 10,
                "eps": 4,
                "paths": {"model": "m2.msgpack"},
            },
            "objectives": {"o7": 0.25},
        }

    def test_moeva_one_record_per_eps(self):
        recs = list(iter_records(self._moeva_metrics()))
        assert [r["eps"] for r in recs] == [0.1, 0.2]
        assert recs[0]["o1"] == 1.0 and recs[1]["o1"] == 0.5
        assert all(r["config_hash"] == "abc" for r in recs)
        assert all(r["project_name"] == "lcld" for r in recs)

    def test_pgd_single_record_keyed_by_loss(self):
        (rec,) = iter_records(self._pgd_metrics())
        assert rec["attack_name"] == "constraints+flip"
        assert rec["eps"] == 4 and rec["o7"] == 0.25
        assert rec["reconstruction"] is None  # absent -> default

    def test_records_streams_a_directory(self, tmp_path):
        with open(tmp_path / "metrics_moeva_abc.json", "w") as f:
            json.dump(self._moeva_metrics(), f)
        with open(tmp_path / "metrics_pgd_def.json", "w") as f:
            json.dump(self._pgd_metrics(), f)
        recs = list(records(str(tmp_path)))
        assert len(recs) == 3
        assert {r["attack_name"] for r in recs} == {"moeva", "constraints+flip"}


class TestExperimentStream:
    def test_events_roundtrip(self, tmp_path):
        from moeva2_ijcai22_replication_tpu.utils.streaming import (
            ExperimentStream,
            read_events,
        )

        p = str(tmp_path / "ev.jsonl")
        with ExperimentStream(p, name="demo") as s:
            s.log_parameters({"budget": 3, "arr": np.array([1, 2])})
            s.log_metric("o7", 0.5)
            s.log_series("loss", np.array([3.0, 2.0, 1.0]))
        evs = list(read_events(p))
        kinds = [e["event"] for e in evs]
        assert kinds[0] == "start" and kinds[-1] == "end"
        assert kinds.count("metric") == 4
        steps = [e["step"] for e in evs if e.get("name") == "loss"]
        assert steps == [0, 1, 2]
        assert all("t" in e for e in evs)

    def test_disabled_stream_writes_nothing(self, tmp_path):
        from moeva2_ijcai22_replication_tpu.utils.streaming import ExperimentStream

        p = str(tmp_path / "off.jsonl")
        with ExperimentStream(p, enabled=False) as s:
            s.log_metric("x", 1)
        assert not (tmp_path / "off.jsonl").exists()


class TestMetricsCli:
    def test_prints_one_row_per_record(self, tmp_path, capsys):
        from moeva2_ijcai22_replication_tpu.utils.metrics import main

        stream = TestMetricsRecords()
        with open(tmp_path / "metrics_moeva_abc.json", "w") as f:
            json.dump(stream._moeva_metrics(), f)
        main([str(tmp_path)])
        out = capsys.readouterr().out.strip().splitlines()
        recs = list(records(str(tmp_path)))
        assert len(out) == 1 + len(recs)  # header + rows
        assert "o7" in out[0] and "attack_name" in out[0]

    def test_empty_dir_reports_cleanly(self, tmp_path, capsys):
        from moeva2_ijcai22_replication_tpu.utils.metrics import main

        main([str(tmp_path)])
        assert "no metrics files" in capsys.readouterr().out
