"""Round-5 on-chip A/B: per-stage budget + the survival formulation knobs.

Measures the FULL production attack program (init + one jitted segment) at
the bench shape, min-of-N, inside one process per variant (env knobs must be
set before import). Variants:

  MOEVA_MXU_COUNTS=1|0   matmul vs VPU count reductions (survival + nds)
  AB_ASSOC_BLOCK=<int>   blocked-scan association (empty = one-shot einsum)

Run me via the driver loop (no args) to sweep all variants in subprocesses,
or with AB_ONE=1 to measure just the current env's variant.
"""

import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

N_STATES = int(os.environ.get("AB_STATES", 1000))
N_GEN = int(os.environ.get("AB_GENS", 60))
N_POP = int(os.environ.get("AB_POP", 100))
REPS = int(os.environ.get("AB_REPS", 3))


def measure_one():
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
    from moeva2_ijcai22_replication_tpu.models.io import load_classifier
    from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

    if os.environ.get("AB_DOMAIN") == "botnet":
        from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints

        b = "/root/reference"
        cons = BotnetConstraints(
            f"{b}/data/botnet/features.csv", f"{b}/data/botnet/constraints.csv"
        )
        x = np.load(f"{b}/data/botnet/x_candidates_common.npy")
        sur = load_classifier(f"{b}/models/botnet/nn.model")
        scaler = load_joblib_scaler(f"{b}/models/botnet/scaler.joblib")
        n_pop = 200
    else:
        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
        from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld

        lcld = "/root/reference/data/lcld"
        cons = LcldConstraints(f"{lcld}/features.csv", f"{lcld}/constraints.csv")
        x = synth_lcld(N_STATES, cons.schema, seed=42)
        sur = load_classifier("/root/reference/models/lcld/nn.model")
        scaler = load_joblib_scaler("/root/reference/models/lcld/scaler.joblib")
        n_pop = N_POP

    blk = os.environ.get("AB_ASSOC_BLOCK") or None
    moeva = Moeva2(
        classifier=sur, constraints=cons, ml_scaler=scaler,
        norm=2, n_gen=N_GEN, n_pop=n_pop, n_offsprings=100, seed=42,
        assoc_block=int(blk) if blk else None,
    )
    N_STATES_EFF = x.shape[0]
    xl_ml, xu_ml = cons.get_feature_min_max(dynamic_input=x)
    xl_ml = np.broadcast_to(np.asarray(xl_ml, float), x.shape)
    xu_ml = np.broadcast_to(np.asarray(xu_ml, float), x.shape)
    init_fn = jax.jit(moeva._build_init())
    seg_fn = jax.jit(moeva._build_segment(), static_argnames="length")
    args = (
        sur.params,
        jnp.asarray(x, moeva.dtype),
        jnp.ones((N_STATES_EFF,), jnp.int32),
        jnp.asarray(xl_ml, moeva.dtype),
        jnp.asarray(xu_ml, moeva.dtype),
    )

    def run():
        carry, _ = init_fn(*args, jax.random.PRNGKey(42))
        carry, _ = seg_fn(*args, carry, length=N_GEN - 1)
        jax.block_until_ready(carry)

    run()  # compile
    times = []
    for _ in range(REPS):
        t0 = time.time()
        run()
        times.append(time.time() - t0)
    best = min(times)
    print(
        f"[ab] mxu={os.environ.get('MOEVA_MXU_COUNTS', '1')} "
        f"assoc_block={blk or '-'}: {best:.3f}s / {N_GEN} gens = "
        f"{best / N_GEN * 1e3:.2f} ms/gen  (all: "
        + " ".join(f"{t:.3f}" for t in times) + ")",
        flush=True,
    )


def sweep():
    blocks = os.environ.get("AB_BLOCKS", ",64,128").split(",")
    variants = [{"MOEVA_MXU_COUNTS": "1", "AB_ASSOC_BLOCK": b} for b in blocks]
    for v in variants:
        env = dict(os.environ, AB_ONE="1", **v)
        r = subprocess.run([sys.executable, __file__], env=env)
        if r.returncode != 0:
            print(f"[ab] variant {v} failed rc={r.returncode}", flush=True)


if __name__ == "__main__":
    if os.environ.get("AB_ONE"):
        measure_one()
    else:
        sweep()
