"""Round-5 adjudication: does the corrected survival kernel converge at the
reference budget (n_gen=1000) on the real botnet candidate set?

Context: the round-4 survival fix (1141e71 — aspiration points folded into
ideal/worst and extreme candidates, nadir clamped to running worst; all
validated against the vendored pymoo 0.4.2.2 oracle) dropped budget-100
o-rates 4.5x (o2 0.899 -> 0.199).  The pre-fix kernel deviated from the
algorithm the reference actually runs (pymoo AspirationPointSurvival, via
``/root/reference/src/attacks/moeva2/moeva2.py:113-124``), so its numbers
measured a *different* attack.  This script measures the corrected attack at
several budgets to show the trajectory, separating final-population rates
from archive rates.

Writes out/adjudication_r5.json.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGETS = [int(g) for g in os.environ.get("ADJ_GENS", "100,300,1000").split(",")]
ARCHIVE = int(os.environ.get("ADJ_ARCHIVE", 24))


def main():
    import jax

    jax.config.update("jax_compilation_cache_dir", "./.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
    from moeva2_ijcai22_replication_tpu.attacks.objective import ObjectiveCalculator
    from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints
    from moeva2_ijcai22_replication_tpu.models.io import load_classifier
    from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

    base = "/root/reference"
    cons = BotnetConstraints(
        f"{base}/data/botnet/features.csv", f"{base}/data/botnet/constraints.csv"
    )
    x = np.load(f"{base}/data/botnet/x_candidates_common.npy")
    sur = load_classifier(f"{base}/models/botnet/nn.model")
    scaler = load_joblib_scaler(f"{base}/models/botnet/scaler.joblib")
    calc = ObjectiveCalculator(
        classifier=sur, constraints=cons,
        thresholds={"f1": 0.5, "f2": 4.0},
        min_max_scaler=scaler, ml_scaler=scaler,
        minimize_class=1, norm=2,
    )

    out = {"n_states": int(x.shape[0]), "archive_size": ARCHIVE, "budgets": {}}
    for n_gen in BUDGETS:
        moeva = Moeva2(
            classifier=sur, constraints=cons, ml_scaler=scaler,
            norm=2, n_gen=n_gen, n_pop=200, n_offsprings=100, seed=42,
            archive_size=ARCHIVE,
        )
        t0 = time.time()
        res = moeva.generate(x, minimize_class=1)
        wall = time.time() - t0
        pop = res.x_ml[:, : moeva.pop_size]
        rates_pop = [round(float(r), 4) for r in calc.success_rate_3d(x, pop)]
        rates_all = [round(float(r), 4) for r in calc.success_rate_3d(x, res.x_ml)]
        out["budgets"][str(n_gen)] = {
            "wall_s": round(wall, 1),
            "o_rates_final_pop": rates_pop,
            "o_rates_with_archive": rates_all,
        }
        print(
            f"[adj] n_gen={n_gen}: {wall:.1f}s  pop o1..o7: "
            + " ".join(f"{r:.3f}" for r in rates_pop)
            + "  | +archive: "
            + " ".join(f"{r:.3f}" for r in rates_all),
            flush=True,
        )

    os.makedirs("out", exist_ok=True)
    with open("out/adjudication_r5.json", "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
