#!/usr/bin/env python
"""Perf+quality+SLO watchdog: diff bench records, normalized by ledger cost.

The committed ``BENCH_r*.json`` series is the repo's performance AND
correctness trajectory; this tool turns it into an enforced contract. It
compares the LATEST record against the best earlier value of each tracked
metric and exits non-zero when a metric moved past the threshold in its
bad direction — runnable standalone or as the repo check wired into tier-1
(``tests/test_cost_ledger.py::TestBenchDiffRepoCheck``).

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json   # pairwise
    python tools/bench_diff.py --check BENCH_r*.json           # whole series
    python tools/bench_diff.py --check                         # globs BENCH_r*.json
    python tools/bench_diff.py --check --threshold 0.4 ...
    python tools/bench_diff.py --check --slo ...               # + serving SLO gate
    python tools/bench_diff.py --check --mesh ...              # + mesh balance gate
    python tools/bench_diff.py --check --json ...              # + CI JSON line

Quality metrics: records carrying a ``telemetry.quality`` (and/or
``real_botnet.quality``) block expose interior-point success rates —
o-rates pinned at interior generation budgets ({100, 300}, where the GA is
budget-sensitive) — and an interior-rate DROP past ``--quality-threshold``
fails the check exactly like a wall-clock regression. Quality drops are
judged on ABSOLUTE rate deltas (rates live in [0, 1]; a relative delta on
an 0.08 interior rate would trip on binomial noise): the seeded runs are
deterministic, run-to-run movement comes only from execution-mode changes
(chunking/compaction reshuffle the RNG), whose observed jitter is within
a few binomial sigmas (~0.02 at 387 states) — the default 0.10 sits ~5
sigma above that and far below the 4.5x class of survival-semantics
regression this gate exists to catch (docs/DESIGN.md § quality watchdog).
Records predating the quality block (r01–r05) simply aren't comparable on
these metrics and are skipped as baselines, never failed — but once any
baseline carries interior rates, a LATEST record without them fails:
losing quality capture would disarm the gate exactly when a regression
could hide behind it.

Normalization: wall-clock metrics are divided by the work a record
actually performed before comparison — the cost-ledger FLOPs total
(``telemetry.cost.flops_total``) when both records carry it, else the
benchmark shape (``execution.n_states * n_gen``) — so a PR that doubles
the bench shape (and honestly reports it) does not masquerade as a 2x
regression, and one that halves the shape cannot hide one. Records
predating the ledger fall back to a raw comparison (the bench defaults
have been stable) with the basis named in the output line.

SLO metrics (``--slo``): serving records carrying a ``telemetry.slo``
block expose the offered-load sweep's saturation knee
(``knee_rps`` — the highest offered rate still served linearly) and the
per-level p99 at each fixed offered load; with ``--slo``, a knee-QPS
drop or a p99-at-fixed-load increase past ``--slo-threshold`` (relative;
default 0.5 — client-observed p99 on a shared CI host jitters far more
than wall-clock totals do, so the latency gate sits wider than the perf
gate) fails the check — and so does a baseline-tracked metric DEGRADING
to null (knee_rps None = no level served linearly; a level p99 of None
= it completed nothing): worse than any number, never a skip. Levels
are compared only at identical offered
rates (a reshaped sweep ladder skips, it doesn't fail), but a latest
serving record with NO slo block while any baseline carries one fails —
losing SLO capture would disarm this gate exactly like losing quality
capture disarms that one. Pre-SLO records (r01–r06) skip as baselines.

Mesh metrics (``--mesh``): records carrying a ``telemetry.mesh`` block
(any record whose execution ran on >1 device) expose the per-device
balance ratio (mean/max useful run seconds; 1.0 = perfectly balanced)
and the hot-loop float-collective count. With ``--mesh``, a balance-ratio
drop past ``--mesh-threshold`` (relative, default 0.25) fails the check,
and ANY growth in hot-loop float collectives fails outright — that one
is the zero-collective states-sharding contract, not a perf number, so
there is no tolerance to tune. Baseline-skip semantics match ``--slo``:
pre-mesh (or single-device) records skip as baselines, but a latest
record that LOST mesh capture while any baseline carries it fails.

Overlap/cold metrics (``--overlap``): records carrying a
``telemetry.gaps`` block expose the device overlap ratio (device-busy
seconds over compile-free wall — 1.0 means the host never left the
device idle), and records carrying the structured ``cold`` breakdown
expose ``cold_steady_ratio`` (cold wall over steady wall — ROADMAP item
2's exit criterion is <= 1.2). With ``--overlap``, an overlap-ratio drop
past ``--overlap-threshold`` (relative, default 0.25) or a
cold/steady-ratio GROWTH past the same threshold fails the check.
Baseline-skip semantics match ``--slo``/``--mesh``: pre-gap records
(r01–r05) skip as baselines, but a latest record that LOST gap or cold
capture while any baseline carries it fails — the gate must not be
disarmable by dropping the measurement.

Cold metrics (``--cold``): the cold-start gate the AOT-cache PR armed.
Two rules: (a) ABSOLUTE — the latest record's ``cold_steady_ratio``
(when it carries the structured ``cold`` breakdown) must not exceed
``--cold-max-ratio`` (default 1.2, ROADMAP item 2's exit criterion);
unlike every other gate this needs no baseline, because the criterion is
a target, not a trajectory. (b) RELATIVE — the warm-start hit rate,
``(hit + aot_hit) / classified executables`` from the cold breakdown's
``persistent_cache.by_outcome``, must not drop more than
``--overlap-threshold`` vs the best baseline exposing one (a replica
that silently stopped finding its caches cold-starts every process).
Pre-cold records skip as baselines; capture loss of the cold breakdown
itself is already non-disarmable under ``--overlap``.

Records may be bare bench JSON or the committed driver wrapper
``{"n", "cmd", "rc", "parsed"}``; wrappers with a non-zero rc or an
empty payload are skipped (a crashed bench is not evidence of a
regression — or of its absence).
"""

from __future__ import annotations

import argparse
import glob
import json
import sys

#: relative slowdown (or throughput loss) that fails the check. The
#: tunnelled bench host shows ~±10% run-to-run jitter (BASELINE.md), so
#: the default trips at 2.5x that noise floor, far below the 2x class of
#: regression this watchdog exists to catch.
DEFAULT_THRESHOLD = 0.25

#: absolute interior-success-rate drop that fails the check (see module
#: docstring for the noise-floor rationale).
DEFAULT_QUALITY_THRESHOLD = 0.10

#: relative SLO regression (knee-QPS drop / fixed-load-p99 increase)
#: that fails the check under --slo (see module docstring).
DEFAULT_SLO_THRESHOLD = 0.5

#: relative balance-ratio drop that fails the check under --mesh. The
#: ratio is mean/max useful seconds in [0, 1]; run-to-run movement comes
#: only from early-exit timing jitter reshuffling which devices park
#: first, well inside 25% at the committed shapes.
DEFAULT_MESH_THRESHOLD = 0.25

#: relative overlap-ratio drop (or cold/steady-ratio growth) that fails
#: the check under --overlap. Both ratios are wall-clock quotients on a
#: tunnelled host with ~±10% jitter on each side, so the gate sits at
#: the same 2.5x-noise-floor margin as the perf threshold.
DEFAULT_OVERLAP_THRESHOLD = 0.25

#: absolute floor on the FLEET record's knee-scaling ratio under --fleet:
#: knee(N_hi) / (knee(N_lo) x N_hi/N_lo) — the acceptance criterion's
#: ">= 0.8x linear from 1 -> 4 replicas".
DEFAULT_FLEET_SCALING_FLOOR = 0.8

#: absolute floor on every measured replica's AOT warm-start fraction
#: (aot_hits / prewarmed executables) under --fleet — ">= 90% warm from
#: the shared cache", the cross-process AOT cache made load-bearing.
DEFAULT_FLEET_WARM_FLOOR = 0.9

#: absolute floor on the chaos segment's recovery ratio (post-kill knee /
#: the (N-1)-replica knee) under --fleet. The two knees are measured
#: minutes apart on a shared host, so the floor sits below 1.0 by more
#: than the sweep ladder's granularity.
DEFAULT_FLEET_RECOVERY_FLOOR = 0.6

#: relative knee-scaling-ratio drop vs the best FLEET baseline that fails
#: under --fleet (trajectory gate on top of the absolute floor).
DEFAULT_FLEET_THRESHOLD = 0.15

#: absolute floor on the QOS record's scavenger shed share under --qos:
#: at saturation with the mixed-class offered load, the lowest class must
#: absorb >= 80% of everything shed — the low-priority-absorbs-overload
#: invariant (admission buckets shed scavenger first by construction).
DEFAULT_QOS_SCAVENGER_SHED_FLOOR = 0.8

#: absolute floor on time_to_complete_s / time_to_first_solved_s for the
#: streaming early-exit workload under --qos: solved rows must surface at
#: least 2x sooner than the full result (the acceptance criterion).
DEFAULT_QOS_TTFS_RATIO_FLOOR = 2.0


#: o-columns tracked at each interior budget: o2 (misclassified) and o7
#: (the full constrained-adversarial criterion) — the two the round-5
#: adjudication pinned (0.199/0.080 @100).
QUALITY_TRACKED = (("o2", 1), ("o7", 6))


def load_record(path: str) -> dict | None:
    """Bench payload from ``path``; None when unusable (crashed/empty)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_diff: skipping {path}: {e}", file=sys.stderr)
        return None
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc:  # committed driver wrapper
        if doc.get("rc") not in (0, None):
            print(
                f"bench_diff: skipping {path}: bench exited rc={doc['rc']}",
                file=sys.stderr,
            )
            return None
        doc = doc.get("parsed")
    return doc if isinstance(doc, dict) and doc else None


def _get(rec: dict, dotted: str):
    cur = rec
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _headline_work(rec: dict) -> dict:
    """Every work basis the headline run's metadata supports (a record
    carrying ledger FLOPs usually carries the bench shape too — both are
    kept so it stays comparable with pre-ledger records via 'shape')."""
    out = {}
    cost = _get(rec, "telemetry.cost") or {}
    flops = cost.get("flops_total")
    if isinstance(flops, (int, float)) and flops > 0:
        out["flops"] = float(flops)
    ex = rec.get("execution") or {}
    n_states, n_gen = ex.get("n_states"), ex.get("n_gen")
    if n_states and n_gen:
        out["shape"] = float(n_states) * float(n_gen)
    return out


def _botnet_work(rec: dict) -> dict:
    rb = rec.get("real_botnet") or {}
    if rb.get("n_states") and rb.get("n_gen"):
        return {"shape": float(rb["n_states"]) * float(rb["n_gen"])}
    return {}


def _serving_best_throughput(rec: dict):
    levels = _get(rec, "serving.levels") or []
    vals = [
        lv.get("throughput_rows_s")
        for lv in levels
        if isinstance(lv.get("throughput_rows_s"), (int, float))
    ]
    return max(vals) if vals else None


#: (name, extractor, lower_is_better, work extractor or None)
METRICS = (
    ("steady_s", lambda r: r.get("steady_s"), True, _headline_work),
    ("value (speedup)", lambda r: r.get("value"), False, None),
    (
        "real_botnet.steady_s",
        lambda r: _get(r, "real_botnet.steady_s"),
        True,
        _botnet_work,
    ),
    (
        "early_exit.speedup",
        lambda r: _get(r, "early_exit.speedup"),
        False,
        None,
    ),
    (
        "serving.throughput_rows_s (best level)",
        _serving_best_throughput,
        False,
        None,
    ),
)


#: normalization bases, strongest first: model FLOPs beat the benchmark
#: shape beat an unnormalized comparison
_BASES = ("flops", "shape", "raw")


def _values_by_basis(rec: dict, extract, work_fn) -> dict:
    """Every normalization of this record's metric value that its
    metadata supports: ``{"raw": v}`` always (when the metric exists),
    plus ``v / work`` per available work basis — ALL of them, so a
    post-ledger record (flops + shape) still compares shape-normalized
    against a pre-ledger one (shape only)."""
    v = extract(rec)
    if not isinstance(v, (int, float)):
        return {}
    out = {"raw": float(v)}
    if work_fn is not None:
        for kind, work in work_fn(rec).items():
            if work:
                out[kind] = float(v) / work
    return out


def _quality_points(rec: dict) -> dict[str, tuple[float, int | None]]:
    """Every interior-rate metric this record's quality blocks expose:
    ``{"<block>.interior@<budget>.<o>": (rate, sample_gen)}`` over the
    headline (``telemetry.quality``) and real-botnet quality blocks. The
    sample's actual generation travels along so the diff can refuse to
    compare samples taken at different gens (a cadence change relabels a
    gen-150 sample as "@300"). ``full`` summaries are deliberately NOT
    tracked — the full-budget rates are the saturated numbers whose
    blindness this watchdog exists to fix."""
    out: dict[str, tuple[float, int | None]] = {}
    for label, dotted in (
        ("quality", "telemetry.quality"),
        ("real_botnet.quality", "real_botnet.quality"),
    ):
        block = _get(rec, dotted)
        if not isinstance(block, dict):
            continue
        interior = block.get("interior") or {}
        for budget, sample in sorted(interior.items()):
            if budget == "full" or not isinstance(sample, dict):
                continue
            rates = sample.get("o_rates")
            if not isinstance(rates, list):
                continue
            gen = sample.get("gen")
            for oname, idx in QUALITY_TRACKED:
                if idx < len(rates) and isinstance(
                    rates[idx], (int, float)
                ):
                    out[f"{label}.interior@{budget}.{oname}"] = (
                        float(rates[idx]),
                        int(gen) if isinstance(gen, (int, float)) else None,
                    )
    return out


def _slo_points(rec: dict) -> dict[str, tuple[float, bool]]:
    """Every SLO metric this record's serving block exposes:
    ``{name: (value, lower_is_better)}`` — the sweep's saturation knee
    (higher is better) plus the client p99 at each offered-load level
    (lower is better, compared only at IDENTICAL offered rates; a
    reshaped ladder is "not comparable", never a fake regression). Both
    the standalone ``bench.py --serving`` wrapper and the full bench
    record keep the sweep under a ``serving`` key."""
    out: dict[str, tuple[float, bool]] = {}
    serving = rec.get("serving")
    if not isinstance(serving, dict):
        return out
    slo = _get(serving, "telemetry.slo")
    if not isinstance(slo, dict):
        # pre-SLO record: its levels DO carry p99 numbers, but they were
        # measured without the SLO discipline (no knee, no shed
        # attribution, warmup mixed in) — the skip-as-baseline convention
        # keys off the telemetry.slo block, like quality keys off
        # telemetry.quality
        return out
    knee = (slo.get("knee") or {}).get("knee_rps")
    if isinstance(knee, (int, float)):
        out["serving.slo.knee_rps"] = (float(knee), False)
    for lv in serving.get("levels") or []:
        rps, p99 = lv.get("offered_rps"), lv.get("p99_ms")
        if isinstance(rps, (int, float)) and isinstance(p99, (int, float)):
            out[f"serving.p99_ms@{rps:g}rps"] = (float(p99), True)
    return out


def _slo_degraded(rec: dict) -> set[str]:
    """SLO metric names whose value DEGRADED TO NOTHING in ``rec`` —
    worse than any number, not 'absent': a knee of None means no level
    served linearly, a level with a null p99 completed zero requests.
    These must fail against a numeric baseline, never silently vanish
    from the comparison (which only walks the latest record's numeric
    points). Only meaningful for records that carry telemetry.slo."""
    serving = rec.get("serving")
    if not isinstance(serving, dict):
        return set()
    slo = _get(serving, "telemetry.slo")
    if not isinstance(slo, dict):
        return set()
    degraded = set()
    knee = slo.get("knee") or {}
    if "knee_rps" in knee and knee["knee_rps"] is None:
        degraded.add("serving.slo.knee_rps")
    for lv in serving.get("levels") or []:
        rps = lv.get("offered_rps")
        if isinstance(rps, (int, float)) and lv.get("p99_ms") is None:
            degraded.add(f"serving.p99_ms@{rps:g}rps")
    return degraded


def _mesh_points(rec: dict) -> dict[str, float]:
    """Every mesh metric this record's ``telemetry.mesh`` block exposes
    (empty for single-device, pre-mesh, or capture-off records — the
    skip-as-baseline convention keys off the numeric points, so an
    ``enabled: False`` block reads the same as no block):
    ``mesh.balance_ratio`` (higher is better, relative) and
    ``mesh.hot_loop_float_collectives`` (the zero-collective contract —
    judged absolutely, any growth fails)."""
    out: dict[str, float] = {}
    mesh = _get(rec, "telemetry.mesh")
    if not isinstance(mesh, dict) or mesh.get("enabled") is False:
        return out
    ratio = (mesh.get("balance") or {}).get("ratio")
    if isinstance(ratio, (int, float)):
        out["mesh.balance_ratio"] = float(ratio)
    hot = ((mesh.get("collectives") or {}).get("hot_loop") or {}).get(
        "float_count"
    )
    if isinstance(hot, (int, float)):
        out["mesh.hot_loop_float_collectives"] = float(hot)
    return out


def _overlap_points(rec: dict) -> dict[str, tuple[float, bool]]:
    """Every overlap/cold metric this record exposes:
    ``{name: (value, lower_is_better)}``. Overlap ratio keys off a
    capture-on ``telemetry.gaps`` block with a numeric ratio;
    cold/steady keys off the structured ``cold`` breakdown sitting next
    to ``cold_steady_ratio`` — presence of the decomposition IS the
    capture marker (a bare cold_s/steady_s pair predates the gate and
    skips as a baseline)."""
    out: dict[str, tuple[float, bool]] = {}
    gaps = _get(rec, "telemetry.gaps")
    # overlap gates HEADLINE records only (steady_s present = the
    # contiguous batch run): a serving-only record's telemetry.gaps wall
    # spans the sweep's request PACING, so its ratio tracks offered load,
    # not host stalls — gating it would fail on a reshaped load ladder
    if (
        isinstance(gaps, dict)
        and gaps.get("enabled") is not False
        and isinstance(rec.get("steady_s"), (int, float))
    ):
        ratio = gaps.get("overlap_ratio")
        if isinstance(ratio, (int, float)):
            out["gaps.overlap_ratio"] = (float(ratio), False)
    cold = rec.get("cold")
    if isinstance(cold, dict) and cold.get("enabled") is not False:
        csr = rec.get("cold_steady_ratio")
        if isinstance(csr, (int, float)):
            out["cold_steady_ratio"] = (float(csr), True)
    return out


#: latest-record cold/steady ceiling enforced under --cold (ROADMAP item
#: 2's exit criterion: a process must come up within 20% of steady).
DEFAULT_COLD_MAX_RATIO = 1.2


def _cold_hit_rate(rec: dict) -> float | None:
    """Warm-start hit rate of a record's cold breakdown: the fraction of
    classified executables that loaded from a persistent tier (jax-cache
    ``hit`` or serialized-executable ``aot_hit``) instead of compiling.
    None for records without a capture-on cold breakdown or with nothing
    classified."""
    cold = rec.get("cold")
    if not isinstance(cold, dict) or cold.get("enabled") is False:
        return None
    by_outcome = (cold.get("persistent_cache") or {}).get("by_outcome")
    if not isinstance(by_outcome, dict):
        return None
    total = sum(
        int(v) for v in by_outcome.values() if isinstance(v, (int, float))
    )
    if total <= 0:
        return None
    hits = int(by_outcome.get("hit", 0)) + int(by_outcome.get("aot_hit", 0))
    return hits / total


def diff_series(
    records: list[tuple[str, dict]],
    threshold: float,
    quality_threshold: float = DEFAULT_QUALITY_THRESHOLD,
    slo: bool = False,
    slo_threshold: float = DEFAULT_SLO_THRESHOLD,
    mesh: bool = False,
    mesh_threshold: float = DEFAULT_MESH_THRESHOLD,
    overlap: bool = False,
    overlap_threshold: float = DEFAULT_OVERLAP_THRESHOLD,
    cold: bool = False,
    cold_max_ratio: float = DEFAULT_COLD_MAX_RATIO,
) -> tuple[list[str], bool, list[dict]]:
    """Compare the last record pairwise against every earlier one, each
    pair in the strongest normalization basis BOTH sides support (ledger
    FLOPs > bench shape > raw), and judge the worst pair per metric.
    Quality metrics (interior success rates) compare by absolute drop
    against the best earlier value. Returns
    (report lines, any_regression, structured entries for --json)."""
    lines: list[str] = []
    entries: list[dict] = []
    regressed = False
    latest_path, latest = records[-1]
    earlier = records[:-1]
    for name, extract, lower_better, work_fn in METRICS:
        new_vals = _values_by_basis(latest, extract, work_fn)
        if not new_vals:
            lines.append(f"  {name}: absent in {latest_path} — skipped")
            entries.append({"metric": name, "verdict": "skipped", "reason": "absent"})
            continue
        pairs = []
        for path, rec in earlier:
            old_vals = _values_by_basis(rec, extract, work_fn)
            basis = next(
                (b for b in _BASES if b in old_vals and b in new_vals), None
            )
            if basis is None or old_vals[basis] == 0:
                continue
            new_v, old_v = new_vals[basis], old_vals[basis]
            rel = (
                (new_v - old_v) / old_v
                if lower_better
                else (old_v - new_v) / old_v
            )
            pairs.append((rel, path, old_v, new_v, basis))
        if not pairs:
            lines.append(f"  {name}: no comparable earlier record — skipped")
            entries.append(
                {"metric": name, "verdict": "skipped", "reason": "no_baseline"}
            )
            continue
        rel, path, old_v, new_v, basis = max(pairs, key=lambda t: t[0])
        bad = rel > threshold
        regressed |= bad
        direction = "worse" if rel > 0 else "better"
        lines.append(
            f"  {name}: {new_v:.6g} vs best {old_v:.6g} ({path}) "
            f"[{basis}-normalized] -> {abs(rel) * 100:.1f}% {direction}"
            + ("  ** REGRESSION **" if bad else "")
        )
        entries.append(
            {
                "metric": name,
                "kind": "perf",
                "basis": basis,
                "baseline": path,
                "old": old_v,
                "new": new_v,
                "delta_rel": rel,
                "verdict": "regression" if bad else "ok",
            }
        )

    # -- quality: interior success rates, absolute-drop judged ------------
    new_quality = _quality_points(latest)
    old_quality: dict[str, list[tuple[str, float, int | None]]] = {}
    for path, rec in earlier:
        for name, (rate, gen) in _quality_points(rec).items():
            old_quality.setdefault(name, []).append((path, rate, gen))
    names = sorted(set(new_quality) | set(old_quality))
    if not names:
        lines.append(
            f"  quality: no telemetry.quality interior rates in "
            f"{latest_path} or any baseline — skipped"
        )
        entries.append(
            {"metric": "quality", "verdict": "skipped", "reason": "absent"}
        )
    for name in names:
        olds = old_quality.get(name, [])
        if name not in new_quality:
            # a metric any baseline exposed must not silently vanish: per
            # BLOCK too (e.g. a crashed real_botnet step drops exactly the
            # adjudicated-trajectory gate) — losing capture would disarm
            # this check precisely when a regression could hide behind it
            regressed = True
            lines.append(
                f"  {name}: present in {olds[0][0]} but ABSENT in "
                f"{latest_path} — quality capture was lost  ** REGRESSION **"
            )
            entries.append(
                {
                    "metric": name,
                    "kind": "quality",
                    "baseline": olds[0][0],
                    "verdict": "regression",
                    "reason": "quality_capture_lost",
                }
            )
            continue
        new_v, new_gen = new_quality[name]
        if not olds:
            lines.append(
                f"  {name}: no comparable earlier record — skipped"
            )
            entries.append(
                {"metric": name, "verdict": "skipped", "reason": "no_baseline"}
            )
            continue
        # only samples taken at the SAME generation compare: a cadence
        # change relabels a different gen as the same budget, which would
        # either fake a regression or mask a real one
        pairs = [
            (old_v - new_v, path, old_v)
            for path, old_v, old_gen in olds
            if old_gen == new_gen
        ]
        if not pairs:
            regressed = True
            gens = sorted({g for _, _, g in olds})
            lines.append(
                f"  {name}: sampled at gen {new_gen} but baselines sampled "
                f"at gen(s) {gens} — cadence changed, not comparable  "
                "** REGRESSION **"
            )
            entries.append(
                {
                    "metric": name,
                    "kind": "quality",
                    "verdict": "regression",
                    "reason": "sample_gen_mismatch",
                    "new_gen": new_gen,
                    "baseline_gens": gens,
                }
            )
            continue
        drop, path, old_v = max(pairs, key=lambda t: t[0])
        bad = drop > quality_threshold
        regressed |= bad
        direction = "worse" if drop > 0 else "better"
        lines.append(
            f"  {name}: {new_v:.4f} vs best {old_v:.4f} ({path}) "
            f"[absolute, gen {new_gen}] -> {abs(drop):.4f} {direction}"
            + ("  ** REGRESSION **" if bad else "")
        )
        entries.append(
            {
                "metric": name,
                "kind": "quality",
                "basis": "absolute",
                "baseline": path,
                "old": old_v,
                "new": new_v,
                "delta_abs": -drop,
                "verdict": "regression" if bad else "ok",
            }
        )

    # -- SLO: knee QPS + p99-at-fixed-load, opt-in via --slo --------------
    if slo:
        new_slo = _slo_points(latest)
        new_degraded = _slo_degraded(latest)
        old_slo: dict[str, list[tuple[str, float]]] = {}
        any_baseline_slo = False
        for path, rec in earlier:
            pts = _slo_points(rec)
            any_baseline_slo |= bool(pts)
            for name, (v, _) in pts.items():
                old_slo.setdefault(name, []).append((path, v))
        # a baseline-tracked metric that DEGRADED to nothing in the
        # latest record (knee None = no level served linearly; a level's
        # p99 null = it completed zero requests) is the worst possible
        # value, not a skip — the comparison loop below only walks the
        # latest record's numeric points and would never see it
        for name in sorted(set(old_slo) & new_degraded):
            regressed = True
            path = old_slo[name][0][0]
            lines.append(
                f"  {name}: numeric in {path} but degraded to null in "
                f"{latest_path} — nothing served at this point  "
                "** REGRESSION **"
            )
            entries.append(
                {
                    "metric": name,
                    "kind": "slo",
                    "baseline": path,
                    "verdict": "regression",
                    "reason": "degraded_to_null",
                }
            )
        if not any_baseline_slo and not new_slo and not new_degraded:
            lines.append(
                f"  slo: no telemetry.slo metrics in {latest_path} or any "
                "baseline — skipped"
            )
            entries.append(
                {"metric": "slo", "verdict": "skipped", "reason": "absent"}
            )
        elif any_baseline_slo and not new_slo and not new_degraded:
            # block-level capture loss: a baseline measured its knee and
            # p99 ladder, the latest record measured nothing — the gate
            # must not be disarmable by dropping the measurement
            regressed = True
            lines.append(
                f"  slo: baselines carry telemetry.slo but {latest_path} "
                "does not — SLO capture was lost  ** REGRESSION **"
            )
            entries.append(
                {
                    "metric": "slo",
                    "kind": "slo",
                    "verdict": "regression",
                    "reason": "slo_capture_lost",
                }
            )
        for name in sorted(new_slo):
            new_v, lower_better = new_slo[name]
            olds = old_slo.get(name, [])
            if not olds:
                lines.append(
                    f"  {name}: no comparable earlier record — skipped"
                )
                entries.append(
                    {"metric": name, "verdict": "skipped",
                     "reason": "no_baseline"}
                )
                continue
            pairs = [
                (
                    (new_v - old_v) / old_v
                    if lower_better
                    else (old_v - new_v) / old_v,
                    path,
                    old_v,
                )
                for path, old_v in olds
                if old_v != 0
            ]
            if not pairs:
                continue
            rel, path, old_v = max(pairs, key=lambda t: t[0])
            bad = rel > slo_threshold
            regressed |= bad
            direction = "worse" if rel > 0 else "better"
            lines.append(
                f"  {name}: {new_v:.6g} vs best {old_v:.6g} ({path}) "
                f"[slo] -> {abs(rel) * 100:.1f}% {direction}"
                + ("  ** REGRESSION **" if bad else "")
            )
            entries.append(
                {
                    "metric": name,
                    "kind": "slo",
                    "basis": "relative",
                    "baseline": path,
                    "old": old_v,
                    "new": new_v,
                    "delta_rel": rel,
                    "verdict": "regression" if bad else "ok",
                }
            )
    # -- mesh: balance ratio + hot-loop contract, opt-in via --mesh -------
    if mesh:
        new_mesh = _mesh_points(latest)
        old_mesh: dict[str, list[tuple[str, float]]] = {}
        any_baseline_mesh = False
        for path, rec in earlier:
            pts = _mesh_points(rec)
            any_baseline_mesh |= bool(pts)
            for name, v in pts.items():
                old_mesh.setdefault(name, []).append((path, v))
        if not any_baseline_mesh and not new_mesh:
            lines.append(
                f"  mesh: no telemetry.mesh metrics in {latest_path} or "
                "any baseline — skipped"
            )
            entries.append(
                {"metric": "mesh", "verdict": "skipped", "reason": "absent"}
            )
        elif any_baseline_mesh and not new_mesh:
            # block-level capture loss: a baseline measured its per-device
            # balance, the latest record measured nothing — same
            # non-disarmable discipline as quality/slo capture
            regressed = True
            lines.append(
                f"  mesh: baselines carry telemetry.mesh but {latest_path} "
                "does not — mesh capture was lost  ** REGRESSION **"
            )
            entries.append(
                {
                    "metric": "mesh",
                    "kind": "mesh",
                    "verdict": "regression",
                    "reason": "mesh_capture_lost",
                }
            )
        for name in sorted(new_mesh):
            new_v = new_mesh[name]
            olds = old_mesh.get(name, [])
            if not olds:
                lines.append(
                    f"  {name}: no comparable earlier record — skipped"
                )
                entries.append(
                    {"metric": name, "verdict": "skipped",
                     "reason": "no_baseline"}
                )
                continue
            if name == "mesh.hot_loop_float_collectives":
                # the states-sharding contract: a float collective in the
                # hot loop is candidate/objective data crossing devices
                # per generation — any growth over the best baseline fails,
                # no threshold (shard_lint catches these pre-commit; this
                # gate catches them in the committed evidence)
                path, old_v = min(olds, key=lambda t: t[1])
                bad = new_v > old_v
                regressed |= bad
                lines.append(
                    f"  {name}: {new_v:g} vs best {old_v:g} ({path}) "
                    "[absolute]"
                    + ("  ** REGRESSION **" if bad else "")
                )
                entries.append(
                    {
                        "metric": name,
                        "kind": "mesh",
                        "basis": "absolute",
                        "baseline": path,
                        "old": old_v,
                        "new": new_v,
                        "verdict": "regression" if bad else "ok",
                    }
                )
                continue
            pairs = [
                ((old_v - new_v) / old_v, path, old_v)
                for path, old_v in olds
                if old_v != 0
            ]
            if not pairs:
                continue
            rel, path, old_v = max(pairs, key=lambda t: t[0])
            bad = rel > mesh_threshold
            regressed |= bad
            direction = "worse" if rel > 0 else "better"
            lines.append(
                f"  {name}: {new_v:.6g} vs best {old_v:.6g} ({path}) "
                f"[mesh] -> {abs(rel) * 100:.1f}% {direction}"
                + ("  ** REGRESSION **" if bad else "")
            )
            entries.append(
                {
                    "metric": name,
                    "kind": "mesh",
                    "basis": "relative",
                    "baseline": path,
                    "old": old_v,
                    "new": new_v,
                    "delta_rel": rel,
                    "verdict": "regression" if bad else "ok",
                }
            )
    # -- overlap/cold: device utilization + cold start, opt-in ------------
    if overlap:
        new_ov = _overlap_points(latest)
        old_ov: dict[str, list[tuple[str, float]]] = {}
        any_baseline_ov = False
        for path, rec in earlier:
            pts = _overlap_points(rec)
            any_baseline_ov |= bool(pts)
            for name, (v, _) in pts.items():
                old_ov.setdefault(name, []).append((path, v))
        if not any_baseline_ov and not new_ov:
            lines.append(
                f"  overlap: no telemetry.gaps/cold metrics in "
                f"{latest_path} or any baseline — skipped"
            )
            entries.append(
                {"metric": "overlap", "verdict": "skipped", "reason": "absent"}
            )
        elif any_baseline_ov and not new_ov:
            # block-level capture loss: a baseline measured its overlap
            # ratio / cold decomposition, the latest record measured
            # nothing — the gate must not be disarmable by dropping the
            # measurement (quality/slo/mesh discipline)
            regressed = True
            lines.append(
                f"  overlap: baselines carry telemetry.gaps/cold but "
                f"{latest_path} does not — gap/cold capture was lost  "
                "** REGRESSION **"
            )
            entries.append(
                {
                    "metric": "overlap",
                    "kind": "overlap",
                    "verdict": "regression",
                    "reason": "overlap_capture_lost",
                }
            )
        # per-metric capture loss (e.g. the latest record kept its gaps
        # block but dropped the cold breakdown): same non-disarmable rule
        for name in sorted(set(old_ov) - set(new_ov)):
            if not new_ov and any_baseline_ov:
                break  # already failed block-level above
            regressed = True
            path = old_ov[name][0][0]
            lines.append(
                f"  {name}: present in {path} but ABSENT in {latest_path} "
                "— overlap/cold capture was lost  ** REGRESSION **"
            )
            entries.append(
                {
                    "metric": name,
                    "kind": "overlap",
                    "baseline": path,
                    "verdict": "regression",
                    "reason": "overlap_capture_lost",
                }
            )
        for name in sorted(new_ov):
            new_v, lower_better = new_ov[name]
            olds = old_ov.get(name, [])
            if not olds:
                lines.append(
                    f"  {name}: no comparable earlier record — skipped"
                )
                entries.append(
                    {"metric": name, "verdict": "skipped",
                     "reason": "no_baseline"}
                )
                continue
            pairs = [
                (
                    (new_v - old_v) / old_v
                    if lower_better
                    else (old_v - new_v) / old_v,
                    path,
                    old_v,
                )
                for path, old_v in olds
                if old_v != 0
            ]
            if not pairs:
                continue
            rel, path, old_v = max(pairs, key=lambda t: t[0])
            bad = rel > overlap_threshold
            regressed |= bad
            direction = "worse" if rel > 0 else "better"
            lines.append(
                f"  {name}: {new_v:.6g} vs best {old_v:.6g} ({path}) "
                f"[overlap] -> {abs(rel) * 100:.1f}% {direction}"
                + ("  ** REGRESSION **" if bad else "")
            )
            entries.append(
                {
                    "metric": name,
                    "kind": "overlap",
                    "basis": "relative",
                    "baseline": path,
                    "old": old_v,
                    "new": new_v,
                    "delta_rel": rel,
                    "verdict": "regression" if bad else "ok",
                }
            )
    # -- cold: absolute cold/steady ceiling + warm-start hit rate ---------
    if cold:
        cold_block = latest.get("cold")
        has_cold = (
            isinstance(cold_block, dict)
            and cold_block.get("enabled") is not False
        )
        csr = latest.get("cold_steady_ratio")
        if has_cold and isinstance(csr, (int, float)):
            # absolute gate: the exit criterion is a target, not a
            # trajectory — no baseline needed
            bad = csr > cold_max_ratio
            regressed |= bad
            lines.append(
                f"  cold_steady_ratio (absolute): {csr:.3f} vs ceiling "
                f"{cold_max_ratio:g}"
                + ("  ** REGRESSION **" if bad else "")
            )
            entries.append(
                {
                    "metric": "cold_steady_ratio (absolute)",
                    "kind": "cold",
                    "basis": "absolute",
                    "ceiling": cold_max_ratio,
                    "new": float(csr),
                    "verdict": "regression" if bad else "ok",
                }
            )
        else:
            lines.append(
                "  cold (absolute): latest record carries no structured "
                "cold breakdown — skipped (capture loss is --overlap's "
                "business)"
            )
            entries.append(
                {"metric": "cold", "verdict": "skipped", "reason": "absent"}
            )
        new_rate = _cold_hit_rate(latest)
        old_rates = [
            (path, r)
            for path, rec in earlier
            if (r := _cold_hit_rate(rec)) is not None
        ]
        if new_rate is not None and old_rates:
            path, old_v = max(old_rates, key=lambda t: t[1])
            rel = (old_v - new_rate) / old_v if old_v > 0 else 0.0
            bad = rel > overlap_threshold
            regressed |= bad
            direction = "worse" if rel > 0 else "better"
            lines.append(
                f"  cold.warm_start_hit_rate: {new_rate:.4f} vs best "
                f"{old_v:.4f} ({path}) [cold] -> {abs(rel) * 100:.1f}% "
                f"{direction}" + ("  ** REGRESSION **" if bad else "")
            )
            entries.append(
                {
                    "metric": "cold.warm_start_hit_rate",
                    "kind": "cold",
                    "basis": "relative",
                    "baseline": path,
                    "old": old_v,
                    "new": new_rate,
                    "delta_rel": rel,
                    "verdict": "regression" if bad else "ok",
                }
            )
        elif new_rate is not None:
            lines.append(
                "  cold.warm_start_hit_rate: no comparable earlier record "
                "— skipped"
            )
            entries.append(
                {
                    "metric": "cold.warm_start_hit_rate",
                    "verdict": "skipped",
                    "reason": "no_baseline",
                }
            )
    return lines, regressed, entries


def fleet_check(
    paths: list[str],
    *,
    scaling_floor: float = DEFAULT_FLEET_SCALING_FLOOR,
    warm_floor: float = DEFAULT_FLEET_WARM_FLOOR,
    recovery_floor: float = DEFAULT_FLEET_RECOVERY_FLOOR,
    threshold: float = DEFAULT_FLEET_THRESHOLD,
) -> tuple[list[str], bool, list[dict]]:
    """The --fleet gate over the committed ``FLEET_r*.json`` series.

    Absolute gates on the LATEST record (the acceptance criteria are
    targets, not trajectories — like ``--cold``'s ratio ceiling):
    knee-scaling ratio >= ``scaling_floor``, every replica's AOT
    warm-start fraction >= ``warm_floor``, and the chaos segment's shed
    accounting — zero unaccounted losses, losses bounded by the dead
    replica's in-flight-at-kill count, recovery ratio >=
    ``recovery_floor``. A latest record that LOST any of these captures
    (null scaling, missing warm evidence, absent chaos block) FAILS —
    the gate must not be disarmable by dropping the measurement.
    Relative gate: the scaling ratio must not drop more than
    ``threshold`` vs the best earlier record. No records at all passes
    trivially (the gate arms with the first committed FLEET record)."""
    lines: list[str] = []
    regressed = False
    entries: list[dict] = []

    def fail(metric: str, msg: str, **extra):
        nonlocal regressed
        regressed = True
        lines.append(f"  fleet.{metric}: {msg} — FAIL")
        entries.append(
            {"metric": f"fleet.{metric}", "verdict": "regression", **extra}
        )

    def ok(metric: str, msg: str, **extra):
        lines.append(f"  fleet.{metric}: {msg} — ok")
        entries.append({"metric": f"fleet.{metric}", "verdict": "ok", **extra})

    if not paths:
        lines.append(
            "  fleet: no FLEET_r*.json records — gate unarmed, passing"
        )
        return lines, False, entries
    records = []
    for p in paths:
        doc = load_record(p)
        rec = doc.get("fleet") if isinstance(doc, dict) else None
        records.append((p, rec))
    latest_path, latest = records[-1]
    lines.append(f"  fleet: gating {latest_path}")
    if not isinstance(latest, dict):
        fail("record", f"{latest_path} carries no fleet payload (lost capture)")
        return lines, regressed, entries

    # -- absolute: knee scaling ------------------------------------------------
    scaling = latest.get("scaling") or {}
    ratio = scaling.get("linear_ratio")
    knees = scaling.get("knee_by_replicas")
    if not isinstance(ratio, (int, float)):
        fail(
            "scaling.linear_ratio",
            f"null (knees {knees}) — a sweep that never measured a knee "
            "at both replica counts proves nothing",
        )
    elif ratio < scaling_floor:
        fail(
            "scaling.linear_ratio",
            f"{ratio:.3f} < floor {scaling_floor:g} (knees {knees})",
            value=ratio,
            floor=scaling_floor,
        )
    else:
        ok(
            "scaling.linear_ratio",
            f"{ratio:.3f} >= floor {scaling_floor:g} (knees {knees})",
            value=ratio,
            floor=scaling_floor,
        )

    # -- absolute: per-replica AOT warm start ---------------------------------
    warm = latest.get("warm") or {}
    min_warm = warm.get("min_warm_fraction")
    per_replica = warm.get("per_replica") or {}
    if not isinstance(min_warm, (int, float)):
        fail(
            "warm.min_warm_fraction",
            "missing — no per-replica AOT warm-start evidence",
        )
    elif min_warm < warm_floor:
        worst = min(
            per_replica.items(),
            key=lambda kv: kv[1].get("warm_fraction") or 0,
            default=(None, {}),
        )
        fail(
            "warm.min_warm_fraction",
            f"{min_warm:.2f} < floor {warm_floor:g} "
            f"(worst replica {worst[0]}: {worst[1]})",
            value=min_warm,
            floor=warm_floor,
        )
    else:
        ok(
            "warm.min_warm_fraction",
            f"{min_warm:.2f} >= floor {warm_floor:g} "
            f"({len(per_replica)} replicas)",
            value=min_warm,
            floor=warm_floor,
        )

    # -- absolute: chaos shed accounting + recovery ---------------------------
    chaos = latest.get("chaos")
    if not isinstance(chaos, dict):
        fail(
            "chaos",
            "no chaos segment — the kill-a-replica proof is the record's "
            "point; a sweep that skipped it is not committable evidence",
        )
    else:
        acct = chaos.get("shed_accounting") or {}
        unaccounted = acct.get("lost_unaccounted")
        lost = acct.get("lost_dead_replica")
        in_flight = acct.get("in_flight_at_kill")
        if unaccounted is None:
            fail("chaos.lost_unaccounted", "missing shed accounting")
        elif unaccounted != 0:
            fail(
                "chaos.lost_unaccounted",
                f"{unaccounted} terminal failures attribute to NO dead "
                "replica — the router shed something it didn't have to",
                value=unaccounted,
            )
        else:
            ok(
                "chaos.lost_unaccounted",
                f"0 (dead-replica losses {lost}, in-flight at kill "
                f"{in_flight}, retried {acct.get('retried')})",
                lost_dead_replica=lost,
                in_flight_at_kill=in_flight,
            )
        if (
            isinstance(lost, (int, float))
            and isinstance(in_flight, (int, float))
            and lost > in_flight
        ):
            fail(
                "chaos.lost_dead_replica",
                f"{lost} > in_flight_at_kill {in_flight} — losses exceed "
                "what the dead replica could have held",
                value=lost,
                bound=in_flight,
            )
        recovery = (chaos.get("recovery") or {}).get("recovery_ratio")
        if not isinstance(recovery, (int, float)):
            fail(
                "chaos.recovery_ratio",
                "null — the post-kill sweep never recovered a knee",
            )
        elif recovery < recovery_floor:
            fail(
                "chaos.recovery_ratio",
                f"{recovery:.3f} < floor {recovery_floor:g}",
                value=recovery,
                floor=recovery_floor,
            )
        else:
            ok(
                "chaos.recovery_ratio",
                f"{recovery:.3f} >= floor {recovery_floor:g}",
                value=recovery,
                floor=recovery_floor,
            )

    # -- relative: scaling trajectory vs best baseline ------------------------
    baselines = [
        (p, (r.get("scaling") or {}).get("linear_ratio"))
        for p, r in records[:-1]
        if isinstance(r, dict)
    ]
    best = max(
        (b for b in baselines if isinstance(b[1], (int, float))),
        key=lambda b: b[1],
        default=None,
    )
    if best is not None and isinstance(ratio, (int, float)):
        rel = (best[1] - ratio) / best[1] if best[1] > 0 else 0.0
        if rel > threshold:
            fail(
                "scaling.linear_ratio (vs baseline)",
                f"{ratio:.3f} vs best {best[1]:.3f} ({best[0]}): "
                f"-{rel:.0%} > {threshold:.0%}",
                value=ratio,
                baseline=best[1],
                delta_rel=-rel,
            )
        else:
            ok(
                "scaling.linear_ratio (vs baseline)",
                f"{ratio:.3f} vs best {best[1]:.3f} ({best[0]}): "
                f"{-rel:+.0%} within {threshold:.0%}",
                value=ratio,
                baseline=best[1],
                delta_rel=-rel,
            )
    return lines, regressed, entries


def incidents_check(paths: list[str]) -> tuple[list[str], bool, list[dict]]:
    """The --incidents gate over the committed ``FLEET_r*.json`` series.

    Incident attribution must not be disarmable by dropping the capture:
    the LATEST fleet record must carry ``telemetry.incidents`` (pre-
    incident records skip as baselines, but once ANY record in the series
    carries the block, losing it fails), every chaos-lost row must be
    attributed to a specific batch/queue slot via the harvested flight
    dump (``shed_accounting.flight.attribution.untracked`` empty), the
    induced kill must appear as a ``replica_dead`` incident, and no
    incident may be open with unfrozen evidence (an open incident whose
    evidence failed to freeze is attribution theater). No FLEET records
    at all passes — the gate arms with the first committed record."""
    lines: list[str] = []
    regressed = False
    entries: list[dict] = []

    def fail(metric: str, msg: str, **extra):
        nonlocal regressed
        regressed = True
        lines.append(f"  incidents.{metric}: {msg} — FAIL")
        entries.append(
            {"metric": f"incidents.{metric}", "verdict": "regression", **extra}
        )

    def ok(metric: str, msg: str, **extra):
        lines.append(f"  incidents.{metric}: {msg} — ok")
        entries.append(
            {"metric": f"incidents.{metric}", "verdict": "ok", **extra}
        )

    if not paths:
        lines.append(
            "  incidents: no FLEET_r*.json records — gate unarmed, passing"
        )
        return lines, False, entries
    records = []
    for p in paths:
        doc = load_record(p)
        rec = doc.get("fleet") if isinstance(doc, dict) else None
        records.append((p, rec))
    latest_path, latest = records[-1]
    lines.append(f"  incidents: gating {latest_path}")
    if not isinstance(latest, dict):
        fail("record", f"{latest_path} carries no fleet payload (lost capture)")
        return lines, regressed, entries

    block = (latest.get("telemetry") or {}).get("incidents")
    baseline_has = any(
        isinstance(r, dict) and "incidents" in (r.get("telemetry") or {})
        for _, r in records[:-1]
    )
    if not isinstance(block, dict):
        if baseline_has:
            fail(
                "telemetry.incidents",
                "missing from the latest record but present in a baseline "
                "— incident capture was LOST, not never armed",
            )
        else:
            lines.append(
                "  incidents: series predates incident capture — gate "
                "unarmed, passing"
            )
        return lines, regressed, entries

    # -- every chaos-lost row attributed via the harvested flight dump ------
    chaos = latest.get("chaos")
    if isinstance(chaos, dict):
        acct = chaos.get("shed_accounting") or {}
        flight = acct.get("flight")
        lost = acct.get("lost_dead_replica") or 0
        if not isinstance(flight, dict):
            fail(
                "chaos.flight",
                "no flight block — the kill ran without harvesting the "
                "victim's black box",
            )
        elif lost and not flight.get("harvested"):
            fail(
                "chaos.flight.harvested",
                f"{lost} lost rows but no flight dump harvested — losses "
                "are countable but not attributable",
                lost=lost,
            )
        else:
            attr = flight.get("attribution") or {}
            untracked = attr.get("untracked") or []
            if untracked:
                fail(
                    "chaos.flight.untracked",
                    f"{len(untracked)} lost rows the flight dump never saw "
                    f"({untracked[:4]}{'...' if len(untracked) > 4 else ''})",
                    untracked=len(untracked),
                )
            else:
                ok(
                    "chaos.flight",
                    f"{attr.get('attributed', 0)}/{lost} lost rows "
                    f"attributed ({attr.get('by_where')})",
                    attributed=attr.get("attributed", 0),
                    lost=lost,
                )
        # the induced kill must be an incident on the record
        by_kind = block.get("by_kind") or {}
        if not by_kind.get("replica_dead"):
            fail(
                "replica_dead",
                "chaos segment killed a replica but no replica_dead "
                "incident was opened",
            )
        else:
            ok(
                "replica_dead",
                f"{by_kind['replica_dead']} incident(s) for the induced kill",
            )

    # -- no open incident with unfrozen evidence ----------------------------
    bad = [
        i
        for i in block.get("incidents") or []
        if i.get("state") == "open" and not i.get("frozen")
    ]
    if bad:
        fail(
            "frozen",
            f"{len(bad)} open incident(s) with unfrozen evidence "
            f"(first: {bad[0].get('kind')}/{bad[0].get('summary')!r})",
            open_unfrozen=len(bad),
        )
    else:
        ok(
            "frozen",
            f"{block.get('open', 0)} open / {block.get('total', 0)} total "
            "incidents, all evidence frozen at open",
            open=block.get("open", 0),
            total=block.get("total", 0),
        )
    return lines, regressed, entries


def qos_check(
    paths: list[str],
    *,
    shed_floor: float = DEFAULT_QOS_SCAVENGER_SHED_FLOOR,
    ttfs_floor: float = DEFAULT_QOS_TTFS_RATIO_FLOOR,
) -> tuple[list[str], bool, list[dict]]:
    """The --qos gate over the committed ``QOS_r*.json`` series.

    Absolute gates on the LATEST record (acceptance criteria, like
    --fleet's):

    - interactive p99 at saturation <= the record's own SLO target (the
      target is derived from the record's light-load baseline and
      committed next to the measurement, so the gate is self-describing);
    - scavenger's share of everything shed >= ``shed_floor`` — the
      low-priority-absorbs-overload invariant;
    - the streaming time_to_complete/time_to_first_solved ratio >=
      ``ttfs_floor`` on the early-exit workload;
    - the QoS-off identity proof: bit-identical rows, zero extra
      compiles, equal dispatch counts.

    A latest record that LOST any of these captures FAILS — the gate
    must not be disarmable by dropping the measurement. No records at
    all passes trivially (the gate arms with the first committed QOS
    record)."""
    lines: list[str] = []
    regressed = False
    entries: list[dict] = []

    def fail(metric: str, msg: str, **extra):
        nonlocal regressed
        regressed = True
        lines.append(f"  qos.{metric}: {msg} — FAIL")
        entries.append(
            {"metric": f"qos.{metric}", "verdict": "regression", **extra}
        )

    def ok(metric: str, msg: str, **extra):
        lines.append(f"  qos.{metric}: {msg} — ok")
        entries.append({"metric": f"qos.{metric}", "verdict": "ok", **extra})

    if not paths:
        lines.append("  qos: no QOS_r*.json records — gate unarmed, passing")
        return lines, False, entries
    records = []
    for p in paths:
        doc = load_record(p)
        rec = doc.get("qos") if isinstance(doc, dict) else None
        records.append((p, rec))
    latest_path, latest = records[-1]
    lines.append(f"  qos: gating {latest_path}")
    if not isinstance(latest, dict):
        fail("record", f"{latest_path} carries no qos payload (lost capture)")
        return lines, regressed, entries

    # -- absolute: interactive p99 vs its committed SLO target ---------------
    sat = latest.get("saturation") or {}
    p99 = sat.get("interactive_p99_ms")
    target = sat.get("slo_target_ms")
    if not isinstance(p99, (int, float)) or not isinstance(
        target, (int, float)
    ):
        fail(
            "saturation.interactive_p99",
            f"p99 {p99} / SLO target {target} missing — a saturation run "
            "that never measured interactive latency proves nothing",
        )
    elif p99 > target:
        fail(
            "saturation.interactive_p99",
            f"{p99:g} ms > SLO target {target:g} ms at offered "
            f"{sat.get('offered_rps')} rps",
            value=p99,
            target=target,
        )
    else:
        ok(
            "saturation.interactive_p99",
            f"{p99:g} ms <= SLO target {target:g} ms at offered "
            f"{sat.get('offered_rps')} rps (capacity "
            f"{sat.get('max_sustainable_qps')})",
            value=p99,
            target=target,
        )

    # -- absolute: who absorbed the overload ---------------------------------
    share = sat.get("scavenger_shed_share")
    totals = sat.get("shed_totals")
    if not isinstance(share, (int, float)):
        fail(
            "saturation.scavenger_shed_share",
            f"null (shed totals {totals}) — a saturation run that shed "
            "nothing never reached saturation",
        )
    elif share < shed_floor:
        fail(
            "saturation.scavenger_shed_share",
            f"{share:.3f} < floor {shed_floor:g} (shed totals {totals}) — "
            "overload leaked past the scavenger class",
            value=share,
            floor=shed_floor,
        )
    else:
        ok(
            "saturation.scavenger_shed_share",
            f"{share:.3f} >= floor {shed_floor:g} (shed totals {totals})",
            value=share,
            floor=shed_floor,
        )

    # -- absolute: streaming time-to-first-solved -----------------------------
    streaming = latest.get("streaming") or {}
    ratio = streaming.get("ttfs_ratio")
    if not isinstance(ratio, (int, float)):
        fail(
            "streaming.ttfs_ratio",
            f"null (first solved {streaming.get('time_to_first_solved_s')}, "
            f"complete {streaming.get('time_to_complete_s')}) — no partial "
            "rows ever streamed",
        )
    elif ratio < ttfs_floor:
        fail(
            "streaming.ttfs_ratio",
            f"{ratio:g} < floor {ttfs_floor:g} (first solved "
            f"{streaming.get('time_to_first_solved_s')}s vs complete "
            f"{streaming.get('time_to_complete_s')}s)",
            value=ratio,
            floor=ttfs_floor,
        )
    else:
        ok(
            "streaming.ttfs_ratio",
            f"{ratio:g} >= floor {ttfs_floor:g} "
            f"({streaming.get('rows_streamed')}/{streaming.get('n_rows')} "
            "rows streamed before completion)",
            value=ratio,
            floor=ttfs_floor,
        )

    # -- absolute: the QoS-off overhead contract ------------------------------
    identity = latest.get("identity") or {}
    bit = identity.get("bit_identical")
    extra = identity.get("extra_compiles")
    d_eq = identity.get("dispatches_equal")
    if bit is not True:
        fail(
            "identity.bit_identical",
            f"{bit} — QoS off must reproduce the pre-QoS path bit-for-bit",
            value=bit,
        )
    elif extra != 0 or d_eq is not True:
        fail(
            "identity.zero_extra_work",
            f"extra_compiles={extra}, dispatches "
            f"{identity.get('dispatches_off')} vs "
            f"{identity.get('dispatches_on')} — QoS bookkeeping leaked "
            "into the device path",
            extra_compiles=extra,
        )
    else:
        ok(
            "identity.zero_extra_work",
            f"bit-identical, extra_compiles=0, dispatches "
            f"{identity.get('dispatches_off')}=="
            f"{identity.get('dispatches_on')}",
        )
    return lines, regressed, entries


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "records",
        nargs="*",
        help="bench record files, oldest first (e.g. BENCH_r*.json)",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="repo-check mode: with no files, glob BENCH_r*.json in cwd",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=DEFAULT_THRESHOLD,
        help=f"relative regression that fails (default {DEFAULT_THRESHOLD})",
    )
    parser.add_argument(
        "--quality-threshold",
        type=float,
        default=DEFAULT_QUALITY_THRESHOLD,
        help="absolute interior-success-rate drop that fails "
        f"(default {DEFAULT_QUALITY_THRESHOLD})",
    )
    parser.add_argument(
        "--slo",
        action="store_true",
        help="also gate the serving SLO metrics: saturation-knee QPS and "
        "p99 at each fixed offered load (pre-SLO records skip as "
        "baselines; a latest record that LOST slo capture fails)",
    )
    parser.add_argument(
        "--slo-threshold",
        type=float,
        default=DEFAULT_SLO_THRESHOLD,
        help="relative SLO regression that fails under --slo "
        f"(default {DEFAULT_SLO_THRESHOLD})",
    )
    parser.add_argument(
        "--mesh",
        action="store_true",
        help="also gate the mesh metrics: per-device balance ratio "
        "(relative drop) and hot-loop float collectives (any growth "
        "fails — the zero-collective contract). Pre-mesh and "
        "single-device records skip as baselines; a latest record that "
        "LOST mesh capture fails",
    )
    parser.add_argument(
        "--mesh-threshold",
        type=float,
        default=DEFAULT_MESH_THRESHOLD,
        help="relative balance-ratio drop that fails under --mesh "
        f"(default {DEFAULT_MESH_THRESHOLD})",
    )
    parser.add_argument(
        "--overlap",
        action="store_true",
        help="also gate the device-utilization metrics: the overlap ratio "
        "(telemetry.gaps device-busy/wall; relative drop fails) and the "
        "cold/steady ratio (records carrying the structured cold "
        "breakdown; relative growth fails). Pre-gap records skip as "
        "baselines; a latest record that LOST gap/cold capture fails",
    )
    parser.add_argument(
        "--overlap-threshold",
        type=float,
        default=DEFAULT_OVERLAP_THRESHOLD,
        help="relative overlap/cold regression that fails under --overlap "
        f"(default {DEFAULT_OVERLAP_THRESHOLD})",
    )
    parser.add_argument(
        "--cold",
        action="store_true",
        help="also gate the cold-start metrics: the latest record's "
        "cold_steady_ratio against an ABSOLUTE ceiling "
        "(--cold-max-ratio; the ROADMAP exit criterion needs no "
        "baseline) and the warm-start hit rate "
        "((hit + aot_hit) / classified executables) against the best "
        "baseline (relative, --overlap-threshold). Pre-cold records "
        "skip as baselines",
    )
    parser.add_argument(
        "--cold-max-ratio",
        type=float,
        default=DEFAULT_COLD_MAX_RATIO,
        help="absolute cold/steady ceiling enforced under --cold "
        f"(default {DEFAULT_COLD_MAX_RATIO})",
    )
    parser.add_argument(
        "--fleet",
        action="store_true",
        help="also gate the committed FLEET_r*.json series (globbed in "
        "cwd): knee-scaling ratio and per-replica AOT warm fraction "
        "against absolute floors, the chaos segment's shed accounting "
        "(zero unaccounted losses, losses bounded by in-flight-at-kill, "
        "recovery to the (N-1)-replica knee), and the scaling ratio's "
        "trajectory vs the best baseline. Lost capture fails; no FLEET "
        "records passes (the gate arms with the first)",
    )
    parser.add_argument(
        "--fleet-scaling-floor",
        type=float,
        default=DEFAULT_FLEET_SCALING_FLOOR,
        help="absolute knee-scaling-ratio floor under --fleet "
        f"(default {DEFAULT_FLEET_SCALING_FLOOR})",
    )
    parser.add_argument(
        "--fleet-warm-floor",
        type=float,
        default=DEFAULT_FLEET_WARM_FLOOR,
        help="absolute per-replica AOT warm-fraction floor under --fleet "
        f"(default {DEFAULT_FLEET_WARM_FLOOR})",
    )
    parser.add_argument(
        "--fleet-recovery-floor",
        type=float,
        default=DEFAULT_FLEET_RECOVERY_FLOOR,
        help="absolute chaos recovery-ratio floor under --fleet "
        f"(default {DEFAULT_FLEET_RECOVERY_FLOOR})",
    )
    parser.add_argument(
        "--fleet-threshold",
        type=float,
        default=DEFAULT_FLEET_THRESHOLD,
        help="relative scaling-ratio drop vs the best FLEET baseline that "
        f"fails under --fleet (default {DEFAULT_FLEET_THRESHOLD})",
    )
    parser.add_argument(
        "--incidents",
        action="store_true",
        help="also gate incident attribution on the committed "
        "FLEET_r*.json series (globbed in cwd): the latest record must "
        "carry telemetry.incidents (capture loss fails once any baseline "
        "has it), every chaos-lost row must be attributed to a specific "
        "batch via the harvested flight dump, the induced kill must "
        "appear as a replica_dead incident, and no incident may be open "
        "with unfrozen evidence. No FLEET records passes (the gate arms "
        "with the first)",
    )
    parser.add_argument(
        "--qos",
        action="store_true",
        help="also gate the committed QOS_r*.json series (globbed in cwd): "
        "interactive p99 at saturation against the record's own SLO "
        "target, the scavenger class's share of the shed against an "
        "absolute floor (low priority absorbs overload), the streaming "
        "time-to-first-solved ratio, and the QoS-off "
        "bit-identity/zero-extra-compiles proof. Lost capture fails; no "
        "QOS records passes (the gate arms with the first)",
    )
    parser.add_argument(
        "--qos-shed-floor",
        type=float,
        default=DEFAULT_QOS_SCAVENGER_SHED_FLOOR,
        help="absolute scavenger-shed-share floor under --qos "
        f"(default {DEFAULT_QOS_SCAVENGER_SHED_FLOOR})",
    )
    parser.add_argument(
        "--qos-ttfs-floor",
        type=float,
        default=DEFAULT_QOS_TTFS_RATIO_FLOOR,
        help="absolute time_to_complete/time_to_first_solved floor under "
        f"--qos (default {DEFAULT_QOS_TTFS_RATIO_FLOOR})",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="append one machine-readable JSON line (per-metric basis, "
        "delta, verdict) after the human-readable report, for CI "
        "annotation",
    )
    args = parser.parse_args(argv)

    paths = list(args.records)
    if not paths and args.check:
        paths = sorted(glob.glob("BENCH_r*.json"))

    # the FLEET series is its own file family (FLEET_r*.json, globbed in
    # cwd like the --check default) gated independently of the BENCH
    # series — a fleet-only invocation needs no BENCH records at all
    fleet_lines: list[str] = []
    fleet_regressed = False
    fleet_entries: list[dict] = []
    if args.fleet:
        fleet_lines, fleet_regressed, fleet_entries = fleet_check(
            sorted(glob.glob("FLEET_r*.json")),
            scaling_floor=args.fleet_scaling_floor,
            warm_floor=args.fleet_warm_floor,
            recovery_floor=args.fleet_recovery_floor,
            threshold=args.fleet_threshold,
        )

    # the incidents gate reads the same FLEET series with its own
    # predicate family (attribution, not throughput)
    inc_lines: list[str] = []
    inc_regressed = False
    inc_entries: list[dict] = []
    if args.incidents:
        inc_lines, inc_regressed, inc_entries = incidents_check(
            sorted(glob.glob("FLEET_r*.json"))
        )

    # the QOS series mirrors the FLEET discipline: its own file family,
    # gated independently of the BENCH series
    qos_lines: list[str] = []
    qos_regressed = False
    qos_entries: list[dict] = []
    if args.qos:
        qos_lines, qos_regressed, qos_entries = qos_check(
            sorted(glob.glob("QOS_r*.json")),
            shed_floor=args.qos_shed_floor,
            ttfs_floor=args.qos_ttfs_floor,
        )

    if not paths and not args.fleet and not args.qos and not args.incidents:
        parser.error("no bench records given (and --check found none)")

    # records are taken in the order GIVEN (oldest first, per the CLI
    # contract) — re-sorting lexically would silently pick the wrong
    # "latest" for names like before.json/after.json; the --check default
    # glob above is sorted because BENCH_r%02d names sort chronologically
    records = []
    for p in paths:
        rec = load_record(p)
        if rec is not None:
            records.append((p, rec))
    if len(records) < 2:
        print(
            f"bench_diff: {len(records)} usable record(s) — nothing to "
            "diff, trivially passing"
        )
        if fleet_lines:
            print("fleet gate:")
            print("\n".join(fleet_lines))
            print(
                "bench_diff: fleet REGRESSION — failing"
                if fleet_regressed
                else "bench_diff: fleet ok"
            )
        if inc_lines:
            print("incidents gate:")
            print("\n".join(inc_lines))
            print(
                "bench_diff: incidents REGRESSION — failing"
                if inc_regressed
                else "bench_diff: incidents ok"
            )
        if qos_lines:
            print("qos gate:")
            print("\n".join(qos_lines))
            print(
                "bench_diff: qos REGRESSION — failing"
                if qos_regressed
                else "bench_diff: qos ok"
            )
        any_regressed = fleet_regressed or qos_regressed or inc_regressed
        if args.json:
            print(
                json.dumps(
                    {"regressed": any_regressed,
                     "reason": "insufficient_records",
                     "usable_records": len(records),
                     "fleet": args.fleet,
                     "incidents": args.incidents,
                     "qos": args.qos,
                     "metrics": fleet_entries + inc_entries + qos_entries}
                )
            )
        return 1 if any_regressed else 0

    print(
        f"bench_diff: {records[-1][0]} vs {len(records) - 1} earlier "
        f"record(s), threshold {args.threshold:.0%}, quality threshold "
        f"{args.quality_threshold:g} abs"
    )
    lines, regressed, entries = diff_series(
        records,
        args.threshold,
        args.quality_threshold,
        slo=args.slo,
        slo_threshold=args.slo_threshold,
        mesh=args.mesh,
        mesh_threshold=args.mesh_threshold,
        overlap=args.overlap,
        overlap_threshold=args.overlap_threshold,
        cold=args.cold,
        cold_max_ratio=args.cold_max_ratio,
    )
    print("\n".join(lines))
    if fleet_lines:
        print("fleet gate:")
        print("\n".join(fleet_lines))
    if inc_lines:
        print("incidents gate:")
        print("\n".join(inc_lines))
    if qos_lines:
        print("qos gate:")
        print("\n".join(qos_lines))
    regressed = regressed or fleet_regressed or qos_regressed or inc_regressed
    entries = entries + fleet_entries + inc_entries + qos_entries
    if regressed:
        print("bench_diff: REGRESSION past threshold — failing")
    else:
        print("bench_diff: ok")
    if args.json:
        # one JSON line AFTER the unchanged human report: CI annotators
        # parse the last line, humans read the rest
        print(
            json.dumps(
                {
                    "latest": records[-1][0],
                    "baselines": [p for p, _ in records[:-1]],
                    "threshold": args.threshold,
                    "quality_threshold": args.quality_threshold,
                    "slo": args.slo,
                    "slo_threshold": args.slo_threshold,
                    "mesh": args.mesh,
                    "mesh_threshold": args.mesh_threshold,
                    "overlap": args.overlap,
                    "overlap_threshold": args.overlap_threshold,
                    "cold": args.cold,
                    "cold_max_ratio": args.cold_max_ratio,
                    "fleet": args.fleet,
                    "fleet_scaling_floor": args.fleet_scaling_floor,
                    "fleet_warm_floor": args.fleet_warm_floor,
                    "fleet_recovery_floor": args.fleet_recovery_floor,
                    "fleet_threshold": args.fleet_threshold,
                    "incidents": args.incidents,
                    "qos": args.qos,
                    "qos_shed_floor": args.qos_shed_floor,
                    "qos_ttfs_floor": args.qos_ttfs_floor,
                    "regressed": regressed,
                    "metrics": entries,
                }
            )
        )
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
