#!/usr/bin/env python
"""Perf-regression watchdog: diff bench records, normalized by ledger cost.

The committed ``BENCH_r*.json`` series is the repo's performance
trajectory; this tool turns it into an enforced contract. It compares the
LATEST record against the best earlier value of each tracked metric and
exits non-zero when a metric moved past the threshold in its bad
direction — runnable standalone or as the repo check wired into tier-1
(``tests/test_cost_ledger.py::TestBenchDiffRepoCheck``).

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json   # pairwise
    python tools/bench_diff.py --check BENCH_r*.json           # whole series
    python tools/bench_diff.py --check                         # globs BENCH_r*.json
    python tools/bench_diff.py --check --threshold 0.4 ...

Normalization: wall-clock metrics are divided by the work a record
actually performed before comparison — the cost-ledger FLOPs total
(``telemetry.cost.flops_total``) when both records carry it, else the
benchmark shape (``execution.n_states * n_gen``) — so a PR that doubles
the bench shape (and honestly reports it) does not masquerade as a 2x
regression, and one that halves the shape cannot hide one. Records
predating the ledger fall back to a raw comparison (the bench defaults
have been stable) with the basis named in the output line.

Records may be bare bench JSON or the committed driver wrapper
``{"n", "cmd", "rc", "parsed"}``; wrappers with a non-zero rc or an
empty payload are skipped (a crashed bench is not evidence of a
regression — or of its absence).
"""

from __future__ import annotations

import argparse
import glob
import json
import sys

#: relative slowdown (or throughput loss) that fails the check. The
#: tunnelled bench host shows ~±10% run-to-run jitter (BASELINE.md), so
#: the default trips at 2.5x that noise floor, far below the 2x class of
#: regression this watchdog exists to catch.
DEFAULT_THRESHOLD = 0.25


def load_record(path: str) -> dict | None:
    """Bench payload from ``path``; None when unusable (crashed/empty)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_diff: skipping {path}: {e}", file=sys.stderr)
        return None
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc:  # committed driver wrapper
        if doc.get("rc") not in (0, None):
            print(
                f"bench_diff: skipping {path}: bench exited rc={doc['rc']}",
                file=sys.stderr,
            )
            return None
        doc = doc.get("parsed")
    return doc if isinstance(doc, dict) and doc else None


def _get(rec: dict, dotted: str):
    cur = rec
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _headline_work(rec: dict) -> dict:
    """Every work basis the headline run's metadata supports (a record
    carrying ledger FLOPs usually carries the bench shape too — both are
    kept so it stays comparable with pre-ledger records via 'shape')."""
    out = {}
    cost = _get(rec, "telemetry.cost") or {}
    flops = cost.get("flops_total")
    if isinstance(flops, (int, float)) and flops > 0:
        out["flops"] = float(flops)
    ex = rec.get("execution") or {}
    n_states, n_gen = ex.get("n_states"), ex.get("n_gen")
    if n_states and n_gen:
        out["shape"] = float(n_states) * float(n_gen)
    return out


def _botnet_work(rec: dict) -> dict:
    rb = rec.get("real_botnet") or {}
    if rb.get("n_states") and rb.get("n_gen"):
        return {"shape": float(rb["n_states"]) * float(rb["n_gen"])}
    return {}


def _serving_best_throughput(rec: dict):
    levels = _get(rec, "serving.levels") or []
    vals = [
        lv.get("throughput_rows_s")
        for lv in levels
        if isinstance(lv.get("throughput_rows_s"), (int, float))
    ]
    return max(vals) if vals else None


#: (name, extractor, lower_is_better, work extractor or None)
METRICS = (
    ("steady_s", lambda r: r.get("steady_s"), True, _headline_work),
    ("value (speedup)", lambda r: r.get("value"), False, None),
    (
        "real_botnet.steady_s",
        lambda r: _get(r, "real_botnet.steady_s"),
        True,
        _botnet_work,
    ),
    (
        "early_exit.speedup",
        lambda r: _get(r, "early_exit.speedup"),
        False,
        None,
    ),
    (
        "serving.throughput_rows_s (best level)",
        _serving_best_throughput,
        False,
        None,
    ),
)


#: normalization bases, strongest first: model FLOPs beat the benchmark
#: shape beat an unnormalized comparison
_BASES = ("flops", "shape", "raw")


def _values_by_basis(rec: dict, extract, work_fn) -> dict:
    """Every normalization of this record's metric value that its
    metadata supports: ``{"raw": v}`` always (when the metric exists),
    plus ``v / work`` per available work basis — ALL of them, so a
    post-ledger record (flops + shape) still compares shape-normalized
    against a pre-ledger one (shape only)."""
    v = extract(rec)
    if not isinstance(v, (int, float)):
        return {}
    out = {"raw": float(v)}
    if work_fn is not None:
        for kind, work in work_fn(rec).items():
            if work:
                out[kind] = float(v) / work
    return out


def diff_series(
    records: list[tuple[str, dict]], threshold: float
) -> tuple[list[str], bool]:
    """Compare the last record pairwise against every earlier one, each
    pair in the strongest normalization basis BOTH sides support (ledger
    FLOPs > bench shape > raw), and judge the worst pair per metric.
    Returns (report lines, any_regression)."""
    lines: list[str] = []
    regressed = False
    latest_path, latest = records[-1]
    earlier = records[:-1]
    for name, extract, lower_better, work_fn in METRICS:
        new_vals = _values_by_basis(latest, extract, work_fn)
        if not new_vals:
            lines.append(f"  {name}: absent in {latest_path} — skipped")
            continue
        pairs = []
        for path, rec in earlier:
            old_vals = _values_by_basis(rec, extract, work_fn)
            basis = next(
                (b for b in _BASES if b in old_vals and b in new_vals), None
            )
            if basis is None or old_vals[basis] == 0:
                continue
            new_v, old_v = new_vals[basis], old_vals[basis]
            rel = (
                (new_v - old_v) / old_v
                if lower_better
                else (old_v - new_v) / old_v
            )
            pairs.append((rel, path, old_v, new_v, basis))
        if not pairs:
            lines.append(f"  {name}: no comparable earlier record — skipped")
            continue
        rel, path, old_v, new_v, basis = max(pairs, key=lambda t: t[0])
        bad = rel > threshold
        regressed |= bad
        direction = "worse" if rel > 0 else "better"
        lines.append(
            f"  {name}: {new_v:.6g} vs best {old_v:.6g} ({path}) "
            f"[{basis}-normalized] -> {abs(rel) * 100:.1f}% {direction}"
            + ("  ** REGRESSION **" if bad else "")
        )
    return lines, regressed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "records",
        nargs="*",
        help="bench record files, oldest first (e.g. BENCH_r*.json)",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="repo-check mode: with no files, glob BENCH_r*.json in cwd",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=DEFAULT_THRESHOLD,
        help=f"relative regression that fails (default {DEFAULT_THRESHOLD})",
    )
    args = parser.parse_args(argv)

    paths = list(args.records)
    if not paths and args.check:
        paths = sorted(glob.glob("BENCH_r*.json"))
    if not paths:
        parser.error("no bench records given (and --check found none)")

    # records are taken in the order GIVEN (oldest first, per the CLI
    # contract) — re-sorting lexically would silently pick the wrong
    # "latest" for names like before.json/after.json; the --check default
    # glob above is sorted because BENCH_r%02d names sort chronologically
    records = []
    for p in paths:
        rec = load_record(p)
        if rec is not None:
            records.append((p, rec))
    if len(records) < 2:
        print(
            f"bench_diff: {len(records)} usable record(s) — nothing to "
            "diff, trivially passing"
        )
        return 0

    print(
        f"bench_diff: {records[-1][0]} vs {len(records) - 1} earlier "
        f"record(s), threshold {args.threshold:.0%}"
    )
    lines, regressed = diff_series(records, args.threshold)
    print("\n".join(lines))
    if regressed:
        print("bench_diff: REGRESSION past threshold — failing")
        return 1
    print("bench_diff: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
