"""Bootstrap the LCLD artifact family without the raw LendingClub CSV.

The reference's LCLD experiment chain consumes artifacts its defense
pipeline derives from the (non-redistributed) raw export: candidate sets,
scalers, and the five defended/undefended models under ``./data/lcld`` +
``./models/lcld``. This tool builds the same family from synthetic
constraint-valid rows (``domains/synth.py``), labelled by the committed
reference model so the learning task matches the real decision surface,
then runs the defense pipeline (``experiments/defense.py``) end to end.

After this, every ``config/*.lcld*.yaml`` grid point is runnable::

    python tools/bootstrap_lcld.py            # writes ./data/lcld ./models/lcld
    python -m moeva2_ijcai22_replication_tpu.experiments.run_all

Knobs via env: BOOT_TRAIN / BOOT_TEST (row counts), BOOT_BUDGET (MoEvA
generations inside the pipeline).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.experiments import defense
from moeva2_ijcai22_replication_tpu.models.io import load_classifier
from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

REF = "/root/reference"
N_TRAIN = int(os.environ.get("BOOT_TRAIN", 4000))
N_TEST = int(os.environ.get("BOOT_TEST", 2000))
BUDGET = int(os.environ.get("BOOT_BUDGET", 100))


def main():
    cons = LcldConstraints(
        f"{REF}/data/lcld/features.csv", f"{REF}/data/lcld/constraints.csv"
    )
    # label with the committed reference classifier: the synthetic features
    # then carry the same decision surface the attacks target. Synthetic
    # rows are mostly "fully paid" under that model, so rejection-sample
    # batches until both classes reach their quota (~1/3 positives).
    ref_model = load_classifier(f"{REF}/models/lcld/nn.model")
    ref_scaler = load_joblib_scaler(f"{REF}/models/lcld/scaler.joblib")

    n_total = N_TRAIN + N_TEST
    want_pos = n_total // 3
    pos, neg = [], []
    for seed in range(42, 142):
        xb = synth_lcld(20000, cons.schema, seed=seed)
        proba = np.asarray(
            ref_model.predict_proba(ref_scaler.transform(jnp.asarray(xb)))
        )[:, 1]
        yb = proba >= 0.5
        pos.append(xb[yb])
        neg.append(xb[~yb])
        if sum(len(p) for p in pos) >= want_pos and sum(
            len(q) for q in neg
        ) >= n_total - want_pos:
            break
    n_pos = sum(len(p) for p in pos)
    n_neg = sum(len(q) for q in neg)
    if n_pos < want_pos or n_neg < n_total - want_pos:
        raise RuntimeError(
            f"class quota not met after sampling: {n_pos} positives "
            f"(need {want_pos}), {n_neg} negatives (need {n_total - want_pos}) "
            "— raise the batch budget or lower BOOT_TRAIN/BOOT_TEST"
        )
    x = np.concatenate(
        [np.concatenate(pos)[:want_pos], np.concatenate(neg)[: n_total - want_pos]]
    )
    rng = np.random.default_rng(0)
    x = x[rng.permutation(len(x))]
    cons.check_constraints_error(x)
    proba = np.asarray(
        ref_model.predict_proba(ref_scaler.transform(jnp.asarray(x)))
    )[:, 1]
    y = (proba >= 0.5).astype(np.int64)
    print(f"labelled {len(x)} rows; positive rate {y.mean():.3f}")

    os.makedirs("data/lcld", exist_ok=True)
    for name, arr in [
        ("x_train", x[:N_TRAIN]), ("x_test", x[N_TRAIN:]),
        ("y_train", y[:N_TRAIN]), ("y_test", y[N_TRAIN:]),
    ]:
        np.save(f"data/lcld/{name}.npy", arr)

    config = {
        "project_name": "lcld",
        "paths": {
            "features": f"{REF}/data/lcld/features.csv",
            "constraints": f"{REF}/data/lcld/constraints.csv",
            "x_train": "data/lcld/x_train.npy",
            "x_test": "data/lcld/x_test.npy",
            "y_train": "data/lcld/y_train.npy",
            "y_test": "data/lcld/y_test.npy",
        },
        "dirs": {"data": "data/lcld", "models": "models/lcld"},
        "misclassification_threshold": 0.25,
        "norm": 2,
        "eps": 0.2,
        "seed": 42,
        "budget": BUDGET,
        "n_pop": 200,
        "n_offsprings": 100,
        "system": {"n_jobs": 1, "verbose": 0},
    }
    artifacts = defense.run(config)
    print("artifact family:")
    for k, v in artifacts.items():
        print(f"  {k}: {v}")


if __name__ == "__main__":
    main()
