#!/usr/bin/env python
"""Domain/spec lint gate: validate the committed constraint specs.

The constraint-IR subsystem (``domains/ir/``) makes domains data; this
gate makes bad data unmergeable. For every committed spec domain
(``domains/__init__.py::SPEC_DOMAINS``) it:

- **parses + statically validates** the spec (``ir.spec.validate_spec``)
  against the domain's schema. Fatal findings: undefined features,
  duplicate constraint names, membership values outside feature bounds.
  "Non-guarded denominator" findings are WARNINGS, not errors — the
  reference's own hand-written lcld kernel divides unguarded in
  g6/g8/g9, and the committed spec documents, not rewrites, the
  reference semantics.
- **checks OHE group coverage** — the schema's one-hot groups must build
  (``core.codec.full_ohe_tables``) so the compiled repair's
  ``harden_onehot`` finale covers every group.
- **recompiles the jnp backend and replays the equivalence fixtures**:
  ``lcld_spec`` vs the hand-written ``lcld_constraint_terms`` and
  ``botnet_spec`` vs ``BotnetConstraints._raw`` must agree BIT-EXACTLY
  on seeded samples (manifold + perturbed); every spec's jnp kernel must
  agree with its own numpy oracle twin at float64 tolerance.
- **compiles the MILP backend** (``ir.milp_backend.make_spec_sat_builder``)
  and builds rows at a sampled hot start — a spec the SAT/repair path
  cannot linearize fails the gate before it fails an attack run.
- **smokes the generated-family path**: ``family0`` compiles and its
  seeded sampler is deterministic (same seed → same bytes).

Dataset-free by construction: lcld/botnet validate against the
code-derived synthetic schemas (``domains/synth.py``) unless the
reference tree exists, in which case botnet also validates against the
real 756-feature schema + ``feat_idx.pickle``; phishing validates
against its committed package data. Same skip-vs-fail convention as
tools/oracle_check.py / tools/shard_lint.py.

    python tools/domain_lint.py --check        # tier-1 repo-check mode
    python tools/domain_lint.py --check --json # + machine-readable line
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

REFERENCE = "/root/reference"

#: validate_spec finding substrings that are advisory, not fatal (the
#: committed lcld spec reproduces the reference kernel's unguarded
#: ratios on purpose — see module docstring)
WARNING_MARKERS = ("non-guarded denominator",)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _split_findings(findings: list) -> tuple[list, list]:
    warnings, errors = [], []
    for f in findings:
        (warnings if any(m in f for m in WARNING_MARKERS) else errors).append(f)
    return errors, warnings


def _domain_artifacts(name: str, tmp: str):
    """(features_csv, constraints_csv, sampler) for one committed spec
    domain — reference artifacts when present, synthetic otherwise."""
    from moeva2_ijcai22_replication_tpu.domains import spec_domain_dir
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_botnet,
        synth_botnet_schema,
        synth_lcld,
        synth_lcld_schema,
        synth_phishing,
    )

    if name == "lcld_spec":
        paths = synth_lcld_schema(os.path.join(tmp, "lcld"))
        return paths["features"], paths["constraints"], synth_lcld
    if name == "botnet_spec":
        ref = os.path.join(REFERENCE, "data", "botnet")
        if os.path.exists(os.path.join(ref, "feat_idx.pickle")):
            return (
                os.path.join(ref, "features.csv"),
                os.path.join(ref, "constraints.csv"),
                synth_botnet,
            )
        paths = synth_botnet_schema(os.path.join(tmp, "botnet"))
        return paths["features"], paths["constraints"], synth_botnet
    if name == "phishing":
        d = spec_domain_dir("phishing")
        return (
            os.path.join(d, "features.csv"),
            os.path.join(d, "constraints.csv"),
            synth_phishing,
        )
    raise KeyError(name)


def lint_spec_domain(name: str, tmp: str) -> dict:
    """All checks for one committed spec domain; returns
    ``{errors, warnings, checks}``."""
    from moeva2_ijcai22_replication_tpu.core.codec import full_ohe_tables
    from moeva2_ijcai22_replication_tpu.core.schema import FeatureSchema
    from moeva2_ijcai22_replication_tpu.domains import (
        SPEC_DIR,
        SPEC_DOMAINS,
        get_constraints_class,
    )
    from moeva2_ijcai22_replication_tpu.domains.ir import (
        load_spec,
        make_spec_sat_builder,
        validate_spec,
    )

    errors: list[str] = []
    warnings: list[str] = []
    checks: list[str] = []
    spec_path = os.path.join(SPEC_DIR, SPEC_DOMAINS[name])
    features_csv, constraints_csv, sampler = _domain_artifacts(name, tmp)

    # 1. parse + static validation against the schema
    spec = load_spec(spec_path, name=name)
    schema = FeatureSchema.from_csv(features_csv)
    errs, warns = _split_findings(validate_spec(spec, schema))
    errors += [f"validate: {e}" for e in errs]
    warnings += [f"validate: {w}" for w in warns]
    checks.append("validate_spec")

    # 2. OHE group coverage must build for the repair finale
    try:
        full_ohe_tables(schema)
        checks.append("ohe_tables")
    except Exception as e:
        errors.append(f"ohe_tables: {type(e).__name__}: {e}")

    # 3. jnp backend compiles + numpy-twin agreement on seeded samples
    try:
        cons = get_constraints_class(name)(features_csv, constraints_csv)
        x = sampler(32, cons.schema, seed=5)
        rng = np.random.default_rng(6)
        x_pert = x * (1.0 + 0.05 * rng.standard_normal(x.shape))
        for label, xx in (("manifold", x), ("perturbed", x_pert)):
            got = np.asarray(cons._raw(np.asarray(xx)))
            want = cons.raw_numpy(xx)
            delta = float(np.nanmax(np.abs(got - want)))
            if not (delta < 1e-8 or np.isnan(delta)):
                errors.append(
                    f"np_twin[{label}]: jnp kernel vs numpy oracle "
                    f"max|Δ|={delta:.3e}"
                )
        checks.append("np_twin")
    except Exception as e:
        errors.append(f"jnp_backend: {type(e).__name__}: {e}")
        return {"errors": errors, "warnings": warnings, "checks": checks}

    # 4. hand-written equivalence fixtures (bit-exact) for the twins
    twin = {"lcld_spec": "lcld", "botnet_spec": "botnet"}.get(name)
    if twin is not None:
        hand = get_constraints_class(twin)(features_csv, constraints_csv)
        for label, xx in (("manifold", x), ("perturbed", x_pert)):
            a = np.asarray(cons._raw(np.asarray(xx)))
            b = np.asarray(hand._raw(np.asarray(xx)))
            exact = bool(
                np.array_equal(a, b) or np.array_equal(
                    np.nan_to_num(a, nan=-1.0), np.nan_to_num(b, nan=-1.0)
                )
            )
            if not exact:
                errors.append(
                    f"equivalence[{label}]: compiled {name} != "
                    f"hand-written {twin} (max|Δ|="
                    f"{float(np.nanmax(np.abs(a - b))):.3e})"
                )
        checks.append(f"equivalence_vs_{twin}")

    # 5. MILP backend compiles and builds rows at a sampled hot start
    try:
        builder = make_spec_sat_builder(cons)
        out = builder(np.asarray(x[0], float), np.asarray(x[0], float))
        n_rows = len(out.rows) + len(out.fixes)
        if out.feasible and n_rows == 0:
            errors.append("milp: builder returned feasible but EMPTY rows")
        checks.append("milp_build")
    except Exception as e:
        errors.append(f"milp: {type(e).__name__}: {e}")

    return {"errors": errors, "warnings": warnings, "checks": checks}


def lint_generated_family(seed: int = 0) -> dict:
    """family<seed> compiles; seeded sampling is byte-deterministic."""
    from moeva2_ijcai22_replication_tpu.domains import (
        domain_origin,
        get_constraints_class,
    )
    from moeva2_ijcai22_replication_tpu.domains.ir import sample_family

    errors: list[str] = []
    name = f"family{seed}"
    cls = get_constraints_class(name)
    origin = domain_origin(name)
    if origin["origin"] != "generated" or not origin["spec_hash"]:
        errors.append(f"{name}: origin record {origin} is not a generated spec")
    xa, _, spec_a = sample_family(16, seed=seed)
    xb, _, spec_b = sample_family(16, seed=seed)
    if not np.array_equal(xa, xb):
        errors.append(f"{name}: seeded sampler is not deterministic")
    from moeva2_ijcai22_replication_tpu.domains.ir import spec_hash

    if spec_hash(spec_a) != spec_hash(spec_b):
        errors.append(f"{name}: seeded generator spec hash is not stable")
    del cls
    return {"errors": errors, "warnings": [], "checks": ["generated_family"]}


def run_lint() -> tuple[dict, int]:
    from moeva2_ijcai22_replication_tpu.domains import SPEC_DOMAINS

    result: dict = {"domains": {}, "ok": True}
    rc = 0
    with tempfile.TemporaryDirectory(prefix="domain_lint_") as tmp:
        for name in sorted(SPEC_DOMAINS):
            res = lint_spec_domain(name, tmp)
            result["domains"][name] = res
            status = "FAILED" if res["errors"] else "ok"
            print(
                f"domain_lint: {name}: {status} "
                f"({len(res['checks'])} checks, "
                f"{len(res['warnings'])} warning(s))"
            )
            for w in res["warnings"]:
                print(f"  warning [{name}] {w}")
            for e in res["errors"]:
                print(f"  ERROR [{name}] {e}")
            if res["errors"]:
                rc = 1
    fam = lint_generated_family(0)
    result["domains"]["family0"] = fam
    print(f"domain_lint: family0: {'FAILED' if fam['errors'] else 'ok'}")
    for e in fam["errors"]:
        print(f"  ERROR [family0] {e}")
    if fam["errors"]:
        rc = 1
    result["ok"] = rc == 0
    print(
        "domain_lint: "
        + ("ok — every committed spec parses, matches its twin, and "
           "linearizes" if rc == 0 else "FAILED")
    )
    return result, rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--check", action="store_true",
        help="lint the committed spec domains (tier-1 repo-check mode)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable last line"
    )
    args = parser.parse_args(argv)
    if not args.check:
        parser.error("pass --check")
    result, rc = run_lint()
    if args.json:
        print(json.dumps(result))
    return rc


if __name__ == "__main__":
    sys.exit(main())
