"""Run an attack-service fleet: N serve.py replicas behind the capacity router.

Quickstart (after ``python tools/bootstrap_lcld.py`` for the LCLD domain)::

    python tools/fleet.py -c config/serving.yaml --replicas 2
    python tools/loadgen.py --url http://127.0.0.1:8700 --domain lcld \
        --requests 64 --concurrency 8

Then::

    curl -s localhost:8700/healthz        # fleet view + per-replica blocks
    curl -s localhost:8700/metrics        # merged SLO + per-replica metrics
    curl -s 'localhost:8700/metrics?format=prom'

Replicas are spawned with ``--port 0 --replica-id rNN`` over ONE shared
config — and thereby one shared AOT/artifact cache directory, so replica
#N boots as warm as #1. The router admits each replica only after its
first healthy /healthz poll with a matching build fingerprint, forwards
/attack to the replica with the most predicted headroom, and fails over
rejected/failed forwards within a bounded retry budget. SIGINT drains
every replica (in-flight requests complete) before exit.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "-c", default="config/serving.yaml", help="serving config yaml"
    )
    parser.add_argument("--host", default="127.0.0.1", help="router bind host")
    parser.add_argument(
        "--port", type=int, default=None, help="router port (default fleet.port)"
    )
    parser.add_argument(
        "--replicas", type=int, default=None, help="override fleet.replicas"
    )
    parser.add_argument(
        "--no-prewarm",
        action="store_true",
        help="spawn replicas without --prewarm (first requests pay "
        "compiles/AOT loads)",
    )
    parser.add_argument("-v", "--verbose", action="store_true", help="access log")
    args = parser.parse_args(argv)

    from moeva2_ijcai22_replication_tpu.serving.fleet import (
        ReplicaManager,
        Router,
        serve_router,
    )
    from moeva2_ijcai22_replication_tpu.utils.config import load_config_file

    cfg = load_config_file(args.c)
    fleet_cfg = cfg.get("fleet", {}) or {}
    n = args.replicas if args.replicas is not None else fleet_cfg.get("replicas", 2)
    port = args.port if args.port is not None else fleet_cfg.get("port", 8700)

    manager = ReplicaManager(
        args.c,
        prewarm=not args.no_prewarm,
        log_dir=fleet_cfg.get("log_dir"),
        boot_timeout_s=fleet_cfg.get("boot_timeout_s", 600.0),
        autoscale=fleet_cfg.get("autoscale"),
    )
    # router-level incident attribution (balance_drop off the served
    # counters, ticked at /healthz) — same serving.incident_detection
    # switch the replicas honour, so one knob silences the whole fleet
    incidents = None
    if cfg.get("serving", {}).get("incident_detection", True):
        from moeva2_ijcai22_replication_tpu.observability.incidents import (
            IncidentDetector,
        )

        incidents = IncidentDetector()
    router = Router(
        manager,
        retry_budget=fleet_cfg.get("retry_budget", 2),
        stale_after_s=fleet_cfg.get("stale_after_s", 10.0),
        capacity_age_max_s=fleet_cfg.get("capacity_age_max_s", 30.0),
        request_timeout_s=cfg.get("serving", {}).get("request_timeout_s", 60.0)
        + 30.0,
        incidents=incidents,
    )
    try:
        for _ in range(int(n)):
            handle = manager.add()
            print(
                f"fleet: admitted {handle.replica_id} at {handle.url} "
                f"(pid {getattr(handle.proc, 'pid', None)})",
                flush=True,
            )
    except Exception:
        manager.close()
        raise

    # background poll + policy loop: keeps the routing signal fresh and
    # drives the autoscaling-shaped hooks (observe-mode by default)
    poll_interval = float(fleet_cfg.get("poll_interval_s", 2.0))
    stop = threading.Event()

    def poll_loop():
        while not stop.wait(poll_interval):
            manager.poll()
            manager.policy_tick()

    threading.Thread(target=poll_loop, daemon=True).start()

    httpd = serve_router(router, args.host, port, verbose=args.verbose)
    bound = httpd.server_address
    print(
        f"fleet router on http://{bound[0]}:{bound[1]} "
        f"({n} replicas; retry budget {router.retry_budget})",
        flush=True,
    )
    # supervisors (systemd, k8s) stop services with SIGTERM; without a
    # handler the default action kills this process before the drain
    # below runs and the replica children are orphaned
    def _on_sigterm(_signum, _frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        httpd.shutdown()
        print("fleet: draining replicas...", flush=True)
        for handle in manager.routable():
            report = manager.drain(handle.replica_id)
            print(
                f"fleet: drained {report['replica_id']} "
                f"(clean={report['drained_clean']}, {report['drain_s']}s)",
                flush=True,
            )
        # fleet.trace_merge: after the drain (sinks complete), merge the
        # per-replica JSONL sinks into ONE Perfetto doc aligned via each
        # replica's last polled clock offset. `true` places the doc next
        # to the sinks; a string is the output path.
        merge_out = fleet_cfg.get("trace_merge")
        trace_log = cfg.get("serving", {}).get("trace_log") or cfg.get(
            "system", {}
        ).get("trace_log")
        if merge_out and trace_log:
            from moeva2_ijcai22_replication_tpu.observability.fleetrace import (
                merge_fleet_traces,
                replica_sink_path,
            )

            out = (
                merge_out
                if isinstance(merge_out, str)
                else os.path.join(
                    os.path.dirname(trace_log) or ".", "fleet_trace.json"
                )
            )
            handles = manager.replicas()
            doc = merge_fleet_traces(
                {
                    h.replica_id: replica_sink_path(trace_log, h.replica_id)
                    for h in handles
                },
                offsets={
                    h.replica_id: h.clock_offset_s or 0.0 for h in handles
                },
                out_path=out,
            )
            rep = doc["otherData"]["fleet_merge"]
            print(
                f"fleet: merged {len(rep['replicas'])} trace sinks -> "
                f"{out} (skipped: {sorted(rep['skipped'])})",
                flush=True,
            )
        manager.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())


# convenience: wait for a replica set to go healthy from a script
def wait_healthy(url: str, timeout_s: float = 60.0) -> dict:
    """Poll a router /healthz until ok (tiny helper for scripts/tests)."""
    import json
    import urllib.request

    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url + "/healthz", timeout=5) as r:
                last = json.loads(r.read())
            if last.get("ok"):
                return last
        except Exception:  # noqa: BLE001 — still booting
            pass
        time.sleep(0.25)
    raise TimeoutError(f"router at {url} not healthy within {timeout_s}s: {last}")
