"""HTTP load generator for the attack service (tools/serve.py).

Open-loop offered load against ``POST /attack``: N requests with
mixed-size constraint-valid synthetic rows, paced at ``--rps`` (0 = as
fast as the concurrency allows), issued from a thread pool. Prints one
JSON summary line: achieved throughput, latency quantiles (with their
sample count, ``quantiles_n`` — a p99 over a handful of requests is the
max, not a tail), and the status breakdown (ok / rejected-429 /
timeout-504 / error) — the client-side mirror of the server's
``/metrics`` record.

Arrival process (``--arrival``): ``uniform`` submits at exact 1/rps
intervals — a metronome no real traffic resembles, which never stacks
arrivals and so under-measures queueing near saturation; ``poisson``
draws exponential inter-arrival gaps at the same mean rate (seeded,
reproducible), the memoryless arrivals real independent callers
produce, whose natural bursts exercise the queue exactly where SLOs
break. Both are OPEN-loop: submission times are fixed up front and
never wait for completions, and paced runs (``--rps > 0``) measure
every latency sample from the request's SCHEDULED arrival — including
any wait for a free worker thread when all ``--concurrency`` slots are
busy — so a slow server inflates measured latency, not the offered
load (the coordinated-omission trap closed-loop generators fall into).
Unpaced runs (``--rps 0``) have no arrival schedule and measure from
send: a throughput probe, not a latency-at-offered-load measurement.
Use ``poisson`` with an explicit ``--rps`` for saturation/knee
measurements, with ``--concurrency`` high enough that in-flight
requests rarely saturate it.

QoS traffic mix (``--mix``): ``--mix interactive=0.2,batch=0.7,\
scavenger=0.1`` draws each request's priority class from the given
weights (seeded — the same ``--seed`` reproduces the same per-request
class sequence), sends it as the payload's ``"priority"`` field, and
splits the report per class (``by_class``: status breakdown + latency
quantiles), so a saturation run shows directly which class absorbed the
shed and which held its SLO.

    python tools/loadgen.py --url http://127.0.0.1:8787 --domain lcld \
        --requests 64 --concurrency 8 --rows-min 1 --rows-max 13 \
        --rps 50 --arrival poisson \
        --mix interactive=0.2,batch=0.7,scavenger=0.1
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moeva2_ijcai22_replication_tpu.utils.observability import (  # noqa: E402
    arrival_offsets,  # canonical: the in-process sweep paces with the
    percentile,  # same disciplines, so HTTP and in-process knees compare
)


def make_rows(domain_cfg: dict, n_rows: int, seed: int):
    """Constraint-valid candidate rows for the domain: synthesized for LCLD
    (no redistributable candidate set), sampled from the committed candidate
    set otherwise (e.g. the 387-row botnet set)."""
    project = domain_cfg["project_name"]
    if project.startswith("lcld"):
        from moeva2_ijcai22_replication_tpu.domains import get_constraints_class
        from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld

        cons = get_constraints_class(project)(
            domain_cfg["paths"]["features"], domain_cfg["paths"]["constraints"]
        )
        return synth_lcld(n_rows, cons.schema, seed=seed).tolist()
    import numpy as np

    path = domain_cfg["paths"].get(
        "x_candidates", "/root/reference/data/botnet/x_candidates_common.npy"
    )
    x = np.load(path)
    idx = np.random.default_rng(seed).integers(0, x.shape[0], size=n_rows)
    return x[idx].tolist()


def post_attack(url: str, payload: dict, timeout: float, t0: float | None = None):
    """POST one attack; latency is measured from ``t0`` when given — the
    request's SCHEDULED arrival time, so time spent waiting for a free
    worker thread (all ``--concurrency`` slots busy) is charged to the
    request like any other queueing. Measuring from the moment the worker
    picks the task up would hide exactly the wait coordinated omission is
    about."""
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{url}/attack", data=body, headers={"Content-Type": "application/json"}
    )
    if t0 is None:
        t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            json.loads(resp.read())
            return "ok", time.monotonic() - t0
    except urllib.error.HTTPError as e:
        code = e.code
        e.read()
        status = {429: "rejected", 504: "timeout"}.get(code, f"http_{code}")
        return status, time.monotonic() - t0
    except Exception as e:  # noqa: BLE001 — loadgen counts, not raises
        return f"error:{type(e).__name__}", time.monotonic() - t0


def parse_mix(spec: str | None) -> list[tuple[str, float]] | None:
    """``interactive=0.2,batch=0.7,scavenger=0.1`` -> [(name, weight)].
    Weights need not sum to 1 (they are normalized at draw time); zero
    and negative weights are rejected rather than silently dropped."""
    if not spec:
        return None
    mix = []
    for part in spec.split(","):
        name, _, w = part.partition("=")
        name = name.strip()
        if not name or not w:
            raise ValueError(f"bad --mix entry {part!r} (want name=weight)")
        weight = float(w)
        if weight <= 0:
            raise ValueError(f"--mix weight for {name!r} must be > 0")
        mix.append((name, weight))
    return mix


def run(args) -> dict:
    import random

    from moeva2_ijcai22_replication_tpu.utils.config import load_config_file

    domain_cfg = load_config_file(args.config)["domains"][args.domain]
    sizes = [
        args.rows_min + i % (args.rows_max - args.rows_min + 1)
        for i in range(args.requests)
    ]
    rows_cache = {
        n: make_rows(domain_cfg, n, seed=1000 + n) for n in sorted(set(sizes))
    }

    paced = args.rps > 0
    if args.arrival == "poisson" and not paced:
        print(
            "loadgen: --arrival poisson needs --rps > 0; unpaced run "
            "submits everything at once",
            file=sys.stderr,
        )
    offsets = arrival_offsets(args.arrival, args.rps, args.requests, args.seed)
    # per-request priority classes: one seeded draw per request (distinct
    # stream from the arrival process so adding --mix never perturbs the
    # arrival schedule of an otherwise-identical run)
    mix = parse_mix(args.mix)
    if mix:
        rng = random.Random(args.seed * 7919 + 13)
        classes = rng.choices(
            [name for name, _ in mix],
            weights=[w for _, w in mix],
            k=args.requests,
        )
    else:
        classes = [None] * args.requests
    t_start = time.monotonic()

    def one(i: int):
        payload = {
            "domain": args.domain,
            "rows": rows_cache[sizes[i]],
            "eps": args.eps,
            "budget": args.budget,
            "loss_evaluation": args.loss_evaluation,
            "request_id": f"loadgen-{i}",
        }
        if classes[i] is not None:
            payload["priority"] = classes[i]
        # PACED runs charge latency from the SCHEDULED arrival, not when a
        # worker thread frees up: executor-queue wait is queueing the
        # client observed, and excluding it would reintroduce coordinated
        # omission through the thread pool. Unpaced (--rps 0) has no
        # schedule — every offset is 0, and charging from t_start would
        # report the run's makespan as every request's latency — so it
        # measures from send: a throughput probe, not an offered-load
        # latency measurement.
        return post_attack(
            args.url, payload, args.timeout,
            t0=t_start + offsets[i] if paced else None,
        )
    results = []
    with concurrent.futures.ThreadPoolExecutor(args.concurrency) as pool:
        futs = []
        for i in range(args.requests):
            delay = t_start + offsets[i] - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            futs.append(pool.submit(one, i))
        results = [f.result() for f in futs]
    duration = max(time.monotonic() - t_start, 1e-9)

    statuses: dict[str, int] = {}
    for status, _ in results:
        statuses[status] = statuses.get(status, 0) + 1
    ok_lat = sorted(dt for status, dt in results if status == "ok")
    # per-class report (only with --mix): the client-side evidence of
    # who got served and who got shed at this offered load
    by_class: dict[str, dict] = {}
    if mix:
        for (status, dt), klass in zip(results, classes):
            c = by_class.setdefault(
                klass, {"requests": 0, "statuses": {}, "_lat": []}
            )
            c["requests"] += 1
            c["statuses"][status] = c["statuses"].get(status, 0) + 1
            if status == "ok":
                c["_lat"].append(dt)
        for c in by_class.values():
            lat = sorted(c.pop("_lat"))
            c["p50_ms"] = (
                round(percentile(lat, 0.50) * 1e3, 2) if lat else None
            )
            c["p99_ms"] = (
                round(percentile(lat, 0.99) * 1e3, 2) if lat else None
            )
            c["quantiles_n"] = len(lat)
    return {
        "url": args.url,
        "domain": args.domain,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "offered_rps": args.rps,
        "arrival": args.arrival,
        "duration_s": round(duration, 3),
        "throughput_rps": round(len(ok_lat) / duration, 2),
        "p50_ms": round(percentile(ok_lat, 0.50) * 1e3, 2) if ok_lat else None,
        "p99_ms": round(percentile(ok_lat, 0.99) * 1e3, 2) if ok_lat else None,
        "quantiles_n": len(ok_lat),
        "statuses": statuses,
        **({"mix": dict(mix), "by_class": dict(sorted(by_class.items()))}
           if mix else {}),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--url", default="http://127.0.0.1:8787")
    parser.add_argument("--config", default="config/serving.yaml",
                        help="serving config (for domain artifact paths)")
    parser.add_argument("--domain", default="lcld")
    parser.add_argument("--requests", type=int, default=64)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--rps", type=float, default=0.0,
                        help="offered request rate; 0 = unpaced")
    parser.add_argument("--arrival", choices=("uniform", "poisson"),
                        default="uniform",
                        help="arrival process at --rps: 'uniform' = exact "
                        "1/rps spacing (a metronome; never stacks arrivals, "
                        "flatters the queue near saturation); 'poisson' = "
                        "seeded exponential inter-arrival gaps at the same "
                        "mean rate (real independent-caller bursts — use "
                        "for saturation/knee measurement). Both are "
                        "open-loop: submission never waits on completions, "
                        "and latency is measured from each request's "
                        "scheduled arrival — including any wait for a free "
                        "worker slot — so queueing is never hidden "
                        "(no coordinated omission)")
    parser.add_argument("--seed", type=int, default=42,
                        help="RNG seed for --arrival poisson and --mix")
    parser.add_argument("--mix", default=None,
                        help="QoS traffic mix, e.g. "
                        "'interactive=0.2,batch=0.7,scavenger=0.1': draw "
                        "each request's priority class from these weights "
                        "(seeded per-request sequence), send it as the "
                        "payload 'priority', and report per-class "
                        "latency/shed under 'by_class'")
    parser.add_argument("--rows-min", type=int, default=1)
    parser.add_argument("--rows-max", type=int, default=13)
    parser.add_argument("--eps", type=float, default=0.2)
    parser.add_argument("--budget", type=int, default=10)
    parser.add_argument("--loss-evaluation", default="flip")
    parser.add_argument("--timeout", type=float, default=120.0)
    args = parser.parse_args(argv)
    print(json.dumps(run(args)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
