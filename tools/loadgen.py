"""HTTP load generator for the attack service (tools/serve.py).

Open-loop offered load against ``POST /attack``: N requests with
mixed-size constraint-valid synthetic rows, paced at ``--rps`` (0 = as
fast as the concurrency allows), issued from a thread pool. Prints one
JSON summary line: achieved throughput, latency quantiles, and the
status breakdown (ok / rejected-429 / timeout-504 / error) — the
client-side mirror of the server's ``/metrics`` record.

    python tools/loadgen.py --url http://127.0.0.1:8787 --domain lcld \
        --requests 64 --concurrency 8 --rows-min 1 --rows-max 13
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moeva2_ijcai22_replication_tpu.utils.observability import percentile  # noqa: E402


def make_rows(domain_cfg: dict, n_rows: int, seed: int):
    """Constraint-valid candidate rows for the domain: synthesized for LCLD
    (no redistributable candidate set), sampled from the committed candidate
    set otherwise (e.g. the 387-row botnet set)."""
    project = domain_cfg["project_name"]
    if project.startswith("lcld"):
        from moeva2_ijcai22_replication_tpu.domains import get_constraints_class
        from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld

        cons = get_constraints_class(project)(
            domain_cfg["paths"]["features"], domain_cfg["paths"]["constraints"]
        )
        return synth_lcld(n_rows, cons.schema, seed=seed).tolist()
    import numpy as np

    path = domain_cfg["paths"].get(
        "x_candidates", "/root/reference/data/botnet/x_candidates_common.npy"
    )
    x = np.load(path)
    idx = np.random.default_rng(seed).integers(0, x.shape[0], size=n_rows)
    return x[idx].tolist()


def post_attack(url: str, payload: dict, timeout: float):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{url}/attack", data=body, headers={"Content-Type": "application/json"}
    )
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            json.loads(resp.read())
            return "ok", time.monotonic() - t0
    except urllib.error.HTTPError as e:
        code = e.code
        e.read()
        status = {429: "rejected", 504: "timeout"}.get(code, f"http_{code}")
        return status, time.monotonic() - t0
    except Exception as e:  # noqa: BLE001 — loadgen counts, not raises
        return f"error:{type(e).__name__}", time.monotonic() - t0


def run(args) -> dict:
    from moeva2_ijcai22_replication_tpu.utils.config import load_config_file

    domain_cfg = load_config_file(args.config)["domains"][args.domain]
    sizes = [
        args.rows_min + i % (args.rows_max - args.rows_min + 1)
        for i in range(args.requests)
    ]
    rows_cache = {
        n: make_rows(domain_cfg, n, seed=1000 + n) for n in sorted(set(sizes))
    }

    def one(i: int):
        payload = {
            "domain": args.domain,
            "rows": rows_cache[sizes[i]],
            "eps": args.eps,
            "budget": args.budget,
            "loss_evaluation": args.loss_evaluation,
            "request_id": f"loadgen-{i}",
        }
        return post_attack(args.url, payload, args.timeout)

    period = 1.0 / args.rps if args.rps > 0 else 0.0
    t_start = time.monotonic()
    results = []
    with concurrent.futures.ThreadPoolExecutor(args.concurrency) as pool:
        futs = []
        for i in range(args.requests):
            target = t_start + i * period
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            futs.append(pool.submit(one, i))
        results = [f.result() for f in futs]
    duration = max(time.monotonic() - t_start, 1e-9)

    statuses: dict[str, int] = {}
    for status, _ in results:
        statuses[status] = statuses.get(status, 0) + 1
    ok_lat = sorted(dt for status, dt in results if status == "ok")
    return {
        "url": args.url,
        "domain": args.domain,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "offered_rps": args.rps,
        "duration_s": round(duration, 3),
        "throughput_rps": round(len(ok_lat) / duration, 2),
        "p50_ms": round(percentile(ok_lat, 0.50) * 1e3, 2) if ok_lat else None,
        "p99_ms": round(percentile(ok_lat, 0.99) * 1e3, 2) if ok_lat else None,
        "statuses": statuses,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--url", default="http://127.0.0.1:8787")
    parser.add_argument("--config", default="config/serving.yaml",
                        help="serving config (for domain artifact paths)")
    parser.add_argument("--domain", default="lcld")
    parser.add_argument("--requests", type=int, default=64)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--rps", type=float, default=0.0,
                        help="offered request rate; 0 = unpaced")
    parser.add_argument("--rows-min", type=int, default=1)
    parser.add_argument("--rows-max", type=int, default=13)
    parser.add_argument("--eps", type=float, default=0.2)
    parser.add_argument("--budget", type=int, default=10)
    parser.add_argument("--loss-evaluation", default="flip")
    parser.add_argument("--timeout", type=float, default=120.0)
    args = parser.parse_args(argv)
    print(json.dumps(run(args)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
