"""Generate RESULTS.md from the runner metrics under out/attacks/.

The metrics JSONs (plus config snapshots) are the committed evidence trail;
this renders them into one reviewable table per experiment family so the
round's numbers are readable without parsing JSON. Run after the grids:

    python tools/make_results.py > RESULTS.md
"""

import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
O_KEYS = ["o1", "o2", "o3", "o4", "o5", "o6", "o7"]


def fmt(v):
    return f"{v:.3f}" if isinstance(v, float) else str(v)


def model_scenario(cfg):
    """Disambiguating ``scenario/model`` cell: rq3 rows attack retrained
    models and rq4 rows attack augmented scenarios under the same attack
    name, so the project name and model artifact travel with every row."""
    project = cfg.get("project_name", "?")
    model = os.path.basename(cfg.get("paths", {}).get("model", "?"))
    for ext in (".model", ".msgpack"):
        if model.endswith(ext):
            model = model[: -len(ext)]
    return f"{project}/{model}"


def compile_flag(d):
    """Whether the reference-schema ``time`` includes trace+compile:
    ``y``/``n`` from the explicit flag, falling back to the timings split
    for files written before the flag existed; ``?`` when neither exists
    (pre-round-6 metrics with no compile/run attribution)."""
    if "includes_compile" in d:
        return "y" if d["includes_compile"] else "n"
    timings = d.get("timings", {})
    if "attack_compile" in timings:
        return "y"
    if "attack_run" in timings:
        return "n"
    return "?"


def compile_seconds(d):
    """Compile wall-clock attributable to this run: the run-scoped cost
    ledger total when the metrics carry one (post-PR-5 runs; 0 on a warm
    engine), else the ``attack_compile`` span (PR-1+ runs), else ``—``
    (warm executable or pre-attribution metrics)."""
    ledger = (d.get("telemetry") or {}).get("cost") or {}
    v = ledger.get("compile_s_total")
    if not isinstance(v, (int, float)) or v == 0:
        v = d.get("timings", {}).get("attack_compile", v)
    return f"{v:.1f}" if isinstance(v, (int, float)) else "—"


def overlap_ratio(d):
    """Device overlap ratio of this run (``telemetry.gaps``: device-busy
    seconds over compile-free wall, 1.0 = the host never left the device
    idle); ``—`` for runs that predate the dispatch-gap ledger or whose
    capture was off."""
    gaps = (d.get("telemetry") or {}).get("gaps") or {}
    v = gaps.get("overlap_ratio")
    return f"{v:.2f}" if isinstance(v, (int, float)) else "—"


def cold_ratio(d):
    """This run's cold multiplier: compile-inclusive wall over run-only
    wall (``time / (time - compile_s)``) — the per-run proxy for the
    cold/steady ratio the bench record gates; ``—`` on warm runs (no
    compile seconds) and pre-attribution metrics."""
    ledger = (d.get("telemetry") or {}).get("cost") or {}
    compile_s = ledger.get("compile_s_total")
    if not isinstance(compile_s, (int, float)) or compile_s <= 0:
        compile_s = d.get("timings", {}).get("attack_compile")
        if isinstance(compile_s, (int, float)):
            # the attack_compile span is the whole cold attack wall, not
            # the compile alone — no honest ratio derivable from it
            return "—"
    t = d.get("time")
    if (
        not isinstance(compile_s, (int, float))
        or not isinstance(t, (int, float))
        or t <= compile_s
    ):
        return "—"
    return f"{t / (t - compile_s):.2f}x"


def aot_sources(d):
    """Where this run's executables came from: ``k/n`` = k of the run's
    n ledgered executables were deserialized from the persistent AOT
    cache (entry ``source: "aot"`` — trace, lower, and compile all
    skipped); ``0/n`` = the run compiled everything (cold, or the AOT
    tier was off — pre-round-10 metrics and disabled-cache runs read the
    same, honestly); ``—`` when the run has no ledgered executables
    (warm engine, zero compiles this run). A record whose entries list
    was bounded (bench's ``bound_record`` adds ``entries_omitted``)
    counts the omitted rows in the denominator and marks the numerator
    as a lower bound (``k+/n``) — the capped list cannot say where the
    dropped executables came from."""
    cost = (d.get("telemetry") or {}).get("cost") or {}
    entries = cost.get("entries")
    if not isinstance(entries, list) or not entries:
        return "—"
    n_aot = sum(1 for e in entries if e.get("source") == "aot")
    omitted = cost.get("entries_omitted") or 0
    return f"{n_aot}{'+' if omitted else ''}/{len(entries) + omitted}"


def interior_rate(d, budget):
    """Engine-judged interior o2/o7 at ``budget`` generation steps from the
    metrics' ``telemetry.quality.interior`` block (post-PR-6 runs with
    ``quality_every`` set); ``—`` when the run recorded no sample there.
    The interior points are the saturation-proof evidence: a survival
    regression moves them while the full-budget o-columns stay all-ones."""
    interior = ((d.get("telemetry") or {}).get("quality") or {}).get(
        "interior"
    ) or {}
    sample = interior.get(str(budget))
    rates = (sample or {}).get("o_rates")
    if not isinstance(rates, list) or len(rates) < 7:
        return "—"
    return f"{rates[1]:.3f}/{rates[6]:.3f}"


def rows_for(path):
    out = []
    for f in sorted(glob.glob(os.path.join(path, "metrics_*.json"))):
        d = json.load(open(f))
        cfg = d.get("config", {})
        base = {
            "model": model_scenario(cfg),
            "budget": cfg.get("budget"),
            "time_s": round(d.get("time", float("nan")), 1),
            "compile": compile_flag(d),
            "compile_s": compile_seconds(d),
            "int100": interior_rate(d, 100),
            "int300": interior_rate(d, 300),
            "overlap": overlap_ratio(d),
            "coldx": cold_ratio(d),
            "aot": aot_sources(d),
            "file": os.path.relpath(f, ROOT),
        }
        if "objectives_list" in d:  # moeva: one row per eps
            for eps, o in zip(cfg.get("eps_list", ["?"]), d["objectives_list"]):
                out.append(
                    dict(
                        base,
                        attack="moeva",
                        eps=eps,
                        o=[o.get(k) for k in O_KEYS],
                    )
                )
        elif "objectives" in d:  # pgd/sat: single eps
            o = d["objectives"]
            out.append(
                dict(
                    base,
                    attack=f"pgd:{cfg.get('loss_evaluation')}",
                    eps=cfg.get("eps"),
                    o=[o.get(k) for k in O_KEYS],
                )
            )
    return out


def main():
    print("# RESULTS — runner metrics snapshot")
    print()
    print("Generated by `tools/make_results.py` from `out/attacks/**/metrics_*.json`")
    print("(committed alongside their `config_*.yaml` snapshots; success metrics are")
    print("the reference's o1..o7, f64-judged). All runs use the corrected")
    print("(pymoo-oracle-validated) survival kernel and the production configs.")
    print()
    print("The `scenario/model` column names the attacked model artifact (rq3 rows")
    print("attack retrained defenses, rq4 rows attack augmented scenarios). The")
    print("`cmp` column says whether the `time` column includes trace + XLA compile")
    print("(`y`), excludes it (`n`, warm executable), or predates the attribution")
    print("split (`?`). `compile (s)` is the compile wall-clock itself — the cost")
    print("ledger's total where recorded, else the `attack_compile` span; `—` for")
    print("warm runs and pre-attribution metrics. `o@100` / `o@300` are the")
    print("ENGINE-judged interior success rates (o2/o7) sampled mid-run at those")
    print("generation budgets (`telemetry.quality`, runs with `quality_every`")
    print("set) — the saturation-proof convergence evidence; `—` for runs that")
    print("recorded no interior sample (strict runs and pre-round-6 metrics).")
    print("`overlap` is the device overlap ratio (`telemetry.gaps`: device-busy")
    print("seconds over compile-free wall; 1.0 = the host never left the device")
    print("idle) and `cold×` the run's cold multiplier (compile-inclusive wall")
    print("over run-only wall, from the cost ledger's compile seconds); `—` for")
    print("warm runs and metrics predating the dispatch-gap ledger (pre-round-9).")
    print("`aot` is the run's cold-source split: k/n of its ledgered executables")
    print("were deserialized from the persistent AOT cache (`telemetry.cost`")
    print("entries with `source: \"aot\"` — trace+lower+compile all skipped);")
    print("0/n runs compiled everything (AOT tier off or a truly cold cache,")
    print("including all pre-round-10 metrics); `—` = zero compiles this run.")
    print()
    print("Grid points ABSENT from a table failed the evaluator's scaled-range")
    print("assert (`objective_calculator.py:72-76` parity: candidates outside the")
    print("min-max scaler envelope invalidate the distance metric): the botnet")
    print("sm1.2 `adaptive_eps_step` variants at ε=0.5 (6 points) push candidates")
    print("past the committed scaler's data range. The reference fails these runs")
    print("identically (its per-point process isolation logs and continues, as")
    print("does `experiments/rq.py`).")
    dirs = sorted(
        {os.path.dirname(f) for f in glob.glob("out/attacks/*/*/metrics_*.json")}
    )
    for d in dirs:
        rows = rows_for(d)
        if not rows:
            continue
        print(f"\n## {os.path.relpath(d, ROOT)}\n")
        print(
            "| attack | scenario/model | budget | ε "
            "| o1 | o2 | o3 | o4 | o5 | o6 | o7 | time (s) | cmp "
            "| compile (s) | o@100 | o@300 | overlap | cold× | aot |"
        )
        print(
            "|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---"
            "|---|---|---|---|"
        )
        for r in sorted(
            rows, key=lambda r: (r["attack"], r["model"], r["budget"] or 0, str(r["eps"]))
        ):
            cells = " | ".join(fmt(v) if v is not None else "—" for v in r["o"])
            print(
                f"| {r['attack']} | {r['model']} | {r['budget']} | {r['eps']} "
                f"| {cells} | {r['time_s']} | {r['compile']} | {r['compile_s']} "
                f"| {r['int100']} | {r['int300']} | {r['overlap']} | {r['coldx']} "
                f"| {r['aot']} |"
            )
    print()


if __name__ == "__main__":
    os.chdir(ROOT)
    sys.exit(main())
