#!/usr/bin/env python
"""Oracle-GA parity harness: budget-100 interior rates vs pymoo R-NSGA-III.

ROADMAP item 5 / VERDICT r5's one epistemic gap: saturated full-budget
records (all-ones o-rates) cannot distinguish two attacks, so a
survival-semantics regression can hide forever; the *interior* budget-100
rates move 4.5x under such a regression but were never validated against
the reference pymoo semantics. This harness produces and checks the
committed fixture ``tests/fixtures/oracle_interior_rates.json``:

- per domain, per recorded seed: the ENGINE's budget-100 o1..o7 rates
  (post-hoc f64 judgement — interior by construction, asserted), and
- an ORACLE-GA run (``tests/oracles/oracle_ga.py``: the engine's loop in
  f64 with every survival round replayed through the vendored pymoo
  oracle in shared-trace mode) whose final rates AND zero-mismatch
  survival trail are the reference-side counterpart.

Domains: ``lcld_synth`` (code-derived schema + deterministic surrogate —
reproduces in any container, the quick-tier fixture), ``botnet`` (the
real reference artifacts at 48 states — engine rates only, slow tier) and
``botnet_oracle`` (8 real botnet states with the full oracle replay).

    python tools/oracle_check.py                  # check committed fixture
    python tools/oracle_check.py --regen          # regenerate + rewrite it
    python tools/oracle_check.py --domains lcld_synth --skip-oracle

Fixture-regen procedure (docs/DESIGN.md § quality watchdog): run --regen
on the CPU x64 test platform (the same env ``tests/conftest.py`` forces),
eyeball the printed interiority/parity lines, commit the JSON. The
quick/slow-tier tests in ``tests/test_quality.py`` then hold every future
kernel change to these numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# standalone runs must pin the test platform BEFORE jax loads (imported
# from pytest these are already set by tests/conftest.py) — including the
# virtual 8-device mesh flag, so fixture generation and the fixture tests
# run on byte-identical platforms
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # standalone `python tools/oracle_check.py`
    sys.path.insert(0, REPO)
TESTS = os.path.join(REPO, "tests")
FIXTURE_PATH = os.path.join(TESTS, "fixtures", "oracle_interior_rates.json")
REFERENCE = "/root/reference"

#: recorded configs — the single source of truth the tests rerun from the
#: committed fixture (which embeds a copy; a mismatch between the two
#: fails the check so the fixture can never drift from the code).
DOMAINS = {
    "lcld_synth": {
        "n_states": 16,
        "n_gen": 100,
        "n_pop": 40,
        "n_offsprings": 20,
        "archive_size": 0,
        "norm": 2,
        "seeds": [42, 43, 44],
        "thresholds": {"f1": 0.5, "f2": 0.5},
        "pool": 512,
        "pool_seed": 11,
        "oracle": True,
        #: strictly-interior pins: a survival/operator semantics change
        #: must MOVE these columns (0-indexed o2/o4), the lesson of the
        #: saturated fixture that let the r3 kernel bug through
        "interior_columns": [1, 3],
    },
    "botnet": {
        "n_states": 48,
        "n_gen": 100,
        "n_pop": 100,
        "n_offsprings": 50,
        "archive_size": 0,
        "norm": 2,
        "seeds": [42, 43, 44],
        "thresholds": {"f1": 0.5, "f2": 4.0},
        "oracle": False,
        "interior_columns": [1, 3],
    },
    "botnet_oracle": {
        "n_states": 8,
        "n_gen": 100,
        "n_pop": 100,
        "n_offsprings": 50,
        "archive_size": 0,
        "norm": 2,
        "seeds": [42],
        "thresholds": {"f1": 0.5, "f2": 4.0},
        "oracle": True,
        # 8 states is oracle-replay budget, not a rate sample — no
        # interiority assertion at this n
        "interior_columns": [],
    },
    "phishing": {
        # the spec-compiled data-only domain (domains/specs/phishing):
        # no hand-written module anywhere in this trajectory — schema +
        # constraints from committed package data, candidates from the
        # constraint-first synthetic sampler. Same dataset-free recipe
        # as lcld_synth, certifying the IR's jnp/repair backends under
        # the full oracle-GA replay.
        "n_states": 16,
        "n_gen": 100,
        "n_pop": 40,
        "n_offsprings": 20,
        "archive_size": 0,
        "norm": 2,
        "seeds": [42, 43, 44],
        "thresholds": {"f1": 0.5, "f2": 0.5},
        "pool": 512,
        "pool_seed": 11,
        "oracle": True,
        "interior_columns": [1, 3],
    },
}

#: |engine mean - oracle-GA mean| bound per tracked column. The two runs
#: share seeds but not arithmetic (f32 scan vs f64 eager), so their
#: trajectories decohere chaotically and only the rate *distribution* is
#: comparable: at 16 states x 3 seeds the difference of two binomial
#: means has sigma ~0.1; 0.3 is ~3 sigma — loose enough for GA noise,
#: far below the 4.5x semantics-regression class.
PARITY_TOLERANCE = 0.3


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_lcld_synth(cfg: dict):
    """Deterministic, container-independent problem: code-derived LCLD
    schema, seed-pinned random surrogate, candidates picked as an evenly
    spread difficulty mix above the decision threshold (so budget-100
    rates are interior: the easiest flip early, the hardest never do)."""
    import tempfile

    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_lcld,
        synth_lcld_schema,
    )
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    tmp = tempfile.mkdtemp(prefix="oracle_check_")
    paths = synth_lcld_schema(tmp)
    cons = LcldConstraints(paths["features"], paths["constraints"])
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=1))
    pool = synth_lcld(cfg["pool"], cons.schema, seed=cfg["pool_seed"])
    # scaler envelope = data ∪ per-state dynamic bounds so attacked
    # candidates at their bound extremes stay inside [0, 1] (the judged
    # distance is scaler-space — bench.py's rule)
    xl_d, xu_d = cons.get_feature_min_max(dynamic_input=pool)
    lo = np.minimum(
        pool.min(0),
        np.broadcast_to(np.asarray(xl_d, float), pool.shape).min(0),
    )
    hi = np.maximum(
        pool.max(0),
        np.broadcast_to(np.asarray(xu_d, float), pool.shape).max(0),
    )
    scaler = fit_minmax(lo, hi)
    p1 = np.asarray(sur.predict_proba(scaler.transform(pool)))[:, 1]
    cand = np.where(p1 >= cfg["thresholds"]["f1"])[0]
    cand = cand[np.argsort(-p1[cand])]
    sel = cand[np.linspace(0, len(cand) - 1, cfg["n_states"]).astype(int)]
    return {"constraints": cons, "surrogate": sur, "scaler": scaler,
            "x": pool[sel]}


def build_phishing(cfg: dict):
    """Dataset-free spec domain: constraints compiled from the committed
    ``domains/specs/phishing`` package data, candidates from the
    constraint-first sampler — the same interior-mix selection recipe as
    :func:`build_lcld_synth`."""
    from moeva2_ijcai22_replication_tpu.domains import (
        get_constraints_class,
        spec_domain_dir,
    )
    from moeva2_ijcai22_replication_tpu.domains.synth import synth_phishing
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    d = spec_domain_dir("phishing")
    cons = get_constraints_class("phishing")(
        os.path.join(d, "features.csv"), os.path.join(d, "constraints.csv")
    )
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=1))
    pool = synth_phishing(cfg["pool"], cons.schema, seed=cfg["pool_seed"])
    xl_d, xu_d = cons.get_feature_min_max(dynamic_input=pool)
    lo = np.minimum(
        pool.min(0),
        np.broadcast_to(np.asarray(xl_d, float), pool.shape).min(0),
    )
    hi = np.maximum(
        pool.max(0),
        np.broadcast_to(np.asarray(xu_d, float), pool.shape).max(0),
    )
    scaler = fit_minmax(lo, hi)
    p1 = np.asarray(sur.predict_proba(scaler.transform(pool)))[:, 1]
    cand = np.where(p1 >= cfg["thresholds"]["f1"])[0]
    cand = cand[np.argsort(-p1[cand])]
    sel = cand[np.linspace(0, len(cand) - 1, cfg["n_states"]).astype(int)]
    return {"constraints": cons, "surrogate": sur, "scaler": scaler,
            "x": pool[sel]}


def build_botnet(cfg: dict):
    """Real reference artifacts (None when the reference tree is absent —
    callers skip, never fake, these domains)."""
    if not os.path.isdir(REFERENCE):
        return None
    from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints
    from moeva2_ijcai22_replication_tpu.models.io import load_classifier
    from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

    cons = BotnetConstraints(
        f"{REFERENCE}/data/botnet/features.csv",
        f"{REFERENCE}/data/botnet/constraints.csv",
    )
    sur = load_classifier(f"{REFERENCE}/models/botnet/nn.model")
    scaler = load_joblib_scaler(f"{REFERENCE}/models/botnet/scaler.joblib")
    x = np.load(f"{REFERENCE}/data/botnet/x_candidates_common.npy")
    return {"constraints": cons, "surrogate": sur, "scaler": scaler,
            "x": x[: cfg["n_states"]]}


def build_problem(name: str, cfg: dict):
    if name == "lcld_synth":
        return build_lcld_synth(cfg)
    if name == "phishing":
        return build_phishing(cfg)
    return build_botnet(cfg)


def _calculator(problem, cfg):
    from moeva2_ijcai22_replication_tpu.attacks.objective import (
        ObjectiveCalculator,
    )

    return ObjectiveCalculator(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        thresholds=dict(cfg["thresholds"]),
        min_max_scaler=problem["scaler"],
        ml_scaler=problem["scaler"],
        minimize_class=1,
        norm=cfg["norm"],
    )


def _engine(problem, cfg, seed, dtype=None):
    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2

    kw = {} if dtype is None else {"dtype": dtype}
    return Moeva2(
        classifier=problem["surrogate"],
        constraints=problem["constraints"],
        ml_scaler=problem["scaler"],
        norm=cfg["norm"],
        n_gen=cfg["n_gen"],
        n_pop=cfg["n_pop"],
        n_offsprings=cfg["n_offsprings"],
        seed=seed,
        archive_size=cfg["archive_size"],
        **kw,
    )


def engine_rates(problem, cfg, seed) -> list[float]:
    """The production engine's (f32 scan, CPU test platform) budget-100
    rates at this seed — the number the quick/slow fixture tests pin."""
    moeva = _engine(problem, cfg, seed)
    res = moeva.generate(problem["x"], minimize_class=1)
    calc = _calculator(problem, cfg)
    return [float(v) for v in calc.success_rate_3d(problem["x"], res.x_ml)]


def oracle_ga_rates(problem, cfg, seed, check_states=None) -> dict:
    """The f64 oracle-GA trajectory at this seed: rates + the survival
    cross-check trail (rounds checked, mismatches — must be zero)."""
    import jax.numpy as jnp

    sys.path.insert(0, TESTS)
    try:
        from oracles.oracle_ga import run_oracle_ga
    finally:
        sys.path.remove(TESTS)
    moeva = _engine(problem, cfg, seed, dtype=jnp.float64)
    out = run_oracle_ga(
        moeva, problem["x"], minimize_class=1, check_states=check_states
    )
    calc = _calculator(problem, cfg)
    rates = [float(v) for v in calc.success_rate_3d(problem["x"], out["x_ml"])]
    return {
        "o_rates": rates,
        "rounds_checked": int(out["rounds_checked"]),
        # rounds whose merged F contained inf (domain kernels emit inf
        # violation sums on degenerate candidates): the NaN-association
        # regime where pymoo's own pick order is float noise — replayed
        # for state continuity, excluded from the exact comparison
        "rounds_skipped_nonfinite": int(out["rounds_skipped_nonfinite"]),
        "mismatches": out["mismatches"],
    }


def run_domain(name: str, cfg: dict, skip_oracle: bool = False) -> dict | None:
    problem = build_problem(name, cfg)
    if problem is None:
        log(f"[oracle_check] {name}: reference artifacts absent — skipped")
        return None
    result: dict = {"config": {k: v for k, v in cfg.items()}, "engine": {}}
    for seed in cfg["seeds"]:
        rates = engine_rates(problem, cfg, seed)
        result["engine"][str(seed)] = rates
        log(f"[oracle_check] {name} seed {seed} engine o1..o7: "
            + " ".join(f"{r:.3f}" for r in rates))
    engine_mean = np.mean(
        [result["engine"][str(s)] for s in cfg["seeds"]], axis=0
    )
    result["engine"]["mean"] = [float(v) for v in engine_mean]
    for col in cfg["interior_columns"]:
        assert 0.0 < engine_mean[col] < 1.0, (
            f"{name}: mean o{col + 1}={engine_mean[col]:.3f} is saturated — "
            "the fixture must stay interior to stay sensitive (retune the "
            "config before committing)"
        )
    if cfg["oracle"] and not skip_oracle:
        result["oracle_ga"] = {}
        for seed in cfg["seeds"]:
            o = oracle_ga_rates(problem, cfg, seed)
            assert not o["mismatches"], (
                f"{name} seed {seed}: kernel survival diverged from the "
                f"pymoo oracle at {len(o['mismatches'])} of "
                f"{o['rounds_checked']} rounds: {o['mismatches'][:3]}"
            )
            result["oracle_ga"][str(seed)] = o
            log(
                f"[oracle_check] {name} seed {seed} oracle-GA o1..o7: "
                + " ".join(f"{r:.3f}" for r in o["o_rates"])
                + f"  ({o['rounds_checked']} survival rounds, 0 mismatches)"
            )
        oracle_mean = np.mean(
            [result["oracle_ga"][str(s)]["o_rates"] for s in cfg["seeds"]],
            axis=0,
        )
        result["oracle_ga"]["mean"] = [float(v) for v in oracle_mean]
        deltas = np.abs(engine_mean - oracle_mean)
        result["parity"] = {
            "max_abs_mean_delta": float(deltas.max()),
            "tolerance": PARITY_TOLERANCE,
        }
        log(f"[oracle_check] {name} engine-vs-oracle max |Δmean|: "
            f"{deltas.max():.3f} (tolerance {PARITY_TOLERANCE})")
        assert deltas.max() <= PARITY_TOLERANCE, (
            f"{name}: engine rates sit outside the oracle seed band "
            f"(max |Δmean| {deltas.max():.3f} > {PARITY_TOLERANCE})"
        )
    return result


def check_against_fixture(results: dict, fixture: dict) -> list[str]:
    """Exact reproduction check of freshly computed results vs the
    committed fixture (engine rates per seed; oracle rates when present)."""
    problems = []
    for name, res in results.items():
        committed = (fixture.get("domains") or {}).get(name)
        if committed is None:
            problems.append(f"{name}: not in committed fixture")
            continue
        if committed["config"] != res["config"]:
            problems.append(f"{name}: config drifted from committed fixture")
        for seed, rates in res["engine"].items():
            want = committed["engine"].get(seed)
            if want is None or not np.allclose(rates, want, atol=0):
                problems.append(
                    f"{name} seed {seed}: engine rates {rates} != "
                    f"committed {want}"
                )
        for seed, o in (res.get("oracle_ga") or {}).items():
            want = (committed.get("oracle_ga") or {}).get(seed)
            if seed == "mean" or want is None:
                continue
            if not np.allclose(o["o_rates"], want["o_rates"], atol=0):
                problems.append(
                    f"{name} seed {seed}: oracle-GA rates drifted from "
                    "committed fixture"
                )
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--regen", action="store_true",
        help="recompute everything and rewrite the committed fixture",
    )
    parser.add_argument(
        "--domains", nargs="*", default=list(DOMAINS),
        choices=list(DOMAINS), help="subset of domains to run",
    )
    parser.add_argument(
        "--skip-oracle", action="store_true",
        help="engine rates only (no pymoo-oracle trajectory replay)",
    )
    args = parser.parse_args(argv)

    results = {}
    for name in args.domains:
        res = run_domain(name, DOMAINS[name], skip_oracle=args.skip_oracle)
        if res is not None:
            results[name] = res

    if args.regen:
        # merge-regen: a subset --regen (e.g. --domains phishing) must
        # refresh ONLY the recomputed domains — silently dropping the
        # other domains' committed records would un-pin them
        merged = dict(results)
        try:
            with open(FIXTURE_PATH) as fh:
                existing = (json.load(fh).get("domains") or {})
        except OSError:
            existing = {}
        for name, rec in existing.items():
            merged.setdefault(name, rec)
        doc = {
            "generated_by": "tools/oracle_check.py --regen (CPU x64 test platform)",
            "note": (
                "Budget-100 interior success rates, oracle-validated: "
                "engine = the production f32 scan; oracle_ga = the f64 "
                "eager trajectory with EVERY survival round replayed "
                "through the vendored pymoo R-NSGA-III oracle in "
                "shared-trace mode (zero mismatches). Interior columns "
                "are strictly inside (0, 1) by construction so any "
                "survival/operator semantics change moves them. Regen: "
                "python tools/oracle_check.py --regen  (then commit)."
            ),
            "parity_tolerance": PARITY_TOLERANCE,
            "domains": merged,
        }
        os.makedirs(os.path.dirname(FIXTURE_PATH), exist_ok=True)
        with open(FIXTURE_PATH, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=False)
            fh.write("\n")
        log(f"[oracle_check] wrote {FIXTURE_PATH}")
        return 0

    try:
        with open(FIXTURE_PATH) as fh:
            fixture = json.load(fh)
    except OSError:
        log(f"[oracle_check] no committed fixture at {FIXTURE_PATH}; "
            "run with --regen first")
        return 2
    problems = check_against_fixture(results, fixture)
    for p in problems:
        log(f"[oracle_check] MISMATCH: {p}")
    log(f"[oracle_check] {'FAIL' if problems else 'ok'} "
        f"({len(results)} domain(s) checked)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
