"""Prototype: cumulative front counts via (M,M) matmuls vs rank histogram."""

import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "./.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

S, M = 1000, 203
N_GEN = 60
rng = np.random.default_rng(0)
UNR = np.iinfo(np.int32).max
ranks_np = rng.integers(0, 12, (S, M)).astype(np.int32)
ranks_np[rng.random((S, M)) < 0.3] = UNR
ranks0 = jnp.asarray(ranks_np)


def timed(name, fn, *args):
    jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(fn(*args))
        ts.append(time.time() - t0)
    print(f"{name}: {min(ts)/N_GEN*1e3:.2f} ms/gen", flush=True)


def _rowsum(mask):
    one = jnp.ones((mask.shape[-1],), jnp.bfloat16)
    return jnp.matmul(
        mask.astype(jnp.bfloat16), one, preferred_element_type=jnp.float32
    ).astype(jnp.int32)


def scan(body):
    @jax.jit
    def run(r):
        def step(rr, _):
            out = body(rr)
            return rr ^ (out & 1), out.sum()
        return jax.lax.scan(step, r, None, length=N_GEN)[1].sum()
    return run


def via_matmul(ranks):
    def one(rk):
        cum_le = _rowsum(rk[None, :] <= rk[:, None])
        cum_lt = _rowsum(rk[None, :] < rk[:, None])
        return cum_le + cum_lt
    return jax.vmap(one)(ranks)


def via_hist(ranks):
    # ranks are either < M or the UNRANKED sentinel: clip sentinel to bin M
    def one(rk):
        b = jnp.clip(rk, 0, M).astype(jnp.int32)
        hist = jnp.zeros((M + 1,), jnp.int32).at[b].add(1)
        cums = jnp.cumsum(hist)
        cum_le = cums[b]
        cum_lt = cums[b] - hist[b]
        return cum_le + cum_lt
    return jax.vmap(one)(ranks)


r_m = np.asarray(via_matmul(ranks0))
r_h = np.asarray(via_hist(ranks0))
# sentinel rows: matmul counts <=UNRANKED including other sentinels — match
np.testing.assert_array_equal(r_m, r_h)
print("bitwise equal", flush=True)

timed("cum via matmul", scan(via_matmul), ranks0)
timed("cum via hist  ", scan(via_hist), ranks0)
