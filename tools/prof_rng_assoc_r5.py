"""Prototype timings: global-key RNG vs per-state vmapped keys; argmax-p2
one-shot association vs the current argmin-dist2 formulation."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "./.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

S, M, R, K = 1000, 203, 106, 3
N_GEN = 60
rng = np.random.default_rng(0)
f = jnp.asarray(rng.random((S, M, K)), jnp.float32)
dirs = jnp.asarray(rng.random((S, R, K)) + 0.1, jnp.float32)
ideal = jnp.zeros((S, K))
nadir = jnp.ones((S, K))


def timed(name, fn, *args):
    jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(2):
        t0 = time.time()
        jax.block_until_ready(fn(*args))
        ts.append(time.time() - t0)
    print(f"{name}: {min(ts)/N_GEN*1e3:.2f} ms/gen", flush=True)


def scan(body):
    @jax.jit
    def run(key):
        def step(k, _):
            k, ks = jax.random.split(k)
            out = body(ks)
            return k, out.sum()
        return jax.lax.scan(step, key, None, length=N_GEN)[1].sum()
    return run


def rng_vmapped(ks):
    keys = jax.random.split(ks, S)
    g1 = jax.vmap(lambda k: jax.random.gumbel(k, (R,)))(keys)
    g2 = jax.vmap(lambda k: jax.random.gumbel(k, (M,)))(keys)
    return g1.sum() + g2.sum(jnp.float32(0))


def rng_vmapped2(ks):
    keys = jax.random.split(ks, S)
    g1 = jax.vmap(lambda k: jax.random.gumbel(k, (R,)))(keys)
    g2 = jax.vmap(lambda k: jax.random.gumbel(k, (M,)))(keys)
    return g1.sum() + g2.sum()


def rng_global(ks):
    k1, k2 = jax.random.split(ks)
    g1 = jax.random.gumbel(k1, (S, R))
    g2 = jax.random.gumbel(k2, (S, M))
    return g1.sum() + g2.sum()


def rng_global_one(ks):
    g = jax.random.gumbel(ks, (S, R + M))
    return g.sum()


def assoc_current(_):
    denom = nadir - ideal
    n = (f - ideal[:, None, :]) / denom[:, None, :]
    d = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    proj = jnp.einsum("smk,srk->smr", n, d)
    dist2 = (n * n).sum(-1)[:, :, None] - proj * proj
    niche = jnp.argmin(dist2, axis=2)
    rmin = jnp.take_along_axis(dist2, niche[..., None], 2)[..., 0]
    return niche + jnp.sqrt(jnp.clip(rmin, 0.0, None))


def assoc_p2(_):
    denom = nadir - ideal
    n = (f - ideal[:, None, :]) / denom[:, None, :]
    d = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    proj = jnp.einsum("smk,srk->smr", n, d)
    p2 = proj * proj
    niche = jnp.argmax(p2, axis=2)
    best = jnp.take_along_axis(p2, niche[..., None], 2)[..., 0]
    dist2 = (n * n).sum(-1) - best
    return niche + jnp.sqrt(jnp.clip(dist2, 0.0, None))


def assoc_p2_maxval(_):
    denom = nadir - ideal
    n = (f - ideal[:, None, :]) / denom[:, None, :]
    d = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    proj = jnp.einsum("smk,srk->smr", n, d)
    p2 = proj * proj
    niche = jnp.argmax(p2, axis=2)
    best = p2.max(axis=2)
    dist2 = (n * n).sum(-1) - best
    return niche + jnp.sqrt(jnp.clip(dist2, 0.0, None))


try:
    timed("rng vmapped        ", scan(rng_vmapped), jax.random.PRNGKey(0))
except Exception:
    pass
timed("rng vmapped        ", scan(rng_vmapped2), jax.random.PRNGKey(0))
timed("rng global 2-key   ", scan(rng_global), jax.random.PRNGKey(0))
timed("rng global 1-key   ", scan(rng_global_one), jax.random.PRNGKey(0))
timed("assoc current      ", scan(assoc_current), jax.random.PRNGKey(0))
timed("assoc argmax-p2    ", scan(assoc_p2), jax.random.PRNGKey(0))
timed("assoc p2 max+argmax", scan(assoc_p2_maxval), jax.random.PRNGKey(0))
