"""Sub-stage profile of survive_batch at bench shape (on-chip, min-of-2).

Stages (cumulative, all inside one lax.scan per measurement):
  P1  _survive_pre (ranks + normalisation + dirs)         [includes nds]
  P2  P1 + association
  P3  P1 + P2 + _survive_post (niching fill)              [= full survival]
Plus isolated pieces: nds-only, gumbel/rng-only, post-only (fixed inputs).
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "./.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

N_STATES = int(os.environ.get("P_STATES", 1000))
N_GEN = int(os.environ.get("P_GENS", 60))

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.moeva.survival import (
    NormState,
    _niche_gumbels,
    _survive_post,
    _survive_pre,
    associate_batch,
)
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import load_classifier
from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

LCLD = "/root/reference/data/lcld"
cons = LcldConstraints(f"{LCLD}/features.csv", f"{LCLD}/constraints.csv")
x = synth_lcld(N_STATES, cons.schema, seed=42)
sur = load_classifier("/root/reference/models/lcld/nn.model")
scaler = load_joblib_scaler("/root/reference/models/lcld/scaler.joblib")
moeva = Moeva2(classifier=sur, constraints=cons, ml_scaler=scaler,
               norm=2, n_gen=N_GEN, n_pop=100, n_offsprings=100, seed=42)

s = N_STATES
pop_size = moeva.pop_size
m = pop_size + moeva.n_offsprings
asp = moeva.asp_points
rng = np.random.default_rng(0)
f0 = jnp.asarray(rng.random((s, m, 3)), jnp.float32)
key0 = jax.random.PRNGKey(0)
st0 = jax.vmap(lambda _: NormState.init(3, jnp.float32))(jnp.arange(s))


def timed(name, fn, *args):
    jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(2):
        t0 = time.time()
        jax.block_until_ready(fn(*args))
        ts.append(time.time() - t0)
    print(f"{name}: {min(ts)/N_GEN*1e3:.2f} ms/gen", flush=True)


def scan(body):
    @jax.jit
    def run(f, key, st):
        def step(carry, _):
            ff, k, sst = carry
            k, ks = jax.random.split(k)
            out, sst = body(ks, ff, sst)
            return (ff + 0.0 * out, k, sst), ()
        return jax.lax.scan(step, (f, key, st), None, length=N_GEN)[0][0]
    return run


def pre_body(ks, ff, sst):
    ranks, dirs, nadir, new = jax.vmap(
        lambda f1, st1: _survive_pre(f1, asp, st1, pop_size)
    )(ff, sst)
    return ranks.sum() + dirs.sum() + nadir.sum(), new


def assoc_body(ks, ff, sst):
    ranks, dirs, nadir, new = jax.vmap(
        lambda f1, st1: _survive_pre(f1, asp, st1, pop_size)
    )(ff, sst)
    niche, dist = associate_batch(ff, dirs, new.ideal, nadir)
    return ranks.sum() + niche.sum() + dist.sum(), new


def full_body(ks, ff, sst):
    from moeva2_ijcai22_replication_tpu.attacks.moeva.survival import survive_batch

    mask, new, ranks = survive_batch(ks, ff, asp, sst, pop_size)
    return mask.sum(), new


def rng_body(ks, ff, sst):
    keys = jax.random.split(ks, s)
    g1 = jax.vmap(lambda k: jax.random.gumbel(k, (103,)))(keys)
    g2 = jax.vmap(lambda k: jax.random.gumbel(k, (m,)))(keys)
    return g1.sum() + g2.sum(), sst


def post_body(ks, ff, sst):
    # fixed niche/dist/ranks: isolates _survive_post (its random fields come
    # from the batched bulk gumbel draws, as in the production survive_batch)
    niche = jnp.zeros((s, m), jnp.int32)
    dist = ff[..., 0]
    ranks = jnp.asarray(rng.integers(0, 4, (s, m)), jnp.int32)
    gum_cut, gum_mem = _niche_gumbels(ks, (s,), 106, m)
    mask = jax.vmap(
        lambda gc, gm, f1, r1, ni, di: _survive_post(
            gc, gm, f1, r1, ni, di, 106, pop_size
        )
    )(gum_cut, gum_mem, ff, ranks, niche, dist)
    return mask.sum(), sst


timed("P1 pre (nds+norm+dirs)", scan(pre_body), f0, key0, st0)
timed("P2 pre+assoc          ", scan(assoc_body), f0, key0, st0)
timed("P3 full survive_batch ", scan(full_body), f0, key0, st0)
timed("X  rng/gumbel only    ", scan(rng_body), f0, key0, st0)
timed("X  post only (fixed)  ", scan(post_body), f0, key0, st0)
