"""Ad-hoc perf breakdown of the MoEvA generation step (not shipped API).

Times three scans over n_gen generations at bench shapes:
  A) objective kernel only (decode+forward+constraints)
  B) A + offspring generation (operators)
  C) full gen_step (A + B + survival)  — the production path
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

N_STATES = int(os.environ.get("P_STATES", 1000))
N_GEN = int(os.environ.get("P_GENS", 50))

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.moeva.operators import make_offspring
from moeva2_ijcai22_replication_tpu.attacks.moeva.survival import NormState, survive_batch
from moeva2_ijcai22_replication_tpu.core import codec as codec_lib
from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
from moeva2_ijcai22_replication_tpu.models.io import load_classifier
from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

LCLD = "/root/reference/data/lcld"
cons = LcldConstraints(f"{LCLD}/features.csv", f"{LCLD}/constraints.csv")
x = synth_lcld(N_STATES, cons.schema, seed=42)
sur = load_classifier("/root/reference/models/lcld/nn.model")
scaler = load_joblib_scaler("/root/reference/models/lcld/scaler.joblib")

moeva = Moeva2(classifier=sur, constraints=cons, ml_scaler=scaler,
               norm=2, n_gen=N_GEN, n_pop=100, n_offsprings=100, seed=42)
codec, tables = moeva.codec, moeva.tables
pop_size, n_off = moeva.pop_size, moeva.n_offsprings

xl_ml, xu_ml = cons.get_feature_min_max(dynamic_input=x)
xl_ml = jnp.asarray(np.broadcast_to(np.asarray(xl_ml, float), x.shape), moeva.dtype)
xu_ml = jnp.asarray(np.broadcast_to(np.asarray(xu_ml, float), x.shape), moeva.dtype)
x_init = jnp.asarray(x, moeva.dtype)
x_init_mm = codec_lib.minmax_normalize(x_init, xl_ml, xu_ml)
mc = jnp.ones((N_STATES,), jnp.int32)
xl_gen, xu_gen = codec_lib.genetic_bounds(codec, xl_ml, xu_ml)

x0 = codec_lib.round_int_genes(codec, codec_lib.ml_to_genetic(codec, x_init))
pop_x = jnp.broadcast_to(x0[:, None, :], (N_STATES, pop_size, codec.gen_length)).astype(moeva.dtype)
params = sur.params
key = jax.random.PRNGKey(0)
asp = moeva.asp_points
s = N_STATES


def timed(name, fn, *args):
    out = jax.block_until_ready(fn(*args))
    t0 = time.time()
    out = jax.block_until_ready(fn(*args))
    dt = time.time() - t0
    print(f"{name}: {dt:.2f}s for {N_GEN} gens -> {dt/N_GEN*1e3:.1f} ms/gen")
    return out


@jax.jit
def scan_eval(pop_x, key):
    def step(carry, _):
        px, k = carry
        f, _ = moeva._evaluate(params, px, x_init, x_init_mm, xl_ml, xu_ml, mc)
        return (px + 0.0 * f.sum() , k), ()
    return jax.lax.scan(step, (pop_x, key), None, length=N_GEN)[0][0]


@jax.jit
def scan_eval_ops(pop_x, key):
    def step(carry, _):
        px, k = carry
        k, k_mate = jax.random.split(k)
        off = jax.vmap(lambda kk, xx, lo, hi: make_offspring(
            kk, tables, xx, lo, hi, n_off))(jax.random.split(k_mate, s), px, xl_gen, xu_gen)
        f, _ = moeva._evaluate(params, off, x_init, x_init_mm, xl_ml, xu_ml, mc)
        px = px + 0.0 * f.sum()
        return (px, k), ()
    return jax.lax.scan(step, (pop_x, key), None, length=N_GEN)[0][0]


init_fn = jax.jit(moeva._build_init())
segment_fn = jax.jit(moeva._build_segment(), static_argnames="length")


def full(params, x_init, mc, xl, xu, key):
    carry, _ = init_fn(params, x_init, mc, xl, xu, key)
    carry, _ = segment_fn(params, x_init, mc, xl, xu, carry, length=N_GEN - 1)
    return carry[0]


timed("A eval-only      ", scan_eval, pop_x, key)
timed("B eval+operators ", scan_eval_ops, pop_x, key)
timed("C full attack    ", full, params, x_init, mc, xl_ml, xu_ml, key)


@jax.jit
def scan_survive(pop_x, key):
    # production path: survive_batch with the engine's association blocking
    merged = jnp.concatenate([pop_x, pop_x[:, :n_off] * 1.001], axis=1)
    def step(carry, _):
        fpop, k, st = carry
        k, ks = jax.random.split(k)
        mask, st, _ = survive_batch(
            ks, fpop, asp, st, pop_size,
            assoc_block=moeva.assoc_block,
        )
        return (fpop + 0.0 * mask.sum(), k, st), ()
    f0, _ = moeva._evaluate(params, merged, x_init, x_init_mm, xl_ml, xu_ml, mc)
    st0 = jax.vmap(lambda _: NormState.init(3, moeva.dtype))(jnp.arange(s))
    return jax.lax.scan(step, (f0, key, st0), None, length=N_GEN)[0][0]


from moeva2_ijcai22_replication_tpu.attacks.moeva.nds import nd_ranks

@jax.jit
def scan_nds(pop_x, key):
    merged = jnp.concatenate([pop_x, pop_x[:, :n_off] * 1.001], axis=1)
    f0, _ = moeva._evaluate(params, merged, x_init, x_init_mm, xl_ml, xu_ml, mc)
    def step(carry, _):
        ff, k = carry
        ranks = nd_ranks(ff)
        return (ff + 0.0 * ranks.sum(), k), ()
    return jax.lax.scan(step, (f0, key), None, length=N_GEN)[0][0]


timed("D survive-only   ", scan_survive, pop_x, key)
timed("E nds-only       ", scan_nds, pop_x, key)
