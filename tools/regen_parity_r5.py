"""Regenerate every number in tests/fixtures/parity_botnet_rq1.json with the
round-4-corrected survival kernel (post-1141e71 semantics).

The round-4 fix changed what the attack computes (pymoo-oracle-validated
aspiration folding + nadir clamp), so every record produced by the pre-fix
kernel is stale. This script re-runs, on the real committed 387-state botnet
artifacts on the chip:

  1. MoEvA rq1 (387 x 1000, pop 200, seed 42, archive 24): o-rates for the
     final population alone ("no-archive semantics" — the archive columns are
     appended, population dynamics identical) and with the archive, at
     eps 0.5 / 1 / 4.
  2. The pinned 8-state slice (x + adv arrays) for the bit-for-bit CI check.
  3. PGD(flip) + SAT repair at budget 200, eps 4.
  4. rq2 augmented-defense and rq3 retrained-model stories (100 gens).

Writes the fixture JSON + slice npys in place, plus out/parity_regen_r5.json
with old-vs-new deltas for the round record.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
FIXTURES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", "fixtures"
)
REF = "/root/reference"
SLICE_STATES = [24, 46, 53, 90, 0, 1, 2, 3]


def main():
    import jax

    jax.config.update("jax_compilation_cache_dir", "./.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
    from moeva2_ijcai22_replication_tpu.attacks.objective import ObjectiveCalculator
    from moeva2_ijcai22_replication_tpu.domains.botnet import (
        BotnetAugmentedConstraints,
        BotnetConstraints,
    )
    from moeva2_ijcai22_replication_tpu.models.io import load_classifier
    from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

    old = json.load(open(f"{FIXTURES}/parity_botnet_rq1.json"))
    cons = BotnetConstraints(
        f"{REF}/data/botnet/features.csv", f"{REF}/data/botnet/constraints.csv"
    )
    x = np.load(f"{REF}/data/botnet/x_candidates_common.npy")
    sur = load_classifier(f"{REF}/models/botnet/nn.model")
    scaler = load_joblib_scaler(f"{REF}/models/botnet/scaler.joblib")

    def calc(thresholds, c=None, s=None, sc=None):
        return ObjectiveCalculator(
            classifier=s or sur, constraints=c or cons, thresholds=thresholds,
            min_max_scaler=sc or scaler, ml_scaler=sc or scaler,
            minimize_class=1, norm=2,
        )

    # -- 1+2: rq1 full scale -------------------------------------------------
    moeva = Moeva2(
        classifier=sur, constraints=cons, ml_scaler=scaler, norm=2,
        n_gen=1000, n_pop=200, n_offsprings=100, seed=42, archive_size=24,
    )
    t0 = time.time()
    res = moeva.generate(x, minimize_class=1)
    wall = time.time() - t0
    pop = res.x_ml[:, : moeva.pop_size]  # archive columns excluded
    c4 = calc({"f1": 0.5, "f2": 4.0})
    vals_pop = c4.objectives(x, pop)
    vals_all = c4.objectives(x, res.x_ml)
    rates_pop = [round(float(r), 6) for r in c4.success_rate_3d(x, pop, vals_pop)]
    by_eps = {}
    for eps in (0.5, 1.0, 4.0):
        ce = calc({"f1": 0.5, "f2": eps})
        by_eps[str(eps)] = [
            round(float(r), 6) for r in ce.success_rate_3d(x, res.x_ml, vals_all)
        ]
    print(f"[regen] rq1 moeva {wall:.1f}s pop: {rates_pop} archive@4: {by_eps['4.0']}",
          flush=True)

    sl = np.array(SLICE_STATES)
    np.save(f"{FIXTURES}/parity_botnet_x.npy", x[sl])
    np.save(f"{FIXTURES}/parity_botnet_adv.npy", res.x_ml[sl].astype(np.float32))
    slice_rates = [
        round(float(r), 6)
        for r in c4.success_rate_3d(x[sl], res.x_ml[sl].astype(np.float32).astype(np.float64))
    ]
    print(f"[regen] slice rates: {slice_rates}", flush=True)

    # -- 3: PGD(flip) + SAT repair ------------------------------------------
    import jax.numpy as jnp

    from moeva2_ijcai22_replication_tpu.attacks.pgd import (
        ConstrainedPGD,
        round_ints_toward_initial,
    )
    from moeva2_ijcai22_replication_tpu.attacks.sat import SatAttack
    from moeva2_ijcai22_replication_tpu.domains.botnet_sat import make_botnet_sat_builder

    t0 = time.time()
    atk = ConstrainedPGD(
        classifier=sur, constraints=cons, scaler=scaler,
        eps=2 - 1e-6, eps_step=0.1, max_iter=200, norm=2,
        loss_evaluation="flip", seed=42,
    )
    xs = np.asarray(scaler.transform(jnp.asarray(x)))
    y = np.asarray(sur.predict_proba(jnp.asarray(xs))).argmax(-1)
    hot = np.asarray(scaler.inverse(jnp.asarray(atk.generate(xs, y))))
    hot = round_ints_toward_initial(hot, x, cons.get_feature_type())
    sat = SatAttack(
        cons, make_botnet_sat_builder(cons), scaler, 2.0, np.inf,
        n_sample=1, n_jobs=-1,
    )
    adv_sat = sat.generate(x, hot)
    sat_rates = [round(float(r), 6) for r in c4.success_rate_3d(x, adv_sat)]
    sat_wall = time.time() - t0
    print(f"[regen] pgd+sat {sat_wall:.1f}s: {sat_rates}", flush=True)

    # -- 4: rq2 augmented defense + rq3 retrained ---------------------------
    cons_a = BotnetAugmentedConstraints(
        f"{REF}/data/botnet/features_augmented_19.csv",
        f"{REF}/data/botnet/constraints_augmented_19.csv",
        f"{REF}/data/botnet/important_features_19.npy",
    )
    sur_a = load_classifier(f"{REF}/models/botnet/nn_augmented_19.model")
    scaler_a = load_joblib_scaler(f"{REF}/models/botnet/scaler_augmented_19.joblib")
    x_a = np.load(f"{REF}/data/botnet/x_candidates_common_augmented.npy")[:32]
    t0 = time.time()
    m2 = Moeva2(
        classifier=sur_a, constraints=cons_a, ml_scaler=scaler_a, norm=2,
        n_gen=100, n_pop=200, n_offsprings=100, seed=42, archive_size=24,
    )
    r2 = m2.generate(x_a, minimize_class=1)
    rq2_rates = [
        round(float(r), 6)
        for r in calc({"f1": 0.5, "f2": 4.0}, c=cons_a, s=sur_a, sc=scaler_a)
        .success_rate_3d(x_a, r2.x_ml)
    ]
    rq2_wall = time.time() - t0
    print(f"[regen] rq2 {rq2_wall:.1f}s: {rq2_rates}", flush=True)

    sur_r3 = load_classifier(f"{REF}/models/botnet/nn_moeva.model")
    t0 = time.time()
    m3 = Moeva2(
        classifier=sur_r3, constraints=cons, ml_scaler=scaler, norm=2,
        n_gen=100, n_pop=200, n_offsprings=100, seed=42, archive_size=24,
    )
    r3 = m3.generate(x, minimize_class=1)
    rq3_rates = [
        round(float(r), 6)
        for r in calc({"f1": 0.5, "f2": 4.0}, s=sur_r3).success_rate_3d(x, r3.x_ml)
    ]
    rq3_wall = time.time() - t0
    print(f"[regen] rq3 {rq3_wall:.1f}s: {rq3_rates}", flush=True)

    # -- write fixture -------------------------------------------------------
    new = {
        "description": (
            "o1..o7 pinned on a slice of the full-scale botnet rq1 MoEvA run "
            "(budget 1000, pop 200, seed 42, TPU) against the reference's "
            "committed candidates+model; thresholds f1=0.5 f2(eps)=4 L2. "
            "REGENERATED round 5 with the corrected (pymoo-oracle-validated) "
            "survival kernel; pre-fix values in pre_fix_r3 for the delta record."
        ),
        "survival_semantics": "post-1141e71 (aspiration-in-ideal/extremes, nadir clamp)",
        "full_scale": {
            "n_states": 387,
            "n_gen": 1000,
            "o_rates": rates_pop,
            "time_s": round(wall, 1),
            "note": (
                "final-population rates (archive columns excluded; population "
                "dynamics are archive-independent). Corrected semantics retain "
                "constrained adversarials in the converged population itself — "
                "pre-fix o4 was 0.0749 here."
            ),
        },
        "slice_states": SLICE_STATES,
        "slice_o_rates": slice_rates,
        "full_scale_archive": {
            "n_states": 387,
            "n_gen": 1000,
            "archive_size": 24,
            "time_s": round(wall, 1),
            "o_rates_eps4": by_eps["4.0"],
            "o_rates_by_eps": by_eps,
        },
        "pgd_flip_sat": {
            "budget": 200,
            "eps": 4,
            "n_states": 387,
            "o_rates": sat_rates,
            "note": old["pgd_flip_sat"]["note"],
        },
        "rq_family_real_runs": {
            "rq2_augmented_defense": {
                "note": old["rq_family_real_runs"]["rq2_augmented_defense"]["note"],
                "o_rates": rq2_rates,
                "time_s": round(rq2_wall, 1),
            },
            "rq3_adversarial_retraining": {
                "note": old["rq_family_real_runs"]["rq3_adversarial_retraining"]["note"],
                "o_rates": rq3_rates,
                "time_s": round(rq3_wall, 1),
            },
        },
        "pre_fix_r3": {
            "note": (
                "round-3 values produced by the PRE-fix survival kernel, kept "
                "for the honesty record: the pre-fix kernel deviated from "
                "pymoo AspirationPointSurvival (the algorithm the reference "
                "runs), so these measured a different attack."
            ),
            "full_scale_o_rates": old["full_scale"]["o_rates"],
            "full_scale_archive_o_rates_eps4": old["full_scale_archive"]["o_rates_eps4"],
            "slice_o_rates": old["slice_o_rates"],
            "rq2_o_rates": old["rq_family_real_runs"]["rq2_augmented_defense"]["o_rates"],
            "rq3_o_rates": old["rq_family_real_runs"]["rq3_adversarial_retraining"]["o_rates"],
        },
    }
    with open(f"{FIXTURES}/parity_botnet_rq1.json", "w") as fh:
        json.dump(new, fh, indent=1)
    os.makedirs("out", exist_ok=True)
    with open("out/parity_regen_r5.json", "w") as fh:
        json.dump({"old": old, "new": new}, fh, indent=1)
    print("[regen] fixture rewritten", flush=True)


if __name__ == "__main__":
    main()
