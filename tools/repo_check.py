#!/usr/bin/env python
"""One tier-1 repo-check entrypoint with a per-gate pass/fail summary.

The repo check grew one flag per observability PR — the raw incantation
was ``python tools/bench_diff.py --check --slo --mesh --overlap`` plus
``python tools/shard_lint.py --check --selftest`` — and every test/doc
call site had to keep the flag list in sync by hand. This wrapper is the
single source of truth for "what does tier-1 enforce":

    python tools/repo_check.py                 # every gate
    python tools/repo_check.py --only bench_diff
    python tools/repo_check.py --only shard_lint --selftest
    python tools/repo_check.py --json          # + one machine-readable line

Gates (each runs as a subprocess of the same interpreter, so a gate that
initializes JAX — shard_lint builds the emulated 8-device mesh — cannot
pollute another gate's process state):

- ``bench_diff`` — the perf+quality+SLO+mesh+overlap+cold watchdog over
  the committed ``BENCH_r*.json`` series (``tools/bench_diff.py --check
  --slo --mesh --overlap --cold``): wall-clock regressions
  (ledger-normalized), interior-success-rate drift, serving knee/p99,
  per-device balance + hot-loop collectives, the device overlap /
  cold-steady ratios, the ABSOLUTE cold/steady ceiling (1.2 — ROADMAP
  item 2's exit criterion), and the warm-start hit rate
  ((hit + aot_hit) / classified executables). ``--qos`` additionally
  gates the committed ``QOS_r*.json`` series: per-class knee p99 held,
  the low-priority-absorbs-overload invariant (scavenger's shed share),
  the streaming time-to-first-solved ratio, and the QoS-off
  bit-identity/zero-extra-compiles proof.
- ``shard_lint`` — the states-sharding contract (``tools/shard_lint.py
  --check``): compiles the committed attack programs on the emulated
  8-device CPU mesh and fails on hot-loop float collectives, oversized
  collective payloads, implicit host↔device transfers, or unintended
  full replication. ``--selftest`` additionally proves the lint still
  trips on injected violations.
- ``domain_lint`` — the constraint-spec contract (``tools/domain_lint.py
  --check``): every committed spec under ``domains/specs/`` parses,
  statically validates against its schema, reproduces its hand-written
  twin bit-exactly where one exists, matches its numpy oracle twin, and
  compiles through the MILP backend; the generated-family path stays
  deterministic.

Exit code: 0 iff every selected gate passed. The summary prints one line
per gate; ``--json`` appends ``{"ok", "gates": {name: {"rc", "ok"}}}``
as the LAST line for CI annotation (per-gate detail stays in each gate's
own captured output above it).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

#: gate name -> argv tail (after ``sys.executable tools/<script>.py``).
#: THE flag list tier-1 enforces — tests and docs reference this file
#: instead of re-spelling it.
GATES = {
    "bench_diff": (
        "bench_diff.py",
        ["--check", "--slo", "--mesh", "--overlap", "--cold", "--fleet",
         "--qos", "--incidents"],
    ),
    "shard_lint": ("shard_lint.py", ["--check"]),
    "domain_lint": ("domain_lint.py", ["--check"]),
}


def run_gate(
    name: str, extra: list[str], timeout: float, cwd: str | None
) -> dict:
    script, args = GATES[name]
    cmd = [sys.executable, os.path.join(HERE, script), *args, *extra]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, cwd=cwd
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (
            e.stdout or ""
        )
        err = f"gate timed out after {timeout:.0f}s"
    return {
        "name": name,
        "cmd": cmd,
        "rc": rc,
        "ok": rc == 0,
        "seconds": round(time.perf_counter() - t0, 1),
        "stdout": out,
        "stderr": err,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--only",
        action="append",
        choices=sorted(GATES),
        help="run only this gate (repeatable); default: every gate",
    )
    parser.add_argument(
        "--selftest",
        action="store_true",
        help="also pass --selftest to shard_lint (prove the lint trips "
        "on injected violations)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="append one machine-readable JSON summary line (and pass "
        "--json through to gates that support it)",
    )
    parser.add_argument(
        "--timeout",
        type=float,
        default=540.0,
        help="per-gate subprocess timeout in seconds (default 540; "
        "shard_lint compiles every attack program on the emulated mesh)",
    )
    parser.add_argument(
        "--cwd",
        default=None,
        help="repo root to check (default: the current directory — "
        "bench_diff globs BENCH_r*.json there)",
    )
    args = parser.parse_args(argv)

    names = args.only or sorted(GATES)
    results = []
    for name in names:
        extra: list[str] = []
        if args.json:
            extra.append("--json")
        if name == "shard_lint" and args.selftest:
            extra.append("--selftest")
        res = run_gate(name, extra, args.timeout, args.cwd)
        results.append(res)
        sys.stdout.write(res["stdout"])
        if res["stderr"]:
            sys.stderr.write(res["stderr"])

    print("repo_check summary:")
    for res in results:
        verdict = "PASS" if res["ok"] else f"FAIL (rc={res['rc']})"
        print(f"  {res['name']:<12} {verdict}  [{res['seconds']}s]")
    ok = all(r["ok"] for r in results)
    print(f"repo_check: {'ok' if ok else 'FAILING'}")
    if args.json:
        print(
            json.dumps(
                {
                    "ok": ok,
                    "gates": {
                        r["name"]: {
                            "rc": r["rc"],
                            "ok": r["ok"],
                            "seconds": r["seconds"],
                        }
                        for r in results
                    },
                }
            )
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
