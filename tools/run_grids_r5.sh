#!/bin/bash
# Round-5 evidence runs with the CORRECTED survival kernel: the full rq
# grid family for both use cases, rq0 smokes, rq4 attacks, and the sm1.1
# sweep — committed metrics for out/attacks/ (round-4 never executed its
# version of this script; the stale pre-fix LCLD outputs were deleted).
# Idempotent: every runner skips config hashes that already have metrics.
set -u
export PYTHONPATH=/root/repo:/root/.axon_site
cd /root/repo
PKG=moeva2_ijcai22_replication_tpu.experiments

step() { echo "=== [$(date +%H:%M:%S)] $* ==="; }

step rq1.lcld
timeout 7200 python -m $PKG.rq -c config/rq1.lcld.yaml
step rq2.lcld
timeout 7200 python -m $PKG.rq -c config/rq2.lcld.yaml
step rq3.lcld
timeout 7200 python -m $PKG.rq -c config/rq3.lcld.yaml
step rq1.botnet
timeout 14400 python -m $PKG.rq -c config/rq1.botnet.yaml
step rq2.botnet
timeout 7200 python -m $PKG.rq -c config/rq2.botnet.yaml
step rq3.botnet
timeout 7200 python -m $PKG.rq -c config/rq3.botnet.yaml
step rq0.botnet
timeout 3600 python -m $PKG.pgd -c config/rq0.botnet.yaml
step rq0.lcld
timeout 3600 python -m $PKG.pgd -c config/rq0.lcld.yaml
step rq4.moeva
timeout 7200 python -m $PKG.moeva -c config/moeva.yaml -c config/rq4.lcld.moeva.yaml
step rq4.moeva_augmented
timeout 7200 python -m $PKG.moeva -c config/moeva.yaml -c config/rq4.lcld.moeva_augmented.yaml
step sm1.1.lcld
timeout 10800 python -m $PKG.rq -c config/sm1.1.lcld.yaml
echo "=== all grids done ==="
