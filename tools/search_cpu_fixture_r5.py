"""Find a small CPU botnet attack whose o-rates are strictly interior.

VERDICT r4: the old parity_botnet_cpu_small fixture had fully saturated 0/1
rates, so it passed unchanged through a behaviour-altering survival fix. A
useful determinism fixture needs success rates strictly inside (0, 1) on the
discriminating columns (o2/o4) so any semantic change moves them.

Runs candidate configs under the EXACT test environment (CPU x64, virtual
8-device platform — tests/conftest.py) and reports their rates; writes the
chosen fixture when a config has 0 < o2 < 1 and 0 < o4 < 1.
"""

import itertools
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
from moeva2_ijcai22_replication_tpu.attacks.objective import ObjectiveCalculator
from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints
from moeva2_ijcai22_replication_tpu.models.io import load_classifier
from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

REF = "/root/reference"
FIXTURES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", "fixtures"
)

cons = BotnetConstraints(
    f"{REF}/data/botnet/features.csv", f"{REF}/data/botnet/constraints.csv"
)
x_all = np.load(f"{REF}/data/botnet/x_candidates_common.npy")
sur = load_classifier(f"{REF}/models/botnet/nn.model")
scaler = load_joblib_scaler(f"{REF}/models/botnet/scaler.joblib")
calc = ObjectiveCalculator(
    classifier=sur, constraints=cons, thresholds={"f1": 0.5, "f2": 4.0},
    min_max_scaler=scaler, ml_scaler=scaler, minimize_class=1, norm=2,
)

N_POP = int(os.environ.get("SEARCH_POP", 100))
N_OFF = int(os.environ.get("SEARCH_OFF", 50))
STATES = [int(s) for s in os.environ.get("SEARCH_STATES", "48,64").split(",")]
GENS = [int(g) for g in os.environ.get("SEARCH_GENS", "40,60,80").split(",")]

best = None
for n_states, n_gen, archive in itertools.product(STATES, GENS, (8,)):
    x = x_all[:n_states]
    moeva = Moeva2(
        classifier=sur, constraints=cons, ml_scaler=scaler, norm=2,
        n_gen=n_gen, n_pop=N_POP, n_offsprings=N_OFF, seed=42,
        archive_size=archive,
    )
    res = moeva.generate(x, minimize_class=1)
    rates = [float(r) for r in calc.success_rate_3d(x, res.x_ml)]
    interior = all(0.0 < rates[i] < 1.0 for i in (1, 3))
    print(f"[search] S={n_states} gens={n_gen} arch={archive}: {rates}"
          f"{'  <-- interior' if interior else ''}", flush=True)
    if interior and best is None:
        best = {
            "n_states": n_states, "n_gen": n_gen, "n_pop": N_POP,
            "n_offsprings": N_OFF, "archive_size": archive, "seed": 42,
            "thresholds": {"f1": 0.5, "f2": 4.0}, "norm": 2,
            "o_rates": rates,
            "note": (
                "rates strictly interior on o2/o4 BY CONSTRUCTION so any "
                "survival/operator semantic change moves them (the old "
                "all-saturated fixture passed through a behaviour-altering "
                "fix unchanged); regenerated round 5 with the corrected "
                "survival kernel on the CPU x64 test platform"
            ),
        }

if best:
    with open(f"{FIXTURES}/parity_botnet_cpu_small.json", "w") as fh:
        json.dump(best, fh, indent=1)
    print(f"[search] fixture written: {best}", flush=True)
else:
    print("[search] NO interior config found", flush=True)
