"""Run the attack service behind its stdlib HTTP front.

Quickstart (after ``python tools/bootstrap_lcld.py`` for the LCLD domain):

    python tools/serve.py -c config/serving.yaml
    python tools/loadgen.py --url http://127.0.0.1:8787 --domain lcld \
        --requests 64 --concurrency 8

Then::

    curl -s localhost:8787/healthz
    curl -s localhost:8787/metrics
    curl -s -X POST localhost:8787/attack -d '{"domain": "lcld",
        "eps": 0.2, "budget": 10, "rows": [[...47 features...]]}'
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "-c", default="config/serving.yaml", help="serving config yaml"
    )
    parser.add_argument("--host", default=None, help="override serving.host")
    parser.add_argument(
        "--port",
        type=int,
        default=None,
        help="override serving.port (0 = auto-assign an ephemeral port; "
        "the bound port is reported on stdout and /healthz)",
    )
    parser.add_argument(
        "--replica-id",
        default=None,
        help="fleet label threaded into /healthz, /metrics, trace ids and "
        "the X-Replica-Id response header (set by serving.fleet's "
        "ReplicaManager when it spawns replicas)",
    )
    parser.add_argument("-v", "--verbose", action="store_true", help="access log")
    parser.add_argument(
        "--prewarm",
        action="store_true",
        help="load the bucket menu's executables at boot, before the "
        "first request lands (compile-or-AOT-load; also enabled by "
        "config serving.prewarm — true for the per-domain defaults, or "
        "a list of {domain, attack, loss_evaluation, budget} specs)",
    )
    args = parser.parse_args(argv)

    from moeva2_ijcai22_replication_tpu.experiments.common import setup_jax_cache
    from moeva2_ijcai22_replication_tpu.serving import AttackService, QosPolicy
    from moeva2_ijcai22_replication_tpu.serving.server import serve
    from moeva2_ijcai22_replication_tpu.utils.config import load_config_file

    cfg = load_config_file(args.c)
    srv_cfg = cfg.get("serving", {})
    setup_jax_cache(cfg)
    # QoS: priority classes + admission + streaming (serving.qos block;
    # absent or enabled:false -> the exact pre-QoS single-queue path)
    qos = QosPolicy.from_config(srv_cfg.get("qos"))

    # request tracing: a JSONL sink enables spans — every /attack response
    # then returns its own span tree and the stream renders in Perfetto via
    # tools/trace_export.py. Off (the default) = counters only, no-op spans.
    recorder = None
    trace_log = srv_cfg.get("trace_log") or cfg.get("system", {}).get(
        "trace_log"
    )
    if trace_log:
        from moeva2_ijcai22_replication_tpu.observability import TraceRecorder

        from moeva2_ijcai22_replication_tpu.observability.fleetrace import (
            replica_sink_path,
        )

        # N replicas share ONE config file: template the sink path per
        # replica (events interleaved from two processes into one JSONL
        # would corrupt both streams; the fleet merge reads the same
        # templated paths back — tools/trace_export.py --fleet)
        trace_log = replica_sink_path(trace_log, args.replica_id)
        recorder = TraceRecorder(sink_path=trace_log)

    service = AttackService(
        cfg["domains"],
        bucket_sizes=srv_cfg.get("bucket_sizes", (8, 16, 32, 64, 128, 256)),
        max_delay_s=srv_cfg.get("max_delay_s", 0.01),
        max_queue_rows=srv_cfg.get("max_queue_rows", 4096),
        seed=srv_cfg.get("seed", 42),
        metrics_window=srv_cfg.get("metrics_window", 8192),
        recorder=recorder,
        slo_buckets=srv_cfg.get("slo_histogram_buckets"),
        capacity_window=srv_cfg.get("capacity_window", 256),
        replica_id=args.replica_id,
        qos=qos,
        flight_ring=srv_cfg.get("flight_ring", 64),
        incident_detection=srv_cfg.get("incident_detection", True),
        flight_dir=srv_cfg.get("flight_dir", "out"),
    )
    # boot-time prewarm: BEFORE the HTTP front binds, so the first caller
    # never pays a compile (engines are single-dispatch objects — this
    # must not race live traffic)
    prewarm_cfg = srv_cfg.get("prewarm")
    if args.prewarm or prewarm_cfg:
        specs = prewarm_cfg if isinstance(prewarm_cfg, list) else None
        report = service.prewarm(specs)
        print(
            f"prewarm: {report['executables']} executables in "
            f"{report['seconds']}s (aot hits {report['aot_hits']}, "
            f"stored {report['aot_stored']})",
            flush=True,
        )
    host = args.host or srv_cfg.get("host", "127.0.0.1")
    port = args.port if args.port is not None else srv_cfg.get("port", 8787)
    httpd = serve(
        service,
        host,
        port,
        request_timeout_s=srv_cfg.get("request_timeout_s", 60.0),
        verbose=args.verbose,
    )
    bound = httpd.server_address
    # machine-readable readiness line FIRST (one JSON object, one line):
    # the fleet ReplicaManager tails stdout for it to learn the bound port
    # under --port 0 without any port bookkeeping
    import json as _json

    print(
        _json.dumps(
            {
                "fleet_ready": {
                    "url": f"http://{bound[0]}:{bound[1]}",
                    "host": bound[0],
                    "port": bound[1],
                    "replica_id": args.replica_id,
                }
            }
        ),
        flush=True,
    )
    print(
        f"attack service on http://{bound[0]}:{bound[1]} "
        f"(domains: {', '.join(sorted(cfg['domains']))}; "
        f"buckets {list(service.menu.sizes)})",
        flush=True,
    )
    # dump-on-SIGTERM: the graceful-drain signal (ReplicaManager's
    # _terminate sends it) leaves a moment SIGKILL never does — use it to
    # land the black box before the process exits, so even a drained
    # replica's last journeys are on disk for the fleet harvest
    import signal as _signal

    def _sigterm(_signum, _frame):
        try:
            service.flight_dump("sigterm")
        except Exception:  # noqa: BLE001 — dying anyway; dump is best-effort
            pass
        raise SystemExit(0)

    try:
        _signal.signal(_signal.SIGTERM, _sigterm)
    except (ValueError, OSError):
        pass  # non-main thread / exotic platform: dump only via POST

    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        service.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
